"""Born-sharded SPMD query execution: device-resident, bucket-range-
sharded inputs flowing stage to stage as single jitted programs.

The deleted legacy `parallel/join.py` path parallelized the BATCH:
every query re-gathered key lanes on the host, re-placed a fresh [S, C]
layout onto the mesh, and synced to the host between stages to size
outputs. This module — now the ONE distributed join architecture —
parallelizes the INDEX, the way the paper's bucketed
layout intends: a committed covering index is *born sharded* — the build
writes per-device parquet shards over the contiguous bucket-range map
(`parallel/mesh.bucket_ranges`), the per-device segment cache holds each
device's bucket range (warm reads assemble the global arrays from HBM
with ZERO link traffic, `mesh.assemble_sharded_rows`), and the
shuffle-free sort-merge join, predicate scan, and group-by aggregate
execute as single jitted SPMD programs under the canonical row sharding:

- **one program per join**: key-lane decomposition, the counting match,
  and the static-capacity pair expansion trace into ONE `instrumented_jit`
  dispatch. The legacy path's host-side sizing sync between match and
  expansion (it read `sum(counts)` to shape the expansion) is replaced
  by a STATIC per-shard output capacity with
  on-device overflow detection — the expansion never waits on the host,
  and the one scalar readback per join carries (total, extra, overflow)
  together *after* everything has dispatched. Overflow triggers an exact
  retry at doubled capacity (the build's all_to_all discipline), and the
  capacity is CLIPPED by the exact per-shard upper bound derived from the
  two sides' bucket histograms, so the retry loop terminates.
- **ICI repartition in-program**: when the two sides' bucket counts
  mismatch (the ranker's fallback), the smaller-bucket side's key lanes
  re-bucket to the larger count through a `shard_map` all_to_all *inside
  the same jitted program* — row payload never routes (the expansion
  carries routed original-row ids and the output gather reaches across
  shards), and nothing crosses through the host.
- **stage-to-stage residency**: join output stays a device-resident
  ColumnBatch; `repartition_sharded` re-buckets it over ICI into a new
  born-sharded layout for the next join, and `sharded_group_aggregate` /
  `sharded_filter` consume the sharded layout directly — a warm
  multi-stage plan records zero D2H link crossings between stages
  (`link.d2h.*` stays flat until result materialization).

Layout contract (`ShardedBatch`): every column is a flat `[S*C]` jax
array under `mesh.shard_rows` — shard s's slice holds the rows of its
bucket range, padded to the common per-shard capacity C with
`row_valid=False` tail rows. Because ownership is a CONTIGUOUS bucket
range, same-key rows co-locate on one shard by construction and the
counting match needs no bucket lane: equal keys hash to one bucket, one
bucket lives on one shard.

String columns are FIRST-CLASS in this layout. Each device's bucket
range carries its own sorted local dictionary (written next to the
parquet shards and recorded in `_shard_layout.json` by mesh builds); a
born-sharded read unifies the ranges into ONE global sorted dictionary
(host metadata, cached version-keyed in the segment cache) and remaps
each shard's codes into it on the host before placement, so the cached
device payload is globally comparable int32 code lanes riding the same
[S*C] row sharding as every numeric column — string BYTES never cross
the link at query time, and a warm read is as link-free as a numeric
one. Joins whose two sides carry different dictionaries unify IN-PROGRAM
through compact rank-remap tables (`string_remap_tables`, THE
lint-enforced remap seam): the int32 local-code -> pair-merged-rank
tables are built once on the host from the dictionaries (derived from
the same precomputed value-hash identity the bucket layout uses), cached
content-keyed in the segment cache, and replicated into the single
jitted SMJ program over ICI — warm repeats serve them straight from HBM
(`spmd.strings.remap_cache_hits`) and ship zero string bytes. String
predicates compile to code-space range tests against the global
dictionary (`engine/compiler.py`), so the jitted filter program never
touches bytes either.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.parallel.mesh import (DCN_AXIS, SHARD_AXIS,
                                          assemble_sharded_rows,
                                          bucket_owner, bucket_ranges,
                                          compat_shard_map, dcn_size,
                                          ici_size, mesh_device_list,
                                          mesh_device_tag, row_spec,
                                          shard_row_segments, shard_rows,
                                          total_shards)

# Static-capacity discipline: first attempt sizes the per-shard output at
# CAPACITY_FACTOR x the per-shard input rows; on-device overflow
# detection doubles it until the expansion fits (exact — nothing is ever
# silently dropped).
CAPACITY_FACTOR = 2.0

# Born-sharded skew guard: when the padded [S, C] layout would out-size
# the true rows by more than this, the caller should fall back to the
# single-chip counting join (whose memory is bounded by the true rows).
PAD_BLOWUP_FACTOR = 4


@dataclass
class ShardedBatch:
    """A born-sharded, device-resident batch: flat [S*C] columns under
    the canonical row sharding, shard s holding its contiguous bucket
    range's rows with invalid padding rows at each shard's tail.
    `lengths` (per-bucket row counts) is layout metadata — None for
    repartitioned intermediates whose per-bucket histogram never
    touched the host."""

    batch: ColumnBatch          # flat [S*C] device columns
    row_valid: object           # [S*C] bool, sharded
    mesh: object
    rows_per_shard: int         # C
    num_buckets: int
    lengths: Optional[np.ndarray] = None
    # Virtual sub-shards (hot-bucket skew): set when the layout was
    # row-balanced INSIDE hot buckets instead of bucket-aligned — keys
    # no longer co-locate per shard, so a join over this side must read
    # its other side ALIGNED to this plan (hot buckets replicated onto
    # every covering shard). None = the canonical bucket-range layout.
    split_plan: Optional["SubshardPlan"] = None

    @property
    def n_shards(self) -> int:
        return total_shards(self.mesh)

    @property
    def num_rows(self) -> int:
        """TRUE row count (padding excluded) when lengths are known."""
        if self.lengths is not None:
            return int(self.lengths.sum())
        import jax.numpy as jnp
        return int(jnp.sum(self.row_valid))


def supports_sharded(schema, key_columns: Sequence[str] = ()) -> bool:
    """Whether a schema fits the born-sharded layout. Strings are
    first-class (per-range dictionaries, module docstring); only a dtype
    outside the engine's host-lane map declines."""
    from hyperspace_tpu.io.columnar import HOST_NP_DTYPES
    try:
        for f in schema.fields:
            if f.dtype not in HOST_NP_DTYPES:
                return False
        for c in key_columns:
            schema.field(c)
    except Exception:
        return False
    return True


def spmd_fallback(reason: str) -> None:
    """Record a decline of the born-sharded SPMD lane while a mesh was
    AVAILABLE (`spmd.fallbacks` + a query event). The counter is the
    one-architecture contract: `bench_tpcds.py` asserts the whole TPC-DS
    set runs with `spmd.fallbacks == 0` and `bench_regress.py` gates it
    absolutely."""
    from hyperspace_tpu import telemetry
    telemetry.get_registry().counter("spmd.fallbacks").inc()
    telemetry.event("spmd", "fallback", reason=reason)


def count_string_predicate_lookups(expression, batch: ColumnBatch) -> None:
    """`spmd.strings.dict_lookups`: one per string column a predicate
    resolves literals against on the SPMD lane (the compiler's
    code-space binary searches, `engine/compiler._string_literal_compare`
    — the jitted program itself never touches bytes)."""
    from hyperspace_tpu import telemetry
    try:
        refs = expression.references()
    except Exception:
        return
    n = 0
    for r in refs:
        try:
            if batch.column(r).is_string:
                n += 1
        except Exception:
            continue
    if n:
        telemetry.get_registry().counter(
            "spmd.strings.dict_lookups").inc(n)


def pad_blowup(lengths, n_shards: int) -> bool:
    """True when per-shard padding to the hottest shard's row count
    would blow the [S*C] layout far past the true rows (the caller
    splits the hot range into virtual sub-shards — `subshard_plan` —
    or falls back to the single-chip counting join)."""
    segs = shard_row_segments(lengths, n_shards)
    C = max(1, max(e - s for s, e in segs))
    rows = int(np.asarray(lengths).sum())
    return C * n_shards > max(PAD_BLOWUP_FACTOR * rows, 1 << 16)


# ---------------------------------------------------------------------------
# Virtual sub-shards: hot-bucket skew without leaving the SPMD lane
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SubshardPlan:
    """Row-balanced virtual sub-shards over a skewed bucket histogram.

    When one bucket range is hot enough that whole-bucket ownership
    would pad the [S*C] layout past `PAD_BLOWUP_FACTOR`x the true rows,
    the skewed side's bucket-ordered row space is cut into EQUAL row
    segments instead — cuts may fall inside a hot bucket, so a hot
    bucket's rows span several consecutive shards (the hierarchical
    range map makes this representation free: segments are just row
    intervals, exactly like `shard_row_segments`' output).

    Splitting breaks per-shard key co-location, so a join over the
    split side reads its OTHER side aligned to this plan:
    `bucket_spans[s]` is the contiguous bucket interval intersecting
    shard s's row segment, and the aligned read places ALL of those
    buckets' rows on shard s — a split bucket's other-side rows are
    REPLICATED onto every shard covering part of it. Each split-side
    row then meets every matching row locally and lives on exactly one
    shard, so inner/left_outer/semi/anti results are bit-identical to
    the unsplit join (full_outer needs unmatched-RIGHT uniqueness and
    stays off this lane)."""

    num_buckets: int
    n_shards: int
    segments: tuple      # per-shard (row_lo, row_hi) into the row space
    bucket_spans: tuple  # per-shard (b_lo, b_hi) intersecting buckets


def subshard_plan(lengths, n_shards: int) -> SubshardPlan:
    """The deterministic split plan for a skewed histogram: equal row
    segments (±1) with their covering bucket intervals."""
    lengths = np.asarray(lengths, dtype=np.int64)
    total = int(lengths.sum())
    per = -(-max(total, 1) // n_shards)
    cum = np.concatenate([[0], np.cumsum(lengths)])
    segments = []
    spans = []
    for s in range(n_shards):
        lo, hi = min(s * per, total), min((s + 1) * per, total)
        segments.append((lo, hi))
        if hi <= lo:
            spans.append((0, 0))
            continue
        # buckets b with cum[b] < hi and cum[b+1] > lo
        b_lo = int(np.searchsorted(cum, lo, side="right")) - 1
        b_hi = int(np.searchsorted(cum, hi, side="left"))
        spans.append((max(b_lo, 0), min(b_hi, len(lengths))))
    return SubshardPlan(len(lengths), n_shards, tuple(segments),
                        tuple(spans))


def _file_cuts(per_bucket: dict, num_buckets: int):
    """Ordered (bucket, file, rows) over the bucket-ordered file list
    plus the cumulative row offsets — the geometry both sub-shard read
    planners slice against. Row counts come from parquet footers."""
    from hyperspace_tpu.io import parquet

    ordered = [(b, f) for b in range(num_buckets)
               for f in per_bucket.get(b, [])]
    counts = parquet.file_row_counts([f for _, f in ordered])
    cum = np.concatenate([[0], np.cumsum(np.asarray(counts,
                                                    dtype=np.int64))])
    return ordered, counts, cum


def plan_skew_read(per_bucket: dict, lengths, n_shards: int):
    """(plan, shard_specs) for the SKEWED side: each shard s reads rows
    [lo, hi) of the bucket-ordered file list — the covering files plus
    a (skip, take) window so a file holding a cut boundary decodes once
    per touching shard but ships only its slice."""
    lengths = np.asarray(lengths, dtype=np.int64)
    plan = subshard_plan(lengths, n_shards)
    ordered, counts, cum = _file_cuts(per_bucket, len(lengths))
    specs = []
    for lo, hi in plan.segments:
        if hi <= lo:
            specs.append(((), 0, 0))
            continue
        f_lo = int(np.searchsorted(cum, lo, side="right")) - 1
        f_hi = int(np.searchsorted(cum, hi, side="left"))
        files = tuple(f for _b, f in ordered[f_lo:f_hi])
        specs.append((files, lo - int(cum[f_lo]), hi - lo))
    return plan, specs


def plan_aligned_read(per_bucket: dict, lengths, plan: SubshardPlan):
    """shard_specs for the side ALIGNED to a split plan: shard s holds
    every row of the buckets intersecting the plan's shard-s segment —
    buckets on a cut boundary are replicated onto each covering
    shard."""
    lengths = np.asarray(lengths, dtype=np.int64)
    cum = np.concatenate([[0], np.cumsum(lengths)])
    specs = []
    for b_lo, b_hi in plan.bucket_spans:
        files = tuple(f for b in range(b_lo, b_hi)
                      for f in per_bucket.get(b, []))
        specs.append((files, 0, int(cum[b_hi] - cum[b_lo])))
    return specs


# ---------------------------------------------------------------------------
# Layout construction
# ---------------------------------------------------------------------------


def shard_bucket_ordered(batch: ColumnBatch, lengths, mesh) -> ShardedBatch:
    """Place a bucket-ordered batch into the born-sharded layout. HOST
    batches pad per shard in numpy and cross the link ONCE through the
    transfer engine's sharded put (each device receives only its range's
    rows); DEVICE batches re-lay out with an on-device gather (the
    per-shard segment boundaries are host metadata, the rows never leave
    the device)."""
    import jax.numpy as jnp

    from hyperspace_tpu.io import transfer

    lengths = np.asarray(lengths, dtype=np.int64)
    n_shards = total_shards(mesh)
    segs = shard_row_segments(lengths, n_shards)
    C = max(1, max(e - s for s, e in segs))
    n = batch.num_rows
    sharding = shard_rows(mesh)
    engine = transfer.get_engine()

    # [S*C] gather index + validity, from the host-side segment map.
    idx = np.zeros(n_shards * C, dtype=np.int64)
    valid = np.zeros(n_shards * C, dtype=bool)
    for s, (lo, hi) in enumerate(segs):
        rows = hi - lo
        idx[s * C:s * C + rows] = np.arange(lo, hi)
        valid[s * C:s * C + rows] = True

    columns = {}
    if batch.is_host:
        for name, col in batch.columns.items():
            data = np.zeros((n_shards * C,) + col.data.shape[1:],
                            dtype=col.data.dtype)
            data[valid] = col.data
            v = None
            if col.validity is not None:
                v = np.zeros(n_shards * C, dtype=bool)
                v[valid] = col.validity
                v = engine.put(v, device=sharding)
            columns[name] = DeviceColumn(
                data=engine.put(data, device=sharding), dtype=col.dtype,
                validity=v, dictionary=col.dictionary,
                dict_hashes=col.dict_hashes)
        row_valid = engine.put(valid, device=sharding)
    else:
        idx_dev = engine.put(np.minimum(idx, max(n - 1, 0)),
                             device=sharding)
        row_valid = engine.put(valid, device=sharding)
        for name, col in batch.columns.items():
            data = jnp.where(
                _expand_mask(row_valid, col.data.ndim),
                jnp.take(jnp.asarray(col.data), idx_dev, axis=0), 0)
            v = None
            if col.validity is not None:
                v = jnp.take(jnp.asarray(col.validity), idx_dev) & row_valid
            columns[name] = DeviceColumn(
                data=engine.put(data, device=sharding), dtype=col.dtype,
                validity=(engine.put(v, device=sharding)
                          if v is not None else None),
                dictionary=col.dictionary, dict_hashes=col.dict_hashes)
    flat = ColumnBatch(batch.schema, columns)
    return ShardedBatch(flat, row_valid, mesh, C, len(lengths),
                        lengths=lengths)


def _expand_mask(mask, ndim: int):
    import jax.numpy as jnp
    out = jnp.asarray(mask)
    for _ in range(ndim - 1):
        out = out[..., None]
    return out


def _build_global_dicts(files: List[str], str_fields: Sequence[str],
                        schema) -> dict:
    """The GLOBAL sorted dictionary (+ precomputed value hashes) of each
    string column of a born-sharded version: preferred source is the
    per-range dictionaries the mesh build recorded in
    `_shard_layout.json` (pure JSON, no data read — any query mesh size
    merges the same union); a version without the record (single-device
    builds, ranges past the `distribution.dictionary.max.entries` cap)
    derives them from one host-side read of the string columns."""
    import os

    from hyperspace_tpu.io.columnar import _string_hash64

    out: dict = {}
    if not files:
        for name in str_fields:
            empty = np.asarray([], dtype=str)
            out[name] = {"dictionary": empty,
                         "hashes": _string_hash64(empty)}
        return out

    remaining = list(str_fields)
    roots = {os.path.dirname(f) for f in files}
    if len(roots) == 1:
        from hyperspace_tpu.io.builder import read_shard_layout
        layout = read_shard_layout(next(iter(roots)))
        recorded = (layout or {}).get("dictionaries") or {}
        for name in list(remaining):
            ranges = recorded.get(name)
            if ranges is None or any(r is None for r in ranges):
                continue  # uncapped record absent: derive from files
            merged = np.unique(np.concatenate(
                [np.asarray(r, dtype=str) for r in ranges]
                + [np.asarray([], dtype=str)]))
            out[name] = {"dictionary": merged,
                         "hashes": _string_hash64(merged)}
            remaining.remove(name)

    if remaining:
        from hyperspace_tpu.io import columnar, parquet
        table = parquet.read_table(files, columns=remaining)
        for name in remaining:
            _codes, dictionary, hashes, _validity = \
                columnar._encode_strings_arrow(table.column(name))
            out[name] = {"dictionary": dictionary, "hashes": hashes}
    return out


def _resolve_global_dicts(per_shard_files: List[List[str]],
                          str_fields: Sequence[str], schema, base_ref,
                          conf, budget, cache) -> dict:
    """Version-keyed cached resolution of the global dictionaries (one
    entry per committed version + column set; warm queries never re-read
    or re-merge — `spmd.strings.remap_cache_hits`)."""
    from hyperspace_tpu import telemetry

    all_files = [f for files in per_shard_files for f in files]
    if base_ref is None:
        return _build_global_dicts(all_files, str_fields, schema)
    filled: List[bool] = []

    def fill():
        filled.append(True)
        payload = _build_global_dicts(all_files, str_fields, schema)
        nbytes = sum(int(e["dictionary"].nbytes) + int(e["hashes"].nbytes)
                     for e in payload.values())
        return payload, max(nbytes, 1)

    key = base_ref.key + (("spmd-dicts", tuple(str_fields)),)
    payload = cache.get_or_fill(key, fill, ref=base_ref, conf=conf,
                                budget=budget)
    if not filled:
        telemetry.get_registry().counter(
            "spmd.strings.remap_cache_hits").inc()
    return payload


def _remap_to_global(host: ColumnBatch, global_dicts: dict) -> ColumnBatch:
    """Swap each string column's LOCAL codes for codes in the global
    dictionary (host-side, before placement) — the cached device payload
    then holds globally comparable int32 lanes and no per-shard
    dictionary state. Fails loudly if a valid local value is missing
    from the global dictionary (the two derive from the same committed
    files, so a miss means the record and the data disagree)."""
    for name, col in host.columns.items():
        if not col.is_string:
            continue
        g = global_dicts[name]["dictionary"]
        local = np.asarray(col.dictionary)
        if len(g):
            remap = np.searchsorted(g, local).astype(np.int32)
            found = g[np.clip(remap, 0, len(g) - 1)] == local
        else:
            remap = np.zeros(len(local), dtype=np.int32)
            found = np.zeros(len(local), dtype=bool)
        codes = np.asarray(col.data)
        used = codes if col.validity is None else codes[col.validity]
        if len(used) and not found[used].all():
            raise HyperspaceException(
                f"Born-sharded read: string column {name!r} holds values "
                "absent from the version's global dictionary — the "
                "recorded per-range dictionaries and the data disagree.")
        safe = np.where(found, remap, 0).astype(np.int32)
        host.columns[name] = DeviceColumn(
            data=safe[codes], dtype="string", validity=col.validity,
            dictionary=col.dictionary, dict_hashes=col.dict_hashes)
    return host


def _files_digest(files) -> str:
    """Compact stable identity of an ordered file tuple for sub-shard
    cache key tags."""
    import hashlib

    h = hashlib.sha1()
    for f in files:
        h.update(str(f).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def read_sharded(per_shard_files: List[List[str]], lengths,
                 columns: Sequence[str], schema, mesh,
                 base_ref=None, conf=None, budget=None,
                 shard_specs=None,
                 split_plan: Optional[SubshardPlan] = None
                 ) -> ShardedBatch:
    """Born-sharded read: each flat shard s's bucket-range files decode
    and place onto DEVICE s through the per-device segment cache
    (per-bucket-range fill granularity — the PR-8 "remaining on this
    axis" item). A warm read touches neither parquet nor the link: the
    cached per-device padded shards assemble into the global sharded
    arrays with zero data movement. Cache keys carry the mesh's DEVICE
    TAG: two replica slices of one topology hold the same ranges on
    different devices and must never alias each other's entries.

    `shard_specs` overrides the canonical whole-bucket segmentation
    with explicit per-shard (files, skip_rows, n_rows) windows — the
    virtual-sub-shard lanes (`plan_skew_read` / `plan_aligned_read`);
    `split_plan` is stamped onto the result so the join knows the
    layout is row-balanced, not bucket-aligned."""
    from hyperspace_tpu import telemetry
    from hyperspace_tpu.io import segcache

    lengths = np.asarray(lengths, dtype=np.int64)
    n_shards = total_shards(mesh)
    if shard_specs is None:
        segs = shard_row_segments(lengths, n_shards)
        ranges = bucket_ranges(len(lengths), n_shards)
        shard_specs = [(tuple(per_shard_files[s]), 0, segs[s][1] - segs[s][0])
                       for s in range(n_shards)]
        key_tags = [("spmd", ranges[s][0], ranges[s][1], n_shards)
                    for s in range(n_shards)]
        out_lengths = lengths
        windowed = False
    else:
        if len(shard_specs) != n_shards:
            raise HyperspaceException(
                f"shard_specs covers {len(shard_specs)} shards; the mesh "
                f"has {n_shards}.")
        # The windowed (skip, rows) coordinates alone do not say WHICH
        # bucket-range files shard s's window slices — the skew/aligned
        # plans depend on the OTHER join side's histogram, so two joins
        # of the same root+version can hand shard s identical window
        # geometry over DIFFERENT bucket spans. The file-tuple digest
        # pins the key to the covered bytes.
        key_tags = [("spmd-sub", spec[1], spec[2], n_shards, s,
                     _files_digest(spec[0]))
                    for s, spec in enumerate(shard_specs)]
        out_lengths = None
        windowed = True
    C = max(1, max(spec[2] for spec in shard_specs))
    devices = mesh_device_list(mesh)
    dev_tag = mesh_device_tag(mesh)
    cols = tuple(columns)
    schema_json = schema.to_json()
    cache = segcache.get_cache()

    out_schema = schema.select(cols)
    str_fields = tuple(f.name for f in out_schema.fields
                       if f.dtype == "string")
    global_dicts = None
    if str_fields:
        # One global sorted dictionary per string column (version-keyed
        # cached): per-shard fills remap their local codes into it on
        # the host, so the cached device lanes are globally comparable.
        all_files = list(dict.fromkeys(
            f for spec in shard_specs for f in spec[0]))
        global_dicts = _resolve_global_dicts([all_files], str_fields,
                                             schema, base_ref, conf,
                                             budget, cache)

    def fill_one(s: int):
        files, skip, rows = shard_specs[s]

        def fill():
            return _fill_device_shard(list(files), cols, schema,
                                      rows, C, devices[s],
                                      global_dicts=global_dicts,
                                      skip=skip, windowed=windowed)

        if base_ref is None:
            return fill()[0]
        key = base_ref.key + (key_tags[s] + (C, dev_tag),
                              cols, schema_json)
        return cache.get_or_fill(key, fill, ref=base_ref, conf=conf,
                                 budget=budget)

    # Concurrent per-shard fills: parquet decode of shard s+1 overlaps
    # shard s's H2D (each fill itself pipelines through put_group). The
    # fan-out rides a DEDICATED lane, not `parquet.io_executor()` — the
    # fills call read_table, which submits to that shared pool and
    # blocks; fanning out on the same pool would deadlock it against
    # itself.
    shards = list(_read_pool().map(
        telemetry.propagating(fill_one), range(n_shards)))

    columns_out = {}
    for f in out_schema.fields:
        data = assemble_sharded_rows(
            mesh, [sh["columns"][f.name]["data"] for sh in shards])
        validity = None
        if any(sh["columns"][f.name].get("validity") is not None
               for sh in shards):
            validity = assemble_sharded_rows(
                mesh, [_shard_validity(sh, f.name, C, devices[s])
                       for s, sh in enumerate(shards)])
        dictionary = dict_hashes = None
        if f.dtype == "string":
            # Codes are already global (the fills remapped); the
            # dictionary is HOST metadata — no bytes on the link.
            from hyperspace_tpu.io.columnar import _split_hashes
            entry = global_dicts[f.name]
            dictionary = entry["dictionary"]
            dict_hashes = _split_hashes(entry["hashes"], device=False)
        columns_out[f.name] = DeviceColumn(data=data, dtype=f.dtype,
                                           validity=validity,
                                           dictionary=dictionary,
                                           dict_hashes=dict_hashes)
    row_valid = assemble_sharded_rows(
        mesh, [_on_device(devices[s],
                          partial(_valid_mask, shard_specs[s][2], C))
               for s in range(n_shards)])
    flat = ColumnBatch(out_schema, columns_out)
    return ShardedBatch(flat, row_valid, mesh, C, len(lengths),
                        lengths=out_lengths, split_plan=split_plan)


_pool = None
_pool_lock = None


def _read_pool():
    """Lazy shared fan-out lane for per-shard fills (one per process,
    atexit-drained). DISTINCT from `parquet.io_executor()` on purpose:
    the fills block on that pool internally."""
    global _pool, _pool_lock
    import threading
    if _pool_lock is None:
        _pool_lock = threading.Lock()
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="hs-spmd-read")
                import atexit
                atexit.register(shutdown_read_pool)
    return _pool


def shutdown_read_pool(wait: bool = True) -> None:
    """Drain + stop the fill fan-out lane (idempotent; lazily
    re-created on the next born-sharded read)."""
    global _pool
    pool, _pool = _pool, None
    if pool is not None:
        pool.shutdown(wait=wait)


def _valid_mask(rows: int, C: int):
    import jax.numpy as jnp
    return jnp.arange(C) < rows


def _on_device(device, fn):
    """Run an eager constant-producing computation ON `device` — device-
    local array creation, no link traffic (XLA materializes the fill on
    the target device)."""
    import jax
    with jax.default_device(device):
        return fn()


def _shard_validity(shard: dict, name: str, C: int, device):
    v = shard["columns"][name].get("validity")
    if v is not None:
        return v
    return _on_device(device, partial(_valid_mask, C, C))


def _fill_device_shard(files: List[str], cols, schema, rows: int, C: int,
                       device, global_dicts=None, skip: int = 0,
                       windowed: bool = False) -> Tuple[dict, int]:
    """Cold fill of one device's bucket range: parquet decode, pad to
    the common per-shard capacity on the host, place every column onto
    THIS device through the transfer engine's fill lane. String columns
    decode to their LOCAL per-range dictionary and remap to the global
    codes on the host (`_remap_to_global`) — only int32 code lanes ever
    cross the link. A virtual-sub-shard window (`skip` > 0 or `rows`
    short of the decoded count) slices the decoded table before
    staging, so a hot bucket split across shards ships each shard only
    its slice. Returns (payload, resident bytes)."""
    from hyperspace_tpu.io import parquet, transfer

    out_schema = schema.select(cols)
    if not files or rows == 0:
        # Empty range: all-padding shard, created device-locally.
        import jax.numpy as jnp

        from hyperspace_tpu.io.columnar import HOST_NP_DTYPES
        cols_out = {}
        for f in out_schema.fields:
            dt = HOST_NP_DTYPES[f.dtype]
            cols_out[f.name] = {
                "data": _on_device(device, partial(jnp.zeros, C, dt)),
                "validity": None}
        payload = {"columns": cols_out, "rows": 0}
        return payload, _payload_nbytes(payload)

    table = parquet.read_table(files, columns=list(cols))
    if table.num_rows < skip + rows or (not windowed
                                        and table.num_rows != rows):
        raise HyperspaceException(
            f"Born-sharded read expected {rows} rows (skip {skip}), "
            f"decoded {table.num_rows} — footer metadata and data "
            f"disagree.")
    if skip or table.num_rows != rows:
        table = table.slice(skip, rows)
    from hyperspace_tpu.io import columnar
    host = columnar.from_arrow(table, out_schema, device=False)
    if global_dicts:
        host = _remap_to_global(host, global_dicts)
    jobs = []
    for f in out_schema.fields:
        col = host.columns[f.name]
        data = np.zeros((C,) + col.data.shape[1:], dtype=col.data.dtype)
        data[:rows] = col.data
        entry = {"data": data}
        if col.validity is not None:
            v = np.zeros(C, dtype=bool)
            v[:rows] = col.validity
            entry["validity"] = v
        jobs.append((f.name, entry))
    engine = transfer.get_engine()
    placed = engine.put_group([partial(lambda e: e, entry)
                               for _name, entry in jobs],
                              device=device, tag="fill")
    cols_out = {name: {"data": entry["data"],
                       "validity": entry.get("validity")}
                for (name, _), entry in zip(jobs, placed)}
    payload = {"columns": cols_out, "rows": rows}
    return payload, _payload_nbytes(payload)


def _payload_nbytes(payload: dict) -> int:
    total = 0
    for entry in payload["columns"].values():
        total += int(getattr(entry["data"], "nbytes", 0))
        if entry.get("validity") is not None:
            total += int(getattr(entry["validity"], "nbytes", 0))
    return total


# ---------------------------------------------------------------------------
# The single-program SPMD join
# ---------------------------------------------------------------------------


def _key_arrays(batch: ColumnBatch, names: Sequence[str]):
    """(data arrays, combined key validity | None) for the key columns.
    String key columns contribute their int32 CODE lanes; cross-side
    comparability comes from the rank-remap tables the join program
    applies in-program (`string_remap_tables`)."""
    import jax.numpy as jnp

    datas = []
    ok = None
    for name in names:
        col = batch.column(name)
        datas.append(jnp.asarray(col.data))
        if col.validity is not None:
            v = jnp.asarray(col.validity)
            ok = v if ok is None else (ok & v)
    return datas, ok


def _dict_fingerprint(dictionary) -> tuple:
    """Content identity of a sorted dictionary (entry count + md5 of the
    packed values) — the cache key of cross-side remap tables. Content
    keying is strictly stronger than version keying: two committed
    versions with identical dictionaries share one resident table."""
    import hashlib

    d = np.ascontiguousarray(np.asarray(dictionary))
    return (int(d.shape[0]), hashlib.md5(d.tobytes()).hexdigest())


def string_remap_tables(lcol: DeviceColumn, rcol: DeviceColumn,
                        conf=None):
    """THE dictionary-remap constructor for the SPMD lane (lint-enforced:
    `check_metrics_coverage.py::check_string_remap_seam` bans calls
    outside this module's consumers). Builds the compact int32
    local-code -> pair-merged-rank tables that make two sides' string
    codes mutually comparable inside the single jitted SMJ program —
    derived from the host dictionaries, NEVER shipping string bytes:
    the tables ride one H2D put cold, are cached content-keyed in the
    segment cache, and replicate into the program over ICI. Warm
    repeats serve them straight from the cache
    (`spmd.strings.remap_cache_hits`) with zero link traffic."""
    from hyperspace_tpu import telemetry
    from hyperspace_tpu.io import segcache, transfer
    from hyperspace_tpu.io.columnar import _merged_dictionary

    key = ("spmd-remap", _dict_fingerprint(lcol.dictionary),
           _dict_fingerprint(rcol.dictionary))
    filled: List[bool] = []

    def fill():
        filled.append(True)
        _merged, (ra, rb), _hashes = _merged_dictionary(
            [lcol.dictionary, rcol.dictionary], device=False)
        engine = transfer.get_engine()
        payload = {"l": engine.put(ra), "r": engine.put(rb)}
        return payload, max(int(ra.nbytes) + int(rb.nbytes), 1)

    payload = segcache.get_cache().get_or_fill(key, fill, conf=conf)
    if not filled:
        telemetry.get_registry().counter(
            "spmd.strings.remap_cache_hits").inc()
    return payload["l"], payload["r"]


def string_like_mask(col: DeviceColumn, pattern_regex: str, conf=None):
    """THE device-side LIKE lane for dictionary-encoded strings: a
    boolean membership mask over the column's sorted dictionary —
    mask[code] == pattern matches dictionary[code] — computed ONCE on
    the host (anchored regex over the distinct values, O(dictionary)),
    shipped over the link once, and cached content-keyed in the segment
    cache exactly like the PR-13 rank-remap tables. The jitted filter
    program then evaluates LIKE as one `take(mask, codes)` — warm
    repeats serve the mask straight from HBM
    (`spmd.strings.like_mask_cache_hits`) with zero host regex work and
    zero link traffic, instead of round-tripping every evaluation
    through the generic host regex + fresh code-list H2D."""
    import re as _re

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.io import segcache, transfer

    key = ("spmd-like", _dict_fingerprint(col.dictionary), pattern_regex)
    filled: List[bool] = []

    def fill():
        filled.append(True)
        rx = _re.compile(pattern_regex, _re.DOTALL)
        d = np.asarray(col.dictionary)
        mask = np.asarray([rx.fullmatch(str(v)) is not None for v in d],
                          dtype=bool)
        return {"mask": mask}, max(int(mask.nbytes), 1)

    cache = segcache.get_cache()
    payload = cache.get_or_fill(key, fill, conf=conf)
    if not filled:
        telemetry.get_registry().counter(
            "spmd.strings.like_mask_cache_hits").inc()
    import jax

    try:
        tracing = not jax.core.trace_state_clean()
    except Exception:
        tracing = True
    if tracing:
        # Inside a jit trace the engine's chunked put would itself be
        # TRACED and the resulting tracer would escape into the cache
        # (a leak); the host mask constant-folds into the program
        # instead, and the next eager caller promotes it below.
        return payload["mask"]
    # The device copy is its OWN cache entry, sized by the device bytes
    # — it rides the cache's fill/accounting/eviction machinery rather
    # than being patched onto the host entry's payload (which would
    # leave its HBM bytes uncharged and race concurrent readers).
    host_mask = payload["mask"]

    def fill_dev():
        dev = transfer.get_engine().put(host_mask)
        return {"dev": dev}, max(int(dev.nbytes), 1)

    return cache.get_or_fill(("spmd-like-dev",) + key[1:], fill_dev,
                             conf=conf)["dev"]


def _string_key_plan(left: "ShardedBatch", right: "ShardedBatch",
                     left_keys: Sequence[str],
                     right_keys: Sequence[str], need_hashes: bool,
                     conf=None):
    """Per-key string unification plan for the SPMD join: which key
    positions are strings (`remap_idx`, static program structure), their
    rank-remap tables, and — when an in-program repartition will route
    the right side — the right dictionaries' value-hash tables (bucket
    identity must hash the VALUE, exactly like the build)."""
    import jax.numpy as jnp

    idx: List[int] = []
    l_remaps: List = []
    r_remaps: List = []
    r_hashes: List = []
    for i, (lk, rk) in enumerate(zip(left_keys, right_keys)):
        lcol = left.batch.column(lk)
        rcol = right.batch.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(
                f"Join key type mismatch: {lk} vs {rk}")
        if not lcol.is_string:
            continue
        ra, rb = string_remap_tables(lcol, rcol, conf=conf)
        idx.append(i)
        l_remaps.append(ra)
        r_remaps.append(rb)
        if need_hashes:
            hi, lo = rcol.dict_hashes
            r_hashes.append((jnp.asarray(hi), jnp.asarray(lo)))
    return (tuple(idx), tuple(l_remaps), tuple(r_remaps),
            tuple(r_hashes))


def _promote_pairs(l_datas, r_datas):
    import jax.numpy as jnp
    lp, rp = [], []
    for ld, rd in zip(l_datas, r_datas):
        if ld.dtype != rd.dtype:
            common = jnp.promote_types(ld.dtype, rd.dtype)
            ld, rd = ld.astype(common), rd.astype(common)
        lp.append(ld)
        rp.append(rd)
    return lp, rp


def _side_lane_chain(datas):
    lanes = []
    for d in datas:
        lanes.extend(keymod.key_lanes(d))
    return lanes


def _route_local(arrs, dest, n_peers: int, capacity: int,
                 axis: str = SHARD_AXIS):
    """Route local rows to their destination peers through ONE
    all_to_all over the named mesh `axis` (shard_map-local shapes):
    stable sort by dest, scatter into the [n_peers, capacity] send
    buffer, swap. The collective is CONFINED to the axis's device
    groups — within-slice hops ride ICI, cross-slice hops ride DCN.
    Returns (routed arrays [n_peers*capacity, ...], overflow count).
    Mirrors `parallel/build._route_stage`."""
    import jax
    import jax.numpy as jnp

    n_local = dest.shape[0]
    iota = jnp.arange(n_local, dtype=jnp.int32)
    dest_sorted, perm = jax.lax.sort([dest, iota], num_keys=1,
                                     is_stable=True)
    seg_start = jnp.searchsorted(
        dest_sorted, jnp.arange(n_peers + 1, dtype=jnp.int32), side="left")
    offset = jnp.arange(n_local, dtype=jnp.int32) - jnp.take(
        seg_start, jnp.clip(dest_sorted, 0, n_peers))
    keep = (offset < capacity) & (dest_sorted < n_peers)
    overflow = jnp.sum((offset >= capacity) & (dest_sorted < n_peers))
    slot = jnp.where(keep, dest_sorted * capacity + offset,
                     n_peers * capacity)

    def route(arr):
        src = jnp.take(arr, perm, axis=0)
        buf = jnp.zeros((n_peers * capacity + 1,) + src.shape[1:],
                        dtype=src.dtype)
        buf = buf.at[slot].set(src, mode="drop")
        send = buf[:n_peers * capacity].reshape(
            (n_peers, capacity) + src.shape[1:])
        recv = jax.lax.all_to_all(send, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        return recv.reshape((n_peers * capacity,) + src.shape[1:])

    return [route(a) for a in arrs], overflow


def _route_slabs(mesh, route_capacity: int):
    """Static slab geometry of one in-program repartition on `mesh`:
    (per-shard routed rows, cap_ici, cap_dcn). Flat mesh: one
    all_to_all over all S peers. 2-axis mesh: two axis-confined hops —
    ICI to the owner's position within the source slice, then DCN to
    the owner slice (the build's `_shard_step` discipline) — each with
    its own per-peer capacity; cap_dcn sizes from the stage-1 output
    with the same headroom factor, and the caller's overflow-retry
    doubling grows both together."""
    S = total_shards(mesh)
    d = dcn_size(mesh)
    if d == 1:
        return S * route_capacity, route_capacity, 0
    # Stage 1 fans over n_ici peers (not S), so its per-peer slab is d
    # times the flat per-peer slab for the same expected row volume.
    # Stage 2 receives at most n_ici * cap_ici rows per shard and fans
    # over d slice peers; cap_ici already carries the headroom factor,
    # so stage 2 inherits it rather than compounding it (a second
    # factor would double the slab memory AND make the DCN byte share
    # a statement about the headroom constant instead of the routing —
    # each row crosses DCN at most once, so the share must sit ~1/2).
    # Cross-slice skew beyond the inherited headroom lands in the
    # overflow-retry doubling like every other capacity here.
    ici = ici_size(mesh)
    cap_ici = route_capacity * d
    cap_dcn = max(16, -(-ici * cap_ici // d))
    return d * cap_dcn, cap_ici, cap_dcn


def _record_repartition_bytes(mesh, route_capacity: int,
                              per_row_bytes: int) -> None:
    """Attribute one repartition dispatch's exchange volume to the
    link that carries it: `spmd.repartition.ici.bytes` for the
    within-slice hop, `spmd.repartition.dcn.bytes` for the cross-slice
    hop. The figure is the full send-buffer volume across the mesh
    (capacity slabs, padding included) — a static upper bound the
    regression differ can compare round over round, not a measured
    wire count."""
    from hyperspace_tpu import telemetry

    reg = telemetry.get_registry()
    S = total_shards(mesh)
    d = dcn_size(mesh)
    _rows, cap_ici, cap_dcn = _route_slabs(mesh, route_capacity)
    if d == 1:
        reg.counter("spmd.repartition.ici.bytes").inc(
            S * S * cap_ici * per_row_bytes)
        return
    ici = ici_size(mesh)
    reg.counter("spmd.repartition.ici.bytes").inc(
        S * ici * cap_ici * per_row_bytes)
    reg.counter("spmd.repartition.dcn.bytes").inc(
        S * d * cap_dcn * per_row_bytes)


def _repartition_lanes(lanes, hash_lanes, null, valid, gid,
                       num_buckets_to: int, mesh, route_capacity: int):
    """In-program re-bucket of one side's KEY LANES (+ null/valid masks
    and original-row ids): each row moves to the shard owning its
    bucket under the TARGET bucket count. `hash_lanes` carry the BUCKET
    identity (the build's value-hash lanes — for string keys the
    gathered dictionary value hashes, NOT the rank lanes used for
    matching) and are consumed for routing only, never routed. Runs as
    a shard_map stage inside the caller's jitted program — payload
    never routes, nothing touches the host.

    Topology-aware: on a flat mesh the route is ONE all_to_all over
    ICI; on a 2-axis (dcn, shard) mesh it is TWO axis-confined hops —
    stage 1 over ICI to the owner's position within the source slice,
    stage 2 over DCN to the owner slice, carrying the owner id along
    (the build exchange's `_shard_step` discipline: each hop changes
    exactly one mesh coordinate, and the heavy fan-out stays on the
    fast axis). Returns ([S*C'] lanes..., null, valid, gid,
    route_overflow); C' comes from `_route_slabs`."""
    import jax.numpy as jnp

    n_shards = total_shards(mesh)
    n_dcn = dcn_size(mesh)
    n_ici = ici_size(mesh)
    _rows, cap_ici, cap_dcn = _route_slabs(mesh, route_capacity)
    rows_spec = row_spec(mesh)
    k = len(lanes)
    kh = len(hash_lanes)

    def body(*flat):
        lanes_l = list(flat[:k])
        hlanes_l = list(flat[k:k + kh])
        null_l, valid_l, gid_l = flat[-3], flat[-2], flat[-1]
        from hyperspace_tpu.ops.hash_partition import flat_hash32
        zeroed = [jnp.where(null_l | ~valid_l, jnp.uint32(0),
                            lane.astype(jnp.uint32))
                  for lane in hlanes_l]
        h = flat_hash32(zeroed)
        bucket = (h % jnp.uint32(num_buckets_to)).astype(jnp.int64)
        owner = bucket_owner(bucket, num_buckets_to,
                             n_shards).astype(jnp.int32)
        if n_dcn == 1:
            dest = jnp.where(valid_l, owner, jnp.int32(n_shards))
            routed, overflow = _route_local(
                lanes_l + [null_l, valid_l, gid_l], dest, n_shards,
                cap_ici)
            return tuple(routed) + (overflow.reshape(1),)
        # Stage 1 (ICI): to the owner's position within THIS slice,
        # owner id riding along for stage 2.
        dest1 = jnp.where(valid_l, owner % n_ici, jnp.int32(n_ici))
        routed1, ovf1 = _route_local(
            lanes_l + [null_l, valid_l, gid_l, owner], dest1, n_ici,
            cap_ici, axis=SHARD_AXIS)
        valid1 = routed1[k + 1]
        owner1 = routed1[-1]
        # Stage 2 (DCN): to the owner slice; empty stage-1 slots carry
        # valid=False (zero-init buffers) and drop here.
        dest2 = jnp.where(valid1, owner1 // n_ici, jnp.int32(n_dcn))
        routed, ovf2 = _route_local(routed1[:-1], dest2, n_dcn,
                                    cap_dcn, axis=DCN_AXIS)
        return tuple(routed) + ((ovf1 + ovf2).reshape(1),)

    flat_in = tuple(lanes) + tuple(hash_lanes) + (null, valid, gid)
    out = compat_shard_map(
        body, mesh=mesh,
        in_specs=tuple(rows_spec for _ in flat_in),
        out_specs=tuple([rows_spec] * (k + 4)),
        check_vma=False)(*flat_in)
    routed = out[:-1]
    overflow = jnp.sum(out[-1])
    return (list(routed[:k]), routed[k], routed[k + 1], routed[k + 2],
            overflow)


def _match_expand(l_lanes2d, r_lanes2d, l_null, r_null, l_pad, r_pad,
                  r_gid, cap: int, left_outer: bool, need_right: bool):
    """The counting match + static-capacity expansion over the combined
    [S, T] layout (T = Cl + Cr). Per shard: ONE stable sort by
    (pad, null, *lanes, side, slot), run grouping from adjacent lane
    differences, right-run brackets by cumulative counting, then the
    expansion into the [S, cap] output slots — all traced into the ONE
    enclosing jit, no host sizing sync between match and expansion.

    `r_gid` maps a right slot to its ORIGINAL global row id (identity
    for co-bucketed sides; the routed ids after an in-program
    repartition). Returns (li, ri, out_valid [S, cap], shard_total [S],
    expand_overflow, right_unmatched_gid [S, T] | None, matchable,
    rights, pos_s)."""
    import jax
    import jax.numpy as jnp

    S, Cl = l_pad.shape
    Cr = r_pad.shape[1]
    T = Cl + Cr
    lanes2d = [jnp.concatenate([ll, rl], axis=1)
               for ll, rl in zip(l_lanes2d, r_lanes2d)]
    pad = jnp.concatenate([l_pad, r_pad], axis=1).astype(jnp.int32)
    null = jnp.concatenate([l_null, r_null], axis=1).astype(jnp.int32)
    side = jnp.broadcast_to(
        jnp.concatenate([jnp.zeros(Cl, jnp.int32),
                         jnp.ones(Cr, jnp.int32)]), (S, T))
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (S, T))
    results = jax.lax.sort([pad, null, *lanes2d, side, pos],
                           num_keys=3 + len(lanes2d), is_stable=True,
                           dimension=1)
    pad_s, null_s = results[0], results[1]
    lanes_s = results[2:-2]
    side_s = results[-2]
    pos_s = results[-1]

    first = jnp.ones((S, 1), dtype=bool)
    rest = jnp.zeros((S, T - 1), dtype=bool)
    for k in lanes_s:
        rest = rest | (k[:, 1:] != k[:, :-1])
    rest = rest | (null_s[:, 1:] | null_s[:, :-1]
                   | pad_s[:, 1:] | pad_s[:, :-1]).astype(bool)
    run_start = jnp.concatenate([first, rest], axis=1)

    posT = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (S, T))
    run_first = jax.lax.cummax(jnp.where(run_start, posT, 0), axis=1)
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(run_start, posT, jnp.int32(T)), axis=1), axis=1),
        axis=1)
    run_last = jnp.concatenate(
        [nxt[:, 1:], jnp.full((S, 1), T, jnp.int32)], axis=1) - 1

    R = jnp.cumsum(side_s, axis=1)
    take = jnp.take_along_axis
    rights = (take(R, run_last, axis=1) - take(R, run_first, axis=1)
              + take(side_s, run_first, axis=1))
    rstart = run_last - rights + 1

    is_left = (side_s == 0) & (pad_s == 0)
    matchable = is_left & (null_s == 0)
    counts = jnp.where(matchable, rights, 0)
    if left_outer:
        counts = jnp.maximum(counts, is_left.astype(counts.dtype))
    counts64 = counts.astype(jnp.int64)
    starts = jnp.cumsum(counts64, axis=1) - counts64  # per-shard excl.
    shard_total = starts[:, -1] + counts64[:, -1]
    expand_overflow = jnp.maximum(jnp.max(shard_total) - cap, 0)

    # Static-capacity expansion: output slot j of shard s belongs to the
    # left element whose [starts, starts+counts) window covers j.
    slots = jnp.arange(cap, dtype=jnp.int64)
    row = jax.vmap(lambda st: jnp.searchsorted(st, slots,
                                               side="right"))(starts) - 1
    row = jnp.clip(row, 0, T - 1).astype(jnp.int32)
    offset = (slots[None, :] - take(starts, row, axis=1)).astype(jnp.int32)
    l_slot = take(pos_s, row, axis=1)
    li = l_slot.astype(jnp.int64) \
        + (jnp.arange(S, dtype=jnp.int64) * Cl)[:, None]
    matched = offset < take(rights, row, axis=1)
    r_sorted = jnp.clip(take(rstart, row, axis=1) + offset, 0, T - 1)
    r_slot = take(pos_s, r_sorted, axis=1) - Cl
    ri = jnp.where(matched,
                   take(r_gid, jnp.clip(r_slot, 0, Cr - 1), axis=1),
                   jnp.int64(-1))
    out_valid = slots[None, :] < jnp.minimum(shard_total, cap)[:, None]

    un_gid_sorted = un_counts = None
    if need_right:
        run_len = run_last - run_first + 1
        lefts = run_len - rights
        r_unmatched = ((side_s == 1) & (pad_s == 0)
                       & ((null_s == 1) | (lefts == 0)))
        gid_sorted = take(r_gid,
                          jnp.clip(pos_s - Cl, 0, Cr - 1), axis=1)
        un_gid = jnp.where(r_unmatched, gid_sorted, jnp.int64(-1))
        # Per-shard compaction IN-PROGRAM (unmatched gids first): the
        # host then assembles the output from contiguous prefixes with
        # one gather — no data-dependent-shaped eager op ever touches
        # the sharded arrays (each such op would recompile per size).
        un_sorted = jax.lax.sort(
            [(un_gid < 0).astype(jnp.int32), un_gid],
            num_keys=1, is_stable=True, dimension=1)
        un_gid_sorted = un_sorted[1]
        un_counts = jnp.sum(un_gid >= 0, axis=1)
    return (li, ri, out_valid, shard_total, expand_overflow,
            un_gid_sorted, un_counts, is_left, matchable, rights, pos_s)


# Per-device dispatch serialization on EMULATED meshes: the CPU
# backend drives every virtual device from one shared runtime, and two
# concurrent multi-device programs whose device sets OVERLAP can
# interleave their per-device tasks into a collective-rendezvous
# inversion (A's device-0 step waits on A's device-1 step queued behind
# B's device-1 step waiting on B's device-0 — a deadlock real hardware
# cannot hit because each device's queue serializes executions). One
# lock per DEVICE, acquired in sorted device-id order, is exactly the
# device-queue semantic: programs on disjoint replica slices still run
# concurrently — which is the whole scale-out story — while any two
# dispatches sharing a device serialize (including a full-mesh program
# — a build, repartition, or the replica-exempt batched lane — against
# a replica-pinned slice program: their sets overlap without being
# equal, so a per-SET lock would not order them). Sorted-order
# acquisition makes the multi-lock hold cycle-free. Real (non-CPU)
# backends skip the lock: their device queues already provide it, and
# host-side pipelining across queries must not be lost.
_DEVICE_LOCKS: Dict[int, object] = {}
_DEVICE_LOCKS_GUARD = None


def dispatch_guard(mesh):
    """THE per-device dispatch lock set (reentrant; see comment above).
    Callers driving multi-device work OUTSIDE this module's entry
    points (`assemble_join_output` gathers, result materialization of a
    concurrent serving loop) hold it around the whole query's device
    section; on non-CPU backends it is a no-op."""
    import contextlib
    import threading

    import jax

    if jax.default_backend() != "cpu":
        return contextlib.nullcontext()
    global _DEVICE_LOCKS_GUARD
    if _DEVICE_LOCKS_GUARD is None:
        _DEVICE_LOCKS_GUARD = threading.Lock()
    tag = mesh_device_tag(mesh)
    with _DEVICE_LOCKS_GUARD:
        locks = []
        for did in sorted(set(tag)):
            lock = _DEVICE_LOCKS.get(did)
            if lock is None:
                lock = threading.RLock()
                _DEVICE_LOCKS[did] = lock
            locks.append(lock)

    @contextlib.contextmanager
    def hold():
        with contextlib.ExitStack() as stack:
            for lock in locks:
                stack.enter_context(lock)
            yield

    return hold()


_dispatch_guard = dispatch_guard


# Program cache: jax.Mesh hashes by value (devices + axis names), so the
# per-query `distribution_mesh()` reconstruction still HITS here — a warm
# repeat join re-dispatches the already-compiled program instead of
# retracing (the retrace counters in `instrumented_jit` pin this).
_PROGRAMS: Dict[tuple, object] = {}


def _cached_program(key: tuple, builder):
    prog = _PROGRAMS.get(key)
    if prog is None:
        prog = builder()
        if len(_PROGRAMS) > 256:  # runaway-shape backstop
            _PROGRAMS.clear()
        _PROGRAMS[key] = prog
    return prog


def _join_program(mesh, n_keys: int, Cl: int, Cr: int, cap: int,
                  left_outer: bool, need_right: bool,
                  repartition_to: Optional[int], route_capacity: int,
                  membership: Optional[str] = None,
                  remap_idx: Tuple[int, ...] = ()):
    """Compile THE join as one jitted SPMD program: (optional) in-program
    ICI repartition of the right side, lane decomposition, counting
    match, static-capacity expansion, per-shard output compaction. All
    shape parameters are static; the only host readback after dispatch
    is the small per-shard count vector + overflow scalars, fetched in
    ONE sync — every device-side output the host then gathers is a
    contiguous per-shard prefix, so no data-dependent shape ever forces
    an eager recompile on the sharded arrays.

    `membership`: None (pair expansion) or "semi"/"anti" — membership
    reads the match-phase masks and compacts hit LEFT indices per shard
    in-program instead of expanding pairs.

    `remap_idx` marks the STRING key positions: those keys arrive as
    int32 code lanes plus per-side rank-remap tables
    (`string_remap_tables`), applied as in-program takes so equal
    values compare equal across the two dictionaries — the tables are
    the only cross-side state, replicated over ICI by GSPMD; string
    bytes never enter the program. When the right side repartitions,
    its string keys route by their gathered dictionary VALUE hashes
    (the build's bucket identity), not the rank lanes."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.telemetry import instrumented_jit

    S = total_shards(mesh)

    def build():
        def step(l_datas, l_ok, l_valid, r_datas, r_ok, r_valid,
                 l_remaps, r_remaps, r_hash_tables):
            l_d = list(l_datas)
            r_d = list(r_datas)
            r_hash_sub = {}
            for j, ki in enumerate(remap_idx):
                if repartition_to is not None:
                    hi, lo = r_hash_tables[j]
                    r_hash_sub[ki] = [jnp.take(hi, r_d[ki]),
                                      jnp.take(lo, r_d[ki])]
                l_d[ki] = jnp.take(l_remaps[j], l_d[ki])
                r_d[ki] = jnp.take(r_remaps[j], r_d[ki])
            l_d, r_d = _promote_pairs(l_d, r_d)
            l_lanes = [x.reshape(S, Cl) for x in _side_lane_chain(l_d)]
            l_pad = ~l_valid.reshape(S, Cl)
            l_null = (jnp.zeros((S, Cl), bool) if l_ok is None
                      else (~l_ok.reshape(S, Cl)) & ~l_pad)

            r_lanes = []
            r_hash_lanes = []
            for ki, d in enumerate(r_d):
                match_lanes = keymod.key_lanes(d)
                r_lanes.extend(match_lanes)
                r_hash_lanes.extend(r_hash_sub.get(ki, match_lanes))
            r_null_f = (jnp.zeros(r_valid.shape[0], bool) if r_ok is None
                        else ~r_ok)
            r_gid_f = jnp.arange(r_valid.shape[0], dtype=jnp.int64)
            route_ovf = jnp.int64(0)
            if repartition_to is not None:
                r_lanes, r_null_f, r_valid_f, r_gid_f, route_ovf = \
                    _repartition_lanes(r_lanes, r_hash_lanes, r_null_f,
                                       r_valid, r_gid_f, repartition_to,
                                       mesh, route_capacity)
                Cr_eff = _route_slabs(mesh, route_capacity)[0]
            else:
                r_valid_f = r_valid
                Cr_eff = Cr
            r_lanes2d = [x.reshape(S, Cr_eff) for x in r_lanes]
            r_pad = ~r_valid_f.reshape(S, Cr_eff)
            r_null2d = r_null_f.reshape(S, Cr_eff) & ~r_pad
            r_gid2d = r_gid_f.reshape(S, Cr_eff)

            (li, ri, _out_valid, shard_total, expand_ovf, un_gid,
             un_counts, is_left, matchable, rights, pos_s) = \
                _match_expand(l_lanes, r_lanes2d, l_null, r_null2d,
                              l_pad, r_pad, r_gid2d, cap, left_outer,
                              need_right)
            if membership is not None:
                # Semi/anti over the match masks: per-shard in-program
                # compaction (hits first), host gathers the prefixes.
                hit = (is_left & (rights == 0) if membership == "anti"
                       else matchable & (rights > 0))
                li2d = (jnp.clip(pos_s, 0, Cl - 1).astype(jnp.int64)
                        + (jnp.arange(S, dtype=jnp.int64) * Cl)[:, None])
                hit_sorted = jax.lax.sort(
                    [(~hit).astype(jnp.int32), li2d], num_keys=1,
                    is_stable=True, dimension=1)
                hit_counts = jnp.sum(hit, axis=1)
                return hit_sorted[1], hit_counts, route_ovf
            counts = jnp.minimum(shard_total, cap)
            if un_counts is None:
                un_gid = jnp.zeros((S, 1), dtype=jnp.int64)
                un_counts = jnp.zeros(S, dtype=jnp.int64)
            return (li, ri, counts, un_gid, un_counts, expand_ovf,
                    route_ovf)

        return instrumented_jit("mesh.spmd_join", step)

    key = ("join", mesh, n_keys, Cl, Cr, cap, left_outer, need_right,
           repartition_to, route_capacity, membership, remap_idx)
    return _cached_program(key, build)


def _prefix_index(counts, width: int) -> np.ndarray:
    """Flat gather index over per-shard contiguous prefixes: shard s
    contributes rows [s*width, s*width + counts[s])."""
    counts = np.asarray(counts, dtype=np.int64)
    return np.concatenate(
        [s * width + np.arange(int(c)) for s, c in enumerate(counts)]
    ) if counts.sum() else np.zeros(0, dtype=np.int64)


_prefix_gather_jit = None
_prefix_gather_i32_jit = None


def _gather_prefixes(arrays, counts, width: int, as_int32: bool = False):
    """ONE fused device gather of the per-shard prefixes (the output
    sides stay device-resident; only the [S] count vector came to the
    host). The flatten, the take, and — with `as_int32` — the output
    cast all trace into a SINGLE jitted dispatch: on the warm serving
    path every eager primitive here was a measurable per-query python
    dispatch (reshape x2 + take + astype x2 ~ a third of a tiny warm
    join's wall), and fusing them lifts the concurrent-QPS ceiling of
    small replica-routed queries."""
    global _prefix_gather_jit, _prefix_gather_i32_jit
    import jax.numpy as jnp

    idx = _prefix_index(counts, width)
    if not len(idx):
        dt = jnp.int32 if as_int32 else None
        return tuple(jnp.zeros(0, dtype=dt or a.dtype) for a in arrays)
    if _prefix_gather_jit is None:
        from hyperspace_tpu.telemetry import instrumented_jit

        @instrumented_jit("mesh.spmd_gather")
        def _take_flat(arrs, ix):
            return tuple(jnp.take(a.reshape(-1), ix) for a in arrs)

        @instrumented_jit("mesh.spmd_gather_i32")
        def _take_flat_i32(arrs, ix):
            return tuple(jnp.take(a.reshape(-1), ix).astype(jnp.int32)
                         for a in arrs)

        _prefix_gather_jit = _take_flat
        _prefix_gather_i32_jit = _take_flat_i32
    fn = _prefix_gather_i32_jit if as_int32 else _prefix_gather_jit
    return fn(tuple(arrays), idx)


# Working-capacity memo: a warm repeat of the same join shape starts at
# the capacity that last succeeded instead of re-discovering it through
# the overflow-retry ladder (each failed attempt is a full dispatch).
_CAP_MEMO: Dict[tuple, int] = {}


def _join_capacity(left: ShardedBatch, right: ShardedBatch,
                   left_outer: bool, factor: float,
                   memo_key: Optional[tuple] = None) -> int:
    """First-attempt static per-shard output capacity. When both sides'
    per-bucket histograms are known, the EXACT per-shard upper bound
    (sum of l_b*r_b [+ l_b for outer] over the shard's bucket range)
    clips the heuristic — an expansion at the bound can never overflow,
    so the doubling retry loop terminates — and a bound within 4x of
    the heuristic is taken OUTRIGHT (one guaranteed-fit dispatch beats
    a maybe-retry at modest extra slots)."""
    if memo_key is not None and memo_key in _CAP_MEMO:
        return _CAP_MEMO[memo_key]
    heur = max(16, int(factor * (left.rows_per_shard
                                 + right.rows_per_shard)))
    if left.lengths is None or right.lengths is None \
            or len(left.lengths) != len(right.lengths):
        return heur
    ll = left.lengths.astype(np.int64)
    rl = right.lengths.astype(np.int64)
    per_bucket = ll * rl + (ll if left_outer else 0)
    bound = max(int(per_bucket[lo:hi].sum())
                for lo, hi in bucket_ranges(len(ll), left.n_shards))
    bound = max(bound, 1)
    if bound <= 4 * heur:
        return max(16, bound)
    return max(16, min(heur, bound))


def _route_cap(right: ShardedBatch) -> int:
    """First-attempt per-peer slab capacity for the in-program
    repartition (the build's `_stage_capacity` sizing)."""
    S = right.n_shards
    return max(16, int(right.rows_per_shard / S * CAPACITY_FACTOR))


def _join_inputs(sh: ShardedBatch, keys: Sequence[str]):
    datas, ok = _key_arrays(sh.batch, keys)
    return tuple(datas), ok, sh.row_valid


def _shard_rows_attribution(left: ShardedBatch, right: ShardedBatch):
    """Per-shard TRUE input rows (the load-balance attribution the mesh
    telemetry reports, legacy-event parity): from the bucket histograms
    when known, else the padded per-shard capacities."""
    S = left.n_shards
    out = []
    for sh in (left, right):
        if sh.lengths is not None:
            segs = shard_row_segments(sh.lengths, S)
            out.append([e - s for s, e in segs])
        else:
            out.append([sh.rows_per_shard] * S)
    return [l + r for l, r in zip(*out)]


def _check_one_mesh(left: ShardedBatch, right: ShardedBatch):
    if left.mesh is not right.mesh and \
            mesh_device_list(left.mesh) != mesh_device_list(right.mesh):
        raise HyperspaceException("sharded join requires one mesh")


def _repartition_target(left: ShardedBatch, right: ShardedBatch):
    """(target bucket count, first-attempt route capacity) when the
    right side must re-bucket in-program; (None, 16) for co-bucketed
    sides. Works on flat AND 2-axis meshes — `_repartition_lanes`
    routes hierarchically (ICI within the slice, one DCN hop across)
    on the latter."""
    if right.num_buckets == left.num_buckets:
        return None, 16
    return left.num_buckets, _route_cap(right)


def sharded_join_indices(left: ShardedBatch, right: ShardedBatch,
                         left_keys: Sequence[str],
                         right_keys: Sequence[str],
                         how: str = "inner",
                         capacity_factor: Optional[float] = None,
                         conf=None):
    """Join-pair indices over two born-sharded sides as ONE jitted SPMD
    program per attempt (static capacity, on-device overflow detection,
    in-program ICI repartition on bucket-count mismatch). Returns
    (li, ri) device int32 arrays indexing the FLAT padded row spaces of
    the two sides. `how`: inner / left_outer / full_outer (callers swap
    sides for right_outer)."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu import telemetry

    if how not in ("inner", "left_outer", "full_outer"):
        raise HyperspaceException(
            f"sharded join supports inner/left_outer/full_outer; "
            f"got {how}.")
    if left.split_plan is not None and how == "full_outer":
        # Replicated right rows break per-shard unmatched-right
        # uniqueness; callers route full_outer off the sub-shard lane.
        raise HyperspaceException(
            "virtual sub-shard joins support inner/left_outer only.")
    _check_one_mesh(left, right)
    mesh = left.mesh
    S = total_shards(mesh)
    left_outer = how in ("left_outer", "full_outer")
    need_right = how == "full_outer"
    repartition_to, route_capacity = _repartition_target(left, right)
    l_in = _join_inputs(left, left_keys)
    r_in = _join_inputs(right, right_keys)
    remap_idx, l_remaps, r_remaps, r_hashes = _string_key_plan(
        left, right, left_keys, right_keys,
        need_hashes=repartition_to is not None, conf=conf)
    factor = (capacity_factor if capacity_factor is not None
              else CAPACITY_FACTOR)
    memo_key = ("cap", mesh, left.rows_per_shard, right.rows_per_shard,
                tuple(left_keys), tuple(right_keys), how)
    cap = _join_capacity(left, right, left_outer, factor,
                         memo_key=memo_key)

    reg = telemetry.get_registry()
    tracer = telemetry.tracer()
    span_ts = tracer.now_us() if tracer is not None else 0.0
    with _dispatch_guard(mesh):
        while True:
            program = _join_program(mesh, len(left_keys),
                                    left.rows_per_shard,
                                    right.rows_per_shard, cap, left_outer,
                                    need_right, repartition_to,
                                    route_capacity, remap_idx=remap_idx)
            if repartition_to is not None:
                # Slab-volume attribution of this attempt's in-program
                # exchange, split by the link that carries each hop.
                _record_repartition_bytes(
                    mesh, route_capacity, 8 * len(right_keys) + 10)
            with telemetry.span("mesh:join:spmd", "mesh", how=how,
                                shards=S, cap=cap):
                (li, ri, counts_d, un_gid, un_counts_d, expand_ovf,
                 route_ovf) = program(*l_in, *r_in, l_remaps, r_remaps,
                                      r_hashes)
                t0 = _time.perf_counter()
                # THE one host readback per attempt: the tiny per-shard
                # count vectors + overflow scalars together, after
                # everything (match AND expansion AND compaction) has
                # dispatched — not a sizing sync in the middle.
                counts, un_counts, e_ovf, r_ovf = jax.device_get(
                    (counts_d, un_counts_d, expand_ovf, route_ovf))
                sync_s = _time.perf_counter() - t0
            reg.counter("mesh.join.sync_s").inc(sync_s)
            telemetry.add_seconds("mesh.sync_s", sync_s)
            if int(e_ovf) == 0 and int(r_ovf) == 0:
                if len(_CAP_MEMO) > 256:
                    _CAP_MEMO.clear()
                _CAP_MEMO[memo_key] = cap
                break
            reg.counter("mesh.spmd.overflow_retries").inc()
            if int(e_ovf):
                cap *= 2
            if int(r_ovf):
                route_capacity *= 2

        total = int(np.asarray(counts).sum())
        extra = int(np.asarray(un_counts).sum()) if need_right else 0
        reg.counter("mesh.join.execs").inc()
        reg.counter("mesh.spmd.join_execs").inc()
        shard_rows_attr = _shard_rows_attribution(left, right)
        reg.histogram("mesh.join.shard_rows").observe_many(
            shard_rows_attr)
        telemetry.event("mesh", "join", how=how, shards=S, pairs=total,
                        lane="spmd", shard_rows=shard_rows_attr)
        if tracer is not None:
            tracer.device_spans("join", span_ts,
                                [int(c) for c in np.asarray(counts)],
                                how=how)
        if total == 0:
            li_f = jnp.zeros(0, dtype=jnp.int64)
            ri_f = jnp.zeros(0, dtype=jnp.int64)
        elif not extra:
            # The valid pairs are contiguous per-shard prefixes by
            # construction: ONE fused gather (incl. the int32 output
            # cast) materializes both sides in a single dispatch.
            return _gather_prefixes((li, ri), counts, cap,
                                    as_int32=True)
        else:
            li_f, ri_f = _gather_prefixes((li, ri), counts, cap)
        if extra:
            (ugid,) = _gather_prefixes((un_gid,), un_counts,
                                       un_gid.shape[1])
            li_f = jnp.concatenate([li_f, jnp.full(extra, -1,
                                                   dtype=jnp.int64)])
            ri_f = jnp.concatenate([ri_f, ugid])
        return li_f.astype(jnp.int32), ri_f.astype(jnp.int32)


def sharded_semi_anti_indices(left: ShardedBatch, right: ShardedBatch,
                              left_keys: Sequence[str],
                              right_keys: Sequence[str],
                              anti: bool = False, conf=None):
    """LEFT SEMI / LEFT ANTI membership over born-sharded sides through
    the same single program (anti emits null-key left rows — NOT EXISTS
    semantics). Membership reads the match-phase masks; the expansion's
    capacity is irrelevant, so only a repartition-route overflow can
    force a retry. Returns indices into the left flat padded space."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu import telemetry

    _check_one_mesh(left, right)
    mesh = left.mesh
    S = total_shards(mesh)
    repartition_to, route_capacity = _repartition_target(left, right)
    remap_idx, l_remaps, r_remaps, r_hashes = _string_key_plan(
        left, right, left_keys, right_keys,
        need_hashes=repartition_to is not None, conf=conf)

    reg = telemetry.get_registry()
    with _dispatch_guard(mesh):
        while True:
            program = _join_program(mesh, len(left_keys),
                                    left.rows_per_shard,
                                    right.rows_per_shard, 16,
                                    left_outer=True, need_right=False,
                                    repartition_to=repartition_to,
                                    route_capacity=route_capacity,
                                    membership="anti" if anti else "semi",
                                    remap_idx=remap_idx)
            if repartition_to is not None:
                _record_repartition_bytes(
                    mesh, route_capacity, 8 * len(right_keys) + 10)
            li_sorted, hit_counts_d, route_ovf = program(
                *_join_inputs(left, left_keys),
                *_join_inputs(right, right_keys),
                l_remaps, r_remaps, r_hashes)
            hit_counts, r_ovf = jax.device_get((hit_counts_d, route_ovf))
            if repartition_to is None or int(r_ovf) == 0:
                break
            reg.counter("mesh.spmd.overflow_retries").inc()
            route_capacity *= 2

        total = int(np.asarray(hit_counts).sum())
        shard_rows_attr = _shard_rows_attribution(left, right)
        reg.histogram("mesh.join.shard_rows").observe_many(
            shard_rows_attr)
        telemetry.event("mesh", "join", how=("anti" if anti else "semi"),
                        shards=S, lane="spmd",
                        shard_rows=shard_rows_attr)
        reg.counter("mesh.join.execs").inc()
        reg.counter("mesh.spmd.join_execs").inc()
        if total == 0:
            return jnp.zeros(0, dtype=jnp.int32)
        (li,) = _gather_prefixes((li_sorted,), hit_counts,
                                 li_sorted.shape[1], as_int32=True)
        return li


# ---------------------------------------------------------------------------
# Stage-to-stage: repartition, filter, aggregate over the sharded layout
# ---------------------------------------------------------------------------


def repartition_sharded(batch: ColumnBatch, key_columns: Sequence[str],
                        num_buckets: int, mesh,
                        capacity_factor: float = CAPACITY_FACTOR
                        ) -> ShardedBatch:
    """Re-bucket a DEVICE-resident batch (e.g. a join output feeding the
    next join) into a born-sharded layout: hash, contiguous-range
    owner, then the topology-aware exchange — ONE all_to_all over ICI
    on a flat mesh, or the two axis-confined hops (ICI within the
    slice, one DCN hop across) on a 2-axis mesh — all inside one jitted
    program, with the routed per-shard layout RETURNED AS-IS (padded +
    valid mask, no global compaction), so no per-bucket histogram and
    no row data ever touch the host between stages. Only the overflow
    scalar syncs. Exchange volume lands in
    `spmd.repartition.{ici,dcn}.bytes` per dispatch."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.io import transfer
    from hyperspace_tpu.io.columnar import batch_to_tree, tree_to_batch
    from hyperspace_tpu.telemetry import instrumented_jit

    n_shards = total_shards(mesh)
    n_dcn = dcn_size(mesh)
    n_ici = ici_size(mesh)
    n = batch.num_rows
    local = -(-n // n_shards)
    padded = local * n_shards
    tree, aux = batch_to_tree(batch)

    def pad(a):
        return jnp.pad(jnp.asarray(a),
                       [(0, padded - n)] + [(0, 0)] * (a.ndim - 1))

    in_tree: dict = {}
    for name, entry in tree.items():
        out = dict(entry)
        out["data"] = pad(entry["data"])
        if "validity" in entry:
            out["validity"] = pad(entry["validity"])
        if "hash_hi" in entry:
            out["hash_hi"] = jnp.tile(jnp.asarray(entry["hash_hi"]),
                                      n_shards)
            out["hash_lo"] = jnp.tile(jnp.asarray(entry["hash_lo"]),
                                      n_shards)
        in_tree[name] = out
    in_tree["__valid__"] = {"data": jnp.concatenate(
        [jnp.ones(n, bool), jnp.zeros(padded - n, bool)])}
    sharding = shard_rows(mesh)
    engine = transfer.get_engine()
    in_tree = jax.tree_util.tree_map(
        lambda a: engine.put(a, device=sharding), in_tree)

    key_names = tuple(batch.schema.field(c).name for c in key_columns)
    reg = telemetry.get_registry()
    factor = capacity_factor
    while True:
        capacity = max(16, int(local / n_shards * factor))
        _rows_out, cap_ici, cap_dcn = _route_slabs(mesh, capacity)
        rows_spec = row_spec(mesh)

        def make_step(capacity=capacity, cap_ici=cap_ici,
                      cap_dcn=cap_dcn):
            def step(t):
                def body(tt):
                    from hyperspace_tpu.ops.build import _tree_hash_lanes
                    from hyperspace_tpu.ops.hash_partition import \
                        flat_hash32

                    valid_l = tt["__valid__"]["data"]
                    lanes = []
                    for nm in key_names:
                        lanes.extend(_tree_hash_lanes(tt[nm]))
                    h = flat_hash32(lanes)
                    bucket = (h % jnp.uint32(num_buckets)) \
                        .astype(jnp.int64)
                    owner = bucket_owner(bucket, num_buckets,
                                         n_shards).astype(jnp.int32)
                    # Route data/validity leaves; dictionary hash tables
                    # stay shard-local (replicated), like the build.
                    to_route = []
                    spec = []
                    for nm, entry in tt.items():
                        if nm == "__valid__":
                            continue
                        spec.append((nm, "data"))
                        to_route.append(entry["data"])
                        if "validity" in entry:
                            spec.append((nm, "validity"))
                            to_route.append(entry["validity"])
                    if n_dcn == 1:
                        dest = jnp.where(valid_l, owner,
                                         jnp.int32(n_shards))
                        routed, overflow = _route_local(
                            to_route + [valid_l], dest, n_shards,
                            capacity)
                    else:
                        # Two axis-confined hops (build discipline):
                        # ICI to the owner's slice position, DCN to the
                        # owner slice, owner id riding along.
                        dest1 = jnp.where(valid_l, owner % n_ici,
                                          jnp.int32(n_ici))
                        routed1, ovf1 = _route_local(
                            to_route + [valid_l, owner], dest1, n_ici,
                            cap_ici, axis=SHARD_AXIS)
                        valid1 = routed1[-2]
                        owner1 = routed1[-1]
                        dest2 = jnp.where(valid1, owner1 // n_ici,
                                          jnp.int32(n_dcn))
                        routed, ovf2 = _route_local(
                            routed1[:-1], dest2, n_dcn, cap_dcn,
                            axis=DCN_AXIS)
                        overflow = ovf1 + ovf2
                    out_t = {nm: dict(entry) for nm, entry in tt.items()
                             if nm != "__valid__"}
                    for (nm, part), arr in zip(spec, routed[:-1]):
                        out_t[nm][part] = arr
                    out_t["__valid__"] = {"data": routed[-1]}
                    out_t["__overflow__"] = {
                        "data": overflow.reshape(1)}
                    return out_t

                return compat_shard_map(
                    body, mesh=mesh,
                    in_specs=(jax.tree_util.tree_map(
                        lambda _: rows_spec, t),),
                    out_specs=rows_spec, check_vma=False)(t)

            return step

        program = _cached_program(
            ("repartition", mesh, key_names, num_buckets, capacity),
            lambda: instrumented_jit("mesh.spmd_repartition",
                                     make_step()))
        per_row = sum(
            int(np.dtype(getattr(e["data"], "dtype", np.int64)).itemsize)
            + (1 if "validity" in e else 0)
            for nm, e in in_tree.items() if nm != "__valid__") + 1
        _record_repartition_bytes(mesh, capacity, per_row)
        with _dispatch_guard(mesh):
            routed_tree = program(in_tree)
            overflow = int(jnp.sum(routed_tree["__overflow__"]["data"]))
        if overflow == 0:
            break
        reg.counter("mesh.spmd.overflow_retries").inc()
        factor *= 2

    C = _route_slabs(mesh, capacity)[0]
    row_valid = routed_tree["__valid__"]["data"]
    out_tree = {}
    for name, entry in routed_tree.items():
        if name.startswith("__"):
            continue
        cleaned = dict(entry)
        if "hash_hi" in cleaned:
            cleaned["hash_hi"] = tree[name]["hash_hi"]
            cleaned["hash_lo"] = tree[name]["hash_lo"]
        out_tree[name] = cleaned
    flat = tree_to_batch(out_tree, batch.schema, aux)
    telemetry.event("mesh", "repartition", shards=n_shards,
                    buckets=num_buckets, rows=n, lane="spmd")
    reg.counter("mesh.spmd.repartition_execs").inc()
    return ShardedBatch(flat, row_valid, mesh, C, num_buckets,
                        lengths=None)


def sharded_filter(sh: ShardedBatch, expression) -> ColumnBatch:
    """Predicate scan over the born-sharded layout as ONE jitted SPMD
    program: the compiled predicate traces together with the validity
    mask; each device evaluates its shard. Only the final compaction
    gather crosses shards. Result equals the single-chip `apply_filter`
    bit for bit."""
    import time as _time

    import jax.numpy as jnp

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.engine.compiler import compile_predicate
    from hyperspace_tpu.io.columnar import batch_to_tree, tree_to_batch
    from hyperspace_tpu.telemetry import instrumented_jit

    reg = telemetry.get_registry()
    count_string_predicate_lookups(expression, sh.batch)
    tree, aux = batch_to_tree(sh.batch)
    schema = sh.batch.schema

    def step(t, valid):
        b = tree_to_batch(t, schema, aux)
        return compile_predicate(expression, b) & valid

    with telemetry.span("mesh:filter", "mesh", rows=sh.num_rows,
                        shards=sh.n_shards), _dispatch_guard(sh.mesh):
        try:
            mask = instrumented_jit("mesh.spmd_filter", step)(
                tree, sh.row_valid)
        except HyperspaceException:
            raise
        except Exception:
            # A predicate shape the tracer cannot close over (host-only
            # op in a UDF, say) degrades to the eager SPMD evaluation —
            # same math, more dispatches.
            reg.counter("mesh.spmd.filter_eager_fallbacks").inc()
            mask = compile_predicate(expression, sh.batch) & sh.row_valid
        t0 = _time.perf_counter()
        count = int(jnp.sum(mask))  # the one sizing readback
        sync_s = _time.perf_counter() - t0
        reg.counter("mesh.filter.execs").inc()
        reg.counter("mesh.filter.sync_s").inc(sync_s)
        telemetry.add_seconds("mesh.sync_s", sync_s)
        telemetry.event("mesh", "filter", shards=sh.n_shards,
                        rows=sh.num_rows, selected=count, lane="spmd")
        (indices,) = jnp.nonzero(mask, size=count, fill_value=0)
        return sh.batch.take(indices)


def sharded_group_aggregate(sh: ShardedBatch,
                            group_columns: Sequence[str], aggregates,
                            out_schema) -> ColumnBatch:
    """Group-by aggregation straight over the born-sharded layout: the
    SPMD partial step consumes the resident [S*C] arrays + validity —
    no re-padding, no re-placement, no link traffic before the tiny
    [n_shards, G] partial tables cross for the host combine."""
    from hyperspace_tpu.parallel.aggregate import distributed_group_aggregate

    with _dispatch_guard(sh.mesh):
        return distributed_group_aggregate(
            sh.batch, group_columns, aggregates, out_schema, sh.mesh,
            pre_sharded=(sh.batch, sh.row_valid))


# ---------------------------------------------------------------------------
# Inter-query batched predicate lane (`engine/batcher.py` is the ONLY
# sanctioned caller — `scripts/check_metrics_coverage.py` enforces it)
# ---------------------------------------------------------------------------
#
# K concurrent point/filter queries over one shared scan differ only in
# their predicate CONSTANTS once they share an execution signature
# (`engine/batcher.py` groups them). This program evaluates all K
# predicates in ONE `instrumented_jit("serve.batch")` dispatch: the
# constants ride [K, T] lanes (K padded to a power-of-two bucket by the
# batcher, so cohort size is a compile bucket, not a retrace per K) and
# the result is a [K, N] boolean mask matrix the batcher slices
# per-query. Term semantics mirror `engine/compiler.py`'s definite-truth
# masks exactly for the supported shapes — numeric comparisons against
# literals (compared in the COLUMN's dtype, matching numpy's
# weak-scalar promotion on the solo path), integer IN lists, and
# IS [NOT] NULL — so a batched member's rows are bit-identical to its
# solo run.

# One shape term is a tuple:
#   ("cmp", op, col_index, lane)       lane: "i" (int64) | "f" (float64)
#   ("in", col_index, padded_len)      int lane, `padded_len` values
#   ("isnull"|"notnull", col_index)
_BATCH_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")


def _batched_predicate_program(shape: tuple, dtypes: tuple,
                               valid_flags: tuple):
    """Build (memoized) the jitted K-predicate program for one static
    term shape over columns of the given dtypes/validity presence."""
    import jax.numpy as jnp

    from hyperspace_tpu.telemetry import instrumented_jit

    def build():
        def body(datas, valids, iconst, fconst):
            ops = {"eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
                   "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
                   "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b}
            vmap = {}
            vi = 0
            for ci, flag in enumerate(valid_flags):
                if flag:
                    vmap[ci] = valids[vi]
                    vi += 1
            total = None
            ii = fi = 0
            for term in shape:
                kind = term[0]
                if kind == "cmp":
                    _k, op, ci, lane = term
                    data = jnp.asarray(datas[ci])
                    if lane == "f":
                        const = fconst[:, fi]
                        fi += 1
                        # Compare in the column's own float width (the
                        # solo path's numpy weak-scalar promotion); int
                        # columns against float literals promote to
                        # float64 on both paths.
                        if data.dtype.kind == "f":
                            const = const.astype(data.dtype)
                        else:
                            data = data.astype(jnp.float64)
                    else:
                        const = iconst[:, ii]
                        ii += 1
                        # Integer compares are exact at any width; lift
                        # the column to int64 so the [K] lane broadcasts
                        # without narrowing the literal.
                        if data.dtype.kind == "f":
                            const = const.astype(data.dtype)
                        else:
                            data = data.astype(jnp.int64)
                    m = ops[op](data[None, :], const[:, None])
                elif kind == "in":
                    _k, ci, padded = term
                    vals = iconst[:, ii:ii + padded]
                    ii += padded
                    data = jnp.asarray(datas[ci]).astype(jnp.int64)
                    m = jnp.any(data[None, :, None] == vals[:, None, :],
                                axis=-1)
                elif kind == "isnull":
                    _k, ci = term
                    v = vmap.get(ci)
                    n = jnp.asarray(datas[ci]).shape[0]
                    m = (jnp.zeros((1, n), bool) if v is None
                         else (~v)[None, :])
                else:  # notnull
                    _k, ci = term
                    v = vmap.get(ci)
                    n = jnp.asarray(datas[ci]).shape[0]
                    m = (jnp.ones((1, n), bool) if v is None
                         else v[None, :])
                if kind in ("cmp", "in"):
                    v = vmap.get(term[2] if kind == "cmp" else term[1])
                    if v is not None:
                        m = m & v[None, :]
                total = m if total is None else total & m
            # A constants-free shape (only null-ness terms) evaluates
            # as one [1, N] row — broadcast so every member slices its
            # own lane regardless.
            return jnp.broadcast_to(
                total, (iconst.shape[0],) + total.shape[1:])

        return instrumented_jit("serve.batch", body)

    return _cached_program(("serve.batch", shape, dtypes, valid_flags),
                           build)


def batched_predicate_masks(shape: tuple, datas: tuple, valids: tuple,
                            iconst, fconst):
    """THE batched-execution entry point: evaluate the K stacked
    predicates described by `shape` over the shared columns. `datas` is
    one array per referenced column (shape order indexes into it),
    `valids` the validity arrays of the columns that HAVE one (presence
    is static program structure), `iconst`/`fconst` the [K_bucket, T]
    padded constant lanes. Returns the [K_bucket, N] boolean mask
    matrix (a jax array; callers slice rows per member)."""
    valid_flags = tuple(v is not None for v in valids)
    dtypes = tuple(str(np.asarray(d).dtype) if isinstance(d, np.ndarray)
                   else str(d.dtype) for d in datas)
    prog = _batched_predicate_program(shape, dtypes, valid_flags)
    present = tuple(v for v in valids if v is not None)
    return prog(tuple(datas), present, iconst, fconst)
