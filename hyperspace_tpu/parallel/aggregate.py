"""Mesh-sharded group-by aggregation: partial aggregation per shard, one
small combine on the host.

The reference delegates aggregation to Spark's partial/final aggregate
pairs over the cluster; the TPU equivalent is SPMD partial aggregation
under `shard_map` — each chip sorts ITS rows by the group key lanes and
segment-reduces into a fixed-capacity [G] slot table (XLA needs static
shapes; ragged group counts are expressed as capacity + validity, with
exact overflow detection and a wider retry, like the build's all_to_all).
Only the [n_shards, G] partials cross to the host, where numpy merges
them by key — combinable forms: count/sum -> sum, min/max -> min/max,
avg -> (sum, count), stddev -> (count, sum, M2) merged by the exact
variance decomposition  M2_tot = sum M2_i + sum cnt_i (mean_i - anchor)^2
with the anchor at the global mean (per-shard deviations stay centered,
so no catastrophic cancellation).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.parallel.mesh import total_shards
from hyperspace_tpu.parallel.scan import shard_batch
from hyperspace_tpu.plan.nodes import AggSpec
from hyperspace_tpu.plan.schema import Schema


def _shard_partials(tree, num_lanes: int, specs_meta: Tuple[Tuple[str, bool],
                                                            ...],
                    capacity: int):
    """Per-shard body. `tree` carries: "lane<i>" group-key lanes,
    "valid" row mask, and per-spec "v<j>" value / "m<j>" value-validity
    arrays. Returns slot tables of size [G]."""
    import jax
    import jax.numpy as jnp

    lanes = [tree[f"lane{i}"] for i in range(num_lanes)]
    row_valid = tree["valid"]
    n = row_valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    # Invalid (padding) rows sort last via a leading validity key.
    sorted_ops = jax.lax.sort([~row_valid, *lanes, iota],
                              num_keys=1 + len(lanes), is_stable=True)
    perm = sorted_ops[-1]
    inv_sorted = sorted_ops[0]
    lanes_sorted = sorted_ops[1:-1]
    differs = jnp.zeros(n, dtype=jnp.int32)
    for k in (inv_sorted, *lanes_sorted):
        differs = differs | jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             (k[1:] != k[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(differs, dtype=jnp.int32)
    valid_sorted = jnp.take(row_valid, perm)
    num_groups = jnp.max(jnp.where(valid_sorted, seg, -1)) + 1
    overflow = jnp.maximum(num_groups - capacity, 0)
    slot = jnp.where(valid_sorted & (seg < capacity), seg, capacity)

    def seg_sum(x):
        return jax.ops.segment_sum(x, slot, num_segments=capacity + 1
                                   )[:capacity]

    out = {"overflow": overflow.reshape(1)}
    # Group identity: first sorted row of each local segment.
    firsts = jnp.searchsorted(seg, jnp.arange(capacity, dtype=jnp.int32),
                              side="left")
    firsts = jnp.clip(firsts, 0, n - 1)
    for i, lane in enumerate(lanes_sorted):
        out[f"key{i}"] = jnp.take(lane, firsts)
    out["rows"] = seg_sum(valid_sorted.astype(jnp.int64))
    out["first_perm"] = jnp.take(perm, firsts)

    for j, (func, _nullable) in enumerate(specs_meta):
        if func == "count_star":
            continue  # rows covers it
        v = jnp.take(tree[f"v{j}"], perm)
        m = jnp.take(tree[f"m{j}"], perm) & valid_sorted
        cnt = seg_sum(m.astype(jnp.int64))
        out[f"cnt{j}"] = cnt
        if func == "count":
            continue
        # Integer aggregates accumulate in int64 — float64 would silently
        # lose exactness past 2^53, diverging from the single-chip path.
        is_float = jnp.issubdtype(v.dtype, jnp.floating)
        acc_dtype = jnp.float64 if is_float else jnp.int64
        if func in ("sum", "avg"):
            out[f"s1{j}"] = seg_sum(jnp.where(m, v, 0).astype(acc_dtype))
        elif func == "min":
            sentinel = jnp.inf if is_float else jnp.iinfo(jnp.int64).max
            big = jnp.where(m, v.astype(acc_dtype), sentinel)
            out[f"mn{j}"] = jax.ops.segment_min(
                big, slot, num_segments=capacity + 1)[:capacity]
        elif func == "max":
            sentinel = -jnp.inf if is_float else jnp.iinfo(jnp.int64).min
            small = jnp.where(m, v.astype(acc_dtype), sentinel)
            out[f"mx{j}"] = jax.ops.segment_max(
                small, slot, num_segments=capacity + 1)[:capacity]
        elif func == "stddev":
            x = jnp.where(m, v, 0).astype(jnp.float64)
            s1 = seg_sum(x)
            mu = s1 / jnp.maximum(cnt.astype(jnp.float64), 1)
            dev = jnp.where(m, x - jnp.take(mu, jnp.clip(slot, 0, capacity - 1)),
                            0.0)
            out[f"s1{j}"] = s1
            out[f"m2{j}"] = seg_sum(dev * dev)
    return out


def make_partial_step(mesh, num_lanes: int, specs_meta, capacity: int):
    import jax

    from hyperspace_tpu.parallel.mesh import compat_shard_map, row_spec
    rows_spec = row_spec(mesh)

    def step(tree):
        body = partial(_shard_partials, num_lanes=num_lanes,
                       specs_meta=specs_meta, capacity=capacity)
        return compat_shard_map(
            body, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: rows_spec, tree),),
            out_specs=rows_spec, check_vma=False)(tree)

    from hyperspace_tpu.telemetry import instrumented_jit
    return instrumented_jit("mesh.aggregate_step", step)


def distributed_group_aggregate(batch: ColumnBatch,
                                group_columns: Sequence[str],
                                aggregates: Sequence[AggSpec],
                                out_schema: Schema, mesh,
                                pre_sharded=None) -> ColumnBatch:
    """SPMD partial aggregation over the mesh + host combine. Requires at
    least one group column (global aggregates are cheap single-chip).

    `pre_sharded` = (flat sharded batch, row_valid) skips the placement
    step entirely for BORN-SHARDED inputs (`parallel/spmd.py`): the
    partial program consumes the already-resident [S*C] layout, so a
    join -> aggregate pipeline stays device-resident stage to stage."""
    if not group_columns:
        raise HyperspaceException(
            "distributed aggregation requires group columns")
    from hyperspace_tpu import telemetry
    n_shards = total_shards(mesh)
    reg = telemetry.get_registry()
    reg.counter("mesh.aggregate.execs").inc()
    telemetry.event("mesh", "aggregate", shards=n_shards,
                    rows=batch.num_rows, groups=len(group_columns))
    with telemetry.span("mesh:aggregate", "mesh", rows=batch.num_rows,
                        shards=n_shards):
        return _distributed_group_aggregate(
            batch, group_columns, aggregates, out_schema, mesh, n_shards,
            reg, pre_sharded=pre_sharded)


def _distributed_group_aggregate(batch, group_columns, aggregates,
                                 out_schema, mesh, n_shards, reg,
                                 pre_sharded=None):
    import jax.numpy as jnp
    import time as _time

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.ops.keys import column_sort_lanes

    if pre_sharded is not None:
        # Born-sharded input: already resident under the canonical row
        # sharding with its own validity mask — zero placement work, and
        # the representative-row gather below indexes the SAME padded
        # layout (first_perm's shard-local positions are global
        # s*C + i here too).
        sharded, row_valid = pre_sharded
        batch = sharded
    else:
        sharded, row_valid = shard_batch(batch, mesh)

    tree = {"valid": row_valid}
    lane_cols: List = []
    for name in group_columns:
        lane_cols.extend(column_sort_lanes(sharded.column(name)))
    for i, lane in enumerate(lane_cols):
        tree[f"lane{i}"] = lane
    specs_meta = []
    for j, spec in enumerate(aggregates):
        if spec.func == "count" and spec.column == "*":
            specs_meta.append(("count_star", False))
            continue
        col = sharded.column(spec.column)
        if col.is_string and spec.func != "count":
            raise HyperspaceException(
                f"Aggregate {spec.func} over string column {spec.column}")
        specs_meta.append((spec.func, col.validity is not None))
        tree[f"v{j}"] = col.data
        tree[f"m{j}"] = (col.validity if col.validity is not None
                         else jnp.ones(col.data.shape[0], dtype=bool))

    local = row_valid.shape[0] // n_shards
    capacity = max(64, min(local, 1 << 14))
    while True:
        step = make_partial_step(mesh, len(lane_cols), tuple(specs_meta),
                                 capacity)
        out = step(tree)
        t0 = _time.perf_counter()
        overflowed = int(np.asarray(out["overflow"]).sum())  # host sync
        sync_s = _time.perf_counter() - t0
        reg.counter("mesh.aggregate.sync_s").inc(sync_s)
        telemetry.add_seconds("mesh.sync_s", sync_s)
        if overflowed == 0:
            break
        reg.counter("mesh.aggregate.overflow_retries").inc()
        capacity *= 2  # exact recovery: rerun wider

    return _combine_partials(batch, out, group_columns, aggregates,
                             specs_meta, out_schema, len(lane_cols),
                             n_shards, capacity, sharded, row_valid)


def _combine_partials(batch, out, group_columns, aggregates, specs_meta,
                      out_schema, num_lanes, n_shards, capacity,
                      sharded, row_valid):
    from hyperspace_tpu.ops.keys import host_dense_group_ids

    rows = np.asarray(out["rows"]).reshape(-1)
    used = rows > 0  # empty slots carry no group
    keys = [np.asarray(out[f"key{i}"]).reshape(-1)[used]
            for i in range(num_lanes)]
    perm, seg_sorted = host_dense_group_ids(keys)
    order = perm
    seg = seg_sorted
    num_groups = int(seg[-1]) + 1 if len(seg) else 0
    starts = np.searchsorted(seg, np.arange(num_groups), side="left")

    def fold(name, default=0.0):
        vals = np.asarray(out[name]).reshape(-1)[used][order]
        return vals, starts

    # Representative original row per group (for the group-key VALUES):
    # first_perm holds, per slot, the LOCAL sorted position's original
    # global row index — valid because shard_batch row-shards the global
    # arrays in order, so shard s's local index i is global s*local + i.
    first_perm = np.asarray(out["first_perm"]).reshape(n_shards, capacity)
    local = row_valid.shape[0] // n_shards
    first_global = (first_perm
                    + (np.arange(n_shards, dtype=np.int64)[:, None] * local))
    first_global = first_global.reshape(-1)[used][order]
    group_first = first_global[starts]

    import jax.numpy as jnp
    rep = batch.take(jnp.asarray(np.minimum(group_first,
                                            batch.num_rows - 1)
                                 .astype(np.int32)))

    columns = {}
    for name in group_columns:
        src = rep.column(name)
        f = batch.schema.field(name)
        columns[f.name] = DeviceColumn(
            data=np.asarray(src.data), dtype=src.dtype,
            validity=(np.asarray(src.validity)
                      if src.validity is not None else None),
            dictionary=src.dictionary, dict_hashes=src.dict_hashes)

    from hyperspace_tpu.io.columnar import HOST_NP_DTYPES as _HOST_NP
    rows_sorted = rows[used][order]
    for j, spec in enumerate(aggregates):
        out_field = out_schema.field(spec.alias)
        if specs_meta[j][0] == "count_star":
            data = np.add.reduceat(rows_sorted, starts).astype(np.int64)
            columns[out_field.name] = DeviceColumn(data, "int64")
            continue
        cnt, _ = fold(f"cnt{j}")
        cnt_tot = np.add.reduceat(cnt, starts).astype(np.int64)
        if spec.func == "count":
            columns[out_field.name] = DeviceColumn(cnt_tot, "int64")
            continue
        validity_out = cnt_tot > 0
        safe_cnt = np.maximum(cnt_tot.astype(np.float64), 1)
        if spec.func in ("sum", "avg"):
            s1, _ = fold(f"s1{j}")
            s1_tot = np.add.reduceat(s1, starts)
            data = (s1_tot if spec.func == "sum"
                    else s1_tot / safe_cnt)
        elif spec.func == "min":
            mn, _ = fold(f"mn{j}")
            data = np.minimum.reduceat(mn, starts)
        elif spec.func == "max":
            mx, _ = fold(f"mx{j}")
            data = np.maximum.reduceat(mx, starts)
        else:  # stddev: exact variance decomposition around the global mean
            s1, _ = fold(f"s1{j}")
            m2, _ = fold(f"m2{j}")
            s1_tot = np.add.reduceat(s1, starts)
            anchor = s1_tot / safe_cnt
            cnt_f = cnt.astype(np.float64)
            shard_mean = np.divide(s1, np.maximum(cnt_f, 1))
            shift = cnt_f * (shard_mean
                             - np.repeat(anchor, np.diff(
                                 np.append(starts, len(s1))))) ** 2
            m2_tot = np.add.reduceat(m2 + shift, starts)
            data = np.sqrt(np.maximum(
                m2_tot / np.maximum(safe_cnt - 1, 1), 0.0))
            validity_out = cnt_tot > 1
        columns[out_field.name] = DeviceColumn(
            data.astype(_HOST_NP[out_field.dtype]), out_field.dtype,
            validity=validity_out)
    return ColumnBatch(out_schema, columns)
