"""Mesh-sharded co-bucketed join — the counting join over shard-local rows.

Each bucket's rows of BOTH sides land on one shard (`shard_plan`:
load-balanced assignment), so the entire match phase runs with ZERO
inter-chip traffic — the claim the JoinIndexRanker's equal-bucket
preference encodes (reference `index/rankers/JoinIndexRanker.scala:40-55`).

Layout: a host-side [S, C] gather plan maps (shard, slot) -> original row
(C = largest shard's row count; padding is masked). Each shard's slice —
key lanes + validity only, never payload — is then device_put with a
sharded `NamedSharding`, so per-chip live bytes are ~total/S. This
replaces the round-3 design's two structural flaws (the round-3 review's
item 3): key lanes replicated to every device (per-chip O(total rows)),
and the padded [B, next_pow2(max_bucket)] layout where one hot bucket
padded every bucket. Since round 5 HOT buckets SPLIT across shards
(`shard_plan`: one side partitions, the other side's bucket rows
replicate to the split shards) so skewed joins keep the whole mesh at
near-ideal per-shard capacity; the match core is the same
sort+cumulative-counting design the single-chip join uses
(`ops/join.py` — skew-immune by construction).

Per shard (all batched over the sharded axis, no collectives until the
host sync that sizes the output):
1. ONE stable dim-1 sort by (pad, null, *key lanes, side, slot);
2. group runs from adjacent lane differences (null/pad break every run);
3. right-run brackets via cumulative max/min counting — no searchsorted;
4. counts -> global exclusive cumsum -> expansion to (li, ri) pairs.

Coverage: inner / left_outer (callers swap for right_outer), full_outer
(left_outer expansion + unmatched-right append from the same match), and
semi/anti membership — all wired into `SortMergeJoinExec`, which routes
co-bucketed sides here whenever a mesh is active (semi/anti over
index-pair layouts included, since round 4's planner keeps their
bucketed alignment instead of always probing bare).

When bucket counts differ (the ranker's fallback), `rebucket` routes the
smaller side through the build pipeline's all_to_all to the larger side's
bucket count first — the "one-sided re-bucket" cost model.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, unify_string_columns
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.parallel.mesh import shard_rows, total_shards

# Mesh-path skew guard: if the [S, C] layout would materially out-size the
# true row count (one shard owns a dominant hot bucket), stay single-chip
# where the flat counting join's memory is bounded by the actual rows.
SKEW_MIN_CELLS = 1 << 20
SKEW_BLOWUP_FACTOR = 4


def _side_lanes(left: ColumnBatch, right: ColumnBatch,
                left_keys: Sequence[str], right_keys: Sequence[str]):
    """Per-key 32-bit lane pairs plus per-row key validity for both sides
    (the shared decomposition, `ops/keys.py` — no cross-side encode).
    Lanes keep their residency: HOST columns yield numpy lanes (the shard
    layout gathers them on the host so each device receives only its
    slice), DEVICE-resident columns yield device lanes that never detour
    through host numpy — `_sharded_inputs` gathers those on device and
    reshards, so a device-resident join pays no D2H for its own keys."""
    import jax.numpy as jnp

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    l_lanes: List = []
    r_lanes: List = []
    l_ok = np.ones(n, dtype=bool)
    r_ok = np.ones(m, dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        if lcol.validity is not None:
            l_ok = l_ok & _host_or_device_mask(lcol.validity)
        if rcol.validity is not None:
            r_ok = r_ok & _host_or_device_mask(rcol.validity)
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        for ll, rl in zip(keymod.key_lanes(ldata), keymod.key_lanes(rdata)):
            l_lanes.append(ll if not isinstance(ll, np.ndarray)
                           else np.asarray(ll))
            r_lanes.append(rl if not isinstance(rl, np.ndarray)
                           else np.asarray(rl))
    return l_lanes, r_lanes, l_ok, r_ok


def _host_or_device_mask(validity):
    """Leave device validity masks on device (combining with a host bool
    array broadcasts device-side); only genuinely host masks stay numpy."""
    return validity if not isinstance(validity, np.ndarray) \
        else np.asarray(validity)


def shard_plan(l_lengths, r_lengths, n_shards: int, split: str):
    """Host-side row->shard assignment for the co-bucketed join, with
    HOT-BUCKET SPLITTING: a bucket whose rows dominate the ideal
    per-shard load is split — one side's rows PARTITION across several
    shards while the other side's rows of that bucket REPLICATE to each,
    so every partitioned row still sees its complete match set and the
    mesh keeps all its chips on skewed data (the round-4 review's item:
    the ranker's parallelism rationale, `JoinIndexRanker.scala:40-55`,
    carried to its TPU-native conclusion instead of a single-chip
    fallback).

    `split` picks which side may partition:
      - "left":   only the left side partitions (LEFT OUTER and the
                  semi/anti membership probes: every left row must see
                  the FULL right set of its bucket, and must be emitted
                  exactly once);
      - "larger": either side may partition (INNER: matches are a union
                  over chunks either way);
      - "none":   whole-bucket assignment only (FULL OUTER: per-shard
                  unmatched-right detection needs the whole bucket).
    Non-split buckets place greedily on the least-loaded shard (LPT),
    which already beats the former static `b % n_shards` ownership.

    Returns ([l_rows per shard], [r_rows per shard]) as int64 index
    arrays into the concat-in-bucket-order row space."""
    l_lengths = np.asarray(l_lengths, dtype=np.int64)
    r_lengths = np.asarray(r_lengths, dtype=np.int64)
    B = len(l_lengths)
    l_starts = np.concatenate([[0], np.cumsum(l_lengths)[:-1]])
    r_starts = np.concatenate([[0], np.cumsum(r_lengths)[:-1]])
    total = int(l_lengths.sum() + r_lengths.sum())
    ideal = max(1, -(-total // n_shards))
    loads = np.zeros(n_shards, dtype=np.int64)
    l_rows: List[List] = [[] for _ in range(n_shards)]
    r_rows: List[List] = [[] for _ in range(n_shards)]
    order = np.argsort(-(l_lengths + r_lengths), kind="stable")
    for b in order:
        lb, rb = int(l_lengths[b]), int(r_lengths[b])
        rows_b = lb + rb
        if rows_b == 0:
            continue
        l_all = np.arange(l_starts[b], l_starts[b] + lb)
        r_all = np.arange(r_starts[b], r_starts[b] + rb)
        if split == "left" or lb >= rb:
            part_rows, part_side = l_all, "l"
            rep_rows = r_all
        else:
            part_rows, part_side = r_all, "r"
            rep_rows = l_all
        # Split only when the PARTITIONED side dominates the load: the
        # replicated side multiplies by the split width, so partitioning
        # a tiny side under a huge replicated one would inflate capacity
        # instead of balancing it (review finding).
        do_split = (split != "none" and n_shards > 1
                    and len(part_rows) > max(ideal, 256))
        if not do_split:
            s = int(np.argmin(loads))
            if lb:
                l_rows[s].append(l_all)
            if rb:
                r_rows[s].append(r_all)
            loads[s] += rows_b
            continue
        k = int(min(n_shards, max(2, -(-len(part_rows) // ideal)),
                    len(part_rows)))
        shards = np.argsort(loads, kind="stable")[:k]
        for s, chunk in zip(shards, np.array_split(part_rows, k)):
            s = int(s)
            if len(chunk):
                (l_rows if part_side == "l" else r_rows)[s].append(chunk)
                if len(rep_rows):
                    (r_rows if part_side == "l" else l_rows)[s].append(
                        rep_rows)
                loads[s] += len(chunk) + len(rep_rows)
    cat = (lambda parts: np.concatenate(parts)
           if parts else np.zeros(0, dtype=np.int64))
    return [cat(p) for p in l_rows], [cat(p) for p in r_rows]


def _rows_to_layout(rows_per_shard):
    """[rows per shard] -> ([S, C] gather idx, valid mask, C)."""
    S = len(rows_per_shard)
    C = max(1, max(len(r) for r in rows_per_shard))
    idx = np.zeros((S, C), dtype=np.int32)
    valid = np.zeros((S, C), dtype=bool)
    for s, rows in enumerate(rows_per_shard):
        idx[s, :len(rows)] = rows
        valid[s, :len(rows)] = True
    return idx, valid, C


def shard_skew(l_lengths, r_lengths, n_shards: int) -> bool:
    """True when hot-bucket skew would blow the [S, C] layout up far past
    the true row count. Only FULL OUTER still routes single-chip on this
    (whole buckets are atomic there); every other join type splits hot
    buckets across shards instead (`shard_plan`)."""
    from hyperspace_tpu.parallel.mesh import bucket_ranges

    l_lengths = np.asarray(l_lengths, dtype=np.int64)
    r_lengths = np.asarray(r_lengths, dtype=np.int64)
    B = len(l_lengths)
    owned = [np.arange(lo, hi) for lo, hi in bucket_ranges(B, n_shards)]
    cl = max(1, max(int(l_lengths[o].sum()) for o in owned))
    cr = max(1, max(int(r_lengths[o].sum()) for o in owned))
    cells = n_shards * (cl + cr)
    rows = int(l_lengths.sum() + r_lengths.sum())
    return (cells > SKEW_MIN_CELLS
            and cells > SKEW_BLOWUP_FACTOR * max(rows, 1))


def _sharded_inputs(left, right, l_lengths, r_lengths, left_keys,
                    right_keys, mesh, split: str = "none"):
    """Build the sharded [S, T] match inputs (T = Cl + Cr): combined key
    lanes, pad mask, null mask, plus the [S, Cl]/[S, Cr] row-index plans
    (load-balanced, hot buckets split per `shard_plan`). Everything is
    gathered host-side from the 1-D lanes and device_put with the
    sharded spec — per-device bytes ~ T, not total rows. Also returns
    the per-shard assigned row counts (the load-balance attribution the
    mesh telemetry reports)."""
    import jax.numpy as jnp

    n_shards = total_shards(mesh)
    l_lanes, r_lanes, l_ok, r_ok = _side_lanes(left, right, left_keys,
                                               right_keys)
    l_rows, r_rows = shard_plan(l_lengths, r_lengths, n_shards, split)
    shard_assigned = [len(lr) + len(rr) for lr, rr in zip(l_rows, r_rows)]
    l_idx, l_valid, Cl = _rows_to_layout(l_rows)
    r_idx, r_valid, Cr = _rows_to_layout(r_rows)

    # Sharded puts STRAIGHT from numpy (transfer engine): jnp.asarray
    # would materialize the full array on the default device first,
    # defeating the per-device memory bound; a put under the row
    # sharding transfers each device only its slice. The engine issues
    # all puts before anything blocks and records the one link crossing.
    from hyperspace_tpu.io import transfer

    sharding = shard_rows(mesh)
    engine = transfer.get_engine()
    put = partial(engine.put, device=sharding)

    def host(x):
        return isinstance(x, np.ndarray)

    def gather2d(llane, rlane):
        """One combined [S, T] key lane. Host lanes gather on the host
        and ride the sharded put; DEVICE-resident lanes gather on device
        (jnp.take by the host layout index) and reshard — their bytes
        never cross back to the host (the round-8 review item: the join
        re-paid D2H for keys the scan had already placed)."""
        if host(llane) and host(rlane):
            return put(np.concatenate([llane[l_idx], rlane[r_idx]],
                                      axis=1))
        lg = (llane[l_idx] if host(llane)
              else jnp.take(llane, jnp.asarray(l_idx), axis=0))
        rg = (rlane[r_idx] if host(rlane)
              else jnp.take(rlane, jnp.asarray(r_idx), axis=0))
        return put(jnp.concatenate([jnp.asarray(lg), jnp.asarray(rg)],
                                   axis=1))

    lanes2d = tuple(gather2d(ll, rl)
                    for ll, rl in zip(l_lanes, r_lanes))
    pad = put(np.concatenate([~l_valid, ~r_valid], axis=1))
    if host(l_ok) and host(r_ok):
        null = put(np.concatenate([l_valid & ~l_ok[l_idx],
                                   r_valid & ~r_ok[r_idx]], axis=1))
    else:
        null = put(jnp.concatenate(
            [jnp.asarray(l_valid) & ~jnp.take(jnp.asarray(l_ok),
                                              jnp.asarray(l_idx), axis=0),
             jnp.asarray(r_valid) & ~jnp.take(jnp.asarray(r_ok),
                                              jnp.asarray(r_idx), axis=0)],
            axis=1))
    staged = (lanes2d, pad, null, put(l_idx), put(r_idx))
    return staged + (Cl, Cr, shard_assigned)


@partial(__import__("jax").jit, static_argnames=("Cl", "left_outer",
                                                 "need_right"))
def _shard_match_core(lanes2d, pad, null, Cl: int, left_outer: bool,
                      need_right: bool):
    """Shard-local counting match over the combined [S, T] layout.

    Per shard row: ONE stable sort by (pad, null, *lanes, side, slot),
    group runs from adjacent differences (null/pad break every run), and
    per-element right-run brackets from cumulative sums — the counting
    design, no searchsorted. Every op is elementwise or axis-1 over the
    sharded [S, T] arrays, so XLA keeps it chip-local.

    Returns (flat counts [S*T], starts [S*T], rights [S, T], rstart
    [S, T], pos_s [S, T], right_unmatched [S, T] or None).
    """
    import jax
    import jax.numpy as jnp

    S, T = pad.shape
    side = jnp.broadcast_to(
        jnp.concatenate([jnp.zeros(Cl, jnp.int32),
                         jnp.ones(T - Cl, jnp.int32)]), (S, T))
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (S, T))
    pad_i = pad.astype(jnp.int32)
    null_i = null.astype(jnp.int32)
    results = jax.lax.sort([pad_i, null_i, *lanes2d, side, pos],
                           num_keys=3 + len(lanes2d), is_stable=True,
                           dimension=1)
    pad_s, null_s = results[0], results[1]
    lanes_s = results[2:-2]
    side_s = results[-2]
    pos_s = results[-1]

    first = jnp.ones((S, 1), dtype=bool)
    rest = jnp.zeros((S, T - 1), dtype=bool)
    for k in lanes_s:
        rest = rest | (k[:, 1:] != k[:, :-1])
    # Null-key and pad elements never share a run with anything.
    rest = rest | (null_s[:, 1:] | null_s[:, :-1]
                   | pad_s[:, 1:] | pad_s[:, :-1]).astype(bool)
    run_start = jnp.concatenate([first, rest], axis=1)

    posT = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (S, T))
    run_first = jax.lax.cummax(jnp.where(run_start, posT, 0), axis=1)
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(run_start, posT, jnp.int32(T)), axis=1), axis=1), axis=1)
    run_last = jnp.concatenate(
        [nxt[:, 1:], jnp.full((S, 1), T, jnp.int32)], axis=1) - 1

    R = jnp.cumsum(side_s, axis=1)  # inclusive right-element count
    take = jnp.take_along_axis
    rights = (take(R, run_last, axis=1) - take(R, run_first, axis=1)
              + take(side_s, run_first, axis=1))
    rstart = run_last - rights + 1  # first right element of the run

    is_left = (side_s == 0) & (pad_s == 0)
    matchable = is_left & (null_s == 0)
    counts = jnp.where(matchable, rights, 0)
    if left_outer:
        # Every REAL left element (incl. null keys) emits at least once.
        counts = jnp.maximum(counts, is_left.astype(counts.dtype))
    # int64 accumulation: a distributed join can produce more than 2^31
    # output pairs; the int32 per-slot counts must not overflow silently
    # in the running total (the expansion sync turns `starts[-1] + ...`
    # into the output size).
    flat = counts.reshape(-1).astype(jnp.int64)
    starts = jnp.cumsum(flat) - flat

    right_unmatched = None
    if need_right:
        run_len = run_last - run_first + 1
        lefts = run_len - rights
        right_unmatched = ((side_s == 1) & (pad_s == 0)
                           & ((null_s == 1) | (lefts == 0)))
    return flat, starts, jnp.where(matchable, rights, 0), rstart, pos_s, \
        right_unmatched


@partial(__import__("jax").jit, static_argnames=("total", "T", "Cl"))
def _shard_expand_core(starts, rights, rstart, pos_s, l_idx, r_idx,
                       total: int, T: int, Cl: int):
    """Expand (shard, sorted slot, offset) -> original row index pairs;
    slots with zero true matches (left_outer reservations) emit right -1."""
    import jax.numpy as jnp

    S = pos_s.shape[0]
    pos_f = pos_s.reshape(-1)
    rights_f = rights.reshape(-1)
    rstart_f = rstart.reshape(-1)
    l_idx_f = l_idx.reshape(-1)
    r_idx_f = r_idx.reshape(-1)
    Cr = T - Cl

    slots = jnp.arange(total, dtype=starts.dtype)
    row = jnp.searchsorted(starts, slots, side="right") - 1
    s = (row // T).astype(jnp.int32)
    offset = (slots - jnp.take(starts, row)).astype(jnp.int32)
    l_slot = jnp.take(pos_f, row)  # combined-slot position of the left el
    li = jnp.take(l_idx_f, s * Cl + l_slot)
    matched = offset < jnp.take(rights_f, row)
    r_sorted = jnp.clip(jnp.take(rstart_f, row) + offset, 0, T - 1)
    r_slot = jnp.take(pos_f, s * T + r_sorted) - Cl
    ri = jnp.where(matched,
                   jnp.take(r_idx_f, s * Cr + jnp.clip(r_slot, 0, None)),
                   jnp.int32(-1))
    return li, ri


def distributed_bucketed_join_indices(
        left: ColumnBatch, right: ColumnBatch,
        l_lengths: np.ndarray, r_lengths: np.ndarray,
        left_keys: Sequence[str], right_keys: Sequence[str], mesh,
        how: str = "inner") -> Tuple:
    """As `ops.bucketed_join.bucketed_join_indices`, over rows sharded by
    bucket ownership: each shard matches ONLY its buckets' rows, with no
    replicated key lanes. `how` is inner / left_outer / full_outer
    (callers swap sides for right_outer). Requires num_buckets divisible
    by the mesh size (the bucket<->shard map)."""
    import jax.numpy as jnp

    if how not in ("inner", "left_outer", "full_outer"):
        raise HyperspaceException(
            f"Distributed bucketed join supports inner/left_outer/"
            f"full_outer; got {how}.")
    num_buckets = len(l_lengths)
    n_shards = total_shards(mesh)
    if num_buckets % n_shards != 0:
        raise ValueError(
            f"num_buckets ({num_buckets}) must be divisible by mesh size "
            f"({n_shards}).")
    n, m = left.num_rows, right.num_rows
    if n == 0 or m == 0:
        # Degenerate sides never reach the mesh (the single-chip path
        # guards these too, `ops/bucketed_join.py`): inner with any empty
        # side is empty; outer expansions are pure index arithmetic.
        empty = jnp.zeros(0, dtype=jnp.int32)
        li = (jnp.arange(n, dtype=jnp.int32)
              if how in ("left_outer", "full_outer") else empty)
        ri = jnp.full(li.shape[0], -1, dtype=jnp.int32)
        if how == "full_outer" and m > 0:
            li = jnp.concatenate([li, jnp.full(m, -1, dtype=jnp.int32)])
            ri = jnp.concatenate([ri, jnp.arange(m, dtype=jnp.int32)])
        return li, ri

    full_outer = how == "full_outer"
    from hyperspace_tpu import telemetry
    import time as _time
    tracer = telemetry.tracer()
    span_ts = tracer.now_us() if tracer is not None else 0.0
    lanes2d, pad, null, l_idx, r_idx, Cl, Cr, shard_assigned = \
        _sharded_inputs(
            left, right, l_lengths, r_lengths, left_keys, right_keys,
            mesh,
            # full_outer's unmatched-right scan needs whole buckets;
            # inner may partition either side; left_outer must keep
            # every left row exactly once with its full right set ->
            # split left only.
            split=("none" if full_outer
                   else ("larger" if how == "inner" else "left")))
    with telemetry.span("mesh:join:match", "mesh", how=how,
                        shards=n_shards):
        counts, starts, rights, rstart, pos_s, right_unmatched = \
            _shard_match_core(lanes2d, pad, null, Cl,
                              left_outer=how in ("left_outer",
                                                 "full_outer"),
                              need_right=full_outer)
        t0 = _time.perf_counter()
        total = int(jnp.sum(counts))  # the one host sync sizing the output
        sync_s = _time.perf_counter() - t0
    reg = telemetry.get_registry()
    reg.counter("mesh.join.execs").inc()
    reg.counter("mesh.join.sync_s").inc(sync_s)
    telemetry.add_seconds("mesh.sync_s", sync_s)
    for rows in shard_assigned:
        reg.histogram("mesh.join.shard_rows").observe(rows)
    telemetry.event("mesh", "join", how=how, shards=n_shards,
                    pairs=total, shard_rows=shard_assigned)
    if tracer is not None:
        tracer.device_spans("join", span_ts, shard_assigned, how=how)
    empty = jnp.zeros(0, dtype=jnp.int32)
    if total == 0:
        li, ri = empty, empty
    else:
        li, ri = _shard_expand_core(starts, rights, rstart, pos_s, l_idx,
                                    r_idx, total, Cl + Cr, Cl)
    if full_outer:
        extra = int(jnp.sum(right_unmatched))  # second host sync
        if extra:
            (rows,) = jnp.nonzero(right_unmatched.reshape(-1), size=extra,
                                  fill_value=0)
            T = Cl + Cr
            s = (rows // T).astype(jnp.int32)
            r_slot = jnp.take(pos_s.reshape(-1), rows) - Cl
            r_orig = jnp.take(r_idx.reshape(-1),
                              s * Cr + jnp.clip(r_slot, 0, None))
            li = jnp.concatenate(
                [li, jnp.full(extra, -1, dtype=jnp.int32)])
            ri = jnp.concatenate([ri, r_orig.astype(jnp.int32)])
    return li, ri


def distributed_semi_anti_indices(
        left: ColumnBatch, right: ColumnBatch,
        l_lengths: np.ndarray, r_lengths: np.ndarray,
        left_keys: Sequence[str], right_keys: Sequence[str], mesh,
        anti: bool = False):
    """Left-row indices for LEFT SEMI / LEFT ANTI over co-bucketed sides,
    sharded by bucket ownership (anti emits null-key left rows — NOT
    EXISTS semantics, mirroring `ops/join.semi_anti_indices`)."""
    import jax.numpy as jnp

    num_buckets = len(l_lengths)
    n_shards = total_shards(mesh)
    if num_buckets % n_shards != 0:
        raise ValueError(
            f"num_buckets ({num_buckets}) must be divisible by mesh size "
            f"({n_shards}).")
    if left.num_rows == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    if right.num_rows == 0:
        return (jnp.arange(left.num_rows, dtype=jnp.int32) if anti
                else jnp.zeros(0, dtype=jnp.int32))
    from hyperspace_tpu import telemetry
    lanes2d, pad, null, l_idx, r_idx, Cl, Cr, shard_assigned = \
        _sharded_inputs(
            left, right, l_lengths, r_lengths, left_keys, right_keys,
            mesh,
            # Membership: every left row must see its bucket's FULL
            # right set (anti requires NO match anywhere) -> only left
            # partitions.
            split="left")
    reg = telemetry.get_registry()
    reg.counter("mesh.join.execs").inc()
    for rows in shard_assigned:
        reg.histogram("mesh.join.shard_rows").observe(rows)
    telemetry.event("mesh", "join", how=("anti" if anti else "semi"),
                    shards=n_shards, shard_rows=shard_assigned)
    with telemetry.span("mesh:join:match", "mesh",
                        how=("anti" if anti else "semi"),
                        shards=n_shards):
        counts, _starts, rights, _rstart, pos_s, _ = _shard_match_core(
            lanes2d, pad, null, Cl, left_outer=True, need_right=False)
    counts2d = counts.reshape(pos_s.shape)
    is_left = counts2d > 0  # left_outer counting marks exactly left slots
    hit = is_left & ((rights == 0) if anti else (rights > 0))
    want = hit.reshape(-1)
    total = int(jnp.sum(want))  # host sync
    if total == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    (rows,) = jnp.nonzero(want, size=total, fill_value=0)
    T = Cl + Cr
    s = (rows // T).astype(jnp.int32)
    l_slot = jnp.take(pos_s.reshape(-1), rows)
    li = jnp.take(l_idx.reshape(-1), s * Cl + l_slot)
    return li.astype(jnp.int32)


def rebucket(batch: ColumnBatch, key_columns: Sequence[str],
             target_buckets: int, mesh, capacity_factor: float = 2.0):
    """One-sided re-bucket (mismatched bucket counts): route a batch to
    `target_buckets` via the build pipeline's all_to_all. Returns
    (batch in bucket order, lengths)."""
    from hyperspace_tpu.parallel.build import distributed_build
    return distributed_build(batch, key_columns, target_buckets, mesh,
                             capacity_factor)
