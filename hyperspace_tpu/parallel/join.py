"""Mesh-sharded co-bucketed join.

The single-chip batched bucket join (`ops/bucketed_join.py`) is already
expressed over a leading bucket axis [B, L]; distributing it is a matter of
SHARDING THAT AXIS over the mesh and letting XLA's SPMD partitioner place
the per-bucket sorts and searchsorted lookups chip-locally — the jax-native
"annotate shardings, let XLA insert collectives" recipe. Because bucket b of
both sides lives on the same shard (bucket % n_shards), the match phase
runs with ZERO inter-chip traffic; only the final ragged expansion
all-gathers its (small) counts — the claim the JoinIndexRanker's
equal-bucket preference encodes (reference
`index/rankers/JoinIndexRanker.scala:40-55`).

When bucket counts differ (the ranker's fallback), `rebucket` routes the
smaller side through the build pipeline's all_to_all to the larger side's
bucket count first — the "one-sided re-bucket" cost model.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.io.columnar import ColumnBatch
from hyperspace_tpu.ops.bucketed_join import (_match_core, _expand_core,
                                              _padded_layout, encode_group_ids,
                                              next_pow2)
from hyperspace_tpu.parallel.mesh import SHARD_AXIS, replicated, shard_rows


def distributed_bucketed_join_indices(
        left: ColumnBatch, right: ColumnBatch,
        l_lengths: np.ndarray, r_lengths: np.ndarray,
        left_keys: Sequence[str], right_keys: Sequence[str], mesh) -> Tuple:
    """As `ops.bucketed_join.bucketed_join_indices`, but with the padded
    [B, L] forms sharded over the mesh's bucket axis. Requires num_buckets
    divisible by the mesh size (the bucket<->shard map)."""
    import jax
    import jax.numpy as jnp

    num_buckets = len(l_lengths)
    n_shards = mesh.shape[SHARD_AXIS]
    if num_buckets % n_shards != 0:
        raise ValueError(
            f"num_buckets ({num_buckets}) must be divisible by mesh size "
            f"({n_shards}).")

    l_ids, r_ids = encode_group_ids(left, right, left_keys, right_keys)
    Ll = next_pow2(max(1, int(np.asarray(l_lengths).max(initial=0))))
    Lr = next_pow2(max(1, int(np.asarray(r_lengths).max(initial=0))))
    l_idx, l_valid = _padded_layout(np.asarray(l_lengths), Ll)
    r_idx, r_valid = _padded_layout(np.asarray(r_lengths), Lr)

    bucket_sharding = shard_rows(mesh)   # shard the bucket axis
    repl = replicated(mesh)
    put = jax.device_put
    l_idx = put(jnp.asarray(l_idx), bucket_sharding)
    l_valid = put(jnp.asarray(l_valid), bucket_sharding)
    r_idx = put(jnp.asarray(r_idx), bucket_sharding)
    r_valid = put(jnp.asarray(r_valid), bucket_sharding)
    l_ids = put(l_ids, repl)
    r_ids = put(r_ids, repl)

    counts, starts, lo_c, l_pos, r_pos, _real = _match_core(
        l_ids, r_ids, l_idx, l_valid, r_idx, r_valid)
    total = int(jnp.sum(counts))
    if total == 0:
        empty = jnp.zeros(0, dtype=jnp.int32)
        return empty, empty
    return _expand_core(starts, counts, lo_c, l_pos, r_pos, l_idx, r_idx,
                        total, Ll)


def rebucket(batch: ColumnBatch, key_columns: Sequence[str],
             target_buckets: int, mesh, capacity_factor: float = 2.0):
    """One-sided re-bucket (mismatched bucket counts): route a batch to
    `target_buckets` via the build pipeline's all_to_all. Returns
    (batch in bucket order, lengths)."""
    from hyperspace_tpu.parallel.build import distributed_build
    return distributed_build(batch, key_columns, target_buckets, mesh,
                             capacity_factor)
