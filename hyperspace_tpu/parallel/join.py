"""Mesh-sharded co-bucketed join.

The single-chip batched bucket join (`ops/bucketed_join.py`) is already
expressed over a leading bucket axis [B, L]; distributing it is a matter of
SHARDING THAT AXIS over the mesh and letting XLA's SPMD partitioner place
the per-bucket work chip-locally — the jax-native "annotate shardings, let
XLA insert collectives" recipe. Because bucket b of both sides lives on the
same shard (bucket % n_shards), the match phase runs with ZERO inter-chip
traffic — the claim the JoinIndexRanker's equal-bucket preference encodes
(reference `index/rankers/JoinIndexRanker.scala:40-55`).

Group encoding is SHARD-LOCAL: matching only ever happens within a bucket,
so key tuples need consistent ids only within each bucket. Both sides'
rows of one bucket are gathered into a combined padded [B, Ll+Lr] matrix
and sorted per bucket (one batched `lax.sort` along the row axis, sharded
over buckets); adjacent-difference ids within each bucket row replace the
round-2 design's REPLICATED global sort over all rows — the scaling
bottleneck the round-2 review called out.

When bucket counts differ (the ranker's fallback), `rebucket` routes the
smaller side through the build pipeline's all_to_all to the larger side's
bucket count first — the "one-sided re-bucket" cost model.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, unify_string_columns
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.ops.bucketed_join import _padded_layout, next_pow2
from hyperspace_tpu.parallel.mesh import SHARD_AXIS, replicated, shard_rows

_I32_MAX = np.int32(np.iinfo(np.int32).max)


def _side_lanes(left: ColumnBatch, right: ColumnBatch,
                left_keys: Sequence[str], right_keys: Sequence[str]):
    """Per-key 32-bit lane pairs plus per-row key validity for both sides
    (the shared decomposition, `ops/keys.py` — no cross-side encode)."""
    import jax.numpy as jnp

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    l_lanes: List = []
    r_lanes: List = []
    l_ok = jnp.ones(n, dtype=bool)
    r_ok = jnp.ones(m, dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        if lcol.validity is not None:
            l_ok = l_ok & lcol.validity
        if rcol.validity is not None:
            r_ok = r_ok & rcol.validity
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        for ll, rl in zip(keymod.key_lanes(ldata), keymod.key_lanes(rdata)):
            l_lanes.append(ll)
            r_lanes.append(rl)
    return tuple(l_lanes), tuple(r_lanes), l_ok, r_ok


@partial(__import__("jax").jit, static_argnames=("left_outer",))
def _dist_match_core(l_lanes, r_lanes, l_ok, r_ok, l_idx, l_valid, r_idx,
                     r_valid, left_outer: bool = False):
    """Shard-local per-bucket match over the combined [B, Ll+Lr] layout.

    Per bucket: gather both sides' key lanes, ONE stable sort by
    (pad, null, *lanes, side, slot), adjacent-difference group ids (null
    keys force their own group, so they never match), then per-element
    right-run brackets via a composite (id, side) searchsorted. Every op
    after the gathers is batched over the bucket axis — sharded over the
    mesh with zero collectives.

    Returns (counts [B*T], starts [B*T], rlo [B, T], rcnt [B, T],
    pos_sorted [B, T]) for `_dist_expand_core`.
    """
    import jax
    import jax.numpy as jnp

    B, Ll = l_idx.shape
    Lr = r_idx.shape[1]
    T = Ll + Lr

    pad = jnp.concatenate([~l_valid, ~r_valid], axis=1).astype(jnp.int32)
    null = jnp.concatenate(
        [jnp.where(l_valid, ~jnp.take(l_ok, l_idx), False),
         jnp.where(r_valid, ~jnp.take(r_ok, r_idx), False)],
        axis=1).astype(jnp.int32)
    side = jnp.broadcast_to(
        jnp.concatenate([jnp.zeros(Ll, jnp.int32),
                         jnp.ones(Lr, jnp.int32)]), (B, T))
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    lanes2d = [jnp.concatenate([jnp.take(ll, l_idx), jnp.take(rl, r_idx)],
                               axis=1)
               for ll, rl in zip(l_lanes, r_lanes)]
    results = jax.lax.sort([pad, null, *lanes2d, side, pos],
                           num_keys=3 + len(lanes2d), is_stable=True,
                           dimension=1)
    pad_s, null_s = results[0], results[1]
    lanes_s = results[2:-2]
    side_s = results[-2]
    pos_s = results[-1]

    differs = jnp.ones((B, 1), dtype=jnp.int32)
    rest = jnp.zeros((B, T - 1), dtype=jnp.int32)
    for k in lanes_s:
        rest = rest | (k[:, 1:] != k[:, :-1]).astype(jnp.int32)
    # Null-key elements never share a group with anything.
    rest = rest | null_s[:, 1:] | null_s[:, :-1]
    rest = rest | pad_s[:, 1:] | pad_s[:, :-1]
    ids = jnp.cumsum(jnp.concatenate([differs, rest], axis=1),
                     axis=1, dtype=jnp.int32)

    # Right-run bracket per element: composite (id, side) is sorted within
    # each bucket row because side is a trailing sort key.
    composite = ids * 2 + side_s
    want = ids * 2 + 1
    rlo = jax.vmap(lambda c, w: jnp.searchsorted(c, w, side="left"))(
        composite, want)
    rhi = jax.vmap(lambda c, w: jnp.searchsorted(c, w, side="right"))(
        composite, want)
    rcnt = rhi - rlo

    is_left = (side_s == 0) & (pad_s == 0)
    matchable = is_left & (null_s == 0)
    counts = jnp.where(matchable, rcnt, 0)
    if left_outer:
        # Every REAL left element (incl. null keys) emits at least one row.
        counts = jnp.maximum(counts, is_left.astype(counts.dtype))
    flat = counts.reshape(-1)
    starts = jnp.cumsum(flat) - flat
    return flat, starts, rlo, jnp.where(matchable, rcnt, 0), pos_s


@partial(__import__("jax").jit, static_argnames=("total", "T", "Ll"))
def _dist_expand_core(starts, rcnt, rlo, pos_s, l_idx, r_idx,
                      total: int, T: int, Ll: int):
    """Expand (bucket, sorted slot, offset) -> original row index pairs;
    slots with zero true matches (left_outer reservations) emit right -1."""
    import jax.numpy as jnp

    slots = jnp.arange(total, dtype=starts.dtype)
    row = jnp.searchsorted(starts, slots, side="right") - 1
    b = (row // T).astype(jnp.int32)
    j = (row % T).astype(jnp.int32)
    offset = (slots - jnp.take(starts, row)).astype(jnp.int32)
    l_slot = pos_s[b, j]
    li = l_idx[b, l_slot]
    matched = offset < rcnt[b, j]
    r_sorted_idx = jnp.clip(rlo[b, j] + offset, 0, T - 1)
    r_slot = pos_s[b, r_sorted_idx] - Ll
    ri = jnp.where(matched, r_idx[b, jnp.clip(r_slot, 0, None)],
                   jnp.int32(-1))
    return li, ri


def distributed_bucketed_join_indices(
        left: ColumnBatch, right: ColumnBatch,
        l_lengths: np.ndarray, r_lengths: np.ndarray,
        left_keys: Sequence[str], right_keys: Sequence[str], mesh,
        how: str = "inner") -> Tuple:
    """As `ops.bucketed_join.bucketed_join_indices`, but with the padded
    [B, T] forms sharded over the mesh's bucket axis and the group encode
    computed per bucket (shard-local — no replicated global sort).
    Requires num_buckets divisible by the mesh size (the bucket<->shard
    map). `how` is inner or left_outer (callers swap sides for
    right_outer)."""
    import jax
    import jax.numpy as jnp

    if how not in ("inner", "left_outer"):
        raise HyperspaceException(
            f"Distributed bucketed join supports inner/left_outer; "
            f"got {how}.")
    num_buckets = len(l_lengths)
    n_shards = mesh.shape[SHARD_AXIS]
    if num_buckets % n_shards != 0:
        raise ValueError(
            f"num_buckets ({num_buckets}) must be divisible by mesh size "
            f"({n_shards}).")

    l_lanes, r_lanes, l_ok, r_ok = _side_lanes(left, right, left_keys,
                                               right_keys)
    Ll = next_pow2(max(1, int(np.asarray(l_lengths).max(initial=0))))
    Lr = next_pow2(max(1, int(np.asarray(r_lengths).max(initial=0))))
    l_idx, l_valid = _padded_layout(np.asarray(l_lengths), Ll)
    r_idx, r_valid = _padded_layout(np.asarray(r_lengths), Lr)

    bucket_sharding = shard_rows(mesh)   # shard the bucket axis
    repl = replicated(mesh)
    put = jax.device_put
    l_idx = put(jnp.asarray(l_idx), bucket_sharding)
    l_valid = put(jnp.asarray(l_valid), bucket_sharding)
    r_idx = put(jnp.asarray(r_idx), bucket_sharding)
    r_valid = put(jnp.asarray(r_valid), bucket_sharding)
    l_lanes = tuple(put(x, repl) for x in l_lanes)
    r_lanes = tuple(put(x, repl) for x in r_lanes)
    l_ok = put(l_ok, repl)
    r_ok = put(r_ok, repl)

    counts, starts, rlo, rcnt, pos_s = _dist_match_core(
        l_lanes, r_lanes, l_ok, r_ok, l_idx, l_valid, r_idx, r_valid,
        left_outer=(how == "left_outer"))
    total = int(jnp.sum(counts))
    if total == 0:
        empty = jnp.zeros(0, dtype=jnp.int32)
        return empty, empty
    return _dist_expand_core(starts, rcnt, rlo, pos_s, l_idx, r_idx,
                             total, Ll + Lr, Ll)


def rebucket(batch: ColumnBatch, key_columns: Sequence[str],
             target_buckets: int, mesh, capacity_factor: float = 2.0):
    """One-sided re-bucket (mismatched bucket counts): route a batch to
    `target_buckets` via the build pipeline's all_to_all. Returns
    (batch in bucket order, lengths)."""
    from hyperspace_tpu.parallel.build import distributed_build
    return distributed_build(batch, key_columns, target_buckets, mesh,
                             capacity_factor)
