"""Virtual multi-device bootstrap for tests and dry runs.

Multi-chip behavior is validated the way the reference validates
distribution — a real local multi-way runtime in one process (`local[4]`
SparkSession, reference `SparkInvolvedSuite.scala:29-35`): here, an
n-device virtual CPU mesh. Used by `tests/conftest.py` and the driver's
`__graft_entry__.dryrun_multichip` gate.
"""

from __future__ import annotations


def ensure_devices(n_devices: int) -> None:
    """Make `jax.devices()` report at least ``n_devices`` devices.

    Real hardware with enough chips is used as-is. Otherwise the live
    backends are dropped and CPU is re-initialized with a forced device
    count. ``clear_backends`` MUST precede the config updates — jax
    refuses ``jax_num_cpu_devices`` changes while backends are live.

    PROCESS-DESTRUCTIVE in the fallback path: it pins jax_platforms=cpu
    for the rest of the process and invalidates every live jax array and
    compiled computation. Call it before any device work (tests do it at
    conftest import; the dryrun gate does it first thing). On jax
    versions without the ``jax_num_cpu_devices`` config (< 0.5) the
    device count is forced through ``XLA_FLAGS`` instead — that path
    DOES write ``os.environ`` (inherited by subprocesses), the flag XLA
    reads at CPU-client init.
    """
    import jax

    if not hasattr(jax.config, "jax_num_cpu_devices"):
        # Older jax: the only knob is the XLA host-platform flag, and
        # XLA parses XLA_FLAGS ONCE per process — it must be in the
        # environment before the first backend init (clear_backends +
        # re-init does NOT re-read it). ensure_devices is documented to
        # run before any device work, so set it ahead of our own probe.
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()

    try:
        if len(jax.devices()) >= n_devices:
            return
    except RuntimeError:
        pass

    import jax.extend.backend

    jax.extend.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", n_devices)
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"virtual mesh bootstrap failed: have {len(jax.devices())} "
            f"devices, requested {n_devices}")
