"""Distribution context: decides whether the data plane runs on a mesh.

The reference delegates this decision to the Spark cluster it runs inside
(every build/join IS distributed there, `CreateActionBase.scala:110-111`,
`JoinIndexRule.scala:124-153`); here the "cluster" is the set of visible
jax devices. `spark.hyperspace.distribution.enabled`:

- "auto" (default): distribute when more than one device is visible;
- "true": distribute (no-op on a single device — there is no mesh to use);
- "false": always single-chip.
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu import constants


def distribution_mesh(conf=None):
    """The mesh to distribute over, or None for single-chip execution."""
    mode = conf.distribution if conf is not None else "auto"
    if mode == "false":
        return None
    import jax

    try:
        devices = jax.devices()
    except RuntimeError:
        return None
    if len(devices) < 2:
        return None
    from hyperspace_tpu.parallel.mesh import make_mesh

    dcn = (conf.get_int(constants.DISTRIBUTION_DCN_SIZE,
                        constants.DISTRIBUTION_DCN_SIZE_DEFAULT)
           if conf is not None
           else constants.DISTRIBUTION_DCN_SIZE_DEFAULT)
    if dcn > 1 and len(devices) % dcn != 0:
        import logging
        logging.getLogger(__name__).warning(
            "distribution.dcn.size=%d does not divide the %d visible "
            "devices; falling back to a FLAT mesh — build re-bucket "
            "collectives will span DCN.", dcn, len(devices))
        dcn = 1
    return make_mesh(len(devices), dcn_size=dcn if dcn > 1 else None)


def mesh_size(mesh) -> int:
    """TOTAL device count of the mesh (both axes of a (dcn, shard) mesh)."""
    from hyperspace_tpu.parallel.mesh import total_shards

    return total_shards(mesh)


def should_distribute(conf, num_rows: Optional[int] = None,
                      host_batch: bool = False):
    """Mesh to use for this operation, or None. In "auto" mode small
    batches stay single-chip — per-shard padding plus collective latency
    dwarfs the work below `distribution.min.rows` — and HOST-lane batches
    stay on the host (they avoided the device link on purpose;
    distributing would pay it anyway). "true" distributes regardless
    (tests use this to exercise the mesh paths). This is THE policy seam:
    every operator with a mesh path answers the question here."""
    mesh = distribution_mesh(conf)
    if mesh is None:
        return None
    mode = conf.distribution if conf is not None else "auto"
    if mode == "auto" and host_batch:
        return None
    min_rows = (conf.distribution_min_rows if conf is not None
                else constants.DISTRIBUTION_MIN_ROWS_DEFAULT)
    if mode == "auto" and num_rows is not None and num_rows < min_rows:
        return None
    return mesh
