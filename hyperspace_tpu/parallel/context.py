"""Distribution context: decides whether the data plane runs on a mesh.

The reference delegates this decision to the Spark cluster it runs inside
(every build/join IS distributed there, `CreateActionBase.scala:110-111`,
`JoinIndexRule.scala:124-153`); here the "cluster" is the set of visible
jax devices. `spark.hyperspace.distribution.enabled`:

- "auto" (default): distribute when more than one device is visible;
- "true": distribute (no-op on a single device — there is no mesh to use);
- "false": always single-chip.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

from hyperspace_tpu import constants

# Replica scope: when a query has been routed to a replica slice
# (`parallel/replica.py` via the scheduler), every distribution decision
# under the scope sees THAT slice's flat submesh instead of the full
# multi-slice mesh — fills land on the slice's devices, the SPMD
# programs run over the slice, and the flat (PR-13) execution path
# applies verbatim. A contextvar so the scope follows the query across
# `telemetry.propagating` pool threads like the recorder/deadline do.
_replica_slice: contextvars.ContextVar = contextvars.ContextVar(
    "hs_replica_slice", default=None)


def active_replica() -> Optional[int]:
    """The replica slice index the current context is pinned to, or
    None (execute over the full mesh)."""
    return _replica_slice.get()


@contextlib.contextmanager
def replica_scope(slice_idx: Optional[int]):
    """Pin distribution decisions in this context to replica
    `slice_idx` (None = no pin; the scope is then a no-op)."""
    if slice_idx is None:
        yield
        return
    token = _replica_slice.set(int(slice_idx))
    try:
        yield
    finally:
        _replica_slice.reset(token)


def topology(conf=None):
    """(n_slices, n_ici) of the configured topology, or None when fewer
    than two devices are visible / distribution is off. n_slices folds
    back to 1 when the knob does not divide the device count."""
    mode = conf.distribution if conf is not None else "auto"
    if mode == "false":
        return None
    import jax

    try:
        devices = jax.devices()
    except RuntimeError:
        return None
    n = len(devices)
    if n < 2:
        return None
    slices = (conf.distribution_slices if conf is not None
              else constants.DISTRIBUTION_DCN_SIZE_DEFAULT)
    if slices > 1 and n % slices != 0:
        import logging
        logging.getLogger(__name__).warning(
            "distribution.slices=%d does not divide the %d visible "
            "devices; falling back to a FLAT mesh — re-bucket "
            "collectives will span DCN.", slices, n)
        slices = 1
    slices = max(1, slices)
    return slices, n // slices


def distribution_mesh(conf=None):
    """The mesh to distribute over, or None for single-chip execution.
    Under an active replica scope on a multi-slice topology, the
    pinned slice's FLAT submesh is returned — the one seam through
    which replica routing confines a query's fills and execution."""
    topo = topology(conf)
    if topo is None:
        return None
    slices, ici = topo
    from hyperspace_tpu.parallel.mesh import make_mesh, slice_submesh

    mesh = make_mesh(slices * ici, dcn_size=slices if slices > 1 else None)
    replica = active_replica()
    if replica is not None and slices > 1:
        return slice_submesh(mesh, replica % slices)
    return mesh


def mesh_size(mesh) -> int:
    """TOTAL device count of the mesh (both axes of a (dcn, shard) mesh)."""
    from hyperspace_tpu.parallel.mesh import total_shards

    return total_shards(mesh)


def should_distribute(conf, num_rows: Optional[int] = None,
                      host_batch: bool = False):
    """Mesh to use for this operation, or None. In "auto" mode small
    batches stay single-chip — per-shard padding plus collective latency
    dwarfs the work below `distribution.min.rows` — and HOST-lane batches
    stay on the host (they avoided the device link on purpose;
    distributing would pay it anyway). "true" distributes regardless
    (tests use this to exercise the mesh paths). This is THE policy seam:
    every operator with a mesh path answers the question here."""
    mesh = distribution_mesh(conf)
    if mesh is None:
        return None
    mode = conf.distribution if conf is not None else "auto"
    if mode == "auto" and host_batch:
        return None
    min_rows = (conf.distribution_min_rows if conf is not None
                else constants.DISTRIBUTION_MIN_ROWS_DEFAULT)
    if mode == "auto" and num_rows is not None and num_rows < min_rows:
        return None
    return mesh
