"""TPC-H on the framework DataFrame API.

The reference pins "all TPC-H and TPC-DS queries serializable" through its
plan layer (`index/serde/package.scala:46-49`); here the 22 TPC-H queries
run end to end — built, optimized (index rules), executed — with pandas
oracles asserting 3-way result equality, the same contract the TPC-DS
suite carries.
"""

from hyperspace_tpu.tpch.generator import generate  # noqa: F401
from hyperspace_tpu.tpch.queries import QUERIES  # noqa: F401
