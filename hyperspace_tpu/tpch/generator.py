"""Deterministic TPC-H generator.

All 8 tables with the columns the 22 queries touch, at a row scale
controlled by `scale` (scale=1.0 ~ SF0.01 fact rows). Value distributions
are synthetic but respect the official join topology and value grammars
the query predicates probe: every (l_partkey, l_suppkey) pair exists in
partsupp, o_orderstatus is derived from the order's line statuses, phone
country codes are `10 + nationkey` (q22), p_type is the official
<quality> <finish> <metal> grammar (q2/q8/q16 LIKE probes), a third of
customers never order (q22's anti join), and some order/supplier comments
carry the `%special%requests%` / `%Customer%Complaints%` needles
(q13/q16).

Everything is seeded — same scale, same bytes. Dates are arrow date32.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)


def days(y: int, m: int, d: int) -> int:
    """date32 value (days since epoch) of a calendar date."""
    return (datetime.date(y, m, d) - _EPOCH).days


_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
# The official 25 nations with their region keys.
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

_QUALITIES = ["ECONOMY", "STANDARD", "PROMO", "MEDIUM", "LARGE", "SMALL"]
_FINISHES = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_METALS = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINERS = ["%s %s" % (a, b)
               for a in ("SM", "MED", "LG", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "PKG", "JAR", "PACK",
                         "CAN", "DRUM")]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
             "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
               "5-LOW"]
_MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
             "TAKE BACK RETURN"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "blanched", "blue", "blush", "brown", "burlywood", "burnished",
           "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
           "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
           "firebrick", "floral", "forest", "frosted", "gainsboro",
           "ghost", "goldenrod", "green", "grey", "honeydew", "hot",
           "indian", "ivory", "khaki", "lace", "lavender"]


def generate(out_dir: str, scale: float = 1.0,
             seed: int = 20260730) -> Dict[str, str]:
    """Write the 8 tables as parquet dirs under `out_dir`; returns
    {table: path}. Idempotent for a given (out_dir, scale, seed)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_part = max(int(400 * scale), 100)
    n_supp = max(int(100 * scale), 40)
    n_cust = max(int(1500 * scale), 300)
    n_ord = n_cust * 10

    tables: Dict[str, dict] = {}
    tables["region"] = {
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": np.array(_REGIONS),
        "r_comment": np.array(["" for _ in _REGIONS]),
    }
    tables["nation"] = {
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": np.array([n for n, _ in _NATIONS]),
        "n_regionkey": np.asarray([r for _, r in _NATIONS],
                                  dtype=np.int64),
    }

    # Round-robin nations (7 coprime with 25 -> full cycle): every nation
    # has suppliers at any scale, so the nation-probing queries
    # (q7 FR/DE, q11 DE, q20 CA, q21 SA) never see an empty side.
    s_nation = ((np.arange(n_supp) * 7) % 25).astype(np.int64)
    tables["supplier"] = {
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": np.array(["Supplier#%09d" % i for i in range(1, n_supp + 1)]),
        "s_address": np.array(["addr s%d" % i for i in range(n_supp)]),
        "s_nationkey": s_nation,
        "s_phone": np.array(["%02d-%03d-%03d-%04d"
                             % (10 + k, 100 + 7 * i % 900,
                                100 + 13 * i % 900, 1000 + 17 * i % 9000)
                             for i, k in enumerate(s_nation)]),
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
        # Every 13th supplier carries the q16 complaints needle.
        "s_comment": np.array([
            "x Customer stuff Complaints y" if i % 13 == 0
            else "supplier note %d" % i for i in range(n_supp)]),
    }

    c_nation = ((np.arange(n_cust) * 11) % 25).astype(np.int64)
    tables["customer"] = {
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": np.array(["Customer#%09d" % i
                            for i in range(1, n_cust + 1)]),
        "c_address": np.array(["addr c%d" % i for i in range(n_cust)]),
        "c_nationkey": c_nation,
        "c_phone": np.array(["%02d-%03d-%03d-%04d"
                             % (10 + k, 100 + 11 * i % 900,
                                100 + 23 * i % 900, 1000 + 29 * i % 9000)
                             for i, k in enumerate(c_nation)]),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
        "c_mktsegment": np.array([_SEGMENTS[i % 5] for i in range(n_cust)]),
        "c_comment": np.array(["customer note %d" % i
                               for i in range(n_cust)]),
    }

    p_name = np.array([" ".join([
        _COLORS[(3 * i) % len(_COLORS)], _COLORS[(7 * i + 1) % len(_COLORS)],
        _COLORS[(11 * i + 2) % len(_COLORS)]]) for i in range(n_part)])
    p_type = np.array(["%s %s %s" % (_QUALITIES[i % 6],
                                     _FINISHES[(i // 6) % 5],
                                     _METALS[(i // 30) % 5])
                       for i in range(n_part)])
    p_container = np.array([_CONTAINERS[i % len(_CONTAINERS)]
                            for i in range(n_part)])
    p_size = (1 + np.arange(n_part) % 50).astype(np.int64)
    # The (brand, container, size) triples q17/q19 probe cannot co-occur
    # through the 25/40/50 cycles (shared factors make the residues
    # incompatible) — plant each bracket on a slice of its brand's parts:
    # i=5 mod 25 is Brand#12, 11 mod 25 Brand#23, 17 mod 25 Brand#34.
    idx = np.arange(n_part)
    for residue, container, size in ((5, "SM PACK", 3),
                                     (11, "MED BOX", 7),
                                     (17, "LG BOX", 9)):
        m = idx % 100 == residue
        p_container[m] = container
        p_size[m] = size
    tables["part"] = {
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": p_name,
        "p_mfgr": np.array(["Manufacturer#%d" % (1 + i % 5)
                            for i in range(n_part)]),
        "p_brand": np.array(["Brand#%d%d" % (1 + i % 5, 1 + (i // 5) % 5)
                             for i in range(n_part)]),
        "p_type": p_type,
        # Deterministic 1..50 cycle (q2 BRASS+15, q16's size list) with
        # the q17/q19 bracket plants above.
        "p_size": p_size,
        "p_container": p_container,
        "p_retailprice": np.round(900 + rng.uniform(0, 1200, n_part), 2),
    }

    # partsupp: each part supplied by 4 suppliers (official fanout).
    ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    ps_supp = np.zeros(n_part * 4, dtype=np.int64)
    for j in range(4):
        ps_supp[j::4] = 1 + (np.arange(n_part) * 7 + j * (n_supp // 4 + 1)) \
            % n_supp
    # Dedup within a part (small n_supp could collide): nudge duplicates.
    ps_supp = ps_supp.reshape(n_part, 4)
    for j in range(1, 4):
        same = (ps_supp[:, j:j + 1] == ps_supp[:, :j]).any(axis=1)
        while same.any():
            ps_supp[same, j] = ps_supp[same, j] % n_supp + 1
            same = (ps_supp[:, j:j + 1] == ps_supp[:, :j]).any(axis=1)
    # q20's chain (forest part -> CANADA supplier with excess stock) must
    # be non-degenerate at every scale: give each forest-named part one
    # CANADA supplier (linear supplier formulas collapse to one supplier
    # set for all i = 22 mod 40 parts, which can miss CANADA entirely).
    canada_key = next(k for k, (n_, _r) in enumerate(_NATIONS)
                      if n_ == "CANADA")
    canada_supp = 1 + int(np.nonzero(s_nation == canada_key)[0][0])
    forest = np.nonzero(np.char.startswith(p_name.astype(str),
                                           "forest"))[0]
    ps_supp = ps_supp.reshape(n_part, 4)
    for i in forest:
        if canada_supp not in ps_supp[i]:
            ps_supp[i, 0] = canada_supp
    ps_supp = ps_supp.reshape(-1)
    tables["partsupp"] = {
        "ps_partkey": ps_part,
        "ps_suppkey": ps_supp,
        "ps_availqty": (500 + rng.integers(0, 9500,
                                           n_part * 4)).astype(np.int64),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_part * 4), 2),
    }

    # orders: only the first 2/3 of customers ever order (q22's anti join
    # needs order-less customers).
    ordering_cust = np.arange(1, max(2 * n_cust // 3, 1) + 1)
    o_cust = rng.choice(ordering_cust, n_ord).astype(np.int64)
    lo, hi = days(1992, 1, 1), days(1998, 8, 2)
    o_date = rng.integers(lo, hi + 1, n_ord).astype(np.int32)
    o_key = np.arange(1, n_ord + 1, dtype=np.int64)
    tables["orders"] = {
        "o_orderkey": o_key,
        "o_custkey": o_cust,
        "o_orderdate": o_date,
        "o_orderpriority": np.array([_PRIORITIES[i % 5]
                                     for i in range(n_ord)]),
        "o_clerk": np.array(["Clerk#%09d" % (1 + i % 1000)
                             for i in range(n_ord)]),
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        # Every 11th order carries the q13 needle.
        "o_comment": np.array([
            "was special handling requests done" if i % 11 == 0
            else "order note %d" % i for i in range(n_ord)]),
    }

    # lineitem: 1..8 lines per order; (partkey, suppkey) drawn FROM
    # partsupp so q9's ps join always resolves.
    n_lines_per = rng.integers(1, 9, n_ord)
    n_li = int(n_lines_per.sum())
    l_order = np.repeat(o_key, n_lines_per)
    l_odate = np.repeat(o_date, n_lines_per)
    ps_pick = rng.integers(0, n_part * 4, n_li)
    l_part = ps_part[ps_pick]
    l_supp = ps_supp[ps_pick]
    l_qty = (1 + rng.integers(0, 50, n_li)).astype(np.int64)
    price = np.round(rng.uniform(900, 2100, n_li), 2)
    l_ship = (l_odate + rng.integers(1, 122, n_li)).astype(np.int32)
    l_commit = (l_odate + rng.integers(30, 91, n_li)).astype(np.int32)
    l_receipt = (l_ship + rng.integers(1, 31, n_li)).astype(np.int32)
    cutoff = days(1995, 6, 17)
    l_status = np.where(l_ship > cutoff, "O", "F")
    l_return = np.where(l_receipt <= cutoff,
                        np.where(rng.random(n_li) < 0.5, "R", "A"), "N")
    linenumber = np.concatenate([np.arange(1, k + 1)
                                 for k in n_lines_per]).astype(np.int64)
    tables["lineitem"] = {
        "l_orderkey": l_order,
        "l_partkey": l_part,
        "l_suppkey": l_supp,
        "l_linenumber": linenumber,
        "l_quantity": l_qty,
        "l_extendedprice": np.round(l_qty * price / 10.0, 2),
        "l_discount": np.round(rng.integers(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(rng.integers(0, 9, n_li) / 100.0, 2),
        "l_returnflag": l_return,
        "l_linestatus": l_status,
        "l_shipdate": l_ship,
        "l_commitdate": l_commit,
        "l_receiptdate": l_receipt,
        "l_shipinstruct": np.array([_INSTRUCT[i % 4] for i in range(n_li)]),
        "l_shipmode": np.array([_MODES[i % 7] for i in range(n_li)]),
    }

    # o_totalprice / o_orderstatus derived from the lines (official
    # consistency): status F iff every line F, O iff every line O, else P.
    per_order_price = np.zeros(n_ord)
    np.add.at(per_order_price, l_order - 1,
              tables["lineitem"]["l_extendedprice"])
    f_cnt = np.zeros(n_ord, dtype=np.int64)
    np.add.at(f_cnt, l_order - 1, (l_status == "F").astype(np.int64))
    status = np.where(f_cnt == n_lines_per, "F",
                      np.where(f_cnt == 0, "O", "P"))
    tables["orders"]["o_totalprice"] = np.round(per_order_price, 2)
    tables["orders"]["o_orderstatus"] = status

    date_cols = {"o_orderdate", "l_shipdate", "l_commitdate",
                 "l_receiptdate"}
    paths: Dict[str, str] = {}
    for name, cols in tables.items():
        path = os.path.join(out_dir, name)
        paths[name] = path
        if os.path.isdir(path) and os.listdir(path):
            continue  # already generated (deterministic)
        os.makedirs(path, exist_ok=True)
        arrays = {}
        for cname, values in cols.items():
            if cname in date_cols:
                arrays[cname] = pa.array(values.astype(np.int32),
                                         type=pa.date32())
            else:
                arrays[cname] = pa.array(values)
        pq.write_table(pa.table(arrays), os.path.join(path,
                                                      "part-0.parquet"))
    return paths
