"""The 22 TPC-H queries on the framework DataFrame API, with pandas
oracles.

Shapes follow the official SQL: expression aggregates (q1), correlated
scalar subqueries as aggregate+join-back (q2/q15/q17/q20), EXISTS /
NOT EXISTS as semi/anti joins (q4/q16/q22), scalar totals via cross join
(q11), LIKE predicates in dictionary space (q2/q9/q13/q14/q16/q20),
CASE pivots (q8/q12/q14), and multi-supplier order logic expressed as
per-order distinct-supplier aggregates (q21 — `exists l2 / not exists
l3` is exactly "the order has >= 2 distinct suppliers and only one
distinct supplier among its late lines").

EXTRACT(year) compiles to a CASE WHEN chain over date32 literals — the
engine stores dates as day ordinals, so the year boundaries are plain
integer comparisons (no date kernel needed).

Queries whose official ORDER BY does not totally order rows append a
deterministic key to BOTH lanes (q3/q10/q18: the 3-way equality check
needs a stable top-N; the TPC-DS suite does the same for q79).

Each oracle doubles as the CPU baseline; `tests/test_tpch.py` and
`bench_tpch.py` assert rules-on == rules-off == oracle — the reference's
E2E guarantee (`E2EHyperspaceRulesTests.scala:330-346`) across the full
TPC-H set its serde layer pins (`index/serde/package.scala:46-49`).
"""

from __future__ import annotations

import datetime
from typing import Callable, Dict, Tuple

import numpy as np
import pandas as pd

from hyperspace_tpu.plan.expr import col, lit, when
from hyperspace_tpu.tpch.generator import days

_EPOCH = datetime.date(1970, 1, 1)


def normalize_result(df: pd.DataFrame) -> pd.DataFrame:
    """THE result-normalization contract the 3-way equality checks use
    (tests + bench): stringify non-str object columns (date objects),
    sort by every column, widen numerics to float64."""
    out = df.copy()
    for c in out.columns:
        if out[c].dtype == object and len(out) and not isinstance(
                out[c].iloc[0], str):
            out[c] = out[c].astype(str)
    out = out.sort_values(list(out.columns)).reset_index(drop=True)
    return out.astype({c: "float64" for c in out.columns
                       if out[c].dtype.kind in "fi"})


def _date(y, m, d):
    return datetime.date(y, m, d)


def _year(s):
    return pd.to_datetime(s).dt.year


def _volume():
    return col("l_extendedprice") * (lit(1.0) - col("l_discount"))


def _year_expr(name: str):
    """EXTRACT(year) over a date32 column as a CASE chain (data years are
    1992..1998)."""
    e = when(col(name) < lit(days(1993, 1, 1)), 1992)
    for y in range(1993, 1999):
        e = e.when(col(name) < lit(days(y + 1, 1, 1)), y)
    return e.otherwise(1999)


# ---------------------------------------------------------------------------
# q1 — pricing summary report
# ---------------------------------------------------------------------------


def q1(dfs):
    li = dfs["lineitem"].filter(
        col("l_shipdate") <= lit(days(1998, 9, 2)))
    disc = _volume()
    charge = (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
              * (lit(1.0) + col("l_tax")))
    return (li.group_by("l_returnflag", "l_linestatus").agg(
        ("sum", "l_quantity", "sum_qty"),
        ("sum", "l_extendedprice", "sum_base_price"),
        ("sum", disc, "sum_disc_price"),
        ("sum", charge, "sum_charge"),
        ("avg", "l_quantity", "avg_qty"),
        ("avg", "l_extendedprice", "avg_price"),
        ("avg", "l_discount", "avg_disc"),
        ("count", "*", "count_order"))
        .sort("l_returnflag", "l_linestatus"))


def q1_pandas(t):
    li = t["lineitem"]
    li = li[li.l_shipdate <= _date(1998, 9, 2)].copy()
    li["disc_price"] = li.l_extendedprice * (1 - li.l_discount)
    li["charge"] = li.disc_price * (1 + li.l_tax)
    g = li.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size")).reset_index()
    return g.sort_values(["l_returnflag", "l_linestatus"]) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# q2 — minimum cost supplier (correlated scalar subquery -> join-back)
# ---------------------------------------------------------------------------


def q2(dfs):
    part = (dfs["part"]
            .filter((col("p_size") == lit(15))
                    & col("p_type").like("%BRASS"))
            .select("p_partkey", "p_mfgr"))
    region = dfs["region"].filter(col("r_name") == lit("EUROPE")) \
        .select("r_regionkey")
    nation = dfs["nation"].select("n_nationkey", "n_name", "n_regionkey")
    nation = nation.join(region, on=col("n_regionkey") == col("r_regionkey")) \
        .select("n_nationkey", "n_name")
    supp = dfs["supplier"].select(
        "s_suppkey", "s_name", "s_address", "s_nationkey", "s_phone",
        "s_acctbal", "s_comment")
    supp = supp.join(nation, on=col("s_nationkey") == col("n_nationkey")) \
        .select("s_suppkey", "s_name", "s_address", "s_phone", "s_acctbal",
                "s_comment", "n_name")
    ps = dfs["partsupp"].select("ps_partkey", "ps_suppkey", "ps_supplycost")
    ps_eu = ps.join(supp, on=col("ps_suppkey") == col("s_suppkey"))
    mincost = (ps_eu.group_by("ps_partkey")
               .agg(("min", "ps_supplycost", "min_cost")))
    j = part.join(ps_eu, on=col("p_partkey") == col("ps_partkey"))
    j = j.join(mincost, on=(col("ps_partkey") == col("ps_partkey"))
               & (col("ps_supplycost") == col("min_cost")))
    return (j.select("s_acctbal", "s_name", "n_name", "p_partkey",
                     "p_mfgr", "s_address", "s_phone", "s_comment")
            .sort("-s_acctbal", "n_name", "s_name", "p_partkey")
            .limit(100))


def q2_pandas(t):
    part = t["part"]
    part = part[(part.p_size == 15)
                & part.p_type.str.endswith("BRASS")][
        ["p_partkey", "p_mfgr"]]
    region = t["region"][t["region"].r_name == "EUROPE"][["r_regionkey"]]
    nation = t["nation"].merge(region, left_on="n_regionkey",
                               right_on="r_regionkey")[
        ["n_nationkey", "n_name"]]
    supp = t["supplier"].merge(nation, left_on="s_nationkey",
                               right_on="n_nationkey")
    ps = t["partsupp"].merge(supp, left_on="ps_suppkey",
                             right_on="s_suppkey")
    mincost = ps.groupby("ps_partkey", as_index=False).agg(
        min_cost=("ps_supplycost", "min"))
    j = part.merge(ps, left_on="p_partkey", right_on="ps_partkey")
    j = j.merge(mincost, on="ps_partkey")
    j = j[j.ps_supplycost == j.min_cost]
    return (j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
               "s_address", "s_phone", "s_comment"]]
            .sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                         ascending=[False, True, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q3 — shipping priority (top unshipped orders)
# ---------------------------------------------------------------------------


def q3(dfs):
    cust = dfs["customer"].filter(
        col("c_mktsegment") == lit("BUILDING")).select("c_custkey")
    orders = dfs["orders"].filter(
        col("o_orderdate") < lit(days(1995, 3, 15))).select(
        "o_orderkey", "o_custkey", "o_orderdate", "o_shippriority")
    li = dfs["lineitem"].filter(
        col("l_shipdate") > lit(days(1995, 3, 15))).select(
        "l_orderkey", "l_extendedprice", "l_discount")
    j = orders.join(cust, on=col("o_custkey") == col("c_custkey"))
    j = li.join(j, on=col("l_orderkey") == col("o_orderkey"))
    return (j.group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(("sum", _volume(), "revenue"))
            .sort("-revenue", "o_orderdate", "l_orderkey").limit(10))


def q3_pandas(t):
    cust = t["customer"]
    cust = cust[cust.c_mktsegment == "BUILDING"][["c_custkey"]]
    orders = t["orders"]
    orders = orders[orders.o_orderdate < _date(1995, 3, 15)]
    li = t["lineitem"]
    li = li[li.l_shipdate > _date(1995, 3, 15)].copy()
    li["revenue"] = li.l_extendedprice * (1 - li.l_discount)
    j = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
    j = li.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False).agg(revenue=("revenue", "sum"))
    return (g.sort_values(["revenue", "o_orderdate", "l_orderkey"],
                          ascending=[False, True, True])
            .head(10).reset_index(drop=True)
            [["l_orderkey", "o_orderdate", "o_shippriority", "revenue"]])


# ---------------------------------------------------------------------------
# q4 — order priority checking (EXISTS -> semi join)
# ---------------------------------------------------------------------------


def q4(dfs):
    orders = dfs["orders"].filter(
        (col("o_orderdate") >= lit(days(1993, 7, 1)))
        & (col("o_orderdate") < lit(days(1993, 10, 1)))).select(
        "o_orderkey", "o_orderpriority")
    late = dfs["lineitem"].filter(
        col("l_commitdate") < col("l_receiptdate")).select("l_orderkey")
    j = orders.join(late, on=col("o_orderkey") == col("l_orderkey"),
                    how="left_semi")
    return (j.group_by("o_orderpriority")
            .agg(("count", "*", "order_count")).sort("o_orderpriority"))


def q4_pandas(t):
    orders = t["orders"]
    orders = orders[(orders.o_orderdate >= _date(1993, 7, 1))
                    & (orders.o_orderdate < _date(1993, 10, 1))]
    li = t["lineitem"]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    j = orders[orders.o_orderkey.isin(late)]
    g = j.groupby("o_orderpriority", as_index=False).agg(
        order_count=("o_orderkey", "size"))
    return g.sort_values("o_orderpriority").reset_index(drop=True)


# ---------------------------------------------------------------------------
# q5 — local supplier volume
# ---------------------------------------------------------------------------


def q5(dfs):
    region = dfs["region"].filter(col("r_name") == lit("ASIA")) \
        .select("r_regionkey")
    nation = dfs["nation"].join(
        region, on=col("n_regionkey") == col("r_regionkey")).select(
        "n_nationkey", "n_name")
    orders = dfs["orders"].filter(
        (col("o_orderdate") >= lit(days(1994, 1, 1)))
        & (col("o_orderdate") < lit(days(1995, 1, 1)))).select(
        "o_orderkey", "o_custkey")
    cust = dfs["customer"].select("c_custkey", "c_nationkey")
    li = dfs["lineitem"].select("l_orderkey", "l_suppkey",
                                "l_extendedprice", "l_discount")
    supp = dfs["supplier"].select("s_suppkey", "s_nationkey")
    j = orders.join(cust, on=col("o_custkey") == col("c_custkey"))
    j = li.join(j, on=col("l_orderkey") == col("o_orderkey"))
    j = j.join(supp, on=(col("l_suppkey") == col("s_suppkey"))
               & (col("c_nationkey") == col("s_nationkey")))
    j = j.join(nation, on=col("s_nationkey") == col("n_nationkey"))
    return (j.group_by("n_name").agg(("sum", _volume(), "revenue"))
            .sort("-revenue"))


def q5_pandas(t):
    region = t["region"][t["region"].r_name == "ASIA"][["r_regionkey"]]
    nation = t["nation"].merge(region, left_on="n_regionkey",
                               right_on="r_regionkey")[
        ["n_nationkey", "n_name"]]
    orders = t["orders"]
    orders = orders[(orders.o_orderdate >= _date(1994, 1, 1))
                    & (orders.o_orderdate < _date(1995, 1, 1))]
    j = orders.merge(t["customer"], left_on="o_custkey",
                     right_on="c_custkey")
    j = t["lineitem"].merge(j, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(t["supplier"], left_on=["l_suppkey", "c_nationkey"],
                right_on=["s_suppkey", "s_nationkey"])
    j = j.merge(nation, left_on="s_nationkey", right_on="n_nationkey")
    j = j.assign(revenue=j.l_extendedprice * (1 - j.l_discount))
    g = j.groupby("n_name", as_index=False).agg(revenue=("revenue", "sum"))
    return g.sort_values("revenue", ascending=False).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q6 — forecasting revenue change (pure filter aggregate)
# ---------------------------------------------------------------------------


def q6(dfs):
    li = dfs["lineitem"].filter(
        (col("l_shipdate") >= lit(days(1994, 1, 1)))
        & (col("l_shipdate") < lit(days(1995, 1, 1)))
        & col("l_discount").between(lit(0.05), lit(0.07))
        & (col("l_quantity") < lit(24)))
    return li.agg(("sum", col("l_extendedprice") * col("l_discount"),
                   "revenue"))


def q6_pandas(t):
    li = t["lineitem"]
    m = ((li.l_shipdate >= _date(1994, 1, 1))
         & (li.l_shipdate < _date(1995, 1, 1))
         & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
         & (li.l_quantity < 24))
    return pd.DataFrame(
        {"revenue": [(li[m].l_extendedprice * li[m].l_discount).sum()]})


# ---------------------------------------------------------------------------
# q7 — volume shipping between two nations
# ---------------------------------------------------------------------------


def q7(dfs):
    pair = col("n_name").isin("FRANCE", "GERMANY")
    n1 = dfs["nation"].filter(pair).select("n_nationkey", "n_name")
    n2 = dfs["nation"].filter(pair).select("n_nationkey", "n_name")
    li = dfs["lineitem"].filter(
        col("l_shipdate").between(lit(days(1995, 1, 1)),
                                  lit(days(1996, 12, 31)))).select(
        "l_orderkey", "l_suppkey", "l_shipdate", "l_extendedprice",
        "l_discount")
    j = li.join(dfs["supplier"].select("s_suppkey", "s_nationkey"),
                on=col("l_suppkey") == col("s_suppkey"))
    j = j.join(dfs["orders"].select("o_orderkey", "o_custkey"),
               on=col("l_orderkey") == col("o_orderkey"))
    j = j.join(dfs["customer"].select("c_custkey", "c_nationkey"),
               on=col("o_custkey") == col("c_custkey"))
    j = j.join(n1, on=col("s_nationkey") == col("n_nationkey"))
    j = j.join(n2, on=col("c_nationkey") == col("n_nationkey"))
    # Only FR/DE rows survive, so "pair in {(FR,DE),(DE,FR)}" == inequality.
    j = j.filter(col("n_name") != col("n_name_r"))
    j = j.select(col("n_name").alias("supp_nation"),
                 col("n_name_r").alias("cust_nation"),
                 _year_expr("l_shipdate").alias("l_year"),
                 _volume().alias("volume"))
    return (j.group_by("supp_nation", "cust_nation", "l_year")
            .agg(("sum", "volume", "revenue"))
            .sort("supp_nation", "cust_nation", "l_year"))


def q7_pandas(t):
    n = t["nation"][t["nation"].n_name.isin(["FRANCE", "GERMANY"])]
    li = t["lineitem"]
    li = li[(li.l_shipdate >= _date(1995, 1, 1))
            & (li.l_shipdate <= _date(1996, 12, 31))]
    j = li.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey",
                right_on="n_nationkey")
    j = j.merge(n[["n_nationkey", "n_name"]], left_on="c_nationkey",
                right_on="n_nationkey", suffixes=("", "_r"))
    j = j[j.n_name != j.n_name_r].copy()
    j["supp_nation"] = j.n_name
    j["cust_nation"] = j.n_name_r
    j["l_year"] = _year(j.l_shipdate)
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["supp_nation", "cust_nation", "l_year"],
                  as_index=False).agg(revenue=("volume", "sum"))
    return g.sort_values(["supp_nation", "cust_nation", "l_year"]) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# q8 — national market share
# ---------------------------------------------------------------------------


def q8(dfs):
    region = dfs["region"].filter(col("r_name") == lit("AMERICA")) \
        .select("r_regionkey")
    n1 = dfs["nation"].join(
        region, on=col("n_regionkey") == col("r_regionkey")).select(
        "n_nationkey")
    n2 = dfs["nation"].select("n_nationkey", "n_name")
    part = dfs["part"].filter(
        col("p_type") == lit("ECONOMY ANODIZED STEEL")).select("p_partkey")
    orders = dfs["orders"].filter(
        col("o_orderdate").between(lit(days(1995, 1, 1)),
                                   lit(days(1996, 12, 31)))).select(
        "o_orderkey", "o_custkey", "o_orderdate")
    li = dfs["lineitem"].select("l_orderkey", "l_partkey", "l_suppkey",
                                "l_extendedprice", "l_discount")
    j = li.join(part, on=col("l_partkey") == col("p_partkey"))
    j = j.join(orders, on=col("l_orderkey") == col("o_orderkey"))
    j = j.join(dfs["customer"].select("c_custkey", "c_nationkey"),
               on=col("o_custkey") == col("c_custkey"))
    j = j.join(n1, on=col("c_nationkey") == col("n_nationkey"))
    j = j.join(dfs["supplier"].select("s_suppkey", "s_nationkey"),
               on=col("l_suppkey") == col("s_suppkey"))
    j = j.join(n2, on=col("s_nationkey") == col("n_nationkey"))
    j = j.select(_year_expr("o_orderdate").alias("o_year"),
                 _volume().alias("volume"), "n_name")
    brazil = when(col("n_name") == lit("BRAZIL"), col("volume")) \
        .otherwise(0.0)
    g = j.group_by("o_year").agg(("sum", brazil, "brazil_volume"),
                                 ("sum", "volume", "total_volume"))
    return (g.select("o_year",
                     (col("brazil_volume") / col("total_volume"))
                     .alias("mkt_share")).sort("o_year"))


def q8_pandas(t):
    region = t["region"][t["region"].r_name == "AMERICA"][["r_regionkey"]]
    n1 = t["nation"].merge(region, left_on="n_regionkey",
                           right_on="r_regionkey")[["n_nationkey"]]
    part = t["part"][t["part"].p_type == "ECONOMY ANODIZED STEEL"][
        ["p_partkey"]]
    orders = t["orders"]
    orders = orders[(orders.o_orderdate >= _date(1995, 1, 1))
                    & (orders.o_orderdate <= _date(1996, 12, 31))]
    j = t["lineitem"].merge(part, left_on="l_partkey",
                            right_on="p_partkey")
    j = j.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(n1, left_on="c_nationkey", right_on="n_nationkey")
    j = j.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(t["nation"][["n_nationkey", "n_name"]],
                left_on="s_nationkey", right_on="n_nationkey")
    j = j.assign(o_year=_year(j.o_orderdate),
                 volume=j.l_extendedprice * (1 - j.l_discount))
    g = j.groupby("o_year", as_index=False).apply(
        lambda x: pd.Series({
            "mkt_share": (x[x.n_name == "BRAZIL"].volume.sum()
                          / x.volume.sum())}), include_groups=False)
    return g.sort_values("o_year").reset_index(drop=True)


# ---------------------------------------------------------------------------
# q9 — product type profit measure
# ---------------------------------------------------------------------------


def q9(dfs):
    part = dfs["part"].filter(col("p_name").like("%green%")) \
        .select("p_partkey")
    li = dfs["lineitem"].select("l_orderkey", "l_partkey", "l_suppkey",
                                "l_quantity", "l_extendedprice",
                                "l_discount")
    j = li.join(part, on=col("l_partkey") == col("p_partkey"))
    j = j.join(dfs["supplier"].select("s_suppkey", "s_nationkey"),
               on=col("l_suppkey") == col("s_suppkey"))
    j = j.join(dfs["partsupp"].select("ps_partkey", "ps_suppkey",
                                      "ps_supplycost"),
               on=(col("l_suppkey") == col("ps_suppkey"))
               & (col("l_partkey") == col("ps_partkey")))
    j = j.join(dfs["orders"].select("o_orderkey", "o_orderdate"),
               on=col("l_orderkey") == col("o_orderkey"))
    j = j.join(dfs["nation"].select("n_nationkey", "n_name"),
               on=col("s_nationkey") == col("n_nationkey"))
    amount = (_volume()
              - col("ps_supplycost") * col("l_quantity"))
    j = j.select(col("n_name").alias("nation"),
                 _year_expr("o_orderdate").alias("o_year"),
                 amount.alias("amount"))
    return (j.group_by("nation", "o_year")
            .agg(("sum", "amount", "sum_profit"))
            .sort("nation", "-o_year"))


def q9_pandas(t):
    part = t["part"][t["part"].p_name.str.contains("green")][["p_partkey"]]
    j = t["lineitem"].merge(part, left_on="l_partkey",
                            right_on="p_partkey")
    j = j.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(t["partsupp"], left_on=["l_suppkey", "l_partkey"],
                right_on=["ps_suppkey", "ps_partkey"])
    j = j.merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
    j = j.assign(nation=j.n_name, o_year=_year(j.o_orderdate),
                 amount=j.l_extendedprice * (1 - j.l_discount)
                 - j.ps_supplycost * j.l_quantity)
    g = j.groupby(["nation", "o_year"], as_index=False).agg(
        sum_profit=("amount", "sum"))
    return g.sort_values(["nation", "o_year"],
                         ascending=[True, False]).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q10 — returned item reporting
# ---------------------------------------------------------------------------


def q10(dfs):
    orders = dfs["orders"].filter(
        (col("o_orderdate") >= lit(days(1993, 10, 1)))
        & (col("o_orderdate") < lit(days(1994, 1, 1)))).select(
        "o_orderkey", "o_custkey")
    li = dfs["lineitem"].filter(col("l_returnflag") == lit("R")).select(
        "l_orderkey", "l_extendedprice", "l_discount")
    j = li.join(orders, on=col("l_orderkey") == col("o_orderkey"))
    j = j.join(dfs["customer"].select(
        "c_custkey", "c_name", "c_acctbal", "c_phone", "c_nationkey",
        "c_address", "c_comment"),
        on=col("o_custkey") == col("c_custkey"))
    j = j.join(dfs["nation"].select("n_nationkey", "n_name"),
               on=col("c_nationkey") == col("n_nationkey"))
    return (j.group_by("c_custkey", "c_name", "c_acctbal", "c_phone",
                       "n_name", "c_address", "c_comment")
            .agg(("sum", _volume(), "revenue"))
            .sort("-revenue", "c_custkey").limit(20))


def q10_pandas(t):
    orders = t["orders"]
    orders = orders[(orders.o_orderdate >= _date(1993, 10, 1))
                    & (orders.o_orderdate < _date(1994, 1, 1))]
    li = t["lineitem"]
    li = li[li.l_returnflag == "R"]
    j = li.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(t["nation"], left_on="c_nationkey", right_on="n_nationkey")
    j = j.assign(revenue=j.l_extendedprice * (1 - j.l_discount))
    g = j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone",
                   "n_name", "c_address", "c_comment"],
                  as_index=False).agg(revenue=("revenue", "sum"))
    return (g.sort_values(["revenue", "c_custkey"],
                          ascending=[False, True])
            .head(20).reset_index(drop=True)
            [["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
              "c_address", "c_comment", "revenue"]])


# ---------------------------------------------------------------------------
# q11 — important stock identification (scalar total via cross join)
# ---------------------------------------------------------------------------


def q11(dfs):
    nation = dfs["nation"].filter(col("n_name") == lit("GERMANY")) \
        .select("n_nationkey")
    supp = dfs["supplier"].select("s_suppkey", "s_nationkey").join(
        nation, on=col("s_nationkey") == col("n_nationkey")).select(
        "s_suppkey")
    ps = dfs["partsupp"].select("ps_partkey", "ps_suppkey",
                                "ps_supplycost", "ps_availqty")
    ps_de = ps.join(supp, on=col("ps_suppkey") == col("s_suppkey"))
    value = col("ps_supplycost") * col("ps_availqty")
    per_part = (ps_de.group_by("ps_partkey").agg(("sum", value, "value")))
    total = ps_de.agg(("sum", value, "total_value"))
    j = per_part.join(total, how="cross")
    j = j.filter(col("value") > col("total_value") * lit(0.0001))
    return j.select("ps_partkey", "value").sort("-value", "ps_partkey")


def q11_pandas(t):
    nation = t["nation"][t["nation"].n_name == "GERMANY"][["n_nationkey"]]
    supp = t["supplier"].merge(nation, left_on="s_nationkey",
                               right_on="n_nationkey")[["s_suppkey"]]
    ps = t["partsupp"].merge(supp, left_on="ps_suppkey",
                             right_on="s_suppkey")
    ps = ps.assign(value=ps.ps_supplycost * ps.ps_availqty)
    g = ps.groupby("ps_partkey", as_index=False).agg(
        value=("value", "sum"))
    g = g[g.value > ps.value.sum() * 0.0001]
    return g.sort_values(["value", "ps_partkey"],
                         ascending=[False, True]).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q12 — shipping modes and order priority (CASE pivots)
# ---------------------------------------------------------------------------


def q12(dfs):
    li = dfs["lineitem"].filter(
        col("l_shipmode").isin("MAIL", "SHIP")
        & (col("l_commitdate") < col("l_receiptdate"))
        & (col("l_shipdate") < col("l_commitdate"))
        & (col("l_receiptdate") >= lit(days(1994, 1, 1)))
        & (col("l_receiptdate") < lit(days(1995, 1, 1)))).select(
        "l_orderkey", "l_shipmode")
    j = li.join(dfs["orders"].select("o_orderkey", "o_orderpriority"),
                on=col("l_orderkey") == col("o_orderkey"))
    high = when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"), 1) \
        .otherwise(0)
    low = when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"), 0) \
        .otherwise(1)
    return (j.group_by("l_shipmode")
            .agg(("sum", high, "high_line_count"),
                 ("sum", low, "low_line_count")).sort("l_shipmode"))


def q12_pandas(t):
    li = t["lineitem"]
    li = li[li.l_shipmode.isin(["MAIL", "SHIP"])
            & (li.l_commitdate < li.l_receiptdate)
            & (li.l_shipdate < li.l_commitdate)
            & (li.l_receiptdate >= _date(1994, 1, 1))
            & (li.l_receiptdate < _date(1995, 1, 1))]
    j = li.merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
    hi = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    j = j.assign(high_line_count=hi.astype(int),
                 low_line_count=(~hi).astype(int))
    g = j.groupby("l_shipmode", as_index=False).agg(
        high_line_count=("high_line_count", "sum"),
        low_line_count=("low_line_count", "sum"))
    return g.sort_values("l_shipmode").reset_index(drop=True)


# ---------------------------------------------------------------------------
# q13 — customer distribution (left outer + NOT LIKE)
# ---------------------------------------------------------------------------


def q13(dfs):
    orders = dfs["orders"].filter(
        ~col("o_comment").like("%special%requests%")).select(
        "o_orderkey", "o_custkey")
    cust = dfs["customer"].select("c_custkey")
    j = cust.join(orders, on=col("c_custkey") == col("o_custkey"),
                  how="left_outer")
    per_cust = (j.group_by("c_custkey")
                .agg(("count", "o_orderkey", "c_count")))
    return (per_cust.group_by("c_count")
            .agg(("count", "*", "custdist"))
            .sort("-custdist", "-c_count"))


def q13_pandas(t):
    orders = t["orders"]
    orders = orders[~orders.o_comment.str.match(
        ".*special.*requests.*")][["o_orderkey", "o_custkey"]]
    j = t["customer"][["c_custkey"]].merge(
        orders, left_on="c_custkey", right_on="o_custkey", how="left")
    per = j.groupby("c_custkey", as_index=False).agg(
        c_count=("o_orderkey", "count"))
    g = per.groupby("c_count", as_index=False).agg(
        custdist=("c_custkey", "size"))
    return g.sort_values(["custdist", "c_count"],
                         ascending=[False, False]).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q14 — promotion effect
# ---------------------------------------------------------------------------


def q14(dfs):
    li = dfs["lineitem"].filter(
        (col("l_shipdate") >= lit(days(1995, 9, 1)))
        & (col("l_shipdate") < lit(days(1995, 10, 1)))).select(
        "l_partkey", "l_extendedprice", "l_discount")
    j = li.join(dfs["part"].select("p_partkey", "p_type"),
                on=col("l_partkey") == col("p_partkey"))
    promo = when(col("p_type").like("PROMO%"), _volume()).otherwise(0.0)
    g = j.agg(("sum", promo, "promo"), ("sum", _volume(), "total"))
    return g.select((lit(100.0) * col("promo") / col("total"))
                    .alias("promo_revenue"))


def q14_pandas(t):
    li = t["lineitem"]
    li = li[(li.l_shipdate >= _date(1995, 9, 1))
            & (li.l_shipdate < _date(1995, 10, 1))]
    j = li.merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    vol = j.l_extendedprice * (1 - j.l_discount)
    promo = vol[j.p_type.str.startswith("PROMO")].sum()
    return pd.DataFrame({"promo_revenue": [100.0 * promo / vol.sum()]})


# ---------------------------------------------------------------------------
# q15 — top supplier (scalar max via join-back on the aggregate)
# ---------------------------------------------------------------------------


def q15(dfs):
    li = dfs["lineitem"].filter(
        (col("l_shipdate") >= lit(days(1996, 1, 1)))
        & (col("l_shipdate") < lit(days(1996, 4, 1)))).select(
        "l_suppkey", "l_extendedprice", "l_discount")
    revenue = (li.group_by("l_suppkey")
               .agg(("sum", _volume(), "total_revenue")))
    top = revenue.agg(("max", "total_revenue", "max_revenue"))
    j = revenue.join(top,
                     on=col("total_revenue") == col("max_revenue"))
    j = j.join(dfs["supplier"].select("s_suppkey", "s_name", "s_address",
                                      "s_phone"),
               on=col("l_suppkey") == col("s_suppkey"))
    return (j.select("s_suppkey", "s_name", "s_address", "s_phone",
                     "total_revenue").sort("s_suppkey"))


def q15_pandas(t):
    li = t["lineitem"]
    li = li[(li.l_shipdate >= _date(1996, 1, 1))
            & (li.l_shipdate < _date(1996, 4, 1))]
    li = li.assign(vol=li.l_extendedprice * (1 - li.l_discount))
    rev = li.groupby("l_suppkey", as_index=False).agg(
        total_revenue=("vol", "sum"))
    top = rev[rev.total_revenue == rev.total_revenue.max()]
    j = top.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    return (j[["s_suppkey", "s_name", "s_address", "s_phone",
               "total_revenue"]].sort_values("s_suppkey")
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q16 — parts/supplier relationship (anti join on complaints)
# ---------------------------------------------------------------------------


def q16(dfs):
    part = dfs["part"].filter(
        (col("p_brand") != lit("Brand#45"))
        & ~col("p_type").like("MEDIUM POLISHED%")
        & col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9)).select(
        "p_partkey", "p_brand", "p_type", "p_size")
    bad_supp = dfs["supplier"].filter(
        col("s_comment").like("%Customer%Complaints%")).select("s_suppkey")
    ps = dfs["partsupp"].select("ps_partkey", "ps_suppkey")
    ps = ps.join(bad_supp, on=col("ps_suppkey") == col("s_suppkey"),
                 how="left_anti")
    j = ps.join(part, on=col("ps_partkey") == col("p_partkey"))
    return (j.group_by("p_brand", "p_type", "p_size")
            .agg(("count_distinct", "ps_suppkey", "supplier_cnt"))
            .sort("-supplier_cnt", "p_brand", "p_type", "p_size"))


def q16_pandas(t):
    part = t["part"]
    part = part[(part.p_brand != "Brand#45")
                & ~part.p_type.str.startswith("MEDIUM POLISHED")
                & part.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    bad = t["supplier"][t["supplier"].s_comment.str.match(
        ".*Customer.*Complaints.*")].s_suppkey
    ps = t["partsupp"][~t["partsupp"].ps_suppkey.isin(bad)]
    j = ps.merge(part, left_on="ps_partkey", right_on="p_partkey")
    g = j.groupby(["p_brand", "p_type", "p_size"], as_index=False).agg(
        supplier_cnt=("ps_suppkey", "nunique"))
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True]) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# q17 — small-quantity-order revenue (correlated avg -> join-back)
# ---------------------------------------------------------------------------


def q17(dfs):
    part = dfs["part"].filter(
        (col("p_brand") == lit("Brand#23"))
        & (col("p_container") == lit("MED BOX"))).select("p_partkey")
    li = dfs["lineitem"].select("l_partkey", "l_quantity",
                                "l_extendedprice")
    avg_qty = (li.group_by("l_partkey")
               .agg(("avg", "l_quantity", "avg_qty")))
    j = li.join(part, on=col("l_partkey") == col("p_partkey"))
    j = j.join(avg_qty, on=col("l_partkey") == col("l_partkey"))
    j = j.filter(col("l_quantity") < col("avg_qty") * lit(0.2))
    g = j.agg(("sum", "l_extendedprice", "total"))
    return g.select((col("total") / lit(7.0)).alias("avg_yearly"))


def q17_pandas(t):
    part = t["part"]
    part = part[(part.p_brand == "Brand#23")
                & (part.p_container == "MED BOX")][["p_partkey"]]
    li = t["lineitem"]
    avg_qty = li.groupby("l_partkey", as_index=False).agg(
        avg_qty=("l_quantity", "mean"))
    j = li.merge(part, left_on="l_partkey", right_on="p_partkey")
    j = j.merge(avg_qty, on="l_partkey")
    j = j[j.l_quantity < 0.2 * j.avg_qty]
    return pd.DataFrame({"avg_yearly": [j.l_extendedprice.sum() / 7.0]})


# ---------------------------------------------------------------------------
# q18 — large volume customers (HAVING sum > 300 -> semi join)
# ---------------------------------------------------------------------------


def q18(dfs):
    li = dfs["lineitem"].select("l_orderkey", "l_quantity")
    big = (li.group_by("l_orderkey").agg(("sum", "l_quantity", "sum_qty"))
           .having(col("sum_qty") > lit(300)).select("l_orderkey"))
    orders = dfs["orders"].select("o_orderkey", "o_custkey", "o_orderdate",
                                  "o_totalprice")
    orders = orders.join(big, on=col("o_orderkey") == col("l_orderkey"),
                         how="left_semi")
    j = orders.join(dfs["customer"].select("c_custkey", "c_name"),
                    on=col("o_custkey") == col("c_custkey"))
    j = li.join(j, on=col("l_orderkey") == col("o_orderkey"))
    return (j.group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                       "o_totalprice")
            .agg(("sum", "l_quantity", "sum_qty"))
            .sort("-o_totalprice", "o_orderdate", "o_orderkey").limit(100))


def q18_pandas(t):
    li = t["lineitem"]
    sums = li.groupby("l_orderkey", as_index=False).agg(
        sum_qty=("l_quantity", "sum"))
    big = sums[sums.sum_qty > 300].l_orderkey
    orders = t["orders"][t["orders"].o_orderkey.isin(big)]
    j = orders.merge(t["customer"], left_on="o_custkey",
                     right_on="c_custkey")
    j = li.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"))
    return (g.sort_values(["o_totalprice", "o_orderdate", "o_orderkey"],
                          ascending=[False, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q19 — discounted revenue (OR-of-brackets above the part join)
# ---------------------------------------------------------------------------


def q19(dfs):
    li = dfs["lineitem"].filter(
        col("l_shipmode").isin("AIR", "REG AIR")
        & (col("l_shipinstruct") == lit("DELIVER IN PERSON"))).select(
        "l_partkey", "l_quantity", "l_extendedprice", "l_discount")
    part = dfs["part"].select("p_partkey", "p_brand", "p_container",
                              "p_size")
    j = li.join(part, on=col("l_partkey") == col("p_partkey"))
    b1 = ((col("p_brand") == lit("Brand#12"))
          & col("p_container").isin("SM CASE", "SM BOX", "SM PACK",
                                    "SM PKG")
          & col("l_quantity").between(lit(1), lit(11))
          & col("p_size").between(lit(1), lit(5)))
    b2 = ((col("p_brand") == lit("Brand#23"))
          & col("p_container").isin("MED BAG", "MED BOX", "MED PKG",
                                    "MED PACK")
          & col("l_quantity").between(lit(10), lit(20))
          & col("p_size").between(lit(1), lit(10)))
    b3 = ((col("p_brand") == lit("Brand#34"))
          & col("p_container").isin("LG CASE", "LG BOX", "LG PACK",
                                    "LG PKG")
          & col("l_quantity").between(lit(20), lit(30))
          & col("p_size").between(lit(1), lit(15)))
    j = j.filter(b1 | b2 | b3)
    return j.agg(("sum", _volume(), "revenue"))


def q19_pandas(t):
    li = t["lineitem"]
    li = li[li.l_shipmode.isin(["AIR", "REG AIR"])
            & (li.l_shipinstruct == "DELIVER IN PERSON")]
    j = li.merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    b1 = ((j.p_brand == "Brand#12")
          & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & j.l_quantity.between(1, 11) & j.p_size.between(1, 5))
    b2 = ((j.p_brand == "Brand#23")
          & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG",
                                "MED PACK"])
          & j.l_quantity.between(10, 20) & j.p_size.between(1, 10))
    b3 = ((j.p_brand == "Brand#34")
          & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & j.l_quantity.between(20, 30) & j.p_size.between(1, 15))
    j = j[b1 | b2 | b3]
    return pd.DataFrame({"revenue": [
        (j.l_extendedprice * (1 - j.l_discount)).sum()]})


# ---------------------------------------------------------------------------
# q20 — potential part promotion (nested IN -> semi joins + join-back)
# ---------------------------------------------------------------------------


def q20(dfs):
    part = dfs["part"].filter(col("p_name").like("forest%")) \
        .select("p_partkey")
    li = dfs["lineitem"].filter(
        (col("l_shipdate") >= lit(days(1994, 1, 1)))
        & (col("l_shipdate") < lit(days(1995, 1, 1)))).select(
        "l_partkey", "l_suppkey", "l_quantity")
    half = (li.group_by("l_partkey", "l_suppkey")
            .agg(("sum", "l_quantity", "qty_sum")))
    ps = dfs["partsupp"].select("ps_partkey", "ps_suppkey", "ps_availqty")
    ps = ps.join(part, on=col("ps_partkey") == col("p_partkey"),
                 how="left_semi")
    j = ps.join(half, on=(col("ps_partkey") == col("l_partkey"))
                & (col("ps_suppkey") == col("l_suppkey")))
    j = j.filter(col("ps_availqty") > col("qty_sum") * lit(0.5))
    supp = dfs["supplier"].select("s_suppkey", "s_name", "s_address",
                                  "s_nationkey")
    supp = supp.join(j.select("ps_suppkey"),
                     on=col("s_suppkey") == col("ps_suppkey"),
                     how="left_semi")
    nation = dfs["nation"].filter(col("n_name") == lit("CANADA")) \
        .select("n_nationkey")
    supp = supp.join(nation, on=col("s_nationkey") == col("n_nationkey"))
    return supp.select("s_name", "s_address").sort("s_name")


def q20_pandas(t):
    part = t["part"][t["part"].p_name.str.startswith("forest")][
        ["p_partkey"]]
    li = t["lineitem"]
    li = li[(li.l_shipdate >= _date(1994, 1, 1))
            & (li.l_shipdate < _date(1995, 1, 1))]
    half = li.groupby(["l_partkey", "l_suppkey"], as_index=False).agg(
        qty_sum=("l_quantity", "sum"))
    ps = t["partsupp"][t["partsupp"].ps_partkey.isin(part.p_partkey)]
    j = ps.merge(half, left_on=["ps_partkey", "ps_suppkey"],
                 right_on=["l_partkey", "l_suppkey"])
    j = j[j.ps_availqty > 0.5 * j.qty_sum]
    nation = t["nation"][t["nation"].n_name == "CANADA"][["n_nationkey"]]
    supp = t["supplier"][t["supplier"].s_suppkey.isin(j.ps_suppkey)]
    supp = supp.merge(nation, left_on="s_nationkey",
                      right_on="n_nationkey")
    return (supp[["s_name", "s_address"]].sort_values("s_name")
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q21 — suppliers who kept orders waiting
# ---------------------------------------------------------------------------


def q21(dfs):
    li = dfs["lineitem"].select("l_orderkey", "l_suppkey", "l_commitdate",
                                "l_receiptdate")
    # Per order: distinct suppliers overall and among LATE lines. The
    # official EXISTS l2 == ">= 2 distinct suppliers"; NOT EXISTS l3 ==
    # "exactly 1 distinct supplier among late lines" (l1 is late, so that
    # one supplier is l1's).
    n_supp = (li.group_by("l_orderkey")
              .agg(("count_distinct", "l_suppkey", "n_supp")))
    late = li.filter(col("l_receiptdate") > col("l_commitdate"))
    n_late = (late.group_by("l_orderkey")
              .agg(("count_distinct", "l_suppkey", "n_late_supp")))
    orders = dfs["orders"].filter(col("o_orderstatus") == lit("F")) \
        .select("o_orderkey")
    j = late.select("l_orderkey", "l_suppkey").join(
        orders, on=col("l_orderkey") == col("o_orderkey"), how="left_semi")
    j = j.join(n_supp, on=col("l_orderkey") == col("l_orderkey"))
    j = j.join(n_late, on=col("l_orderkey") == col("l_orderkey"))
    j = j.filter((col("n_supp") >= lit(2)) & (col("n_late_supp") == lit(1)))
    supp = dfs["supplier"].select("s_suppkey", "s_name", "s_nationkey")
    nation = dfs["nation"].filter(col("n_name") == lit("SAUDI ARABIA")) \
        .select("n_nationkey")
    supp = supp.join(nation, on=col("s_nationkey") == col("n_nationkey"))
    j = j.join(supp, on=col("l_suppkey") == col("s_suppkey"))
    return (j.group_by("s_name").agg(("count", "*", "numwait"))
            .sort("-numwait", "s_name").limit(100))


def q21_pandas(t):
    li = t["lineitem"]
    n_supp = li.groupby("l_orderkey").l_suppkey.nunique()
    late = li[li.l_receiptdate > li.l_commitdate]
    n_late = late.groupby("l_orderkey").l_suppkey.nunique()
    orders = set(t["orders"][t["orders"].o_orderstatus == "F"].o_orderkey)
    j = late[late.l_orderkey.isin(orders)].copy()
    j = j[j.l_orderkey.map(n_supp).ge(2)
          & j.l_orderkey.map(n_late).eq(1)]
    nation = t["nation"][t["nation"].n_name == "SAUDI ARABIA"]
    supp = t["supplier"].merge(nation, left_on="s_nationkey",
                               right_on="n_nationkey")
    j = j.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
    g = j.groupby("s_name", as_index=False).agg(
        numwait=("l_orderkey", "size"))
    return (g.sort_values(["numwait", "s_name"], ascending=[False, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q22 — global sales opportunity (anti join + scalar avg + SUBSTR group)
# ---------------------------------------------------------------------------


def q22(dfs):
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cust = dfs["customer"].select(
        col("c_phone").substr(1, 2).alias("cntrycode"), "c_acctbal",
        "c_custkey")
    cust = cust.filter(col("cntrycode").isin(*codes))
    pos_avg = (cust.filter(col("c_acctbal") > lit(0.0))
               .agg(("avg", "c_acctbal", "avg_bal")))
    cust = cust.join(pos_avg, how="cross")
    cust = cust.filter(col("c_acctbal") > col("avg_bal"))
    orders = dfs["orders"].select("o_custkey")
    cust = cust.join(orders, on=col("c_custkey") == col("o_custkey"),
                     how="left_anti")
    return (cust.group_by("cntrycode")
            .agg(("count", "*", "numcust"), ("sum", "c_acctbal", "totacctbal"))
            .sort("cntrycode"))


def q22_pandas(t):
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = t["customer"].copy()
    cust["cntrycode"] = cust.c_phone.str[:2]
    cust = cust[cust.cntrycode.isin(codes)]
    avg_bal = cust[cust.c_acctbal > 0.0].c_acctbal.mean()
    cust = cust[cust.c_acctbal > avg_bal]
    cust = cust[~cust.c_custkey.isin(t["orders"].o_custkey)]
    g = cust.groupby("cntrycode", as_index=False).agg(
        numcust=("c_custkey", "size"), totacctbal=("c_acctbal", "sum"))
    return g.sort_values("cntrycode").reset_index(drop=True)


# ---------------------------------------------------------------------------
# Registry + index definitions
# ---------------------------------------------------------------------------


QUERIES: Dict[str, Tuple[Callable, Callable]] = {
    "q1": (q1, q1_pandas), "q2": (q2, q2_pandas), "q3": (q3, q3_pandas),
    "q4": (q4, q4_pandas), "q5": (q5, q5_pandas), "q6": (q6, q6_pandas),
    "q7": (q7, q7_pandas), "q8": (q8, q8_pandas), "q9": (q9, q9_pandas),
    "q10": (q10, q10_pandas), "q11": (q11, q11_pandas),
    "q12": (q12, q12_pandas), "q13": (q13, q13_pandas),
    "q14": (q14, q14_pandas), "q15": (q15, q15_pandas),
    "q16": (q16, q16_pandas), "q17": (q17, q17_pandas),
    "q18": (q18, q18_pandas), "q19": (q19, q19_pandas),
    "q20": (q20, q20_pandas), "q21": (q21, q21_pandas),
    "q22": (q22, q22_pandas),
}


# (index name, table, (indexed, included), used by) — the hot equi-join
# pairs (lineitem<->orders on the order key; lineitem<->part on the part
# key) plus the shipdate filter index q1/q6 can cover.
_INDEX_DEFS = [
    ("tpch_li_ord", "lineitem", (["l_orderkey"],
     ["l_suppkey", "l_extendedprice", "l_discount", "l_quantity",
      "l_shipdate", "l_returnflag"]),
     ("q3", "q5", "q7", "q10", "q18")),
    ("tpch_ord_key", "orders", (["o_orderkey"],
     ["o_custkey", "o_orderdate", "o_shippriority", "o_totalprice",
      "o_orderpriority"]),
     ("q3", "q5", "q7", "q10", "q12", "q18")),
    ("tpch_li_part", "lineitem", (["l_partkey"],
     ["l_suppkey", "l_quantity", "l_extendedprice", "l_discount",
      "l_shipdate", "l_shipmode", "l_shipinstruct"]),
     ("q8", "q9", "q14", "q17", "q19")),
    ("tpch_part_key", "part", (["p_partkey"],
     ["p_brand", "p_type", "p_size", "p_container", "p_name", "p_mfgr"]),
     ("q8", "q9", "q14", "q17", "q19")),
    ("tpch_li_ship", "lineitem", (["l_shipdate"],
     ["l_quantity", "l_extendedprice", "l_discount", "l_tax",
      "l_returnflag", "l_linestatus"]),
     ("q1", "q6")),
]


def create_indexes(hs, dfs, queries=None, skip=()) -> None:
    """Build the covering indexes the given queries (default: all) can
    use — the hot join pairs and the shipdate filter index."""
    from hyperspace_tpu import IndexConfig

    wanted = None if queries is None else set(queries)
    for name, table, (indexed, included), used_by in _INDEX_DEFS:
        if wanted is not None and not (wanted & set(used_by)):
            continue
        if name in skip:
            continue
        hs.create_index(dfs[table], IndexConfig(name, indexed, included))
