"""Rule-driven alerting with evidence-bundled incidents.

The operations plane so far OBSERVES — counters, windows, burn rates,
flight entries — but deciding "this is bad, look now" was left to a
human watching `/metrics`. This module closes that gap in-process, the
same no-side-services discipline as everything else: declarative rules
over the sampler's windowed series, evaluated on every tick, opening
STRUCTURED incidents that carry their own evidence.

**Rules.** Each `AlertRule` names a value source (`kind`), a predicate
(threshold with a direction; window deltas and multiplicative trends
are kinds whose value IS the delta/ratio), a **sustain** duration (the
breach must hold continuously that long before firing — one hiccup
tick is not an incident) and a **clear** level for hysteresis (a
firing rule resolves only when the value crosses `clear`, not when it
dips below `threshold` — no flapping at the boundary). Every knob is
conf-tunable and every rule conf-disableable via
`spark.hyperspace.telemetry.alerts.rule.<name>.*`.

**Default rules** (the table in docs/telemetry.md): SLO burn > 1
(eating error budget faster than earned), segment-cache hit-rate
collapse, retrace storms (`compile.traces` still rising while warm),
HBM admission headroom exhausted, breaker opens, and queue-depth
saturation.

**Incidents.** A firing rule opens ONE incident (repeat breaches while
it is open are counted `alerts.suppressed`, not duplicated), attaches
an evidence bundle — registry snapshot, sliding-window quantiles,
recent flight entries with critical paths, a slowlog-style dump of the
slowest recent query, and a rate-limited `profiler.request_capture`
device trace — transitions firing→resolved with exact counter
agreement (`alerts.fired - alerts.resolved == active incidents`,
always), and persists into the durable history store
(`telemetry/history.py`) at both transitions. Live state is served at
the `/alerts` ops endpoint and as the `incidents` section of
`/healthz`.

Evaluation must never cost a query: the tick hook guards everything
into `alerts.eval_errors`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["AlertRule", "AlertManager", "DEFAULT_RULES", "get_manager",
           "set_manager", "reset_manager", "configure", "on_tick",
           "alerts_doc"]

# How many resolved incidents the manager retains for /alerts (active
# incidents are always retained).
RECENT_INCIDENTS = 32


class AlertRule:
    """One declarative rule. `kind` selects the value source:

    - ``burn``         — scheduler SLO burn rate (decayed live read)
    - ``window_rate``  — per-second rate of counter `series` over
                         `window_s`
    - ``window_delta`` — raw counter delta of `series` over `window_s`
    - ``hit_ratio``    — hits/(hits+misses) of the `series` counter
                         family over `window_s` (gated on `min_count`
                         observations so an idle cache never "collapses")
    - ``trend``        — multiplicative trend: this window's delta of
                         `series` over the PREVIOUS equal window's
                         (2.0 = doubled)
    - ``gauge``        — current registry gauge value
    - ``gauge_frac``   — gauge value over a conf-derived capacity
                         (`capacity_of(conf)`), e.g. queue depth /
                         queue bound

    The predicate is `value > threshold` for direction "above"
    (`value < threshold` for "below"), sustained for `sustain_s`; a
    firing rule resolves when value crosses `clear` on the other side.
    `warm_min` gates evaluation on a cumulative counter
    (`warm_counter`) having reached that value — the retrace-storm
    rule only means something once the process is warm."""

    __slots__ = ("name", "kind", "series", "threshold", "clear",
                 "direction", "sustain_s", "window_s", "description",
                 "min_count", "warm_counter", "warm_min",
                 "capacity_of")

    def __init__(self, name: str, kind: str, series: Optional[str],
                 threshold: float, clear: float,
                 description: str, direction: str = "above",
                 sustain_s: float = 0.0,
                 window_s: Optional[float] = None,
                 min_count: int = 0,
                 warm_counter: Optional[str] = None, warm_min: float = 0,
                 capacity_of=None):
        self.name = name
        self.kind = kind
        self.series = series
        self.threshold = float(threshold)
        self.clear = float(clear)
        self.direction = direction
        self.sustain_s = float(sustain_s)
        self.window_s = window_s
        self.description = description
        self.min_count = int(min_count)
        self.warm_counter = warm_counter
        self.warm_min = float(warm_min)
        self.capacity_of = capacity_of

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "series": self.series, "threshold": self.threshold,
                "clear": self.clear, "direction": self.direction,
                "sustain_s": self.sustain_s, "window_s": self.window_s,
                "description": self.description}


def _hbm_budget(conf) -> float:
    return float(conf.serve_hbm_budget_bytes) if conf is not None else 0.0


def _queue_bound(conf) -> float:
    return float(conf.serve_queue_depth) if conf is not None else 0.0


# The shipped rule set. Thresholds are starting points, each tunable
# via `telemetry.alerts.rule.<name>.{threshold,clear,sustain.seconds,
# window.seconds,enabled}`; the lint in scripts/check_metrics_coverage
# requires every series referenced here to have a docs/telemetry.md
# row.
DEFAULT_RULES: List[AlertRule] = [
    AlertRule(
        "slo_burn", "burn", "serve.slo.burn_rate",
        threshold=1.0, clear=0.5, sustain_s=3.0,
        description="SLO error budget burning faster than earned "
                    "(burn rate > 1 over the SLO window)"),
    AlertRule(
        "segcache_hit_collapse", "hit_ratio", "cache.segments",
        threshold=0.5, clear=0.75, direction="below", sustain_s=5.0,
        min_count=32,
        description="segment-cache hit rate collapsed below 50% over "
                    "the window (warm reads paying the link again)"),
    AlertRule(
        "retrace_storm", "window_rate", "compile.traces",
        threshold=0.5, clear=0.1, sustain_s=5.0,
        warm_counter="queries.total", warm_min=50,
        description="compilation still tracing while warm — shape "
                    "churn defeating the executable cache"),
    AlertRule(
        "hbm_headroom", "gauge_frac", "serve.admitted_bytes",
        threshold=0.95, clear=0.80, sustain_s=5.0,
        capacity_of=_hbm_budget,
        description="admitted HBM bytes above 95% of the serving "
                    "budget — admission about to reject"),
    AlertRule(
        "breaker_open", "window_delta", "resilience.breaker.opened",
        threshold=0.0, clear=0.5, sustain_s=0.0,
        description="an index degradation circuit breaker opened in "
                    "the window"),
    AlertRule(
        "queue_saturation", "gauge_frac", "serve.queue_depth",
        threshold=0.9, clear=0.5, sustain_s=5.0,
        capacity_of=_queue_bound,
        description="wait queue above 90% of its bound — next "
                    "arrivals will be rejected"),
    AlertRule(
        "ingest_staleness", "gauge", "ingest.staleness.seconds",
        threshold=30.0, clear=10.0, sustain_s=5.0,
        description="index staleness above 30 s sustained — appends "
                    "outrunning incremental refresh (coordinator "
                    "deferred, conceding, or failing)"),
]


class _RuleState:
    __slots__ = ("breach_since", "incident")

    def __init__(self):
        self.breach_since: Optional[float] = None
        self.incident: Optional[dict] = None


class AlertManager:
    """Rule evaluation + incident lifecycle. One per process
    (`get_manager()`); `evaluate()` runs from the sampler's tick hook
    with the tick's own timestamp, so scripted tests drive sustain and
    hysteresis deterministically through `tick(t=...)`."""

    def __init__(self, rules: Optional[List[AlertRule]] = None):
        self.rules = list(DEFAULT_RULES if rules is None else rules)
        self._lock = threading.Lock()
        self._states: Dict[str, _RuleState] = {}
        self._incidents: List[dict] = []   # resolved ring + active
        self._conf = None
        self._seq = 0

    def configure(self, conf) -> None:
        self._conf = conf

    # -- conf-resolved rule knobs ---------------------------------------

    def _resolved(self, rule: AlertRule, conf):
        """(enabled, threshold, clear, sustain_s, window_s) with the
        per-rule conf overrides applied."""
        enabled, threshold, clear = True, rule.threshold, rule.clear
        sustain, window = rule.sustain_s, rule.window_s
        if conf is not None:
            try:
                ov = conf.alert_rule_override
                v = ov(rule.name, "enabled")
                if v is not None:
                    enabled = (v or "true").lower() == "true"
                v = ov(rule.name, "threshold")
                if v is not None:
                    threshold = float(v)
                v = ov(rule.name, "clear")
                if v is not None:
                    clear = float(v)
                v = ov(rule.name, "sustain.seconds")
                if v is not None:
                    sustain = float(v)
                v = ov(rule.name, "window.seconds")
                if v is not None:
                    window = float(v)
            except Exception:
                pass  # a malformed override never disables alerting
        return enabled, threshold, clear, sustain, window

    # -- value sources ---------------------------------------------------

    def _value(self, rule: AlertRule, sampler, conf,
               window_s: Optional[float]) -> Optional[float]:
        reg = _registry.get_registry()
        if rule.warm_counter and \
                reg.counter(rule.warm_counter).value < rule.warm_min:
            return None  # not warm yet: the rule is not meaningful
        if rule.kind == "burn":
            from hyperspace_tpu.engine.scheduler import get_scheduler
            return get_scheduler().slo.refresh(conf)
        if rule.kind == "gauge":
            return reg.gauge(rule.series).value
        if rule.kind == "gauge_frac":
            cap = rule.capacity_of(conf) if rule.capacity_of else 0.0
            if cap <= 0:
                return None  # unbounded: nothing to saturate
            return reg.gauge(rule.series).value / cap
        if sampler is None:
            return None
        if rule.kind == "window_rate":
            return sampler.window_rate(rule.series, window_s=window_s)
        if rule.kind == "window_delta":
            delta, covered = sampler.window_delta(rule.series,
                                                  window_s=window_s)
            return delta if covered > 0 else None
        if rule.kind == "hit_ratio":
            hits, ch = sampler.window_delta(f"{rule.series}.hits",
                                            window_s=window_s)
            misses, cm = sampler.window_delta(f"{rule.series}.misses",
                                              window_s=window_s)
            total = hits + misses
            if max(ch, cm) <= 0 or total < max(rule.min_count, 1):
                return None  # idle cache: no collapse to report
            return hits / total
        if rule.kind == "trend":
            w = window_s or sampler.window_s
            recent, c1 = sampler.window_delta(rule.series, window_s=w)
            both, c2 = sampler.window_delta(rule.series,
                                            window_s=2 * w)
            previous = both - recent
            if c2 <= c1 or previous <= 0:
                return None  # no full previous window to trend against
            return recent / previous
        return None

    @staticmethod
    def _breaches(value: float, threshold: float,
                  direction: str) -> bool:
        return value > threshold if direction == "above" \
            else value < threshold

    @staticmethod
    def _cleared(value: float, clear: float, direction: str) -> bool:
        return value < clear if direction == "above" else value > clear

    # -- evaluation ------------------------------------------------------

    def evaluate(self, sampler=None, conf=None,
                 now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over every rule (the tick hook's entry
        point). Returns the incidents that TRANSITIONED this pass
        (opened or resolved). Counter contract: `alerts.evaluations`
        counts rule evaluations with an available value,
        `alerts.fired`/`alerts.resolved` count incident transitions
        exactly, `alerts.suppressed` counts breaches while the rule's
        incident was already open."""
        conf = conf if conf is not None else self._conf
        if conf is not None:
            try:
                if not conf.alerts_enabled:
                    return []
            except Exception:
                pass
        now = time.time() if now is None else float(now)
        reg = _registry.get_registry()
        transitions: List[dict] = []
        for rule in self.rules:
            enabled, threshold, clear, sustain, window = \
                self._resolved(rule, conf)
            if not enabled:
                continue
            try:
                value = self._value(rule, sampler, conf, window)
            except Exception:
                reg.counter("alerts.eval_errors").inc()
                continue
            if value is None:
                continue
            reg.counter("alerts.evaluations").inc()
            with self._lock:
                state = self._states.setdefault(rule.name, _RuleState())
                breaching = self._breaches(value, threshold,
                                           rule.direction)
                if state.incident is not None:
                    # Firing: hysteresis — resolve only on crossing
                    # `clear`, suppress repeat breaches meanwhile.
                    if self._cleared(value, clear, rule.direction):
                        incident = state.incident
                        incident["state"] = "resolved"
                        incident["resolved_at"] = round(now, 3)
                        incident["resolved_value"] = round(value, 6)
                        state.incident = None
                        state.breach_since = None
                        reg.counter("alerts.resolved").inc()
                        transitions.append(incident)
                    elif breaching:
                        reg.counter("alerts.suppressed").inc()
                    continue
                if not breaching:
                    state.breach_since = None
                    continue
                if state.breach_since is None:
                    state.breach_since = now
                if now - state.breach_since < sustain:
                    continue  # breaching, not yet sustained
                incident = self._open(rule, value, threshold, clear,
                                      sustain, now, conf)
                state.incident = incident
                transitions.append(incident)
        for incident in transitions:
            self._persist(incident, conf)
        reg.gauge("alerts.active").set(
            sum(1 for s in self._states.values()
                if s.incident is not None))
        return transitions

    def _open(self, rule: AlertRule, value: float, threshold: float,
              clear: float, sustain: float, now: float, conf) -> dict:
        # Caller holds the lock.
        reg = _registry.get_registry()
        self._seq += 1
        incident = {
            "id": f"inc-{int(now * 1000)}-{self._seq:04d}",
            "rule": rule.name,
            "kind": rule.kind,
            "series": rule.series,
            "description": rule.description,
            "state": "firing",
            "opened_at": round(now, 3),
            "resolved_at": None,
            "value": round(value, 6),
            "threshold": threshold,
            "clear": clear,
            "sustain_s": sustain,
            "evidence": self._evidence(rule, conf),
        }
        self._incidents.append(incident)
        # Bound the ring, but never evict a still-firing incident.
        resolved = [i for i in self._incidents
                    if i["state"] == "resolved"]
        overflow = len(self._incidents) - RECENT_INCIDENTS \
            - len([i for i in self._incidents
                   if i["state"] == "firing"])
        for stale in resolved[:max(overflow, 0)]:
            self._incidents.remove(stale)
        reg.counter("alerts.fired").inc()
        return incident

    # -- evidence --------------------------------------------------------

    @staticmethod
    def _evidence(rule: AlertRule, conf) -> dict:
        """The bundle a responder needs, captured AT fire time, each
        section error-isolated (an incident with partial evidence
        beats no incident)."""
        evidence: dict = {"captured_at": round(time.time(), 3)}

        def section(name, fn):
            try:
                evidence[name] = fn()
            except Exception as exc:
                evidence[name] = {"error": repr(exc)}

        def _windows():
            from hyperspace_tpu.telemetry import timeseries
            sampler = timeseries.get_sampler()
            latest = sampler._latest()
            names = list(sampler.histograms)
            if latest is not None:
                names.extend(k for k in latest.hists
                             if k not in sampler.histograms)
            out = {}
            for name in names:
                buckets, covered = sampler.window_buckets(name)
                count = sum(buckets.values())
                if not count:
                    continue
                out[name] = {
                    "count": count,
                    "covered_s": round(covered, 3),
                    "p50": timeseries.quantile_from_buckets(buckets, .50),
                    "p90": timeseries.quantile_from_buckets(buckets, .90),
                    "p99": timeseries.quantile_from_buckets(buckets, .99),
                }
            return out

        def _flight():
            from hyperspace_tpu.telemetry import flight
            out = []
            for qm in flight.get_recorder().queries(n=8):
                out.append({
                    "description": getattr(qm, "description", None),
                    "flight_seq": getattr(qm, "flight_seq", None),
                    "wall_s": getattr(qm, "wall_s", None),
                    "tenant": getattr(qm, "tenant", None),
                    "replica": getattr(qm, "replica", None),
                    "critical_path": getattr(qm, "critical_path", None),
                })
            return out

        def _slowlog():
            # The slowlog-dump shape for the slowest recent query,
            # built in memory (no file, no threshold): the same
            # self-contained diagnosis document a slow-query dump
            # would have written.
            from hyperspace_tpu.telemetry import flight
            entries = [qm for qm in flight.get_recorder().queries(n=8)
                       if getattr(qm, "wall_s", None) is not None]
            if not entries:
                return None
            worst = max(entries, key=lambda qm: qm.wall_s)
            doc = {"kind": "hyperspace-slowlog",
                   "dumped_at": round(time.time(), 3),
                   "threshold_s": None,
                   "wall_s": worst.wall_s,
                   "description": worst.description,
                   "metrics": worst.to_dict()}
            cp = getattr(worst, "critical_path", None)
            if cp is not None:
                doc["critical_path"] = cp
            return doc

        def _capture():
            from hyperspace_tpu.telemetry import profiler
            return profiler.request_capture(
                conf, reason=f"incident:{rule.name}")

        def _slo():
            from hyperspace_tpu.engine.scheduler import get_scheduler
            return get_scheduler().slo_snapshot(conf)

        section("registry", _registry.get_registry().to_dict)
        section("window_quantiles", _windows)
        section("flight", _flight)
        section("slowlog", _slowlog)
        section("device_profile", _capture)
        section("slo", _slo)
        return evidence

    def _persist(self, incident: dict, conf) -> None:
        """Incident transitions land in the durable history store
        immediately (not on the next interval) — the incident record
        must survive the process that suffered it."""
        try:
            from hyperspace_tpu.telemetry import history
            h = history.get_history()
            if h is not None:
                h.flush(conf=conf, reason="incident",
                        incidents=[incident])
        except Exception:
            _registry.get_registry().counter(
                "alerts.persist_errors").inc()

    # -- inspection ------------------------------------------------------

    def incidents(self, active_only: bool = False) -> List[dict]:
        """Incident documents, oldest first (`active_only` keeps the
        still-firing ones)."""
        with self._lock:
            out = [dict(i) for i in self._incidents]
        if active_only:
            out = [i for i in out if i["state"] == "firing"]
        return out

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for i in self._incidents
                       if i["state"] == "firing")

    def snapshot(self) -> dict:
        """The `/alerts` payload: rule table (conf-resolved), live
        incidents, and the exact counters."""
        conf = self._conf
        reg = _registry.get_registry()
        rules = []
        for rule in self.rules:
            enabled, threshold, clear, sustain, window = \
                self._resolved(rule, conf)
            row = rule.to_dict()
            row.update({"enabled": enabled, "threshold": threshold,
                        "clear": clear, "sustain_s": sustain,
                        "window_s": window})
            with self._lock:
                state = self._states.get(rule.name)
                row["firing"] = bool(state and state.incident)
            rules.append(row)
        counters = reg.counters_dict()
        return {
            "enabled": (conf is None or self._safe_enabled(conf)),
            "rules": rules,
            "active": self.incidents(active_only=True),
            "recent": self.incidents()[-RECENT_INCIDENTS:],
            "counters": {k: counters.get(k, 0) for k in (
                "alerts.evaluations", "alerts.fired",
                "alerts.resolved", "alerts.suppressed")},
        }

    @staticmethod
    def _safe_enabled(conf) -> bool:
        try:
            return bool(conf.alerts_enabled)
        except Exception:
            return True

    def digest(self) -> dict:
        """The compact block bench artifacts embed (and
        `bench_regress.py --serve` gates `fired == 0` on a clean lap):
        the four exact counters plus a compact incident list."""
        counters = _registry.get_registry().counters_dict()
        return {
            "evaluations": int(counters.get("alerts.evaluations", 0)),
            "fired": int(counters.get("alerts.fired", 0)),
            "resolved": int(counters.get("alerts.resolved", 0)),
            "suppressed": int(counters.get("alerts.suppressed", 0)),
            "active": self.active_count(),
            "incidents": [
                {k: i.get(k) for k in ("id", "rule", "state",
                                       "opened_at", "resolved_at",
                                       "value", "threshold")}
                for i in self.incidents()],
        }

    def reset(self) -> None:
        """Forget incidents and sustain state (test isolation). The
        `alerts.*` counters live in the registry and reset with it."""
        with self._lock:
            self._states.clear()
            self._incidents.clear()


# ---------------------------------------------------------------------------
# Process-wide manager + wiring
# ---------------------------------------------------------------------------

_manager: Optional[AlertManager] = None
_manager_lock = threading.Lock()


def get_manager() -> AlertManager:
    """THE process alert manager (created on first use; rules are the
    defaults until `set_manager` installs others)."""
    global _manager
    if _manager is None:
        with _manager_lock:
            if _manager is None:
                _manager = AlertManager()
    return _manager


def set_manager(manager: AlertManager) -> AlertManager:
    global _manager
    with _manager_lock:
        _manager = manager
    return manager


def reset_manager() -> None:
    global _manager
    with _manager_lock:
        _manager = None


def configure(conf) -> Optional[AlertManager]:
    """Session-init wiring (called from `ops_server.configure` next to
    the sampler and the history writer): hands the manager its conf.
    Never a startup failure."""
    try:
        manager = get_manager()
        manager.configure(conf)
        return manager
    except Exception:
        import logging
        logging.getLogger(__name__).warning(
            "alert manager configuration failed; alerting disabled",
            exc_info=True)
        return None


def on_tick(sampler, now: Optional[float] = None) -> None:
    """The sampler's tick hook: evaluate every rule against this
    tick's windows."""
    m = _manager
    if m is not None:
        m.evaluate(sampler=sampler, now=now)


def alerts_doc() -> dict:
    """The `/alerts` payload (manager snapshot; a never-configured
    manager still renders — empty incidents, default rule table)."""
    return get_manager().snapshot()
