"""The live operations endpoint: an in-process, pull-based HTTP server.

The source paper's design keeps all index state on the lake with no
side services; the operations plane keeps the same discipline — no
agent, no push gateway, no sidecar. When
`spark.hyperspace.telemetry.ops.port` is set, a stdlib
`ThreadingHTTPServer` starts inside the engine process (the ONE
sanctioned `http.server` use — `scripts/check_metrics_coverage.py`
bans it anywhere else) and serves six read-only endpoints:

- **`/metrics`** — the registry's Prometheus text exposition
  (`MetricsRegistry.to_text()`), including the sampler's
  `window.<series>.*` sliding-window gauges and the
  `compile.<name>.{flops,bytes_accessed}` device-cost counters. A
  scrape first takes a fresh sampler tick when the last one is older
  than the sampling interval, so the window gauges a scraper reads are
  never staler than its own scrape period.
- **`/healthz`** — one JSON document of serving-plane state: scheduler
  pressure and SLO burn, per-index breaker states, segment-cache
  residency, replica routing/load counts, and the flight ring grouped
  by routed replica.
- **`/timeseries`** — the sampler's ring as JSON (the raw material of
  the `/metrics` window gauges, for dashboards that want the history
  rather than the trailing point). `?since=<seq>` returns only ticks
  newer than the caller's cursor — the flight recorder's
  `snapshot(since_seq)` contract, so incremental scrapers stop
  re-downloading the whole ring; `last_seq` in the payload is the next
  cursor.
- **`/critpath`** — the latency anatomy
  (`telemetry/critical_path.py`): trailing-window segment shares of
  query wall plus the per-query decompositions of the flight ring's
  recent entries.
- **`/profile`** — the sampling profiler (`telemetry/profiler.py`):
  host-time tables, flamegraph JSON (or `?format=collapsed` for the
  flamegraph.pl/speedscope text form), and the recent triggered
  device captures.
- **`/alerts`** — the incident plane (`telemetry/alerts.py`): the
  conf-resolved rule table, active and recent incidents with their
  evidence bundles, and the exact
  `alerts.{evaluations,fired,resolved,suppressed}` counters.

Security: the server binds `telemetry.ops.host` — 127.0.0.1 by
default. The endpoints are unauthenticated, read-only operational
surfaces; binding beyond localhost is an explicit operator decision
(front it with real auth if you do). Request-handler errors are
counted (`ops.http.errors`), never raised into serving threads.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from hyperspace_tpu.telemetry import registry as _registry
from hyperspace_tpu.telemetry import timeseries as _timeseries

__all__ = ["OpsServer", "get_server", "start_server", "stop_server",
           "configure", "healthz_doc", "critpath_doc"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# The last conf handed to configure(): healthz sections that need conf
# context (the index-usage report) read it, because an HTTP handler
# thread has no session in hand.
_conf = None


def healthz_doc() -> dict:
    """The `/healthz` payload, assembled defensively: each section
    degrades to an `{"error": ...}` stub rather than failing the whole
    health read — a health endpoint that 500s because one subsystem is
    mid-teardown would be lying about everything else."""
    doc: dict = {"status": "ok",
                 "time": round(time.time(), 3),
                 "uptime_s": round(
                     time.time()
                     - _registry.get_registry().started_at, 3)}

    def section(name, fn):
        try:
            doc[name] = fn()
        except Exception as exc:
            doc[name] = {"error": repr(exc)}

    def _scheduler():
        from hyperspace_tpu.engine.scheduler import get_scheduler
        sched = get_scheduler()
        out = sched.pressure()
        out["active_queries"] = sched.active_queries()
        out["peak_admitted_bytes"] = sched.peak_admitted_bytes
        out["slo"] = sched.slo_snapshot()
        return out

    def _breakers():
        from hyperspace_tpu.engine.scheduler import get_scheduler
        return get_scheduler().breakers.snapshot()

    def _segments():
        from hyperspace_tpu.io import segcache
        return segcache.get_cache().snapshot()

    def _replicas():
        from hyperspace_tpu.engine.scheduler import get_scheduler
        from hyperspace_tpu.parallel import replica as _replica
        sched = get_scheduler()
        return {
            "routed": _replica.get_router().routed_counts(),
            "inflight": sched.replica_inflight(),
            "admitted_bytes": sched.replica_admitted_bytes(),
        }

    def _flight():
        from hyperspace_tpu.telemetry import flight
        rec = flight.get_recorder()
        entries = rec.queries()
        by_replica: dict = {}
        by_tenant: dict = {}
        for qm in entries:
            key = getattr(qm, "replica", None)
            key = "unrouted" if key is None else str(key)
            by_replica[key] = by_replica.get(key, 0) + 1
            t = getattr(qm, "tenant", None) or "default"
            by_tenant[t] = by_tenant.get(t, 0) + 1
        return {"ring": len(entries), "last_seq": rec.last_seq,
                "by_replica": by_replica, "by_tenant": by_tenant}

    def _tenants():
        from hyperspace_tpu.engine.scheduler import get_scheduler
        from hyperspace_tpu.telemetry import tenant_digest
        sched = get_scheduler()
        out = sched.tenant_snapshot()
        for t, usage in tenant_digest().items():
            out.setdefault(t, {})["usage"] = usage
        return out

    def _incidents():
        from hyperspace_tpu.telemetry import alerts
        m = alerts.get_manager()
        counters = _registry.get_registry().counters_dict()
        return {
            "active": [
                {k: i.get(k) for k in ("id", "rule", "series", "state",
                                       "opened_at", "value",
                                       "threshold", "description")}
                for i in m.incidents(active_only=True)],
            "fired": int(counters.get("alerts.fired", 0)),
            "resolved": int(counters.get("alerts.resolved", 0)),
        }

    def _index_usage():
        if _conf is None:
            return {"skipped": "no configured session in this process"}
        from hyperspace_tpu.facade import index_usage_report
        from hyperspace_tpu.index.manager import \
            CachingIndexCollectionManager
        rows = index_usage_report(CachingIndexCollectionManager(_conf))
        return {"indexes": rows,
                "unused": [r["index"] for r in rows if r["unused"]]}

    section("scheduler", _scheduler)
    section("breakers", _breakers)
    section("segments", _segments)
    section("replicas", _replicas)
    section("flight", _flight)
    section("tenants", _tenants)
    section("incidents", _incidents)
    section("index_usage", _index_usage)
    return doc


def critpath_doc(recent: int = 10) -> dict:
    """The `/critpath` payload: trailing-window segment shares (the
    sampler's view) plus the stamped per-query decompositions of the
    flight ring's newest entries — totals AND exemplars in one read."""
    from hyperspace_tpu.telemetry import critical_path, flight
    doc: dict = {"window": critical_path.window_shares()}
    entries = []
    for qm in flight.get_recorder().queries(n=recent):
        cp = getattr(qm, "critical_path", None)
        if cp is None:
            continue
        entries.append({"description": qm.description,
                        "flight_seq": getattr(qm, "flight_seq", None),
                        "tenant": getattr(qm, "tenant", None),
                        "critical_path": cp})
    doc["recent"] = entries
    reg = _registry.get_registry()
    totals = reg.counters_dict()
    doc["totals"] = {k: round(v, 6) for k, v in totals.items()
                    if k.startswith("critpath.")}
    return doc


class _Handler(BaseHTTPRequestHandler):
    server_version = "hyperspace-ops/1"

    # BaseHTTPRequestHandler logs every request to stderr by default;
    # a scraper polling at 15s would spam the serving process's logs.
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        reg = _registry.get_registry()
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._fresh_tick()
                body = reg.to_text().encode("utf-8")
                self._send(200, PROM_CONTENT_TYPE, body)
            elif path == "/healthz":
                body = json.dumps(healthz_doc(),
                                  default=str).encode("utf-8")
                self._send(200, "application/json", body)
            elif path == "/timeseries":
                since = self._since_param()
                body = json.dumps(
                    _timeseries.get_sampler().snapshot(since_seq=since),
                    default=str).encode("utf-8")
                self._send(200, "application/json", body)
            elif path == "/alerts":
                from hyperspace_tpu.telemetry import alerts
                body = json.dumps(alerts.alerts_doc(),
                                  default=str).encode("utf-8")
                self._send(200, "application/json", body)
            elif path == "/critpath":
                self._fresh_tick()
                body = json.dumps(critpath_doc(),
                                  default=str).encode("utf-8")
                self._send(200, "application/json", body)
            elif path == "/profile":
                from hyperspace_tpu.telemetry import profiler
                query = self.path.partition("?")[2]
                if "format=collapsed" in query:
                    p = profiler.get_profiler()
                    text = p.collapsed() if p is not None else ""
                    self._send(200, "text/plain; charset=utf-8",
                               text.encode("utf-8"))
                else:
                    body = json.dumps(profiler.profile_doc(),
                                      default=str).encode("utf-8")
                    self._send(200, "application/json", body)
            else:
                self._send(404, "text/plain; charset=utf-8",
                           b"not found: /metrics /healthz /timeseries "
                           b"/critpath /profile /alerts\n")
            reg.counter("ops.http.requests").inc()
        except Exception:
            reg.counter("ops.http.errors").inc()
            try:
                self._send(500, "text/plain; charset=utf-8",
                           b"internal error\n")
            except Exception:
                pass  # client gone mid-write

    def _since_param(self) -> Optional[int]:
        """The `?since=<seq>` cursor, or None when absent/malformed (a
        bad cursor degrades to the full ring, never a 4xx — same
        lenience as the flight recorder's filters)."""
        from urllib.parse import parse_qs
        query = self.path.partition("?")[2]
        try:
            values = parse_qs(query).get("since")
            return int(values[0]) if values else None
        except (ValueError, TypeError):
            return None

    @staticmethod
    def _fresh_tick() -> None:
        """Refresh the window gauges when the last sample is older
        than one interval — a scrape always reads a current window,
        even if the background thread was never started."""
        sampler = _timeseries.get_sampler()
        latest = sampler._latest()
        if latest is None or time.time() - latest.t >= sampler.interval_s:
            sampler.tick()


class OpsServer:
    """Lifecycle wrapper around the ThreadingHTTPServer: bind, serve on
    one daemon thread (handlers each get their own daemon thread from
    ThreadingHTTPServer), stop idempotently."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The BOUND port (meaningful for ephemeral port 0)."""
        return self._httpd.server_address[1] \
            if self._httpd is not None else None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "OpsServer":
        if self.running:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        name="hs-ops-server",
                                        daemon=True)
        self._thread.start()
        _registry.get_registry().gauge("ops.server.port").set(self.port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Process-wide server
# ---------------------------------------------------------------------------

_server: Optional[OpsServer] = None
_server_lock = threading.Lock()


def get_server() -> Optional[OpsServer]:
    return _server


def start_server(host: str = "127.0.0.1", port: int = 0) -> OpsServer:
    """Start (or return) THE process ops server. A second start with a
    different port is ignored with a warning — the server is process-
    wide, same caveat as the transfer-engine knobs."""
    global _server
    with _server_lock:
        if _server is not None and _server.running:
            if port not in (0, _server.port) or host != _server.host:
                import logging
                logging.getLogger(__name__).warning(
                    "ops server already bound to %s:%s; ignoring "
                    "request for %s:%s", _server.host, _server.port,
                    host, port)
            return _server
        _server = OpsServer(host=host, port=port).start()
        return _server


def stop_server() -> None:
    global _server
    with _server_lock:
        server, _server = _server, None
    if server is not None:
        server.stop()


def configure(conf) -> Optional[OpsServer]:
    """Session-init wiring (next to `transfer.configure` and
    `configure_persistent_cache`): when `telemetry.ops.port` is set,
    start the sampler and the server; unset = no-op. Failures degrade
    to a warning — the operations plane is an observability feature,
    never a startup failure."""
    global _conf
    if conf is not None:
        _conf = conf
    # The sampling profiler, alert manager, and history writer all
    # configure independently of the ops port — an operator can alert
    # and persist history without exposing HTTP (and vice versa).
    try:
        from hyperspace_tpu.telemetry import profiler as _profiler
        _profiler.configure(conf)
    except Exception:
        pass  # profiler.configure logs its own failures
    try:
        from hyperspace_tpu.telemetry import alerts as _alerts
        _alerts.configure(conf)
    except Exception:
        pass  # alerts.configure logs its own failures
    try:
        from hyperspace_tpu.telemetry import history as _history
        _history.configure(conf)
    except Exception:
        pass  # history.configure logs its own failures
    try:
        port = conf.telemetry_ops_port if conf is not None else None
    except Exception:
        port = None
    if port is None:
        return _server
    try:
        _timeseries.configure(conf)
        return start_server(host=conf.telemetry_ops_host, port=port)
    except Exception:
        import logging
        logging.getLogger(__name__).warning(
            "ops server failed to start; operations endpoints "
            "disabled", exc_info=True)
        return None


def _atexit_stop() -> None:
    try:
        stop_server()
    except Exception:
        pass


import atexit  # noqa: E402

atexit.register(_atexit_stop)
