"""Query flight recorder: the last-K completed queries, always on,
plus a slow-query dump for post-hoc diagnosis.

The per-query recorder (`telemetry/__init__.py`) already captures
everything about one execution — but until now it evaporated with the
Python object unless the caller thought to keep it. Production
diagnosis works the other way round: the interesting query has ALREADY
finished by the time anyone asks. So the engine keeps a bounded ring
of the last `CAPACITY` completed `QueryMetrics` (every session-attached
collect appends; one deque append + threshold check per query), and
any query whose wall exceeds `spark.hyperspace.telemetry.slowlog.seconds`
persists a self-contained dump — its full metric tree, a process
registry snapshot, and the slice of the trace ring covering the query
(when tracing is on) — to `spark.hyperspace.telemetry.slowlog.dir`.
A dump can be reloaded (`load_dump`) and diffed against a live re-run
(`telemetry.diff.diff_trees`) without ever re-running the original
under instrumentation, because the instrumentation was never off.

Dumping never fails a query: any dump error is swallowed, counted
(`flight.dump_errors`) and logged. Only the newest
`spark.hyperspace.telemetry.slowlog.keep` dumps are retained.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["FlightRecorder", "get_recorder", "record", "load_dump"]

logger = logging.getLogger(__name__)

# Ring depth: enough to cover a burst of concurrent sessions' recent
# history while holding only finished recorders (operator node refs
# are already released by QueryMetrics.finish()).
CAPACITY = 64


class FlightRecorder:
    """Thread-safe bounded ring of completed `QueryMetrics` + the
    slow-query dump policy. One per process (`get_recorder()`);
    concurrent collects from any number of sessions append safely."""

    def __init__(self, capacity: int = CAPACITY):
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = itertools.count()  # dump-name monotonicity
        # Per-query monotonic sequence id, stamped on every recorded
        # QueryMetrics as `flight_seq` (1-based; 0 = "from the start").
        # Incremental consumers — the index advisor's workload miner —
        # poll `snapshot(since_seq)` instead of re-reading the ring.
        self._record_seq = 0
        # Slow-dump writer lane: dumps are QUEUED to one background
        # thread instead of serializing + fsyncing on the serving
        # thread (a slow query is exactly the one whose caller is
        # already past its latency budget). `drain()` flushes pending
        # writes; the module atexit hook drains the process recorder
        # so interpreter teardown cannot lose a queued dump.
        self._dump_pool = None
        self._pending: set = set()

    # -- recording ------------------------------------------------------

    def record(self, metrics, conf=None) -> Optional[str]:
        """Fold one FINISHED query recorder into the ring; dump it when
        the session's slowlog threshold says so. Returns the dump path
        when a dump was QUEUED (None otherwise) — the write itself
        rides the background lane; `drain()` flushes it."""
        with self._lock:
            self._record_seq += 1
            metrics.flight_seq = self._record_seq
            self._ring.append(metrics)
        _registry.get_registry().counter("flight.queries").inc()
        if conf is None:
            return None
        try:
            threshold = conf.slowlog_seconds
        except Exception:
            return None
        if threshold <= 0 or metrics.wall_s is None \
                or metrics.wall_s < threshold:
            return None
        try:
            return self._dump_slow(metrics, conf, threshold)
        except Exception:
            # A diagnosis feature must never fail the query it
            # diagnoses: count, log, move on.
            _registry.get_registry().counter("flight.dump_errors").inc()
            logger.warning("slow-query dump failed", exc_info=True)
            return None

    # -- inspection -----------------------------------------------------

    def queries(self, n: Optional[int] = None) -> List:
        """The most recent completed `QueryMetrics`, oldest first
        (last element = latest); `n` limits to the newest n."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def snapshot(self, since_seq: int = 0, replica=None, tenant=None):
        """Incremental, lock-light poll: `(new_entries, last_seq)` where
        `new_entries` are the ring's completed `QueryMetrics` with
        `flight_seq > since_seq`, oldest first, and `last_seq` is the
        highest sequence id ever recorded (pass it back as the next
        `since_seq`). `replica` narrows to entries the scheduler routed
        to that replica slice (the per-replica dimension stamped as
        `metrics.replica`; pass it to ask "what has slice 2 served
        lately"); `tenant` narrows to entries billed to that tenant
        (the dimension stamped as `metrics.tenant` — every scheduled
        query carries one, "default" included). The filters COMPOSE:
        `snapshot(seq, replica=2, tenant="acme")` is acme's traffic on
        slice 2. `last_seq` still advances over skipped entries, so a
        filtered consumer's cursor stays global. The lock is held only
        for the ring copy — the filter runs outside it, and a consumer
        polling with its previous `last_seq` re-reads nothing. Entries
        that rotated out of the ring between polls are simply gone (the
        ring is a bounded diagnosis buffer, not a durable log):
        `last_seq` still advances past them, so a slow consumer skips
        rather than stalls."""
        with self._lock:
            entries = list(self._ring)
            last = self._record_seq
        fresh = [m for m in entries
                 if getattr(m, "flight_seq", 0) > since_seq
                 and (replica is None
                      or getattr(m, "replica", None) == replica)
                 and (tenant is None
                      or getattr(m, "tenant", None) == tenant)]
        return fresh, last

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._record_seq

    def clear(self) -> None:
        """Empty the ring (test isolation). Sequence ids keep counting —
        a consumer's `since_seq` cursor stays valid across clears."""
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- dump lane lifecycle --------------------------------------------

    def _lane(self):
        if self._dump_pool is None:
            with self._lock:
                if self._dump_pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._dump_pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="hs-flight-dump")
        return self._dump_pool

    def drain(self) -> None:
        """Block until every queued slow-query dump has landed (or
        failed and been counted). Idempotent; `session.close()` and the
        atexit hook call this."""
        while True:
            with self._lock:
                futs = list(self._pending)
            if not futs:
                return
            for fut in futs:
                try:
                    fut.result()
                except Exception:
                    pass  # counted + logged by the job itself
            with self._lock:
                self._pending.difference_update(futs)

    def shutdown(self) -> None:
        """Drain and stop the dump lane (idempotent; lazily re-created
        by the next dump)."""
        self.drain()
        with self._lock:
            pool, self._dump_pool = self._dump_pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- slow-query dump ------------------------------------------------

    def _dump_slow(self, metrics, conf, threshold: float) -> str:
        # The SNAPSHOT happens on the calling thread (the metric tree
        # and registry state of the moment the query finished); only
        # the serialization + disk IO ride the background lane.
        dump_dir = conf.slowlog_dir
        keep = conf.slowlog_keep
        doc = {
            "kind": "hyperspace-slowlog",
            "dumped_at": round(time.time(), 3),
            "threshold_s": threshold,
            "wall_s": metrics.wall_s,
            "description": metrics.description,
            "metrics": metrics.to_dict(),
            "registry": _registry.get_registry().to_dict(),
        }
        # Latency anatomy: the stamped decomposition makes the dump
        # self-diagnosing — where the wall went, without a live re-run.
        cp = getattr(metrics, "critical_path", None)
        if cp is not None:
            doc["critical_path"] = cp
        # A slow query is exactly when a device profile is worth its
        # cost: fire a triggered capture (armed only when
        # `telemetry.profiler.capture.seconds` > 0; rate-limited) and
        # record where it will land so the dump points at it.
        try:
            from hyperspace_tpu.telemetry import profiler
            capture = profiler.request_capture(conf, reason="slowlog")
            if capture is not None:
                doc["device_profile"] = capture
        except Exception:
            logger.debug("slowlog-triggered capture failed",
                         exc_info=True)
        trace_slice = self._trace_slice(metrics)
        if trace_slice is not None:
            doc["trace"] = trace_slice
        # Name sorts in creation order WITHIN this process (wall-clock
        # ms + a monotonic sequence); pruning still orders by mtime so
        # multiple processes sharing a dump dir prune correctly.
        fname = (f"slow-{int(doc['dumped_at'] * 1000)}-"
                 f"{os.getpid()}-{next(self._seq):06d}.json")
        path = os.path.join(dump_dir, fname)
        fut = self._lane().submit(self._write_dump, doc, dump_dir, path,
                                  keep, metrics.wall_s, threshold)
        with self._lock:
            self._pending.add(fut)
        fut.add_done_callback(
            lambda f: self._pending.discard(f))
        return path

    def _write_dump(self, doc: dict, dump_dir: str, path: str,
                    keep: int, wall_s, threshold: float) -> None:
        """The dump-lane job: atomic write + prune. Failures are
        counted + logged here (the query is long gone — nothing to
        fail), same contract as the old synchronous path."""
        try:
            os.makedirs(dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)  # a reader never sees a torn dump
            self._prune(dump_dir, keep)
            _registry.get_registry().counter("flight.slow_dumps").inc()
            logger.warning("slow query (%.3fs >= %.3fs): metrics "
                           "dumped to %s", wall_s, threshold, path)
        except Exception:
            _registry.get_registry().counter("flight.dump_errors").inc()
            logger.warning("slow-query dump failed", exc_info=True)

    @staticmethod
    def _trace_slice(metrics) -> Optional[dict]:
        """The tracer-ring events overlapping this query's execution
        window (None when tracing is off). Timestamps stay on the
        tracer's clock so the slice loads in Perfetto as-is."""
        from hyperspace_tpu.telemetry import trace as _trace
        t = _trace.tracer()
        if t is None:
            return None
        start_us = (metrics._t0 - t.t0_s) * 1e6
        with t._lock:
            events = [e for e in t.events
                      if e.get("ts", 0) + e.get("dur", 0) >= start_us]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    @staticmethod
    def _prune(dump_dir: str, keep: int) -> None:
        def order(fname: str):
            try:
                return (os.path.getmtime(os.path.join(dump_dir, fname)),
                        fname)
            except OSError:
                return (0.0, fname)  # already pruned: oldest

        dumps = sorted((f for f in os.listdir(dump_dir)
                        if f.startswith("slow-")
                        and f.endswith(".json")), key=order)
        for stale in dumps[:max(len(dumps) - max(keep, 1), 0)]:
            try:
                os.remove(os.path.join(dump_dir, stale))
            except OSError:
                pass  # concurrent pruner got it first


_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """THE process-wide flight recorder (sessions share it)."""
    return _RECORDER


def _atexit_drain() -> None:
    # Interpreter teardown must not lose a queued slow-query dump.
    try:
        _RECORDER.shutdown()
    except Exception:
        pass


import atexit  # noqa: E402

atexit.register(_atexit_drain)


def record(metrics, conf=None) -> Optional[str]:
    """Module-level convenience the engine's collect path calls."""
    return _RECORDER.record(metrics, conf=conf)


def load_dump(path: str) -> dict:
    """Reload a slow-query dump. `doc["metrics"]` is a full
    `QueryMetrics.to_dict()` tree — `telemetry.diff.diff_trees(
    doc["metrics"], live.to_dict())` diffs it against a fresh run."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != "hyperspace-slowlog":
        raise ValueError(f"{path}: not a slow-query dump")
    return doc
