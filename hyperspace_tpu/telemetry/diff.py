"""Regression attribution: diff two bench artifacts (or two
`QueryMetrics` trees) and decompose each wall-clock delta into
attributed buckets.

PRs 1-5 built the telemetry that can EXPLAIN a regression — operator
trees, link spans, retrace-cause events, cache series, degradation
events — but nothing consumed two rounds and said *why* one is slower;
BENCH_TPCDS_r04 regressed 3.9x against r03 and sat unexplained for two
PRs. This module is that consumer. Given an old and a new artifact
(canonical schema, `telemetry/artifact.py`), it aligns queries by name
and operator nodes by tree path, and splits every query's wall delta
into:

- `compute`   — per-operator self-time movement net of link/compile/
                device-dispatch (node-level deltas ride in the bucket
                detail) — i.e. host-side OVERHEAD;
- `device_bound` — measured warm jit-dispatch seconds
                (`device.dispatch_s`, the device half of the
                device-bound-vs-overhead split), with the modeled XLA
                cost movement (`device.{flops,bytes_accessed}`) as
                evidence;
- `link`      — H2D/D2H seconds from the per-query `link.{h2d,d2h}_s`
                counters (the transfer engine's chunk counters ride
                along as evidence);
- `compile`   — `compile.seconds` movement + the retrace-cause events
                of the new run;
- `plan`      — optimizer/planning seconds (`plan_s`);
- `cache`     — cache-behavior evidence: per-query
                `cache.<name>.{hits,misses,evictions}` deltas. Counted
                in events, not seconds — the seconds a miss costs
                already land in compute/link, so attributing them here
                too would double-count;
- `fallback`  — resilience degradation events (`resilience.fallbacks`,
                `degraded`); evidence, not seconds, same reason;
- `cancellation` — serving-plane interruptions: deadline/cancel events
                with the phase they interrupted
                (`serve.interrupted.<phase>` counters), so timeout
                clusters name their phase instead of landing in
                residual;
- `framework_common` — LEGACY-artifact coarse attribution: the part of
                the rules-on slowdown matching the rules-OFF lane's
                relative slowdown. Both lanes share everything except
                the index rewrite, so a shift both paid is environment
                / framework-wide (the shared tunneled link's ~2x
                time-of-day wobble lands here), not index-path work;
- `residual`  — whatever the telemetry cannot attribute.

Buckets are ranked by attributed magnitude; `dominant` names the
biggest. `ArtifactDiff.format_tree()` renders the ranked attribution
tree `scripts/bench_diff.py` prints, and `scripts/bench_regress.py`
auto-runs on any gate failure so a failed gate arrives with its own
diagnosis. `diff_trees()` diffs two raw `QueryMetrics` trees directly
— a flight-recorder dump against a live re-run, say — without any
artifact around them.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

__all__ = ["Bucket", "QueryDiff", "ArtifactDiff", "diff_artifacts",
           "diff_trees"]

# Evidence-only buckets attribute counts, never seconds (their cost is
# already inside compute/link); they rank below any timed bucket.
_EVIDENCE_BUCKETS = ("cache", "fallback", "cancellation")


class Bucket:
    """One attributed slice of a wall-clock delta."""

    __slots__ = ("name", "seconds", "detail")

    def __init__(self, name: str, seconds: float,
                 detail: Optional[dict] = None):
        self.name = name
        self.seconds = float(seconds)
        self.detail = detail or {}

    def to_dict(self) -> dict:
        d = {"name": self.name, "seconds": round(self.seconds, 4)}
        if self.detail:
            d["detail"] = self.detail
        return d


def _rollup(block) -> Optional[dict]:
    """Normalize a telemetry block into one comparable shape.

    Accepts a full `QueryMetrics.to_dict()` tree (operators as a LIST
    of records with parent links — node alignment possible), a
    `summary()` digest (operators as a per-name rollup dict), or a
    `QueryMetrics` instance. Returns
    {wall, per_op: {name: self_s}, nodes: {path: self_s} | None,
     counters, events} or None when there is nothing to roll up."""
    if block is None:
        return None
    if hasattr(block, "to_dict"):  # live QueryMetrics
        block = block.to_dict()
    if not isinstance(block, dict):
        return None
    ops = block.get("operators")
    counters = dict(block.get("counters") or {})
    events = list(block.get("events") or [])
    wall = block.get("wall_s")
    if isinstance(ops, list):
        # Tree form: self time = wall minus direct children's walls.
        child_s: Dict[Optional[int], float] = {}
        for op in ops:
            child_s[op.get("parent_id")] = \
                child_s.get(op.get("parent_id"), 0.0) \
                + float(op.get("wall_s") or 0.0)
        per_op: Dict[str, float] = {}
        nodes: Dict[str, float] = {}
        # Path = name#occurrence under the parent — stable across runs
        # of the same plan, insensitive to op_id numbering.
        paths: Dict[Optional[int], str] = {None: ""}
        sibling_seen: Dict[tuple, int] = {}
        for op in ops:
            parent = op.get("parent_id")
            name = op.get("name", "?")
            k = (parent, name)
            idx = sibling_seen.get(k, 0)
            sibling_seen[k] = idx + 1
            path = f"{paths.get(parent, '?')}/{name}#{idx}"
            paths[op.get("op_id")] = path
            self_s = max(float(op.get("wall_s") or 0.0)
                         - child_s.get(op.get("op_id"), 0.0), 0.0)
            per_op[name] = per_op.get(name, 0.0) + self_s
            nodes[path] = nodes.get(path, 0.0) + self_s
        return {"wall": wall, "per_op": per_op, "nodes": nodes,
                "counters": counters, "events": events}
    if isinstance(ops, dict):  # summary form
        per_op = {name: float(ent.get("self_s") or 0.0)
                  for name, ent in ops.items()}
        return {"wall": wall, "per_op": per_op, "nodes": None,
                "counters": counters, "events": events}
    if counters or wall is not None:
        return {"wall": wall, "per_op": {}, "nodes": None,
                "counters": counters, "events": events}
    return None


def _counter(roll: Optional[dict], *names: str) -> float:
    if not roll:
        return 0.0
    return sum(float(roll["counters"].get(n, 0.0)) for n in names)


def _cache_deltas(old: Optional[dict], new: Optional[dict]) -> dict:
    out: Dict[str, float] = {}
    keys = set()
    for roll in (old, new):
        if roll:
            keys.update(k for k in roll["counters"]
                        if k.startswith("cache."))
    for k in sorted(keys):
        d = _counter(new, k) - _counter(old, k)
        if d:
            out[k] = round(d, 4)
    return out


class QueryDiff:
    """Attribution of ONE aligned query's wall-clock delta."""

    def __init__(self, name: str, old_wall: Optional[float],
                 new_wall: Optional[float]):
        self.name = name
        self.old_wall = old_wall
        self.new_wall = new_wall
        self.buckets: List[Bucket] = []
        self.notes: List[str] = []

    @property
    def delta(self) -> Optional[float]:
        if self.old_wall is None or self.new_wall is None:
            return None
        return self.new_wall - self.old_wall

    @property
    def ratio(self) -> Optional[float]:
        if not self.old_wall or self.new_wall is None:
            return None
        return self.new_wall / self.old_wall

    def ranked(self) -> List[Bucket]:
        timed = [b for b in self.buckets
                 if b.name not in _EVIDENCE_BUCKETS]
        evid = [b for b in self.buckets if b.name in _EVIDENCE_BUCKETS]
        timed.sort(key=lambda b: -abs(b.seconds))
        return timed + evid

    @property
    def dominant(self) -> Optional[str]:
        """Largest attributed bucket, or None when nothing moved."""
        for b in self.ranked():
            if abs(b.seconds) > 1e-9:
                return b.name
        return None

    def to_dict(self) -> dict:
        return {
            "query": self.name,
            "old_wall_s": self.old_wall,
            "new_wall_s": self.new_wall,
            "delta_s": (round(self.delta, 4)
                        if self.delta is not None else None),
            "ratio": (round(self.ratio, 3)
                      if self.ratio is not None else None),
            "dominant": self.dominant,
            "buckets": [b.to_dict() for b in self.ranked()],
            "notes": list(self.notes),
        }


def _attribute_from_rollups(qd: QueryDiff, old: Optional[dict],
                            new: Optional[dict]) -> None:
    """Telemetry-based decomposition. Sums exactly:
    delta = plan + compute + link + compile + device_bound + residual
    (compute is the operator self-time movement net of the link/
    compile/device-dispatch seconds that happened inside operators —
    no double counting; what remains in `compute` is host-side
    overhead, the other half of the device-bound-vs-overhead split)."""
    link_d = (_counter(new, "link.h2d_s", "link.d2h_s")
              - _counter(old, "link.h2d_s", "link.d2h_s"))
    compile_d = (_counter(new, "compile.seconds")
                 - _counter(old, "compile.seconds"))
    device_d = (_counter(new, "device.dispatch_s")
                - _counter(old, "device.dispatch_s"))
    plan_d = _counter(new, "plan_s") - _counter(old, "plan_s")
    self_d = (sum((new or {}).get("per_op", {}).values())
              - sum((old or {}).get("per_op", {}).values()))
    compute_d = self_d - link_d - compile_d - device_d
    delta = qd.delta if qd.delta is not None else self_d + plan_d
    residual = delta - plan_d - self_d

    compute_detail: dict = {}
    old_nodes = (old or {}).get("nodes")
    new_nodes = (new or {}).get("nodes")
    if old_nodes is not None and new_nodes is not None:
        moves = {p: round(new_nodes.get(p, 0.0) - old_nodes.get(p, 0.0), 4)
                 for p in set(old_nodes) | set(new_nodes)}
        top = sorted(moves.items(), key=lambda kv: -abs(kv[1]))[:5]
        compute_detail["top_node_deltas"] = {p: d for p, d in top if d}
    else:
        per = {n: round((new or {}).get("per_op", {}).get(n, 0.0)
                        - (old or {}).get("per_op", {}).get(n, 0.0), 4)
               for n in set((old or {}).get("per_op", {}))
               | set((new or {}).get("per_op", {}))}
        top = sorted(per.items(), key=lambda kv: -abs(kv[1]))[:5]
        compute_detail["top_operator_deltas"] = {n: d for n, d in top if d}

    link_detail = {}
    for k in ("link.h2d_bytes", "link.d2h_bytes"):
        d = _counter(new, k) - _counter(old, k)
        if d:
            link_detail[k] = int(d)
    compile_detail: dict = {
        "traces": int(_counter(new, "compile.traces")
                      - _counter(old, "compile.traces"))}
    retraces = [e for e in (new or {}).get("events", [])
                if e.get("category") == "compile"
                and e.get("name") == "retrace"
                and e.get("cause") != "first trace"]
    if retraces:
        compile_detail["retrace_causes"] = [
            {"target": e.get("target"), "cause": e.get("cause")}
            for e in retraces[:5]]

    # Device-bound vs overhead: the measured warm-dispatch seconds the
    # instrumented jits charged (`device.dispatch_s`) move in their own
    # bucket, with the MODELED cost movement (XLA cost_analysis flops /
    # bytes) as evidence — "the chip did 2x the flops" and "the chip
    # did the same flops slower" are different regressions.
    device_detail: dict = {}
    for k in ("device.flops", "device.bytes_accessed"):
        d = _counter(new, k) - _counter(old, k)
        if d:
            device_detail[k] = round(d, 1)

    qd.buckets.append(Bucket("compute", compute_d, compute_detail))
    qd.buckets.append(Bucket("link", link_d, link_detail))
    qd.buckets.append(Bucket("compile", compile_d, compile_detail))
    qd.buckets.append(Bucket("device_bound", device_d, device_detail))
    qd.buckets.append(Bucket("plan", plan_d))
    qd.buckets.append(Bucket("residual", residual))

    caches = _cache_deltas(old, new)
    qd.buckets.append(Bucket("cache", 0.0, caches or {}))
    fallbacks = int(_counter(new, "resilience.fallbacks")
                    - _counter(old, "resilience.fallbacks"))
    degraded = [e for e in (new or {}).get("events", [])
                if e.get("category") == "resilience"]
    qd.buckets.append(Bucket(
        "fallback", 0.0,
        {"fallbacks": fallbacks,
         "events": degraded[:3]} if (fallbacks or degraded) else {}))

    # Serving-plane interruptions: a deadline/cancellation event is
    # recorded WITH the phase it interrupted (scan/operator/stage/
    # transfer/write — `serve.interrupted.<phase>` counters + `serve`
    # events), so a cluster of timeouts attributes to its phase bucket
    # here instead of polluting `residual` — "q64 times out in
    # transfer" is actionable, "q64 got slower somehow" is not.
    serve_detail: dict = {}
    phases = {}
    for roll, sign in ((old, -1), (new, +1)):
        for k, v in ((roll or {}).get("counters") or {}).items():
            if k.startswith("serve.interrupted."):
                phase = k.split(".", 2)[2]
                phases[phase] = phases.get(phase, 0) + sign * int(v)
    phases = {p: d for p, d in phases.items() if d}
    if phases:
        serve_detail["interrupted_by_phase"] = phases
    serve_events = [e for e in (new or {}).get("events", [])
                    if e.get("category") == "serve"
                    and e.get("name") in ("cancelled",
                                          "deadline_exceeded",
                                          "rejected")]
    if serve_events:
        serve_detail["events"] = serve_events[:3]
    qd.buckets.append(Bucket("cancellation", 0.0, serve_detail))


def _attribute_legacy(qd: QueryDiff, old_entry: dict,
                      new_entry: dict) -> None:
    """Coarse per-lane attribution when per-query telemetry is absent
    (legacy rounds): the rules-OFF lane runs the same engine minus the
    index rewrite, so the slowdown BOTH lanes paid is framework/
    environment-common; only the remainder is index-path-specific."""
    old_off = old_entry.get("rules_off_s")
    new_off = new_entry.get("rules_off_s")
    delta = qd.delta or 0.0
    common = 0.0
    detail: dict = {}
    if old_off and new_off and qd.old_wall:
        off_ratio = new_off / old_off
        common = qd.old_wall * (off_ratio - 1.0)
        detail = {"rules_off_s": [old_off, new_off],
                  "rules_off_ratio": round(off_ratio, 3)}
        qd.notes.append(
            f"rules-off lane moved x{off_ratio:.2f} "
            f"({old_off:.1f}s -> {new_off:.1f}s): shared framework/"
            "environment cost, not index-path work")
    qd.buckets.append(Bucket("framework_common", common, detail))
    qd.buckets.append(Bucket("residual", delta - common))
    old_cpu = old_entry.get("pandas_s")
    new_cpu = new_entry.get("pandas_s")
    if old_cpu and new_cpu:
        qd.notes.append(
            f"pandas baseline moved x{new_cpu / old_cpu:.2f} "
            f"({old_cpu:.1f}s -> {new_cpu:.1f}s) — vs_baseline shifts "
            "independently of the framework's own wall")
    qd.notes.append("no per-query telemetry in at least one artifact "
                    "(legacy round): attribution is per-lane only")


def _entry_block(entry: dict):
    """Best telemetry block in a per-query artifact entry: the full
    tree when the round committed one, else the summary digest."""
    return entry.get("tree") or entry.get("metrics")


def _tree_critpath(tree) -> Optional[dict]:
    if isinstance(tree, dict):
        return tree.get("critical_path")
    return getattr(tree, "critical_path", None)


def diff_trees(old_tree, new_tree, name: str = "query") -> QueryDiff:
    """Diff two `QueryMetrics` trees (instances or `to_dict()` dicts)
    directly — e.g. a flight-recorder dump against a live re-run.
    When both trees carry a stamped critical-path decomposition
    (`telemetry/critical_path.py`), the biggest segment movements ride
    along as a note: the differ's bucket attribution and the anatomy's
    closed-set view of the same delta, side by side."""
    old_roll = _rollup(old_tree)
    new_roll = _rollup(new_tree)
    qd = QueryDiff(name,
                   (old_roll or {}).get("wall"),
                   (new_roll or {}).get("wall"))
    _attribute_from_rollups(qd, old_roll, new_roll)
    old_cp, new_cp = _tree_critpath(old_tree), _tree_critpath(new_tree)
    if old_cp and new_cp:
        deltas = {
            seg: (new_cp.get("segments", {}).get(seg, 0.0)
                  - old_cp.get("segments", {}).get(seg, 0.0))
            for seg in (set(old_cp.get("segments", {}))
                        | set(new_cp.get("segments", {})))}
        movers = sorted(deltas.items(), key=lambda kv: -abs(kv[1]))[:3]
        if movers and any(abs(d) > 1e-9 for _, d in movers):
            qd.notes.append(
                "critical path moved: " + ", ".join(
                    f"{seg} {d:+.4f}s" for seg, d in movers
                    if abs(d) > 1e-9))
    return qd


def _diff_query_entry(name: str, old_entry: dict,
                      new_entry: dict) -> QueryDiff:
    old_roll = _rollup(_entry_block(old_entry))
    new_roll = _rollup(_entry_block(new_entry))
    old_wall = old_entry.get("rules_on_s",
                             (old_roll or {}).get("wall"))
    new_wall = new_entry.get("rules_on_s",
                             (new_roll or {}).get("wall"))
    qd = QueryDiff(name, old_wall, new_wall)
    if old_roll and new_roll:
        _attribute_from_rollups(qd, old_roll, new_roll)
    else:
        _attribute_legacy(qd, old_entry, new_entry)
    return qd


class ArtifactDiff:
    """Attribution of a whole round-over-round artifact pair."""

    def __init__(self, old_doc: dict, new_doc: dict,
                 old_name: str = "old", new_name: str = "new"):
        self.old_name = old_name
        self.new_name = new_name
        self.old_vs_baseline = old_doc.get("vs_baseline")
        self.new_vs_baseline = new_doc.get("vs_baseline")
        self.old_value = old_doc.get("value")
        self.new_value = new_doc.get("value")
        self.metric = new_doc.get("metric") or old_doc.get("metric")
        self.queries: List[QueryDiff] = []
        self.only_old: List[str] = []
        self.only_new: List[str] = []
        self.notes: List[str] = []

        old_q = old_doc.get("queries") or {}
        new_q = new_doc.get("queries") or {}
        # bench.py artifacts carry rungs instead of queries; their
        # device_s walls and metrics digests diff the same way.
        if not old_q and not new_q:
            old_q = {k: self._rung_entry(v)
                     for k, v in (old_doc.get("rungs") or {}).items()}
            new_q = {k: self._rung_entry(v)
                     for k, v in (new_doc.get("rungs") or {}).items()}
        for name in sorted(set(old_q) | set(new_q)):
            if name not in old_q:
                self.only_new.append(name)
                continue
            if name not in new_q:
                self.only_old.append(name)
                continue
            self.queries.append(
                _diff_query_entry(name, old_q[name], new_q[name]))

        self._environment_notes(old_doc, new_doc)

    @staticmethod
    def _rung_entry(rung: dict) -> dict:
        entry = dict(rung)
        if "rules_on_s" not in entry and "device_s" in entry:
            entry["rules_on_s"] = entry["device_s"]
        if "pandas_s" not in entry and "cpu_s" in entry:
            entry["pandas_s"] = entry["cpu_s"]
        return entry

    def _environment_notes(self, old_doc: dict, new_doc: dict) -> None:
        op = (old_doc.get("link_probe") or {})
        np_ = (new_doc.get("link_probe") or {})
        if op.get("h2d_mb_s") and np_.get("h2d_mb_s"):
            self.notes.append(
                f"link probe: h2d {op['h2d_mb_s']} -> "
                f"{np_['h2d_mb_s']} MB/s, sync floor "
                f"{op.get('sync_latency_s')} -> "
                f"{np_.get('sync_latency_s')}s")
        for doc, label in ((old_doc, self.old_name),
                           (new_doc, self.new_name)):
            if doc.get("legacy"):
                self.notes.append(
                    f"{label} is a migrated legacy round: no telemetry "
                    "sections; attribution is per-lane only")
        ot, nt = old_doc.get("platform"), new_doc.get("platform")
        if ot and nt and ot != nt:
            self.notes.append(
                f"PLATFORM CHANGED {ot} -> {nt}: walls are not "
                "hardware-comparable; read ratios, not seconds")
        os_, ns = old_doc.get("scale"), new_doc.get("scale")
        if os_ is not None and ns is not None and os_ != ns:
            self.notes.append(
                f"SCALE CHANGED {os_} -> {ns}: walls are not "
                "workload-comparable; read ratios, not seconds")

    def ranked_queries(self) -> List[QueryDiff]:
        return sorted(self.queries,
                      key=lambda q: -abs(q.delta or 0.0))

    def to_dict(self) -> dict:
        return {
            "old": self.old_name,
            "new": self.new_name,
            "metric": self.metric,
            "vs_baseline": [self.old_vs_baseline, self.new_vs_baseline],
            "value": [self.old_value, self.new_value],
            "queries": [q.to_dict() for q in self.ranked_queries()],
            "only_in_old": self.only_old,
            "only_in_new": self.only_new,
            "notes": list(self.notes),
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=str)

    def format_tree(self) -> str:
        lines = [f"Attribution: {self.old_name} -> {self.new_name}"]
        if self.old_vs_baseline is not None \
                and self.new_vs_baseline is not None:
            ch = (self.new_vs_baseline / self.old_vs_baseline - 1.0
                  if self.old_vs_baseline else 0.0)
            lines.append(
                f"  headline vs_baseline {self.old_vs_baseline:.3f} -> "
                f"{self.new_vs_baseline:.3f} ({ch:+.1%})")
        if isinstance(self.old_value, (int, float)) \
                and isinstance(self.new_value, (int, float)):
            lines.append(f"  {self.metric or 'value'} "
                         f"{self.old_value:.3f} -> {self.new_value:.3f}")
        for note in self.notes:
            lines.append(f"  note: {note}")
        for qd in self.ranked_queries():
            head = f"+- {qd.name}"
            if qd.old_wall is not None and qd.new_wall is not None:
                head += (f"  {qd.old_wall:.3f}s -> {qd.new_wall:.3f}s"
                         f"  ({qd.delta:+.3f}s"
                         + (f", x{qd.ratio:.2f}" if qd.ratio else "")
                         + ")")
            if qd.dominant:
                head += f"  dominant: {qd.dominant}"
            lines.append(head)
            for b in qd.ranked():
                detail = ""
                if b.detail:
                    detail = "  " + json.dumps(b.detail, default=str,
                                               sort_keys=True)
                    if len(detail) > 140:
                        detail = detail[:137] + "..."
                lines.append(f"   +- {b.name:16s} {b.seconds:+9.3f}s"
                             f"{detail}")
            for note in qd.notes:
                lines.append(f"   |  note: {note}")
        for name in self.only_old:
            lines.append(f"+- {name}  (only in {self.old_name})")
        for name in self.only_new:
            lines.append(f"+- {name}  (only in {self.new_name})")
        return "\n".join(lines)


def diff_artifacts(old_doc: dict, new_doc: dict, old_name: str = "old",
                   new_name: str = "new") -> ArtifactDiff:
    """Diff two canonical (or migrated) artifact documents. Callers
    loading from disk should go through `telemetry.artifact.load` so
    driver envelopes are unwrapped and legacy rounds are explicit."""
    return ArtifactDiff(old_doc, new_doc, old_name=old_name,
                        new_name=new_name)
