"""Process-wide metrics registry: named counters, gauges, log-bucketed
histograms.

PR 1's `QueryMetrics` answers "what did THIS query do"; this registry
answers "what has this PROCESS done" — aggregate counts and timings
across every query, session, index-maintenance action, and mesh
dispatch since startup. It is the scrape surface for a long-running
service: `to_text()` emits Prometheus exposition format, `to_dict()`
a JSON-able snapshot, and the last N structured action reports ride
along for the maintenance audit trail.

One registry per process (`get_registry()`); sessions share it —
`HyperspaceSession.metrics_registry()` is just the surface. All metric
mutation goes through one registry-level lock: the hot callers
(operator hooks, fusion stats, link transfers) update at far below the
rate where that lock could contend, and a single lock keeps
counter/histogram pairs mutually consistent for scrapers.

`engine.fusion.STATS` is a view over this registry (counters
`fusion.*`), so the legacy whole-run profiling contract and the
registry can never drift.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry"]


class Counter:
    """Monotonic accumulator (float). `set()` exists ONLY for the
    consumer-reset contract inherited from `fusion.STATS` (profiling
    scripts zero the fusion counters between warm runs); service
    scrapers should treat counters as monotonic."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value (device count, cache sizes, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Log2-bucketed histogram: observation `v` lands in the bucket with
    upper bound `2**ceil(log2(v))` (non-positive values in a "0"
    bucket). Powers of two track the quantities measured here — bytes
    over the link, seconds per action phase — across their full dynamic
    range with ~2x resolution and no preconfigured bounds."""

    __slots__ = ("name", "_buckets", "count", "sum", "min", "max",
                 "_lock")

    _EXP_MIN, _EXP_MAX = -40, 64  # ~1e-12 .. ~1.8e19: clamp, don't drop

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._buckets: Dict[Optional[int], int] = {}
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = lock

    @classmethod
    def _exp(cls, v: float) -> Optional[int]:
        if v <= 0:
            return None
        return max(cls._EXP_MIN, min(cls._EXP_MAX,
                                     math.ceil(math.log2(v))))

    def observe(self, v: float) -> None:
        v = float(v)
        exp = self._exp(v)
        with self._lock:
            self._buckets[exp] = self._buckets.get(exp, 0) + 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def observe_many(self, values) -> None:
        """Batch observation under ONE lock acquisition — per-shard
        attribution vectors land per dispatch on serving hot paths
        (mesh join shard_rows), where a lock per element is measurable
        python on a sub-millisecond warm query."""
        values = [float(v) for v in values]
        if not values:
            return
        with self._lock:
            for v in values:
                exp = self._exp(v)
                self._buckets[exp] = self._buckets.get(exp, 0) + 1
                self.count += 1
                self.sum += v
                self.min = v if self.min is None else min(self.min, v)
                self.max = v if self.max is None else max(self.max, v)

    def bucket_state(self) -> dict:
        """Raw cumulative state for delta math (`telemetry/
        timeseries.py`): bucket counts keyed by the log2 EXPONENT (None
        = the non-positive bucket), not the rendered upper bound —
        subtracting two states bucket-by-bucket yields the interval's
        observation histogram, which is what makes the sliding-window
        quantiles mergeable."""
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "buckets": dict(self._buckets)}

    def to_dict(self) -> dict:
        buckets = {("0" if exp is None else repr(float(2 ** exp))): n
                   for exp, n in sorted(
                       self._buckets.items(),
                       key=lambda kv: (-1e99 if kv[0] is None
                                       else kv[0]))}
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": self.min, "max": self.max, "buckets": buckets}


def _prom_name(name: str) -> str:
    """Map a dotted metric name onto the Prometheus name grammar
    `[a-zA-Z_:][a-zA-Z0-9_:]*` (exposition format): every illegal
    character becomes `_`, and the `hs_` prefix both namespaces the
    export and guarantees a legal first character."""
    # ASCII ranges, not str.isalnum(): isalnum() accepts Unicode
    # letters/digits (tenant ids are user strings), which the grammar
    # does not.
    out = "".join(c if ("a" <= c <= "z" or "A" <= c <= "Z"
                        or "0" <= c <= "9" or c == "_") else "_"
                  for c in name)
    return "hs_" + out


def _escape_help(text: str) -> str:
    """Escape a `# HELP` line per the exposition format: backslash and
    line feed only (double quotes are NOT escaped in HELP)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    """Escape a label VALUE per the exposition format: backslash,
    double quote, and line feed."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class MetricsRegistry:
    """Get-or-create metric namespace + the action-report ring."""

    ACTION_REPORT_RING = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._action_reports: deque = deque(maxlen=self.ACTION_REPORT_RING)
        self.started_at = time.time()

    def _get_or_create(self, name: str, cls):
        metric = self._metrics.get(name)
        if metric is None:
            with self._lock:
                metric = self._metrics.get(name)
                if metric is None:
                    metric = cls(name, self._lock)
                    self._metrics[name] = metric
        if not isinstance(metric, cls):
            raise TypeError(
                f"Metric {name!r} already registered as "
                f"{type(metric).__name__}, requested {cls.__name__}.")
        return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    # -- action reports ------------------------------------------------

    def record_action_report(self, report: dict) -> None:
        with self._lock:
            self._action_reports.append(report)

    def action_reports(self) -> List[dict]:
        """The last N structured action reports (newest last)."""
        with self._lock:
            return list(self._action_reports)

    def last_action_report(self) -> Optional[dict]:
        with self._lock:
            return self._action_reports[-1] if self._action_reports \
                else None

    # -- snapshots -----------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            metrics = dict(self._metrics)
        counters = {}
        gauges = {}
        histograms = {}
        for name in sorted(metrics):
            m = metrics[name]
            if isinstance(m, Counter):
                counters[name] = round(m.value, 6)
            elif isinstance(m, Gauge):
                gauges[name] = round(m.value, 6)
            else:
                histograms[name] = m.to_dict()
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def counters_dict(self) -> Dict[str, float]:
        """Counters only — the compact form bench artifacts embed."""
        return self.to_dict()["counters"]

    def series_snapshot(self) -> dict:
        """Raw series state for the timeseries sampler: unrounded
        counter/gauge values and full `bucket_state()` histograms, in
        one pass (one lock acquisition for the metric map; each
        histogram state is read under the shared metric lock)."""
        with self._lock:
            metrics = dict(self._metrics)
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, dict] = {}
        for name, m in metrics.items():
            if isinstance(m, Counter):
                counters[name] = m.value
            elif isinstance(m, Gauge):
                gauges[name] = m.value
            else:
                hists[name] = m.bucket_state()
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def to_text(self) -> str:
        """Prometheus text exposition format (the `/metrics` payload a
        service deployment would scrape). Conformance contract (pinned
        by `tests/test_artifact_diff.py::test_prometheus_conformance`):
        every family gets `# HELP` then `# TYPE` before its samples,
        names obey the Prometheus grammar (dotted names sanitized via
        `_prom_name`; the HELP text carries the original dotted name
        for the reverse mapping), label values are escaped per the
        format, and histogram buckets are cumulative with a closing
        `+Inf` bucket equal to `_count`. Dotted names that collide
        after sanitization are disambiguated with a numeric suffix —
        a repeated `# TYPE` for one family is a format violation."""
        with self._lock:
            metrics = dict(self._metrics)
        lines: List[str] = []
        taken: Dict[str, str] = {}  # prom name -> dotted source name
        for name in sorted(metrics):
            m = metrics[name]
            pname = _prom_name(name)
            serial = 2
            while pname in taken and taken[pname] != name:
                pname = f"{_prom_name(name)}_{serial}"
                serial += 1
            taken[pname] = name
            kind = ("counter" if isinstance(m, Counter)
                    else "gauge" if isinstance(m, Gauge)
                    else "histogram")
            lines.append(f"# HELP {pname} "
                         + _escape_help(f"hyperspace metric '{name}'"))
            lines.append(f"# TYPE {pname} {kind}")
            if kind in ("counter", "gauge"):
                lines.append(f"{pname} {m.value:g}")
                continue
            cum = 0
            for exp, n in sorted(
                    m._buckets.items(),
                    key=lambda kv: (-1e99 if kv[0] is None
                                    else kv[0])):
                cum += n
                le = "0" if exp is None else f"{float(2 ** exp):g}"
                le = _escape_label_value(le)
                lines.append(f'{pname}_bucket{{le="{le}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
            lines.append(f"{pname}_sum {m.sum:g}")
            lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric and report. A test/ops hook — a live
        service never resets (rates are derived by the scraper)."""
        with self._lock:
            self._metrics.clear()
            self._action_reports.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """THE process-wide registry (sessions share it)."""
    return _REGISTRY
