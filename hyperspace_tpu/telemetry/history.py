"""Durable on-lake telemetry history: trend memory that survives the
process.

Every observability surface before this module — registry, sampler
ring, SLO burn, flight recorder — is in-process and evaporates on
exit, so trend questions ("is warm p99 creeping week over week?",
"when did the cache hit rate collapse?") were only answerable through
hand-committed bench artifacts. The source paper's core discipline is
that ALL index data and metadata live on the lake with no side
services; telemetry history is metadata and gets the same treatment:

- **Writer** — `TelemetryHistory.flush()` assembles one append-only,
  schema-versioned SEGMENT document (registry snapshot, the sampler
  samples since the previous flush, SLO/burn state, a flight-ring
  digest, and any incidents the alert manager handed over) and
  publishes it atomically (tmp + rename via
  `file_utils.atomic_publish`, the action-report discipline — a
  reader never sees a torn segment from a live writer) under
  `spark.hyperspace.telemetry.history.dir`
  (default `<warehouse>/.hyperspace_telemetry`). The sampler's tick
  hook calls `maybe_flush()` (interval-gated); incident capture calls
  `flush(reason="incident")` immediately. Old segments are pruned by
  age (`history.keep.seconds`) and by total byte budget
  (`history.keep.bytes`), oldest first — the same keep-N discipline as
  the slowlog dumps, but budgeted in time and bytes because history is
  long-lived.
- **Reader** — `read_segments()` loads every segment in a directory,
  SKIPPING unparseable files (a crash mid-write before the rename
  leaves a `.tmp` the reader never selects; a torn file from a foreign
  writer is skipped and counted, never fatal) and `merge()` folds
  segments from any number of process lifetimes and replicas into one
  time-ordered view (samples ordered by wall time, incidents
  deduplicated by id, per-process provenance retained).
- **CLI** — `python -m hyperspace_tpu.telemetry.history report
  [--dir D] [--window S] [--series NAME] [--baseline ARTIFACT]`
  renders per-series windows and rate deltas from the merged history,
  and regression vs a named baseline round (a committed canonical
  bench artifact: its `process_metrics` counters against the history's
  latest cumulative values).

This module is the ONE place history segments are written —
`scripts/check_metrics_coverage.py` bans the directory literal
everywhere else, the same seam discipline as the ops HTTP server and
the profiler.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["TelemetryHistory", "get_history", "set_history",
           "reset_history", "configure", "read_segments", "merge",
           "trend_report", "SCHEMA_VERSION", "SEGMENT_PREFIX"]

SCHEMA_VERSION = 1
SEGMENT_PREFIX = "history-"
SEGMENT_KIND = "hyperspace-telemetry-history"

DEFAULT_INTERVAL_S = 60.0
DEFAULT_KEEP_SECONDS = 7 * 24 * 3600.0
DEFAULT_KEEP_BYTES = 64 * 1024 * 1024


def _file_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        from hyperspace_tpu.utils import storage
        if storage.is_url(path):
            try:
                fs, real = storage.get_fs(path)
                return int(fs.size(real))
            except Exception:
                return 0
        return 0


class TelemetryHistory:
    """The segment writer: one per process (`get_history()`), flushed
    from the sampler's tick hook. Every public method swallows its own
    failures into `history.flush_errors` — losing a history segment
    must never cost a query."""

    def __init__(self, directory: str,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 keep_seconds: float = DEFAULT_KEEP_SECONDS,
                 keep_bytes: int = DEFAULT_KEEP_BYTES):
        self.directory = directory
        self.interval_s = max(1.0, float(interval_s))
        self.keep_seconds = float(keep_seconds)
        self.keep_bytes = int(keep_bytes)
        self._lock = threading.Lock()
        self._seq = 0
        self._last_flush_t: Optional[float] = None
        self._last_sample_seq = 0

    # -- writing ---------------------------------------------------------

    def maybe_flush(self, conf=None, now: Optional[float] = None
                    ) -> Optional[str]:
        """Interval-gated flush (the tick hook's entry point): writes a
        segment only when `interval_s` has elapsed since the last one.
        Returns the segment path when one was written."""
        now = time.time() if now is None else float(now)
        with self._lock:
            due = (self._last_flush_t is None
                   or now - self._last_flush_t >= self.interval_s)
        if not due:
            return None
        return self.flush(conf=conf, reason="interval", now=now)

    def flush(self, conf=None, reason: str = "manual",
              now: Optional[float] = None,
              incidents: Optional[List[dict]] = None) -> Optional[str]:
        """Write one segment NOW (incident capture and `close()` call
        this directly). Returns the published path, or None on failure
        (counted `history.flush_errors`, never raised)."""
        reg = _registry.get_registry()
        now = time.time() if now is None else float(now)
        try:
            doc = self._segment_doc(conf, reason, now, incidents)
            with self._lock:
                self._seq += 1
                seq = self._seq
                self._last_flush_t = now
            fname = (f"{SEGMENT_PREFIX}{int(now * 1000)}-"
                     f"{os.getpid()}-{seq:06d}.json")
            path = os.path.join(self.directory, fname)
            from hyperspace_tpu.utils import file_utils
            file_utils.create_directory(self.directory)
            file_utils.atomic_publish(path, json.dumps(doc, default=str))
            self._prune(now)
            reg.counter("history.flushes").inc()
            reg.gauge("history.last_flush_t").set(now)
            return path
        except Exception:
            reg.counter("history.flush_errors").inc()
            import logging
            logging.getLogger(__name__).warning(
                "telemetry history flush failed", exc_info=True)
            return None

    def _segment_doc(self, conf, reason: str, now: float,
                     incidents: Optional[List[dict]]) -> dict:
        from hyperspace_tpu.telemetry import timeseries as _timeseries
        sampler = _timeseries.get_sampler()
        with self._lock:
            since_seq = self._last_sample_seq
        samples = sampler.samples(since_seq=since_seq)
        if samples:
            with self._lock:
                self._last_sample_seq = max(
                    self._last_sample_seq,
                    max(s.get("seq") or 0 for s in samples))
        doc: dict = {
            "kind": SEGMENT_KIND,
            "schema_version": SCHEMA_VERSION,
            "written_at": round(now, 3),
            "pid": os.getpid(),
            "reason": reason,
            "registry": _registry.get_registry().to_dict(),
            "samples": samples,
        }
        # SLO/burn state rides every segment so a post-hoc reader can
        # place an incident in its burn context without the sampler
        # having been running.
        try:
            from hyperspace_tpu.engine.scheduler import get_scheduler
            doc["slo"] = get_scheduler().slo_snapshot(conf)
        except Exception as exc:
            doc["slo"] = {"error": repr(exc)}
        try:
            doc["flight"] = self._flight_digest()
        except Exception as exc:
            doc["flight"] = {"error": repr(exc)}
        if incidents:
            doc["incidents"] = list(incidents)
        return doc

    @staticmethod
    def _flight_digest(recent: int = 8) -> dict:
        """A compact digest of the flight ring — enough to correlate a
        history window with the queries that flew through it, without
        persisting full operator trees every minute."""
        from hyperspace_tpu.telemetry import flight
        rec = flight.get_recorder()
        entries = []
        for qm in rec.queries(n=recent):
            entries.append({
                "description": getattr(qm, "description", None),
                "flight_seq": getattr(qm, "flight_seq", None),
                "wall_s": getattr(qm, "wall_s", None),
                "tenant": getattr(qm, "tenant", None),
                "replica": getattr(qm, "replica", None),
            })
        return {"ring": len(rec), "last_seq": rec.last_seq,
                "recent": entries}

    # -- pruning ---------------------------------------------------------

    def _prune(self, now: float) -> None:
        """Keep-by-age then keep-by-byte-budget, oldest first. Segment
        names embed the write-time millisecond, so ordering needs no
        stat calls and multiple processes sharing a directory prune
        consistently."""
        reg = _registry.get_registry()
        try:
            names = sorted(
                f for f in self._listdir()
                if f.startswith(SEGMENT_PREFIX) and f.endswith(".json"))
        except Exception:
            return
        stale: List[str] = []
        if self.keep_seconds > 0:
            cutoff_ms = int((now - self.keep_seconds) * 1000)
            for f in names:
                ms = self._name_ms(f)
                if ms is not None and ms < cutoff_ms:
                    stale.append(f)
        survivors = [f for f in names if f not in set(stale)]
        if self.keep_bytes > 0:
            sizes = [(f, _file_size(os.path.join(self.directory, f)))
                     for f in survivors]
            total = sum(s for _f, s in sizes)
            for f, s in sizes[:-1]:  # never prune the newest segment
                if total <= self.keep_bytes:
                    break
                stale.append(f)
                total -= s
        from hyperspace_tpu.utils import file_utils
        for f in stale:
            try:
                file_utils.delete(os.path.join(self.directory, f))
                reg.counter("history.segments_pruned").inc()
            except Exception:
                pass  # concurrent pruner got it first

    def _listdir(self) -> List[str]:
        from hyperspace_tpu.utils import storage
        if storage.is_url(self.directory):
            return storage.listdir_names(self.directory)
        try:
            return os.listdir(self.directory)
        except OSError:
            return []

    @staticmethod
    def _name_ms(fname: str) -> Optional[int]:
        try:
            return int(fname[len(SEGMENT_PREFIX):].split("-", 1)[0])
        except (ValueError, IndexError):
            return None


# ---------------------------------------------------------------------------
# Reading + merging (any process, any replica)
# ---------------------------------------------------------------------------


def read_segments(directory: str) -> Tuple[List[dict], int]:
    """Every parseable segment in `directory`, ordered by
    `written_at`, plus the count of files SKIPPED: `.tmp` leftovers of
    a crashed writer are excluded by name, and a torn/foreign file
    that fails to parse (or isn't a history segment) is skipped and
    counted (`history.read_skipped`), never fatal — the crash-torn
    final segment of a dead process must not poison the merge."""
    from hyperspace_tpu.utils import file_utils, storage
    if storage.is_url(directory):
        names = storage.listdir_names(directory)
    else:
        try:
            names = os.listdir(directory)
        except OSError:
            names = []
    segments: List[dict] = []
    skipped = 0
    for fname in sorted(names):
        if not fname.startswith(SEGMENT_PREFIX) \
                or not fname.endswith(".json"):
            continue
        path = os.path.join(directory, fname)
        try:
            doc = json.loads(file_utils.read_contents(path))
            if doc.get("kind") != SEGMENT_KIND:
                raise ValueError("not a history segment")
        except Exception:
            skipped += 1
            continue
        doc["_file"] = fname
        segments.append(doc)
    if skipped:
        _registry.get_registry().counter("history.read_skipped").inc(
            skipped)
    segments.sort(key=lambda d: d.get("written_at") or 0)
    return segments, skipped


def merge(directory: str) -> dict:
    """Merge every segment under `directory` — across process
    lifetimes and replicas — into one time-ordered view: all samples
    by wall time, incidents deduplicated by id (latest state wins:
    a resolved incident supersedes its firing record), and the newest
    registry snapshot per writing process."""
    segments, skipped = read_segments(directory)
    samples: List[dict] = []
    incidents: Dict[str, dict] = {}
    latest_registry: Dict[str, dict] = {}
    writers: Dict[str, dict] = {}
    for seg in segments:
        pid = str(seg.get("pid"))
        writers.setdefault(pid, {"segments": 0,
                                 "first_written_at": seg.get("written_at")})
        writers[pid]["segments"] += 1
        writers[pid]["last_written_at"] = seg.get("written_at")
        samples.extend(seg.get("samples") or [])
        for inc in seg.get("incidents") or []:
            iid = inc.get("id")
            if iid is None:
                continue
            prev = incidents.get(iid)
            if prev is None or (inc.get("resolved_at") or 0) >= \
                    (prev.get("resolved_at") or 0):
                incidents[iid] = inc
        latest_registry[pid] = seg.get("registry") or {}
    samples.sort(key=lambda s: s.get("t") or 0)
    return {
        "directory": directory,
        "schema_version": SCHEMA_VERSION,
        "segments": len(segments),
        "skipped": skipped,
        "writers": writers,
        "samples": samples,
        "incidents": sorted(incidents.values(),
                            key=lambda i: i.get("opened_at") or 0),
        "registry_by_pid": latest_registry,
    }


def trend_report(merged: dict, window_s: float = 300.0,
                 series: Optional[List[str]] = None,
                 baseline: Optional[dict] = None) -> dict:
    """Per-series trends over the merged history: for each counter,
    the rate over the trailing `window_s` next to the all-history
    rate (the delta is the trend); for each histogram, windowed
    p50/p90/p99. `baseline` (a canonical bench artifact dict) adds a
    regression section: the history's latest cumulative counters vs
    the round's committed `process_metrics`."""
    from hyperspace_tpu.telemetry.timeseries import (delta_buckets,
                                                     quantile_from_buckets)
    samples = merged.get("samples") or []
    out: dict = {"window_s": window_s, "samples": len(samples),
                 "counters": {}, "histograms": {},
                 "incidents": len(merged.get("incidents") or [])}
    if not samples:
        return out
    latest = samples[-1]
    t_end = latest.get("t") or 0
    t0 = t_end - window_s
    base = None          # newest sample at or before the window start
    first = samples[0]
    for s in samples:
        if (s.get("t") or 0) <= t0:
            base = s
        else:
            break
    names = set()
    for s in (first, base or first, latest):
        names.update((s.get("counters") or {}).keys())
    if series:
        wanted = set(series)
        names = {n for n in names if n in wanted
                 or any(n.startswith(w) for w in wanted)}
    for name in sorted(names):
        now_v = (latest.get("counters") or {}).get(name, 0.0)
        first_v = (first.get("counters") or {}).get(name, 0.0)
        span = max((latest.get("t") or 0) - (first.get("t") or 0), 1e-9)
        overall = max(0.0, now_v - first_v) / span
        row = {"value": round(now_v, 6),
               "overall_rate": round(overall, 6)}
        if base is not None:
            base_v = (base.get("counters") or {}).get(name, 0.0)
            covered = max(t_end - (base.get("t") or 0), 1e-9)
            wrate = max(0.0, now_v - base_v) / covered
            row["window_rate"] = round(wrate, 6)
            row["rate_delta"] = round(wrate - overall, 6)
        out["counters"][name] = row
    hist_names = set((latest.get("histograms") or {}).keys())
    if series:
        wanted = set(series)
        hist_names = {n for n in hist_names if n in wanted
                      or any(n.startswith(w) for w in wanted)}
    for name in sorted(hist_names):
        new_st = _parse_hist((latest.get("histograms") or {}).get(name))
        old_st = _parse_hist(((base or {}).get("histograms")
                              or {}).get(name)) if base else None
        buckets = delta_buckets(new_st, old_st)
        count = sum(buckets.values())
        if not count:
            continue
        out["histograms"][name] = {
            "count": count,
            "p50": quantile_from_buckets(buckets, 0.50),
            "p90": quantile_from_buckets(buckets, 0.90),
            "p99": quantile_from_buckets(buckets, 0.99),
        }
    if baseline is not None:
        base_counters = baseline.get("process_metrics") or {}
        reg = {}
        for name in sorted(set(base_counters)
                           & set((latest.get("counters") or {}))):
            old_v = float(base_counters.get(name) or 0.0)
            new_v = float((latest.get("counters") or {}).get(name, 0.0))
            if old_v == 0.0 and new_v == 0.0:
                continue
            reg[name] = {"baseline": round(old_v, 6),
                         "history": round(new_v, 6),
                         "change": (round(new_v / old_v, 4)
                                    if old_v else None)}
        out["vs_baseline"] = {
            "metric": baseline.get("metric"),
            "driver": baseline.get("driver"),
            "counters": reg,
        }
    return out


def _parse_hist(st: Optional[dict]) -> dict:
    """A sample's serialized histogram (`to_dict` form: string bucket
    keys, "-inf" for the non-positive bucket) back into the
    `bucket_state()` shape `delta_buckets` subtracts."""
    if not st:
        return {"count": 0, "sum": 0.0, "buckets": {}}
    buckets: Dict[Optional[int], int] = {}
    for key, n in (st.get("buckets") or {}).items():
        buckets[None if key == "-inf" else int(key)] = n
    return {"count": st.get("count", 0), "sum": st.get("sum", 0.0),
            "buckets": buckets}


# ---------------------------------------------------------------------------
# Process-wide writer + session wiring
# ---------------------------------------------------------------------------

_history: Optional[TelemetryHistory] = None
_history_lock = threading.Lock()


def get_history() -> Optional[TelemetryHistory]:
    """The process history writer, or None when never configured."""
    return _history


def set_history(history: Optional[TelemetryHistory]
                ) -> Optional[TelemetryHistory]:
    """Install a specific writer (tests: fresh directory/intervals)."""
    global _history
    with _history_lock:
        _history = history
    return history


def reset_history() -> None:
    set_history(None)


def configure(conf) -> Optional[TelemetryHistory]:
    """Session-init wiring (called from `ops_server.configure` next to
    the sampler): installs the process writer when
    `telemetry.history.enabled` is true. Failures degrade to a warning
    — history must never be a startup failure."""
    global _history
    try:
        if conf is None or not conf.telemetry_history_enabled:
            return _history
        with _history_lock:
            if _history is None:
                _history = TelemetryHistory(
                    directory=conf.telemetry_history_dir,
                    interval_s=conf.telemetry_history_interval_seconds,
                    keep_seconds=conf.telemetry_history_keep_seconds,
                    keep_bytes=conf.telemetry_history_keep_bytes)
            else:
                _history.directory = conf.telemetry_history_dir
                _history.interval_s = max(
                    1.0, conf.telemetry_history_interval_seconds)
                _history.keep_seconds = \
                    conf.telemetry_history_keep_seconds
                _history.keep_bytes = conf.telemetry_history_keep_bytes
            return _history
    except Exception:
        import logging
        logging.getLogger(__name__).warning(
            "telemetry history configuration failed; durable history "
            "disabled", exc_info=True)
        return None


def on_tick(conf=None, now: Optional[float] = None) -> None:
    """The sampler's tick hook: interval-gated flush through the
    process writer (no-op until `configure` installed one)."""
    h = _history
    if h is not None:
        h.maybe_flush(conf=conf, now=now)


# ---------------------------------------------------------------------------
# CLI: python -m hyperspace_tpu.telemetry.history report
# ---------------------------------------------------------------------------


def _main(argv: List[str]) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.telemetry.history",
        description="Render trends from on-lake telemetry history.")
    sub = parser.add_subparsers(dest="cmd")
    rep = sub.add_parser("report", help="merged trend report")
    rep.add_argument("--dir", default=None,
                     help="history directory (default: "
                          "<warehouse>/.hyperspace_telemetry via conf)")
    rep.add_argument("--window", type=float, default=300.0,
                     help="trailing window seconds (default 300)")
    rep.add_argument("--series", action="append", default=None,
                     help="series name or prefix filter (repeatable)")
    rep.add_argument("--baseline", default=None,
                     help="canonical bench artifact to regress against")
    args = parser.parse_args(argv)
    if args.cmd != "report":
        parser.print_help()
        return 2
    directory = args.dir
    if directory is None:
        from hyperspace_tpu.config import HyperspaceConf
        directory = HyperspaceConf().telemetry_history_dir
    baseline = None
    if args.baseline:
        from hyperspace_tpu.telemetry import artifact
        baseline = artifact.load(args.baseline, migrate_legacy=True)
    merged = merge(directory)
    report = trend_report(merged, window_s=args.window,
                          series=args.series, baseline=baseline)
    report["directory"] = directory
    report["segments"] = merged["segments"]
    report["skipped_segments"] = merged["skipped"]
    report["writers"] = merged["writers"]
    report["incident_list"] = [
        {k: i.get(k) for k in ("id", "rule", "state", "opened_at",
                               "resolved_at", "value", "threshold")}
        for i in merged.get("incidents") or []]
    print(json.dumps(report, indent=1, default=str))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
