"""Compile observability: THE `jax.jit` wrapper for every engine entry
point.

A retrace storm is invisible in wall-time telemetry — the cost hides
inside whichever dispatch happened to trace — so every jitted entry
point in the package routes through `instrumented_jit(name)` instead of
calling `jax.jit` directly (`scripts/check_metrics_coverage.py` fails
the build on any direct `jax.jit` call outside this module). Each call
then records:

- a `compile` span on the executing thread whenever XLA actually traced
  (its own category — and track — in the Perfetto export), covering
  trace + lowering + backend compile (the first dispatch is dominated
  by them);
- registry counters `compile.{traces,cache_hits,seconds}` plus
  per-entry-point `compile.<name>.traces`, and the jit executable-cache
  series `cache.jit.{hits,misses,entries}`;
- the same counters per-query on the active `QueryMetrics`
  (`metrics.compile` digests them — re-running an identical query must
  show ZERO new traces);
- the retrace CAUSE as a per-query decision event: the shape/dtype
  signature delta against this entry point's previous trace
  (`[compile] retrace {"target": ..., "cause": "shape: f64[100] ->
  f64[200]"}`).

Trace detection uses the one property jit guarantees: the wrapped
Python body executes exactly when XLA traces (a cache hit never re-runs
it). The wrapper pushes a per-thread frame, the body marks it, and the
call site reads the mark after dispatch — nested instrumented jits keep
their own frames.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Optional

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["instrumented_jit", "REGISTRY", "configure_persistent_cache",
           "persistent_cache_dir", "aot_warmup", "reset_aot_memo",
           "entry_point_costs"]


def entry_point_costs() -> Dict[str, tuple]:
    """{entry point name: (flops, bytes_accessed)} of the last traced
    program per instrumented jit (the memo every dispatch charges)."""
    with _sig_lock:
        return dict(_costs)

# name -> instrumented wrapper (the coverage lint audits the stamps).
REGISTRY: Dict[str, object] = {}

# name -> (flops, bytes_accessed) of the last traced program: XLA's
# own cost analysis, captured at trace time (where the lowering is
# already paid for) and charged on every subsequent dispatch of the
# entry point — the modeled-device-cost half of roofline attribution
# (`QueryMetrics.roofline`; measured wall is the other half).
_costs: Dict[str, tuple] = {}

# name -> last traced signature, PROCESS-wide (not per wrapper): entry
# points that rebuild their jit per call (the mesh step factories) must
# diff against the previous trace of the same NAME, or every trace
# would read "first trace" and a fresh-jit retrace storm would hide its
# cause ("signature unchanged (executable cache dropped)").
_last_sigs: Dict[str, tuple] = {}
_sig_lock = threading.Lock()

_tls = threading.local()

# Warm-start compilation: the persistent-cache dir currently wired into
# jax (None = not configured). One process-wide setting — jax's
# compilation cache is global, so co-resident sessions share it (same
# caveat as the transfer-engine knobs).
_persistent_dir: Optional[str] = None
_persistent_lock = threading.Lock()


def persistent_cache_dir() -> Optional[str]:
    """The configured persistent compilation cache dir, or None."""
    return _persistent_dir


def configure_persistent_cache(conf) -> bool:
    """Wire JAX's persistent compilation cache behind
    `spark.hyperspace.compile.cache.dir` (called at session init, next
    to `transfer.configure`). Every `instrumented_jit` entry point then
    participates for free — jax keys persisted executables below its
    in-memory executable cache — so a FRESH replica pointed at a shared
    cache dir serves its first canonical-shape query from disk instead
    of paying the trace (the PR-3 warm `compile.traces == 0` property,
    surviving process restarts; the restored-from-disk dispatch still
    re-runs the traced body, so it counts as one trace with near-zero
    `compile.seconds` rather than a cache hit).

    The size/compile-time eligibility floors are dropped so the
    engine's small bucketed kernels qualify. Returns True iff the cache
    is (now) active; an unset knob or a jax build without the option
    degrades to False with a warning — warm-start is an optimization,
    never a startup failure. Counted as
    `compile.persistent_cache.configured`."""
    global _persistent_dir
    try:
        path = conf.compile_cache_dir if conf is not None else None
    except Exception:
        path = None
    if not path:
        return _persistent_dir is not None
    with _persistent_lock:
        if _persistent_dir == path:
            return True
        import logging

        import jax
        try:
            jax.config.update("jax_compilation_cache_dir", str(path))
        except Exception:
            logging.getLogger(__name__).warning(
                "persistent compilation cache unsupported by this jax "
                "build; compile.cache.dir ignored", exc_info=True)
            return False
        # Eligibility floors: jax defaults skip small/fast executables,
        # which is exactly what this engine's per-bucket kernels are.
        # Best-effort — older builds lack the knobs.
        for opt, val in (
                ("jax_persistent_cache_min_entry_size_bytes", -1),
                ("jax_persistent_cache_min_compile_time_secs", 0.0)):
            try:
                jax.config.update(opt, val)
            except Exception:
                pass
        _persistent_dir = str(path)
        _registry.get_registry().counter(
            "compile.persistent_cache.configured").inc()
        return True


# Warm-start AOT executables: keys already primed this process (e.g.
# one per (index root, version, predicate shape, cohort bucket) for the
# batched serve lane). The memo makes priming idempotent — a replica
# warming on every index open never re-pays an executed warmup.
_aot_keys: set = set()
_aot_lock = threading.Lock()


def reset_aot_memo() -> None:
    """Forget which warmup keys ran (tests simulating a fresh replica).
    Does NOT drop compiled executables — jax's caches are untouched."""
    with _aot_lock:
        _aot_keys.clear()


def aot_warmup(key: tuple, fn, args_fn) -> bool:
    """Prime a jit entry point for one canonical shape, once per `key`:
    call `fn(*args_fn())` so the trace + backend compile (or, on a
    fresh replica pointed at the persistent compile cache, the
    executable LOAD) happens now — at index-open / replica-start time —
    instead of inside the first serving query. A real dummy-argument
    call is used rather than `.lower().compile()` because only a
    dispatched call populates jax's executable cache: the warmed shape's
    first serving query must show `compile.traces == 0`, not a cheap
    re-trace. Returns True iff the warmup ran (False: memo hit, or the
    attempt failed — warm-start is an optimization, never a failure).
    Counted as `compile.aot.{warmups,memo_hits,errors}`."""
    with _aot_lock:
        if key in _aot_keys:
            _registry.get_registry().counter("compile.aot.memo_hits").inc()
            return False
        _aot_keys.add(key)
    try:
        fn(*args_fn())
        _registry.get_registry().counter("compile.aot.warmups").inc()
        return True
    except Exception:
        import logging
        logging.getLogger(__name__).warning(
            "AOT warmup failed for %r (serving proceeds; the first "
            "query of this shape pays the trace)", key, exc_info=True)
        _registry.get_registry().counter("compile.aot.errors").inc()
        return False


def _frames() -> list:
    frames = getattr(_tls, "frames", None)
    if frames is None:
        frames = []
        _tls.frames = frames
    return frames


def _abstract(leaf) -> str:
    """One signature atom: dtype[shape] for arrays, repr for statics
    (truncated — stage-program keys can be long)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(s) for s in shape)}]"
    r = repr(leaf)
    return r if len(r) <= 80 else r[:77] + "..."


def _signature(args, kwargs):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return str(treedef), tuple(_abstract(l) for l in leaves)


def _retrace_cause(prev, sig) -> str:
    """Human-readable delta between the previous trace's signature and
    this one's — the 'why did this retrace' answer."""
    if prev is None:
        return "first trace"
    prev_tree, prev_leaves = prev
    tree, leaves = sig
    if prev_tree != tree:
        return "argument structure changed"
    if len(prev_leaves) != len(leaves):
        return (f"argument count changed "
                f"({len(prev_leaves)} -> {len(leaves)})")
    deltas = [f"{a} -> {b}" for a, b in zip(prev_leaves, leaves)
              if a != b]
    if not deltas:
        # Same abstract signature yet jax re-traced: the executable
        # cache was dropped (clear_cache / eviction), not a shape delta.
        return "signature unchanged (executable cache dropped)"
    shown = "; ".join(deltas[:3])
    more = f" (+{len(deltas) - 3} more)" if len(deltas) > 3 else ""
    return f"shape/dtype: {shown}{more}"


class _Frame:
    __slots__ = ("traced",)

    def __init__(self):
        self.traced = False


def _capture_cost(name: str, jfn, args, kwargs) -> Optional[tuple]:
    """XLA cost analysis of the program just traced: re-lower with the
    same arguments (the trace path already paid once; observability
    rides the slow path, never the warm one) and read the modeled
    flops / bytes accessed. Best-effort — any backend or shape that
    cannot be lowered out-of-line returns None and the dispatch
    proceeds uncounted. Re-entrancy guard: the re-lower re-runs the
    wrapped body, and a NESTED instrumented jit called from it must
    not count phantom traces or recurse into its own capture."""
    if getattr(_tls, "in_cost_capture", False):
        return None
    _tls.in_cost_capture = True
    try:
        lowered = jfn.lower(*args, **kwargs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None
        flops = float(ca.get("flops") or 0.0)
        nbytes = float(ca.get("bytes accessed") or 0.0)
        return (flops, nbytes)
    except Exception:
        return None
    finally:
        _tls.in_cost_capture = False


def instrumented_jit(name: str, fn=None, **jit_kwargs):
    """`jax.jit` with compile observability. Use exactly like jit:

        run = instrumented_jit("fusion.run_stage",
                               static_argnames=("prog",))(body)

    The returned callable forwards `clear_cache` and exposes
    `cache_size()` (the live executable count, where jax provides it).
    Usable as `instrumented_jit(name, fn)` or as a decorator factory.
    """
    if fn is None:
        return lambda f: instrumented_jit(name, f, **jit_kwargs)

    import jax

    @functools.wraps(fn)
    def body(*args, **kwargs):
        frames = _frames()
        if frames:
            frames[-1].traced = True
        return fn(*args, **kwargs)

    jfn = jax.jit(body, **jit_kwargs)

    def cache_size() -> Optional[int]:
        probe = getattr(jfn, "_cache_size", None)
        try:
            return int(probe()) if callable(probe) else None
        except Exception:
            return None

    @functools.wraps(fn)
    def call(*args, **kwargs):
        from hyperspace_tpu import telemetry

        if getattr(_tls, "in_cost_capture", False):
            # Nested dispatch under a cost-analysis re-lower: execute
            # without instrumentation (the outer capture would
            # otherwise pollute trace counters and recurse).
            return jfn(*args, **kwargs)
        frames = _frames()
        frame = _Frame()
        frames.append(frame)
        tracer = telemetry.tracer()
        ts = tracer.now_us() if tracer is not None else 0.0
        t0 = time.perf_counter()
        try:
            out = jfn(*args, **kwargs)
        finally:
            if frames and frames[-1] is frame:
                frames.pop()
        elapsed = time.perf_counter() - t0
        reg = _registry.get_registry()
        if frame.traced:
            sig = _signature(args, kwargs)
            with _sig_lock:
                cause = _retrace_cause(_last_sigs.get(name), sig)
                _last_sigs[name] = sig
            reg.counter("compile.traces").inc()
            reg.counter("compile.seconds").inc(elapsed)
            reg.counter(f"compile.{name}.traces").inc()
            # Device cost attribution: capture XLA's modeled flops /
            # bytes for THIS program while the trace is already the
            # slow path; every later dispatch charges the memoized
            # cost (per-query and process-wide).
            cost = _capture_cost(name, jfn, args, kwargs)
            if cost is not None:
                with _sig_lock:
                    _costs[name] = cost
                reg.counter(f"compile.{name}.flops").inc(cost[0])
                reg.counter(
                    f"compile.{name}.bytes_accessed").inc(cost[1])
            telemetry.memory.cache_miss("jit")
            entries = cache_size()
            if entries is not None:
                reg.gauge(f"cache.jit.{name}.entries").set(entries)
            telemetry.add_count("compile.traces")
            telemetry.add_seconds("compile.seconds", elapsed)
            telemetry.event("compile",
                            "trace" if cause == "first trace"
                            else "retrace",
                            target=name, cause=cause,
                            seconds=round(elapsed, 4))
            if tracer is not None:
                tracer.complete(f"compile {name}", "compile", ts,
                                elapsed * 1e6,
                                args={"target": name, "cause": cause})
        else:
            reg.counter("compile.cache_hits").inc()
            telemetry.memory.cache_hit("jit")
            telemetry.add_count("compile.cache_hits")
            # Warm dispatch wall = measured device-side seconds (the
            # traced path's elapsed is compile time and stays in the
            # compile bucket). Dispatch-side on async backends.
            reg.counter("device.dispatch.seconds").inc(elapsed)
            telemetry.charge_tenant("device.dispatch.seconds", elapsed)
            telemetry.add_seconds("device.dispatch_s", elapsed)
        cost = _costs.get(name)
        if cost is not None:
            # The device executed this program either way: charge the
            # modeled cost per dispatch — per-query, process-wide, AND
            # to the active tenant's `tenant.<id>.device.*` bill at the
            # same site, so per-tenant sums equal the globals exactly
            # (the chargeback contract `Hyperspace.tenant_report()`
            # asserts).
            reg.counter("device.flops").inc(cost[0])
            reg.counter("device.bytes_accessed").inc(cost[1])
            telemetry.charge_tenant("device.flops", cost[0])
            telemetry.charge_tenant("device.bytes_accessed", cost[1])
            telemetry.add_seconds("device.flops", cost[0])
            telemetry.add_seconds("device.bytes_accessed", cost[1])
        return out

    call.__compile_span_instrumented__ = True
    call.__wrapped_jit__ = jfn
    call.cache_size = cache_size
    # Drop-in jit surface: forward the introspection/maintenance API so
    # callers (HLO probes via `.lower()`, cache resets, existing
    # `_cache_size` call sites) need not know about the wrapper.
    for attr in ("clear_cache", "lower", "eval_shape", "trace",
                 "_cache_size"):
        impl = getattr(jfn, attr, None)
        if impl is not None:
            setattr(call, attr, impl)
    REGISTRY[name] = call
    return call
