"""Canonical bench-artifact schema: ONE versioned shape for every
committed benchmark round.

Until PR 6 the committed artifacts were three ad-hoc shapes — bench.py
printed a rung document, bench_tpcds.py a query document, and the
driver sometimes wrapped either in a `{n, cmd, rc, tail, parsed}`
command envelope — so two rounds could not be compared mechanically,
and the regression differ (`telemetry/diff.py`) had nothing stable to
stand on. This module is the schema authority:

- `make_artifact(...)` — the ONE emitter both bench drivers route
  their final JSON through. It stamps `schema_version`, the driver
  name, and ALWAYS attaches the three process-wide digests
  (`process_metrics`, `memory`, `transfer`), so no committed round can
  miss the telemetry the differ attributes from.
  `scripts/check_metrics_coverage.py` fails any bench driver that
  prints an artifact without routing through this seam.
- `query_metrics_block(qm)` — the per-query telemetry block: the
  compact `summary()` digest next to the FULL `to_dict()` operator
  tree (`"tree"`), which is what `diff.py` aligns node-by-node.
- `load(path)` / `migrate(doc)` — read any committed artifact,
  unwrapping the driver envelope; legacy (pre-schema) documents raise
  `LegacyArtifactError` unless migration is requested. Migration is
  lossless: every legacy field is preserved, `schema_version` is
  stamped, and `"legacy": true` records that the telemetry sections
  are absent-by-history rather than absent-by-bug.

Run `python -m hyperspace_tpu.telemetry.artifact migrate FILE...` to
migrate committed artifacts in place (the driver envelope, when
present, is preserved and its `parsed` payload migrated).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

# A canonical artifact MUST carry these; `validate()` reports what is
# missing and the regression gate refuses to gate without them.
REQUIRED_FIELDS = ("schema_version", "metric", "value", "vs_baseline",
                   "process_metrics")


class LegacyArtifactError(Exception):
    """Raised when a pre-schema artifact is loaded without asking for
    migration — gating or diffing it silently would compare shapes
    that do not mean the same thing."""

    def __init__(self, path: str, missing: List[str]):
        self.path = path
        self.missing = missing
        super().__init__(
            f"{path}: legacy-schema bench artifact (missing "
            f"{', '.join(missing)}). Re-run the bench driver (it now "
            "emits the canonical schema), or migrate in place: "
            "python -m hyperspace_tpu.telemetry.artifact migrate "
            f"{path}")


def transfer_digest() -> dict:
    """Process-lifetime digest of the pipelined transfer engine's link
    counters — embedded by every driver so the overlap the engine
    claims is a committed number, not an assumption."""
    from hyperspace_tpu.telemetry import registry as _registry

    c = _registry.get_registry().counters_dict()
    return {
        "h2d_bytes": int(c.get("link.h2d.bytes", 0)),
        "h2d_seconds": round(c.get("link.h2d.seconds", 0.0), 3),
        "h2d_chunks": int(c.get("link.h2d.chunks", 0)),
        "h2d_transfers": int(c.get("link.h2d.transfers", 0)),
        "d2h_bytes": int(c.get("link.d2h.bytes", 0)),
        "d2h_seconds": round(c.get("link.d2h.seconds", 0.0), 3),
        "d2h_chunks": int(c.get("link.d2h.chunks", 0)),
        "d2h_prefetch_errors": int(c.get("link.d2h.prefetch_errors", 0)),
        "overlap_saved_seconds": round(
            c.get("transfer.overlap_saved_seconds", 0.0), 3),
    }


def segments_digest() -> dict:
    """Process-lifetime digest of the HBM segment cache
    (`io/segcache.py`) — hit/miss/fill/eviction counts and current
    residency. Bench drivers embed it (with per-rung warm deltas) so
    "repeat queries are link-free" is a committed, gateable number:
    `scripts/bench_regress.py`'s warm-rung gate reads this block."""
    from hyperspace_tpu.telemetry import registry as _registry

    reg = _registry.get_registry()
    c = reg.counters_dict()
    return {
        "hits": int(c.get("cache.segments.hits", 0)),
        "misses": int(c.get("cache.segments.misses", 0)),
        "fills": int(c.get("cache.segments.fills", 0)),
        "evictions": int(c.get("cache.segments.evictions", 0)),
        "fill_bytes": int(c.get("transfer.fill.bytes", 0)),
        "fill_chunks": int(c.get("transfer.fill.chunks", 0)),
        "bytes_held": int(reg.gauge("cache.segments.bytes_held").value),
        "entries": int(reg.gauge("cache.segments.entries").value),
        "pins": int(reg.gauge("cache.segments.pins").value),
    }


def device_cost_digest() -> dict:
    """Process-lifetime roofline digest: modeled device cost (XLA
    cost_analysis, captured per trace and charged per dispatch by
    `instrumented_jit`) next to the measured warm-dispatch wall, plus
    the per-entry-point cost memo — so a committed round carries
    whether the work was device-bound or overhead-bound, not just how
    long it took."""
    from hyperspace_tpu.telemetry import compilation
    from hyperspace_tpu.telemetry import registry as _registry

    c = _registry.get_registry().counters_dict()
    flops = float(c.get("device.flops", 0.0))
    nbytes = float(c.get("device.bytes_accessed", 0.0))
    disp = float(c.get("device.dispatch.seconds", 0.0))
    return {
        "flops": round(flops, 1),
        "bytes_accessed": round(nbytes, 1),
        "dispatch_seconds": round(disp, 6),
        "intensity_flops_per_byte": (round(flops / nbytes, 4)
                                     if nbytes else None),
        "achieved_flops_per_s": (round(flops / disp, 1)
                                 if disp > 0 else None),
        "per_entry_point": {
            name: {"flops": round(f, 1), "bytes_accessed": round(b, 1)}
            for name, (f, b)
            in sorted(compilation.entry_point_costs().items())},
    }


def tenant_cost_digest() -> dict:
    """Per-tenant chargeback digest: each known tenant's billed device
    cost, link bytes, and cache fills (`telemetry.tenant_digest()`),
    plus the exactness check — per-tenant sums vs the global counters.
    Attached to every artifact so a committed round records WHO spent
    the device-seconds, not just that they were spent."""
    from hyperspace_tpu import telemetry

    usage = telemetry.tenant_digest()
    counters = telemetry.get_registry().counters_dict()
    totals = {name: sum(u.get(name, 0) for u in usage.values())
              for name in telemetry.TENANT_CHARGE_COUNTERS}
    global_ = {name: counters.get(name, 0)
               for name in telemetry.TENANT_CHARGE_COUNTERS}
    return {
        "tenants": usage,
        "totals": {k: round(v, 6) if isinstance(v, float) else v
                   for k, v in totals.items()},
        "global": {k: round(v, 6) if isinstance(v, float) else v
                   for k, v in global_.items()},
        "exact": all(abs(totals[n] - global_[n])
                     <= 1e-9 * max(1.0, abs(global_[n]))
                     for n in totals),
    }


def critpath_digest() -> dict:
    """Process-lifetime latency anatomy: total seconds attributed to
    each critical-path segment across every stamped query
    (`telemetry/critical_path.py`), their share of total query wall,
    and the dominant segment. Attached to every artifact so a
    committed round records WHERE the wall went, not just how long it
    was."""
    from hyperspace_tpu.telemetry import critical_path
    from hyperspace_tpu.telemetry import registry as _registry

    c = _registry.get_registry().counters_dict()
    wall = float(c.get("critpath.wall.seconds", 0.0))
    seconds = {seg: round(float(
        c.get(f"critpath.{seg}.seconds", 0.0)), 6)
        for seg in critical_path.SEGMENTS}
    out = {
        "queries": int(c.get("critpath.queries", 0)),
        "wall_seconds": round(wall, 6),
        "seconds": seconds,
        "shares": {seg: (round(v / wall, 4) if wall else 0.0)
                   for seg, v in seconds.items()},
        "overlap_seconds": round(float(
            c.get("critpath.overlap.seconds", 0.0)), 6),
    }
    out["dominant"] = (max(seconds, key=seconds.get)
                       if wall else None)
    return out


def query_metrics_block(qm) -> dict:
    """Per-query telemetry block: `summary()` (the compact rollup
    earlier rounds embedded) plus the full `to_dict()` operator tree
    the differ aligns node-by-node. `qm` may be None (e.g. a lane that
    never executed under a recorder) — both keys are then None so the
    artifact shape stays diffable."""
    if qm is None:
        return {"metrics": None, "tree": None}
    return {"metrics": qm.summary(), "tree": qm.to_dict()}


def make_artifact(*, driver: str, metric: str, value, unit: str,
                  vs_baseline, queries: Optional[Dict[str, dict]] = None,
                  rungs: Optional[Dict[str, dict]] = None,
                  extra: Optional[dict] = None) -> dict:
    """Assemble the canonical artifact document. The three process-wide
    digests are attached HERE, unconditionally — a driver cannot emit a
    canonical artifact that lacks them."""
    from hyperspace_tpu import telemetry

    doc: dict = {
        "schema_version": SCHEMA_VERSION,
        "driver": driver,
        "generated_at": round(time.time(), 3),
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }
    import sys
    if "jax" in sys.modules:  # record the backend without forcing one
        import jax
        doc["platform"] = jax.devices()[0].platform
    if extra:
        doc.update(extra)
    if queries is not None:
        doc["queries"] = queries
    if rungs is not None:
        doc["rungs"] = rungs
    doc["transfer"] = transfer_digest()
    doc["process_metrics"] = telemetry.get_registry().counters_dict()
    doc["memory"] = telemetry.memory.artifact_section()
    doc["device_cost"] = device_cost_digest()
    doc["tenant_cost"] = tenant_cost_digest()
    doc["critical_path"] = critpath_digest()
    return doc


# ---------------------------------------------------------------------------
# Loading / validation / migration
# ---------------------------------------------------------------------------


def unwrap(doc: dict) -> dict:
    """Strip the external driver's `{n, cmd, rc, tail, parsed}` command
    envelope, when present (the driver wraps whatever the bench process
    printed; the payload is what the schema governs)."""
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict) \
            and "cmd" in doc:
        return doc["parsed"]
    return doc


def validate(doc: dict) -> List[str]:
    """Missing required canonical fields (empty list = canonical)."""
    doc = unwrap(doc)
    return [f for f in REQUIRED_FIELDS if f not in doc]


def is_canonical(doc: dict) -> bool:
    return not validate(doc)


def migrate(doc: dict, source: str = "") -> dict:
    """Upgrade a legacy document to the canonical schema IN MEMORY,
    losslessly: every field the legacy round committed is preserved,
    `schema_version` is stamped, telemetry sections the round never
    recorded are filled with empty dicts, and `"legacy": true` marks
    that those sections are absent-by-history. Canonical input is
    returned unchanged."""
    doc = unwrap(doc)
    if is_canonical(doc):
        return doc
    out = dict(doc)
    out["schema_version"] = SCHEMA_VERSION
    out["legacy"] = True
    if source:
        out["migrated_from"] = source
    out.setdefault("process_metrics", {})
    # Headline fields a driver-less legacy blob (e.g. the pre-r06
    # MULTICHIP `{n_devices, rc, ok}` smoke checks) never carried:
    # present-but-null keeps the shape canonical while every gate
    # treats the non-numeric values as not-gateable history.
    out.setdefault("metric", "legacy")
    out.setdefault("value", None)
    out.setdefault("vs_baseline", None)
    return out


def load(path: str, migrate_legacy: bool = False) -> dict:
    """Load a committed artifact (driver envelope unwrapped). Legacy
    documents raise `LegacyArtifactError` unless `migrate_legacy`."""
    with open(path) as f:
        doc = json.load(f)
    doc = unwrap(doc)
    if not isinstance(doc, dict):
        raise LegacyArtifactError(path, list(REQUIRED_FIELDS))
    missing = validate(doc)
    if missing:
        if not migrate_legacy:
            raise LegacyArtifactError(path, missing)
        doc = migrate(doc, source=path)
    return doc


def migrate_file(path: str) -> bool:
    """Migrate a committed artifact file in place, preserving the
    driver envelope when present. Returns True if the file changed."""
    with open(path) as f:
        outer = json.load(f)
    inner = unwrap(outer)
    if is_canonical(inner):
        return False
    migrated = migrate(inner, source="legacy "
                       + (inner.get("metric") or "artifact"))
    if inner is not outer:
        outer = dict(outer)
        outer["parsed"] = migrated
    else:
        outer = migrated
    with open(path, "w") as f:
        json.dump(outer, f)
        f.write("\n")
    return True


def _main(argv: List[str]) -> int:
    if len(argv) >= 2 and argv[0] == "migrate":
        for path in argv[1:]:
            changed = migrate_file(path)
            print(f"{path}: {'migrated' if changed else 'already canonical'}")
        return 0
    print("usage: python -m hyperspace_tpu.telemetry.artifact "
          "migrate FILE...")
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
