"""Device-memory accountant + byte-aware cache instrumentation.

PRs 1-2 measure TIME (operator walls, spans, link seconds); this module
lights the RESOURCE dimension — the triad that bites first in any
production accelerator stack:

- **HBM**: per-device live/peak bytes, sampled at span boundaries
  (operator finish), at every instrumented H2D/D2H link transfer, and
  at query end. On real accelerators the numbers come from
  `device.memory_stats()` (allocator truth, including fragmentation);
  on CPU/virtual meshes — where `memory_stats()` returns None — an
  accounting fallback sums `jax.live_arrays()` per device (sharded
  arrays split their bytes across their device set). Samples land as
  registry gauges (`memory.<dev>.bytes_in_use` / `.peak_bytes`),
  per-query peak watermarks on the active `QueryMetrics`
  (`peak_hbm_bytes` + per-device), and — when tracing — Chrome
  counter-track events (`"ph":"C"`), one track per device in Perfetto.

- **Caches**: every cache in the system reports
  `cache.<name>.{hits,misses,evictions}` counters and
  `cache.<name>.{bytes_held,entries}` gauges through the helpers here
  (fusion promotion + broadcast-table caches, the fused-stage trace
  cache, the jit executable caches, parquet read/host/device batch
  caches, the index metadata cache) — so cache thrash is a scrape-able
  series instead of a guess.

Sampling discipline: `maybe_sample()` is a no-op unless a per-query
recorder is active or tracing is enabled (the same always-off contract
as every other hook), and throttles to `SAMPLE_MIN_INTERVAL_S` between
walks so the live-arrays fallback cannot dominate a tight operator
loop; `sample(force=True)` bypasses the throttle at query boundaries.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["DeviceMemoryAccountant", "get_accountant", "maybe_sample",
           "sample", "snapshot", "artifact_section", "cache_hit",
           "cache_miss", "cache_eviction", "cache_stats"]

# Minimum seconds between throttled samples. The live-arrays fallback
# walks every live jax array; at span-boundary call rates an unthrottled
# walk would tax exactly the hot paths telemetry must not.
SAMPLE_MIN_INTERVAL_S = 0.01


def _device_label(device) -> str:
    try:
        return f"{device.platform}:{device.id}"
    except Exception:
        return str(device)


def _stats_sample() -> Optional[Dict[str, Tuple[int, int]]]:
    """{device: (bytes_in_use, peak_bytes)} from the allocator, or None
    when ANY visible device lacks `memory_stats()` (CPU/virtual meshes,
    older runtimes) — mixed sources would make per-device comparison
    meaningless, so the fallback then covers all of them."""
    import jax

    out: Dict[str, Tuple[int, int]] = {}
    for d in jax.devices():
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if not st or "bytes_in_use" not in st:
            return None
        in_use = int(st["bytes_in_use"])
        out[_device_label(d)] = (in_use,
                                 int(st.get("peak_bytes_in_use", in_use)))
    return out or None


def _live_arrays_sample() -> Dict[str, Tuple[int, int]]:
    """Accounting fallback: sum live-array bytes per device. A sharded
    array's `nbytes` is the GLOBAL logical size; its per-device share is
    the even split over its device set (exact for the engine's row
    sharding). Peak is tracked by the accountant, not the walk."""
    import jax

    live: Dict[str, int] = {}
    for arr in jax.live_arrays():
        try:
            devices = arr.devices()
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        if not devices:
            continue
        share = nbytes // len(devices)
        for d in devices:
            label = _device_label(d)
            live[label] = live.get(label, 0) + share
    return {label: (b, b) for label, b in live.items()}


class DeviceMemoryAccountant:
    """Tracks per-device live and peak HBM bytes for the process, and
    attributes per-query peak watermarks to the active recorder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_sample_t = 0.0
        self.live: Dict[str, int] = {}
        self.peak: Dict[str, int] = {}
        self.backend: Optional[str] = None  # "memory_stats"|"live_arrays"
        self.samples = 0

    # -- sampling ------------------------------------------------------

    def sample(self, force: bool = True) -> Optional[Dict[str, int]]:
        """Take one sample: update gauges, process peaks, the active
        recorder's watermarks, and (when tracing) the per-device counter
        tracks. Returns {device: bytes_in_use} or None when throttled."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_sample_t \
                    < SAMPLE_MIN_INTERVAL_S:
                return None
            self._last_sample_t = now
        per_dev = _stats_sample()
        if per_dev is not None:
            backend = "memory_stats"
        else:
            per_dev = _live_arrays_sample()
            backend = "live_arrays"
        reg = _registry.get_registry()
        live: Dict[str, int] = {}
        with self._lock:
            self.backend = backend
            self.samples += 1
            for dev, (in_use, dev_peak) in per_dev.items():
                self.live[dev] = in_use
                self.peak[dev] = max(self.peak.get(dev, 0), dev_peak,
                                     in_use)
                live[dev] = in_use
            peaks = dict(self.peak)
        for dev, in_use in live.items():
            reg.gauge(f"memory.{dev}.bytes_in_use").set(in_use)
            reg.gauge(f"memory.{dev}.peak_bytes").set(peaks[dev])
        from hyperspace_tpu import telemetry
        rec = telemetry.current()
        if rec is not None:
            rec.observe_hbm(live)
        tracer = telemetry.tracer()
        if tracer is not None:
            for dev, in_use in live.items():
                tracer.counter(f"HBM {dev}", {"bytes_in_use": in_use})
        return live

    def maybe_sample(self) -> None:
        """Throttled sample, and only when someone is listening (active
        recorder or tracer) — THE span-boundary hook."""
        from hyperspace_tpu import telemetry
        if telemetry.current() is None and telemetry.tracer() is None:
            return
        self.sample(force=False)

    # -- snapshots -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "backend": self.backend,
                "samples": self.samples,
                "devices": {dev: {"bytes_in_use": self.live.get(dev, 0),
                                  "peak_bytes": peak}
                            for dev, peak in sorted(self.peak.items())},
                "peak_hbm_bytes": sum(self.peak.values()),
            }


_ACCOUNTANT = DeviceMemoryAccountant()


def get_accountant() -> DeviceMemoryAccountant:
    """THE process-wide device-memory accountant."""
    return _ACCOUNTANT


def maybe_sample() -> None:
    _ACCOUNTANT.maybe_sample()


def sample(force: bool = True):
    return _ACCOUNTANT.sample(force=force)


def snapshot() -> dict:
    return _ACCOUNTANT.snapshot()


# ---------------------------------------------------------------------------
# Byte-aware cache instrumentation: one naming scheme for every cache.
# ---------------------------------------------------------------------------


def cache_hit(name: str, n: int = 1) -> None:
    _registry.get_registry().counter(f"cache.{name}.hits").inc(n)
    _query_cache_count(f"cache.{name}.hits", n)


def cache_miss(name: str, n: int = 1) -> None:
    _registry.get_registry().counter(f"cache.{name}.misses").inc(n)
    _query_cache_count(f"cache.{name}.misses", n)


def cache_eviction(name: str, n: int = 1) -> None:
    if n:
        _registry.get_registry().counter(f"cache.{name}.evictions").inc(n)
        _query_cache_count(f"cache.{name}.evictions", n)


def _query_cache_count(counter: str, n: int) -> None:
    """Mirror a cache event onto the active per-query recorder (no-op
    without one) — the regression differ's `cache` bucket reads these
    per-query `cache.<name>.*` deltas, so WHICH query thrashed a cache
    is attributable round-over-round, not just that the process did."""
    from hyperspace_tpu import telemetry
    telemetry.add_count(counter, n)


def cache_stats(name: str, bytes_held: Optional[int],
                entries: Optional[int]) -> None:
    """Post-mutation residency gauges; pass None to leave one unset
    (e.g. a metadata cache with no meaningful byte size)."""
    reg = _registry.get_registry()
    if bytes_held is not None:
        reg.gauge(f"cache.{name}.bytes_held").set(bytes_held)
    if entries is not None:
        reg.gauge(f"cache.{name}.entries").set(entries)


def artifact_section() -> dict:
    """The memory/compile block bench artifacts embed next to
    `process_metrics`: per-device peak HBM, per-cache
    hit/miss/eviction/bytes-held series, compile trace/cache-hit
    counts. Everything a regression gate (`scripts/bench_regress.py`)
    or a committed round needs to carry the resource story."""
    snap = _ACCOUNTANT.snapshot()
    reg = _registry.get_registry().to_dict()
    caches: Dict[str, dict] = {}
    for kind, metrics in (("counters", reg["counters"]),
                          ("gauges", reg["gauges"])):
        for name, value in metrics.items():
            if not name.startswith("cache."):
                continue
            _, cache_name, series = name.split(".", 2)
            caches.setdefault(cache_name, {})[series] = value
    # Complete each cache's standard series with explicit zeros — a
    # cache that never evicted (or never hit) still reports the full
    # shape, so artifact consumers diff like-for-like across rounds.
    for series in ("hits", "misses", "evictions"):
        for stats in caches.values():
            stats.setdefault(series, 0)
    compile_stats = {k.split(".", 1)[1]: v
                     for k, v in reg["counters"].items()
                     if k.startswith("compile.")}
    snap["caches"] = caches
    snap["compile"] = compile_stats
    return snap
