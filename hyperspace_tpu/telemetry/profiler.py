"""Continuous host profiling + triggered device-trace capture.

Two instruments, one discipline (in-process, pull-based, opt-in):

**Sampling profiler** — a daemon thread walks every live thread's
stack (`sys._current_frames()`) at `telemetry.profiler.hz` and
aggregates host time by collapsed stack. Cheap enough to leave on in
production (the overhead gate in `bench_regress.py --serve` holds it
under 2% of closed-loop QPS): sampling costs one frame walk per
thread per tick, no tracing hooks, no interpreter callbacks. Exports
the two standard shapes — collapsed stacks (`module:function;... N`,
the flamegraph.pl / speedscope input) and nested flamegraph JSON
(d3-flame-graph) — plus by-module/by-function host-time tables,
all served by the `/profile` ops endpoint.

**Triggered device capture** — the ONE sanctioned `jax.profiler`
seam in the tree (`scripts/check_metrics_coverage.py` bans the import
anywhere else, like the ops-HTTP and link-transfer seams).
`device_trace(path)` wraps `jax.profiler.trace` under a process lock
(jax allows one active trace session); the executor's `trace.dir`
per-query capture routes through it. `request_capture()` fires a
BACKGROUND capture — used by the scheduler when SLO burn crosses 1.0
and by the flight recorder when a slowlog dump lands — writing a
`profile-*` directory next to the slow-query dumps with the same
atomic-rename + keep-N pruning, rate-limited by
`telemetry.profiler.capture.min.interval.seconds` so a burn storm
cannot turn the profiler into the incident.

Nothing here starts unless asked: `configure(conf)` starts the
sampler only when `telemetry.profiler.enabled` is true, and triggered
capture only arms when `telemetry.profiler.capture.seconds` > 0.
"""

from __future__ import annotations

import logging
import os
import shutil
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["SamplingProfiler", "get_profiler", "configure",
           "device_trace", "request_capture", "maybe_capture_on_burn",
           "recent_captures", "profile_doc"]

logger = logging.getLogger(__name__)

DEFAULT_HZ = 19.0  # off the 10/100Hz grid: avoids aliasing periodic work

# How many frames of each stack to keep (leaf-most). Bounds the key
# space: a deep recursive planner stack collapses to its hot suffix.
MAX_STACK_DEPTH = 48


def _frame_key(frame) -> Optional[Tuple[str, ...]]:
    """Collapse one thread's stack to a root-first tuple of
    `module:function` labels. None for frames inside this module
    (the sampler never profiles itself)."""
    labels: List[str] = []
    depth = 0
    f = frame
    while f is not None and depth < MAX_STACK_DEPTH * 2:
        code = f.f_code
        mod = f.f_globals.get("__name__", "?")
        if mod == __name__:
            return None
        labels.append(f"{mod}:{code.co_name}")
        f = f.f_back
        depth += 1
    labels.reverse()
    return tuple(labels[-MAX_STACK_DEPTH:])


class SamplingProfiler:
    """The always-on host profiler: one daemon thread, one dict of
    collapsed stacks -> sample counts. `start()`/`stop()` are
    idempotent; `drain()` waits for the loop to exit; `reset()` clears
    the aggregate without stopping (the bench's A/B phases use it)."""

    def __init__(self, hz: float = DEFAULT_HZ):
        self.hz = max(float(hz), 0.1)
        self._stacks: Dict[Tuple[str, ...], int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.started_at: Optional[float] = None
        self.samples = 0  # thread-stack samples folded in (all threads)
        self.ticks = 0    # sampling-loop iterations

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        self.started_at = time.time()
        self._thread = threading.Thread(target=self._loop,
                                        name="hs-profiler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def drain(self, timeout: float = 5.0) -> None:
        """Stop and wait for the sampling thread to exit."""
        self.stop()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._thread = None

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self.samples = 0
            self.ticks = 0
        self.started_at = time.time()

    # -- the sampling loop ----------------------------------------------

    def _loop(self) -> None:
        reg = _registry.get_registry()
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            t0 = time.perf_counter()
            try:
                frames = sys._current_frames()
            except Exception:
                continue
            batch: List[Tuple[str, ...]] = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                key = _frame_key(frame)
                if key:
                    batch.append(key)
            with self._lock:
                for key in batch:
                    self._stacks[key] = self._stacks.get(key, 0) + 1
                self.samples += len(batch)
                self.ticks += 1
            reg.counter("profiler.samples").inc(len(batch))
            reg.counter("profiler.sample.seconds").inc(
                time.perf_counter() - t0)

    # -- aggregation + export -------------------------------------------

    def snapshot(self) -> Dict[Tuple[str, ...], int]:
        with self._lock:
            return dict(self._stacks)

    def by_module(self, top: int = 25) -> List[dict]:
        """Host time by the LEAF frame's module — where threads
        actually were, attributed to one module each (self time)."""
        agg: Dict[str, int] = {}
        total = 0
        for stack, n in self.snapshot().items():
            mod = stack[-1].split(":", 1)[0]
            agg[mod] = agg.get(mod, 0) + n
            total += n
        return [{"module": m, "samples": n,
                 "share": round(n / total, 4) if total else 0.0}
                for m, n in sorted(agg.items(),
                                   key=lambda kv: -kv[1])[:top]]

    def by_function(self, top: int = 25) -> List[dict]:
        agg: Dict[str, int] = {}
        total = 0
        for stack, n in self.snapshot().items():
            agg[stack[-1]] = agg.get(stack[-1], 0) + n
            total += n
        return [{"function": fn, "samples": n,
                 "share": round(n / total, 4) if total else 0.0}
                for fn, n in sorted(agg.items(),
                                    key=lambda kv: -kv[1])[:top]]

    def collapsed(self) -> str:
        """Collapsed-stack text (`a;b;c N` per line) — the input
        format of flamegraph.pl and speedscope."""
        lines = [f"{';'.join(stack)} {n}"
                 for stack, n in sorted(self.snapshot().items())]
        return "\n".join(lines) + ("\n" if lines else "")

    def flamegraph(self) -> dict:
        """Nested d3-flame-graph JSON: each node
        `{name, value, children}` where value counts samples in the
        whole subtree."""
        root = {"name": "all", "value": 0, "children": {}}
        for stack, n in self.snapshot().items():
            root["value"] += n
            node = root
            for label in stack:
                child = node["children"].get(label)
                if child is None:
                    child = {"name": label, "value": 0, "children": {}}
                    node["children"][label] = child
                child["value"] += n
                node = child

        def listify(node: dict) -> dict:
            out = {"name": node["name"], "value": node["value"]}
            kids = [listify(c) for c in node["children"].values()]
            if kids:
                out["children"] = sorted(kids,
                                         key=lambda c: -c["value"])
            return out

        return listify(root)


# ---------------------------------------------------------------------------
# Process-wide sampler
# ---------------------------------------------------------------------------

_profiler: Optional[SamplingProfiler] = None
_profiler_lock = threading.Lock()


def get_profiler() -> Optional[SamplingProfiler]:
    """The process sampling profiler, or None when never enabled."""
    return _profiler


def start_profiler(hz: float = DEFAULT_HZ) -> SamplingProfiler:
    """Start (or return) THE process sampler. Starting while already
    running keeps the running rate (the sampler is process-wide);
    restarting a stopped sampler adopts the new rate, keeping the
    accumulated stacks (`reset()` clears them)."""
    global _profiler
    with _profiler_lock:
        if _profiler is not None and _profiler.running:
            return _profiler
        if _profiler is None:
            _profiler = SamplingProfiler(hz=hz)
        else:
            _profiler.hz = max(float(hz), 0.1)
        return _profiler.start()


def stop_profiler() -> None:
    with _profiler_lock:
        p = _profiler
    if p is not None:
        p.drain()


def configure(conf) -> Optional[SamplingProfiler]:
    """Session-init wiring (called from `ops_server.configure` next to
    the sampler): starts the host sampler when
    `telemetry.profiler.enabled` is set. Failures degrade to a warning
    — profiling must never be a startup failure."""
    try:
        if conf is None or not conf.profiler_enabled:
            return _profiler
        return start_profiler(hz=conf.profiler_hz)
    except Exception:
        logger.warning("sampling profiler failed to start",
                       exc_info=True)
        return None


# ---------------------------------------------------------------------------
# Device-trace capture: the one jax.profiler seam
# ---------------------------------------------------------------------------

# jax supports one active profiler session per process; concurrent
# captures (per-query trace.dir + a triggered burn capture) serialize
# here rather than erroring inside jax.
_trace_lock = threading.Lock()

_capture_lock = threading.Lock()
_capture_pool = None
_last_capture_t: Optional[float] = None
_capture_seq = 0
_recent_captures: List[dict] = []

_CAPTURE_PREFIX = "profile-"


@contextmanager
def device_trace(path: str):
    """Capture a jax device trace of the enclosed block into `path`
    (a directory, per the jax profiler's layout). THE one place the
    tree touches `jax.profiler`; everything else routes through here
    so captures serialize under one lock."""
    import jax
    with _trace_lock:
        with jax.profiler.trace(path):
            yield


def recent_captures(n: int = 10) -> List[dict]:
    """The newest triggered captures ({path, reason, requested_at,
    state}), newest last. State moves queued -> done | error."""
    with _capture_lock:
        return [dict(c) for c in _recent_captures[-n:]]


def _capture_dir(conf) -> str:
    # Captures live next to the slow-query dumps — a dump and the
    # device profile it triggered prune and ship together.
    return conf.slowlog_dir


def request_capture(conf, reason: str = "manual") -> Optional[str]:
    """Fire a background device-trace capture of the next
    `telemetry.profiler.capture.seconds` of device activity. Returns
    the capture directory the trace will land in, or None when
    triggered capture is disabled (`capture.seconds` <= 0) or the
    rate limit (`capture.min.interval.seconds`) says not yet. Never
    blocks and never raises into the caller: the capture itself rides
    a one-thread background lane; errors are counted
    (`profiler.capture_errors`) and logged."""
    global _capture_pool, _last_capture_t, _capture_seq
    try:
        seconds = float(conf.profiler_capture_seconds)
    except Exception:
        return None
    if seconds <= 0:
        return None
    now = time.monotonic()
    with _capture_lock:
        if _last_capture_t is not None and \
                now - _last_capture_t < conf.profiler_capture_min_interval_s:
            return None
        _last_capture_t = now
        _capture_seq += 1
        seq = _capture_seq
        if _capture_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _capture_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hs-profiler-capture")
        pool = _capture_pool
    target = os.path.join(
        _capture_dir(conf),
        f"{_CAPTURE_PREFIX}{int(time.time() * 1000)}-"
        f"{os.getpid()}-{seq:06d}")
    entry = {"path": target, "reason": reason,
             "requested_at": round(time.time(), 3), "state": "queued"}
    with _capture_lock:
        _recent_captures.append(entry)
        del _recent_captures[:-32]
    keep = conf.profiler_capture_keep
    pool.submit(_run_capture, target, seconds, keep, entry)
    return target


def _run_capture(target: str, seconds: float, keep: int,
                 entry: dict) -> None:
    """The background capture job: trace into `<target>.tmp`, sleep
    out the window, atomically rename, prune. A reader never sees a
    half-written capture directory."""
    reg = _registry.get_registry()
    tmp = target + ".tmp"
    try:
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with device_trace(tmp):
            time.sleep(seconds)
        os.replace(tmp, target)
        _prune_captures(os.path.dirname(target), keep)
        reg.counter("profiler.captures").inc()
        with _capture_lock:
            entry["state"] = "done"
        logger.warning("device profile (%s) captured to %s",
                       entry.get("reason"), target)
    except Exception:
        reg.counter("profiler.capture_errors").inc()
        with _capture_lock:
            entry["state"] = "error"
        shutil.rmtree(tmp, ignore_errors=True)
        logger.warning("triggered device capture failed", exc_info=True)


def _prune_captures(capture_dir: str, keep: int) -> None:
    def order(fname: str):
        try:
            return (os.path.getmtime(os.path.join(capture_dir, fname)),
                    fname)
        except OSError:
            return (0.0, fname)

    try:
        caps = sorted((f for f in os.listdir(capture_dir)
                       if f.startswith(_CAPTURE_PREFIX)
                       and not f.endswith(".tmp")), key=order)
    except OSError:
        return
    for stale in caps[:max(len(caps) - max(keep, 1), 0)]:
        shutil.rmtree(os.path.join(capture_dir, stale),
                      ignore_errors=True)


def maybe_capture_on_burn(conf, burn_rate: float) -> Optional[str]:
    """The scheduler's SLO hook: when the sliding-window burn rate
    crosses 1.0 (eating error budget faster than earning it), grab a
    device profile of the incident while it is still happening. The
    rate limit in `request_capture` makes a sustained burn produce a
    trickle of captures, not a flood."""
    if burn_rate is None or burn_rate <= 1.0:
        return None
    return request_capture(conf, reason=f"slo-burn:{burn_rate:.2f}")


def profile_doc() -> dict:
    """The `/profile` JSON payload: sampler state + host-time tables +
    flamegraph + recent triggered captures. Renders a useful shape
    even with the sampler off (enabled=false, captures still listed)."""
    p = get_profiler()
    doc: dict = {"enabled": p is not None and p.running,
                 "captures": recent_captures()}
    if p is not None:
        doc.update({
            "hz": p.hz,
            "started_at": p.started_at,
            "samples": p.samples,
            "ticks": p.ticks,
            "by_module": p.by_module(),
            "by_function": p.by_function(),
            "flamegraph": p.flamegraph(),
        })
    return doc


def _atexit_stop() -> None:
    global _capture_pool
    try:
        stop_profiler()
    except Exception:
        pass
    with _capture_lock:
        pool, _capture_pool = _capture_pool, None
    if pool is not None:
        try:
            pool.shutdown(wait=True)
        except Exception:
            pass


import atexit  # noqa: E402

atexit.register(_atexit_stop)
