"""Per-query critical-path extraction: where did this query's wall GO.

The recorder (`telemetry/__init__.py`) already captures every timed
fact about one execution — queue wait, batch gather, cache-fill waits,
compile, device dispatch, link transfers — but as a flat counter bag.
This module turns that bag into a LATENCY ANATOMY: every completed
query's wall is decomposed into a CLOSED set of segments,

    queue_wait       admission-queue wait before execution started
    admission        admission bookkeeping around the queue wait
    batch_window     batched-execution lane: the leader's gather
                     window, or a member's whole wait on its cohort
    cache_fill_wait  blocked on ANOTHER thread's segment-cache fill
    compile          XLA trace/lower/compile time this query caused
    device_dispatch  measured warm jit-dispatch walls
    link_h2d/link_d2h  device-link transfer walls
    host_python      the residual: host orchestration the other
                     segments cannot claim (decode, planning, python)

with the same sum-exactness contract as `telemetry/diff.py`: the
segments sum EXACTLY to the measured query wall, because the residual
is defined as wall minus the attributed segments. The residual is
SIGNED — a query whose pool threads overlap link transfers with
compute can attribute more seconds than its wall, and a negative
`host_python` says precisely that (the positive overlap is also
reported as `overlap_s`). "The decomposition couldn't explain it" is
itself a measured number, never a silent gap.

Three surfaces:

- **per query**: `stamp(metrics)` (called by the scheduler at query
  finish) attaches the decomposition as `metrics.critical_path`, so
  flight-ring entries, slow-query dumps, and `to_dict()` trees carry
  their own anatomy;
- **windowed**: each stamped query feeds `critpath.<segment>.seconds`
  registry counters (plus `critpath.wall.seconds`); the PR-15 sampler
  selects the `critpath.` family into its ring, and
  `window_shares()` derives the trailing-window share of each segment
  — what `/critpath` serves and `bench_serve.py` embeds per arrival
  rate;
- **timeline**: `span_timeline(metrics)` reconstructs the query's
  span DAG from the PR-2 tracer ring (spans nest by ts/dur
  containment per thread) and classifies each span into the same
  closed set — the ordered blocking path a dump viewer renders next
  to the totals. Tracing off = None, same always-off contract as
  every tracer hook.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["SEGMENTS", "SEGMENT_SOURCES", "decompose", "stamp",
           "window_shares", "span_timeline", "SUM_EXACT_EPSILON_S"]

# The closed segment set, in blocking order (queue first, residual
# last). Every decomposition has exactly these keys.
SEGMENTS = (
    "queue_wait",
    "admission",
    "batch_window",
    "cache_fill_wait",
    "compile",
    "device_dispatch",
    "link_h2d",
    "link_d2h",
    "host_python",
)

# Segment -> the per-query counter that feeds it (`metrics.counters`).
# `host_python` has no source counter: it is DEFINED as the residual.
SEGMENT_SOURCES: Dict[str, str] = {
    "queue_wait": "serve.queue_wait_s",
    "admission": "serve.admission_s",
    "batch_window": "serve.batch.window_s",
    "cache_fill_wait": "cache.fill_wait_s",
    "compile": "compile.seconds",
    "device_dispatch": "device.dispatch_s",
    "link_h2d": "link.h2d_s",
    "link_d2h": "link.d2h_s",
}

# Tracer span category/name -> segment, for the timeline view. Spans
# in no mapped category are host work by definition.
_SPAN_SEGMENTS = (
    ("compile", "compile"),
    ("link", None),            # direction decided by the span name
    ("cache", "cache_fill_wait"),
    ("serve.batch", "batch_window"),
)

# |sum(segments) - wall| tolerance: the residual makes the sum exact
# by construction, so only float rounding (segments are rounded to
# 1 µs for serialization) can open a gap.
SUM_EXACT_EPSILON_S = 1e-4


def decompose(metrics) -> Optional[dict]:
    """The closed-set decomposition of one FINISHED query's wall.
    Returns None for an unfinished recorder (no wall to decompose).

    The sum contract: `sum(segments.values()) == wall_s` to within
    float rounding, because `host_python` is wall minus the rest —
    negative when pool-thread overlap attributed more than the wall
    (the overlap is then also reported positively as `overlap_s`)."""
    wall = metrics.wall_s
    if wall is None:
        return None
    wall = round(float(wall), 6)
    segments: Dict[str, float] = {}
    for name, source in SEGMENT_SOURCES.items():
        segments[name] = round(
            max(float(metrics.counters.get(source, 0.0)), 0.0), 6)
    attributed = sum(segments.values())
    segments["host_python"] = round(wall - attributed, 6)
    dominant = max(SEGMENTS, key=lambda s: segments[s])
    return {
        "wall_s": wall,
        "segments": segments,
        "dominant": dominant,
        "overlap_s": round(max(attributed - wall, 0.0), 6),
        "sum_s": round(sum(segments.values()), 6),
    }


def stamp(metrics, publish: bool = True) -> Optional[dict]:
    """Decompose one finished query and attach the result as
    `metrics.critical_path` (rides `to_dict()`/`summary()`, the flight
    ring, and slow-query dumps). With `publish` (the default), each
    segment also feeds the process-wide `critpath.<segment>.seconds`
    counters — the sampler's raw material for windowed shares. The
    negative part of the residual never decrements a counter (counters
    are monotonic); it lands in `critpath.overlap.seconds` instead."""
    cp = decompose(metrics)
    if cp is None:
        return None
    metrics.critical_path = cp
    if publish:
        reg = _registry.get_registry()
        for name, seconds in cp["segments"].items():
            if seconds > 0:
                reg.counter(f"critpath.{name}.seconds").inc(seconds)
        if cp["overlap_s"] > 0:
            reg.counter("critpath.overlap.seconds").inc(cp["overlap_s"])
        reg.counter("critpath.wall.seconds").inc(cp["wall_s"])
        reg.counter("critpath.queries").inc()
    return cp


def window_shares(window_s: Optional[float] = None,
                  since_t: Optional[float] = None) -> dict:
    """Trailing-window segment shares from the sampler ring: for each
    segment, (windowed `critpath.<segment>.seconds` rate) / (windowed
    `critpath.wall.seconds` rate). Shares can sum slightly above 1.0
    when queries overlapped their own segments (`overlap` reports the
    windowed overlap share). Returns zeroed shares with `queries == 0`
    when the window saw no stamped queries — a caller can always
    render the shape."""
    from hyperspace_tpu.telemetry import timeseries as _timeseries
    sampler = _timeseries.get_sampler()
    wall_rate = sampler.window_rate("critpath.wall.seconds",
                                    window_s=window_s, since_t=since_t)
    q_rate = sampler.window_rate("critpath.queries",
                                 window_s=window_s, since_t=since_t)
    out = {"queries_per_s": round(q_rate or 0.0, 4),
           "wall_seconds_per_s": round(wall_rate or 0.0, 6),
           "shares": {}, "dominant": None}
    reg = _registry.get_registry()
    for name in SEGMENTS:
        rate = sampler.window_rate(f"critpath.{name}.seconds",
                                   window_s=window_s,
                                   since_t=since_t) or 0.0
        share = (rate / wall_rate) if wall_rate else 0.0
        out["shares"][name] = round(share, 4)
        reg.gauge(f"window.critpath.{name}.share").set(round(share, 6))
    overlap_rate = sampler.window_rate("critpath.overlap.seconds",
                                       window_s=window_s,
                                       since_t=since_t) or 0.0
    out["overlap"] = round((overlap_rate / wall_rate)
                           if wall_rate else 0.0, 4)
    if wall_rate:
        out["dominant"] = max(SEGMENTS, key=lambda s: out["shares"][s])
    return out


def _classify_span(cat: str, name: str) -> Optional[str]:
    for prefix, segment in _SPAN_SEGMENTS:
        if cat == prefix or cat.startswith(prefix + "."):
            if segment is not None:
                return segment
            return "link_d2h" if name.startswith("d2h") else "link_h2d"
    return None


def span_timeline(metrics) -> Optional[dict]:
    """The span-DAG view of one query: tracer-ring events overlapping
    the query's execution window, classified into the closed segment
    set and ordered by start time — the blocking chain a dump viewer
    renders. Spans on the query's own threads nest by containment
    (the Chrome trace-event discipline); unclassified spans are host
    work (`host_python`). None without an active tracer — the
    counter-based `decompose` needs no tracer and is the sum-exact
    source of truth; this is the visual companion."""
    from hyperspace_tpu.telemetry import trace as _trace
    t = _trace.tracer()
    if t is None or metrics.wall_s is None:
        return None
    start_us = (metrics._t0 - t.t0_s) * 1e6
    end_us = start_us + metrics.wall_s * 1e6
    with t._lock:
        events = [e for e in t.events
                  if e.get("ph") == "X"
                  and e.get("ts", 0) + e.get("dur", 0) >= start_us
                  and e.get("ts", 0) <= end_us]
    spans: List[dict] = []
    for e in sorted(events, key=lambda e: e.get("ts", 0)):
        segment = _classify_span(e.get("cat", ""), e.get("name", ""))
        spans.append({
            "t_rel_s": round((e["ts"] - start_us) / 1e6, 6),
            "dur_s": round(e.get("dur", 0) / 1e6, 6),
            "name": e.get("name"),
            "cat": e.get("cat"),
            "tid": e.get("tid"),
            "segment": segment or "host_python",
        })
    return {"wall_s": round(metrics.wall_s, 6), "spans": spans}
