"""Query-level telemetry: per-operator metrics + structured decision events.

The reference ships real query observability — `PlanAnalyzer.explain` /
`whyNot` tell the user which index rules fired and why
(`PlanAnalyzer.scala:45-360`) — and leans on Spark's per-operator SQL
metrics for its tuning story. This package is the engine's runtime half
of that: ONE `QueryMetrics` recorder is threaded through a query
execution end-to-end and returned to the user, capturing

- per-physical-operator wall time and output row counts (the executor's
  operator walk, instrumented in `engine/physical.py`);
- structured decision events: optimizer rule fired/skipped with reason
  (`plan/rules/*`), fusion lane chosen (masked-device vs eager-host)
  with its trigger, trace-cache hit/miss, device dispatch vs sync
  seconds (`engine/fusion.py` — the per-query scoping of the
  module-level `fusion.STATS` aggregate);
- index usage: which covering index served which scan, bucket counts,
  files scanned vs pruned (`plan/rules/*` + `ScanExec`).

Scoping: the active recorder is a `contextvars.ContextVar`, so
concurrent sessions (or threads) never see each other's metrics; the
engine's internal thread pools re-establish the context explicitly via
`propagating(...)`. When no recorder is active every hook is a
single ContextVar read + None check — the always-off cost on hot paths.

Surface: `DataFrame.collect(with_metrics=True)` returns the recorder
next to the result; `session.last_query_metrics()` returns the most
recent one; `to_json()` / `format_tree()` render reports, and
`PlanAnalyzer.explain_string(..., metrics=...)` places the runtime
numbers next to the plan diff.

Process-wide observability rides in sibling modules re-exported here:
`registry` (named counters/gauges/log-bucketed histograms aggregating
across queries and sessions + the structured action-report ring;
Prometheus text dump), `trace` (span tracer with Chrome trace-event /
Perfetto export — `enable_tracing()` then `export_trace(path)`; spans
cover queries, operators, fusion stages, maintenance-action phases,
mesh dispatches, and H2D/D2H link transfers on their real threads),
`memory` (the device-memory accountant — per-device live/peak HBM
gauges, per-query `peak_hbm_bytes` watermarks, Perfetto counter
tracks — plus the byte-aware `cache.<name>.*` instrumentation every
cache in the system reports through), and `compilation`
(`instrumented_jit`: compile spans, trace/cache-hit counters, and
retrace-cause decision events for every jit entry point).

Regression attribution (PR 6) closes the loop: `artifact` (the ONE
canonical, versioned bench-artifact schema both bench drivers emit),
`diff` (align two artifacts or two QueryMetrics trees and decompose
each wall delta into compute / link / compile / cache / fallback /
residual buckets — the ranked attribution tree `scripts/bench_diff.py`
prints and `scripts/bench_regress.py` auto-runs on gate failure), and
`flight` (the always-on ring of the last-K completed QueryMetrics plus
the slow-query dump, `spark.hyperspace.telemetry.slowlog.*`).
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from hyperspace_tpu.telemetry.registry import (MetricsRegistry,
                                               get_registry)
from hyperspace_tpu.telemetry.trace import (Tracer, disable_tracing,
                                            enable_tracing, export_trace,
                                            link_transfer,
                                            record_link_transfer, span,
                                            tracer, tracing_enabled)
from hyperspace_tpu.telemetry import memory  # noqa: F401
from hyperspace_tpu.telemetry import compilation  # noqa: F401
from hyperspace_tpu.telemetry import artifact  # noqa: F401
from hyperspace_tpu.telemetry import diff  # noqa: F401
from hyperspace_tpu.telemetry import flight  # noqa: F401
from hyperspace_tpu.telemetry import timeseries  # noqa: F401
from hyperspace_tpu.telemetry import ops_server  # noqa: F401
from hyperspace_tpu.telemetry import critical_path  # noqa: F401
from hyperspace_tpu.telemetry import profiler  # noqa: F401
from hyperspace_tpu.telemetry.compilation import instrumented_jit
from hyperspace_tpu.telemetry.flight import (FlightRecorder,
                                             get_recorder)
from hyperspace_tpu.telemetry.memory import (DeviceMemoryAccountant,
                                             get_accountant)

__all__ = [
    "QueryMetrics", "OperatorRecord", "current", "recording",
    "propagating", "event", "annotate", "add_seconds", "add_count",
    "current_deadline", "deadline_scope", "check_deadline",
    "DEFAULT_TENANT", "current_tenant", "tenant_scope", "charge_tenant",
    "known_tenants", "tenant_digest", "TENANT_CHARGE_COUNTERS",
    "MetricsRegistry", "get_registry", "Tracer", "enable_tracing",
    "disable_tracing", "tracing_enabled", "tracer", "span",
    "link_transfer", "record_link_transfer", "export_trace",
    "memory", "compilation", "instrumented_jit", "artifact", "diff",
    "flight", "FlightRecorder", "get_recorder",
    "DeviceMemoryAccountant", "get_accountant",
    "timeseries", "ops_server", "critical_path", "profiler",
]


_current: contextvars.ContextVar[Optional["QueryMetrics"]] = \
    contextvars.ContextVar("hyperspace_query_metrics", default=None)

# The active query's Deadline (`engine/scheduler.Deadline`) rides the
# SAME contextvar scoping as the recorder: set by the scheduler around
# execution, carried across the engine's pool threads by
# `propagating(...)`, read by the cooperative-cancellation checkpoints
# (`check_deadline`) at operator / fusion-stage / transfer-chunk /
# sorted-run-write boundaries. The var lives HERE (not in the
# scheduler) because every checkpoint module already imports telemetry
# — the hooks stay one ContextVar read + None check when serving
# features are off, the same always-off contract as the recorder.
_deadline: contextvars.ContextVar = \
    contextvars.ContextVar("hyperspace_query_deadline", default=None)

# The active TENANT identity rides the same contextvar scoping as the
# recorder and deadline: set by the scheduler/session seam
# (`session.tenant(...)` / `collect(tenant=...)` — raw writes anywhere
# else are banned by `scripts/check_metrics_coverage.py`), carried
# across pool threads by `propagating(...)`, read by every chargeback
# site (`compilation.instrumented_jit`, `trace.record_link_transfer`,
# the segment-cache fill paths) to mirror global counters onto
# `tenant.<id>.*`. Unset means the DEFAULT tenant — charges never go
# unattributed, so summing `tenant.<id>.*` over all tenants (including
# "default") equals the global counters EXACTLY.
_tenant: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("hyperspace_query_tenant", default=None)

DEFAULT_TENANT = "default"

# Tenants observed by any chargeback/scope since process start, so the
# report/healthz surfaces can enumerate `tenant.<id>.*` families
# without parsing metric names (tenant ids may themselves contain
# dots). Guarded by its own lock; never pruned (ids are few).
_known_tenants: set = {DEFAULT_TENANT}
_known_tenants_lock = threading.Lock()


def current() -> Optional["QueryMetrics"]:
    """The recorder of the query executing on this thread, or None."""
    return _current.get()


def current_deadline():
    """The Deadline of the query executing on this thread, or None."""
    return _deadline.get()


@contextmanager
def deadline_scope(deadline):
    """Make `deadline` the active cancellation token for the calling
    context (None is allowed and makes the scope a no-op carrier)."""
    token = _deadline.set(deadline)
    try:
        yield deadline
    finally:
        _deadline.reset(token)


def check_deadline(phase: str) -> None:
    """Cooperative-cancellation checkpoint: raises the active
    deadline's typed error (QueryCancelledError /
    QueryDeadlineExceededError, tagged with `phase`) when the query
    was cancelled or its deadline passed; no-op without an active
    deadline. `phase` names what the raise would interrupt —
    scan/operator/stage/transfer/write — so timeout clusters are
    attributable to a bucket (`telemetry/diff.py`), not `residual`."""
    d = _deadline.get()
    if d is not None:
        d.check(phase)


def current_tenant() -> str:
    """The tenant the calling context charges to — the contextvar if a
    tenant scope is active, else the DEFAULT tenant. Never None:
    chargeback sites must always have someone to bill."""
    return _tenant.get() or DEFAULT_TENANT


def known_tenants() -> List[str]:
    """Sorted ids of every tenant observed since process start."""
    with _known_tenants_lock:
        return sorted(_known_tenants)


def _note_tenant(tenant: str) -> None:
    if tenant not in _known_tenants:  # racy pre-check; set add is safe
        with _known_tenants_lock:
            _known_tenants.add(tenant)


@contextmanager
def tenant_scope(tenant: Optional[str]):
    """Make `tenant` the active billing identity for the calling
    context (None keeps the surrounding scope — a no-op carrier). This
    is the ONE sanctioned write seam besides `propagating`; the
    metrics-coverage lint bans raw `_tenant.set(...)` elsewhere."""
    if tenant is None:
        yield None
        return
    tenant = str(tenant)
    _note_tenant(tenant)
    token = _tenant.set(tenant)
    try:
        yield tenant
    finally:
        _tenant.reset(token)


# Every counter family the chargeback sites mirror per-tenant. The
# digest (and `Hyperspace.tenant_report()`) reads exactly these, and
# the exactness contract is: for each name here, the sum of
# `tenant.<id>.<name>` over ALL known tenants equals the global
# counter of the same name.
TENANT_CHARGE_COUNTERS = (
    "device.flops", "device.bytes_accessed", "device.dispatch.seconds",
    "link.h2d.bytes", "link.d2h.bytes", "cache.segments.fills",
)


def tenant_digest() -> Dict[str, Dict[str, float]]:
    """{tenant: {charge counter: value}} for every known tenant, read
    from the registry's `tenant.<id>.*` mirrors. Tenants with zero
    usage are included (the default tenant always appears), so a
    consumer can verify the exactness contract by summing columns."""
    counters = get_registry().counters_dict()
    out: Dict[str, Dict[str, float]] = {}
    for t in known_tenants():
        out[t] = {name: counters.get(f"tenant.{t}.{name}", 0)
                  for name in TENANT_CHARGE_COUNTERS}
    return out


def charge_tenant(name: str, amount: float = 1.0,
                  tenant: Optional[str] = None) -> str:
    """Mirror a global-counter increment onto the active tenant's
    `tenant.<id>.<name>` series. Call this at the SAME site as the
    global `reg.counter(name).inc(amount)` so per-tenant sums stay
    exactly equal to the global counters (the chargeback exactness
    contract `Hyperspace.tenant_report()` asserts). Returns the tenant
    charged."""
    t = tenant if tenant is not None else current_tenant()
    _note_tenant(t)
    get_registry().counter(f"tenant.{t}.{name}").inc(amount)
    return t


@contextmanager
def recording(metrics: "QueryMetrics"):
    """Make `metrics` the active recorder for the calling context."""
    token = _current.set(metrics)
    try:
        yield metrics
    finally:
        _current.reset(token)


def propagating(fn):
    """Wrap `fn` for execution on another thread (the engine's internal
    pools), carrying over the active recorder AND the caller's position
    in the operator tree — contextvars do not cross thread boundaries on
    their own, and the worker's operator records must parent under the
    operator that forked the work (e.g. the bucketed join reading its
    two sides concurrently). The active Deadline rides along too: a
    cancelled query's pool-side subtree hits the same cooperative
    checkpoints its main thread does, and the active TENANT rides along
    so pool-side device dispatches charge the right bill."""
    rec = _current.get()
    deadline = _deadline.get()
    tenant = _tenant.get()
    if rec is None and deadline is None and tenant is None:
        return fn
    parent = rec._current_op_id() if rec is not None else None

    def run(*args, **kwargs):
        token = _current.set(rec)
        dtoken = _deadline.set(deadline)
        ttoken = _tenant.set(tenant)
        if rec is not None:
            rec._adopt_parent(parent)
        try:
            return fn(*args, **kwargs)
        finally:
            if rec is not None:
                rec._clear_adoption()
            _tenant.reset(ttoken)
            _deadline.reset(dtoken)
            _current.reset(token)

    return run


def event(category: str, name: str, **detail) -> None:
    """Record a structured decision event on the active recorder (no-op
    without one). Keep values JSON-serializable."""
    rec = _current.get()
    if rec is not None:
        rec.event(category, name, **detail)


def annotate(**detail) -> None:
    """Attach detail to the operator record currently executing on this
    thread (no-op without a recorder or outside an operator)."""
    rec = _current.get()
    if rec is not None:
        rec.annotate_current(**detail)


def add_seconds(counter: str, seconds: float) -> None:
    """Accumulate a per-query timing counter (no-op without a recorder)."""
    rec = _current.get()
    if rec is not None:
        rec.add_seconds(counter, seconds)


def add_count(counter: str, n: int = 1) -> None:
    rec = _current.get()
    if rec is not None:
        rec.add_count(counter, n)


def _fmt_bytes(n: int) -> str:
    """Human-readable bytes for report rendering (binary units)."""
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if value < 1024 or unit == "TiB":
            return (f"{int(value)}{unit}" if unit == "B"
                    else f"{value:.1f}{unit}")
        value /= 1024
    return f"{n}B"


class OperatorRecord:
    """One physical operator execution: identity, tree position, wall
    time, and output rows. `rows_out` for device batches is the static
    shape (no sync is forced to report it); `wall_s` on the device lane
    measures dispatch-side time unless the operator itself syncs.

    The display label (`simple_string()` of the node) is resolved
    LAZILY — at query finish or first report — so the per-operator
    recording cost on the execute hot path stays at two perf_counter
    reads plus an append."""

    __slots__ = ("op_id", "parent_id", "name", "bucketed",
                 "wall_s", "rows_out", "detail", "error", "_t0",
                 "_node", "_label")

    def __init__(self, op_id: int, parent_id: Optional[int], name: str,
                 node, bucketed: bool):
        self.op_id = op_id
        self.parent_id = parent_id
        self.name = name
        self.bucketed = bucketed
        self.wall_s = 0.0
        self.rows_out: Optional[int] = None
        self.detail: Dict = {}
        self.error: Optional[str] = None
        self._node = node
        self._label: Optional[str] = None
        self._t0 = time.perf_counter()

    @property
    def label(self) -> str:
        if self._label is None:
            node, self._node = self._node, None
            if node is None:
                self._label = self.name
            else:
                try:
                    self._label = node.simple_string()
                except Exception:
                    self._label = self.name
        return self._label

    def to_dict(self) -> dict:
        d = {"op_id": self.op_id, "parent_id": self.parent_id,
             "name": self.name, "label": self.label,
             "wall_s": round(self.wall_s, 6), "rows_out": self.rows_out}
        if self.bucketed:
            d["bucketed"] = True
        if self.detail:
            d["detail"] = dict(self.detail)
        if self.error is not None:
            d["error"] = self.error
        return d


class QueryMetrics:
    """Everything recorded about ONE query execution. Thread-safe for
    append (operators may execute on pool threads); the per-thread
    operator stack lives in a threading.local so concurrent subtree
    executions keep their own parent chains."""

    def __init__(self, description: str = ""):
        self.description = description
        self.started_at = time.time()
        self.wall_s: Optional[float] = None
        self.operators: List[OperatorRecord] = []
        self.events: List[dict] = []
        self.counters: Dict[str, float] = {}
        # Peak HBM watermarks observed while this query was recording:
        # per device, and the peak TOTAL across devices (the headline).
        # Fed by the device-memory accountant at span boundaries and
        # link transfers (`telemetry/memory.py`); 0/{} when the query
        # never touched a device (pure host lane).
        self.peak_hbm_bytes: int = 0
        self.peak_hbm_per_device: Dict[str, int] = {}
        # Serving dimensions, stamped by the scheduler and the batch
        # lane: the routed replica slice (None = unrouted) and the
        # batched-execution cohort this query rode ({"id", "size"},
        # None = solo), plus the tenant billed for the query (None =
        # default tenant / no tenant scope). The flight ring inherits
        # all three, so post-hoc tail diagnosis can group by replica,
        # cohort, and tenant.
        self.replica = None
        self.cohort: Optional[dict] = None
        self.tenant: Optional[str] = None
        # Latency anatomy, stamped at query finish by
        # `telemetry/critical_path.py`: the wall decomposed into the
        # closed segment set ({wall_s, segments, dominant, ...}),
        # segments summing exactly to wall_s. None until stamped.
        self.critical_path: Optional[dict] = None
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._tls = threading.local()
        self._t0 = time.perf_counter()

    # -- recorder side (engine hooks) ----------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _current_op_id(self) -> Optional[int]:
        stack = self._stack()
        return stack[-1].op_id if stack else None

    def _adopt_parent(self, parent_id: Optional[int]) -> None:
        """Root this worker thread's operator chain under `parent_id`
        (see `propagating`)."""
        self._tls.adopted = parent_id

    def _clear_adoption(self) -> None:
        self._tls.adopted = None

    def start_operator(self, name: str, node=None,
                       bucketed: bool = False) -> OperatorRecord:
        stack = self._stack()
        parent = (stack[-1].op_id if stack
                  else getattr(self._tls, "adopted", None))
        # next() on itertools.count and list.append are both atomic
        # under the GIL — the hot path takes no lock.
        op = OperatorRecord(next(self._ids), parent, name, node, bucketed)
        self.operators.append(op)
        stack.append(op)
        return op

    def finish_operator(self, op: OperatorRecord,
                        rows_out: Optional[int] = None,
                        error: Optional[str] = None) -> None:
        op.wall_s = time.perf_counter() - op._t0
        op.rows_out = rows_out
        op.error = error
        stack = self._stack()
        if stack and stack[-1] is op:
            stack.pop()
        else:  # unbalanced (exception skipped a frame): resync
            while stack and stack[-1] is not op:
                stack.pop()
            if stack:
                stack.pop()

    def annotate_current(self, **detail) -> None:
        stack = self._stack()
        if stack:
            stack[-1].detail.update(detail)

    def event(self, category: str, name: str, **detail) -> None:
        e = {"category": category, "name": name}
        e.update(detail)
        with self._lock:
            self.events.append(e)

    def add_seconds(self, counter: str, seconds: float) -> None:
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0.0) \
                + float(seconds)

    def add_count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + n

    def observe_hbm(self, live_bytes_per_device: Dict[str, int]) -> None:
        """Fold one device-memory sample into this query's peak
        watermarks (called by the accountant while recording)."""
        with self._lock:
            for dev, b in live_bytes_per_device.items():
                if b > self.peak_hbm_per_device.get(dev, 0):
                    self.peak_hbm_per_device[dev] = int(b)
            total = sum(live_bytes_per_device.values())
            if total > self.peak_hbm_bytes:
                self.peak_hbm_bytes = int(total)

    def finish(self) -> "QueryMetrics":
        self.wall_s = time.perf_counter() - self._t0
        for op in self.operators:
            op.label  # resolve now; releases the node references
        return self

    # -- user side (reports) -------------------------------------------

    @property
    def compile(self) -> dict:
        """This query's compile story: how many XLA traces it caused,
        how many jit dispatches were served from the executable cache,
        and the seconds spent tracing/compiling. A warmed query re-run
        must show traces == 0 — nonzero here on a repeat run is a
        retrace, and the `[compile] retrace` events name the
        shape/dtype delta that caused it."""
        return {
            "traces": int(self.counters.get("compile.traces", 0)),
            "cache_hits": int(self.counters.get("compile.cache_hits", 0)),
            "seconds": round(
                float(self.counters.get("compile.seconds", 0.0)), 6),
        }

    @property
    def roofline(self) -> dict:
        """This query's device cost story, from the XLA cost analyses
        `instrumented_jit` captured at trace time and the per-dispatch
        measured walls: modeled flops and bytes accessed, the measured
        warm-dispatch seconds, the device share of the query's wall
        (the device-bound-vs-overhead split — a low share says the
        bottleneck is host orchestration, not the chip), and the
        arithmetic intensity that places the work on a roofline plot.
        Walls on async backends are dispatch-side unless an operator
        syncs, so achieved flops/s is a floor estimate."""
        flops = float(self.counters.get("device.flops", 0.0))
        nbytes = float(self.counters.get("device.bytes_accessed", 0.0))
        disp = float(self.counters.get("device.dispatch_s", 0.0))
        wall = self.wall_s
        return {
            "flops": round(flops, 1),
            "bytes_accessed": round(nbytes, 1),
            "dispatch_s": round(disp, 6),
            "device_share": (round(min(disp / wall, 1.0), 4)
                             if wall else None),
            "intensity_flops_per_byte": (round(flops / nbytes, 4)
                                         if nbytes else None),
            "achieved_flops_per_s": (round(flops / disp, 1)
                                     if disp > 0 else None),
        }

    def events_of(self, category: str, name: Optional[str] = None
                  ) -> List[dict]:
        return [e for e in self.events
                if e["category"] == category
                and (name is None or e["name"] == name)]

    def rows_in(self, op: OperatorRecord) -> Optional[int]:
        """Sum of the operator's direct children's output rows (None when
        no child reported rows — e.g. a leaf scan)."""
        rows = [c.rows_out for c in self.operators
                if c.parent_id == op.op_id and c.rows_out is not None]
        return sum(rows) if rows else None

    def index_usage(self) -> List[dict]:
        """Index-usage records: one per rule application (index name,
        side, bucket count) joined against the scan records that actually
        read the index data (files scanned vs pruned). Bucketed scans no
        rule claimed (hand-built layouts) are reported without a name."""
        scans = [op for op in self.operators if op.name == "Scan"]
        claimed: set = set()
        out = []
        for e in self.events_of("rule"):
            if e.get("action") != "applied":
                continue
            for use in e.get("indexes", []):
                rec = dict(use)
                rec["rule"] = e["name"]
                root = use.get("root")
                for op in scans:
                    if root and root in op.detail.get("roots", ()):
                        claimed.add(op.op_id)
                        for k in ("files_scanned", "files_total",
                                  "buckets_scanned", "buckets_total",
                                  "lane"):
                            if k in op.detail:
                                rec[k] = op.detail[k]
                        rec["rows_out"] = op.rows_out
                out.append(rec)
        for op in scans:
            if op.op_id in claimed or "buckets_total" not in op.detail:
                continue
            rec = {"name": None, "rule": None,
                   "root": (op.detail.get("roots") or [None])[0],
                   "rows_out": op.rows_out}
            for k in ("files_scanned", "files_total", "buckets_scanned",
                      "buckets_total", "lane"):
                if k in op.detail:
                    rec[k] = op.detail[k]
            out.append(rec)
        return out

    def to_dict(self) -> dict:
        out = {
            "description": self.description,
            "started_at": self.started_at,
            "wall_s": (round(self.wall_s, 6)
                       if self.wall_s is not None else None),
            "operators": [op.to_dict() for op in self.operators],
            "events": list(self.events),
            "counters": {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in self.counters.items()},
            "index_usage": self.index_usage(),
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "peak_hbm_per_device": dict(self.peak_hbm_per_device),
            "compile": self.compile,
            "roofline": self.roofline,
        }
        if self.replica is not None:
            out["replica"] = self.replica
        if self.cohort is not None:
            out["cohort"] = dict(self.cohort)
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.critical_path is not None:
            out["critical_path"] = dict(self.critical_path)
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=False,
                          default=str)

    def summary(self) -> dict:
        """Compact per-query digest — what the bench artifacts embed so
        future rounds carry operator-level trajectories, not just
        totals. Operator seconds are summed per operator type over
        SELF time (child time subtracted), so the digest adds up instead
        of double-counting nested walls."""
        child_s: Dict[Optional[int], float] = {}
        for op in self.operators:
            child_s[op.parent_id] = child_s.get(op.parent_id, 0.0) \
                + op.wall_s
        per_op: Dict[str, dict] = {}
        for op in self.operators:
            ent = per_op.setdefault(op.name, {"count": 0, "self_s": 0.0,
                                              "rows_out": 0})
            ent["count"] += 1
            ent["self_s"] += max(op.wall_s
                                 - child_s.get(op.op_id, 0.0), 0.0)
            ent["rows_out"] += op.rows_out or 0
        for ent in per_op.values():
            ent["self_s"] = round(ent["self_s"], 4)
        lanes: Dict[str, int] = {}
        for e in self.events_of("fusion", "lane"):
            lanes[e.get("lane", "?")] = lanes.get(e.get("lane", "?"), 0) + 1
        rules: Dict[str, int] = {}
        for e in self.events_of("rule"):
            key = f"{e['name']}:{e.get('action', '?')}"
            rules[key] = rules.get(key, 0) + 1
        out = {
            "wall_s": (round(self.wall_s, 4)
                       if self.wall_s is not None else None),
            "operators": per_op,
            "fusion_lanes": lanes,
            "rules": rules,
            "counters": {k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in self.counters.items()},
            "index_usage": self.index_usage(),
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "compile": self.compile,
            "roofline": self.roofline,
        }
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.critical_path is not None:
            out["critical_path"] = dict(self.critical_path)
        return out

    def format_tree(self) -> str:
        """Operator tree with runtime numbers — the companion view to
        `PlanAnalyzer.explain_string`'s plan diff."""
        children: Dict[Optional[int], List[OperatorRecord]] = {}
        for op in self.operators:
            children.setdefault(op.parent_id, []).append(op)
        lines: List[str] = []
        header = "Query metrics"
        if self.description:
            header += f" — {self.description}"
        if self.wall_s is not None:
            header += f" ({self.wall_s:.3f}s)"
        lines.append(header)

        def emit(op: OperatorRecord, depth: int) -> None:
            pad = "  " * depth + ("+- " if depth else "")
            rows = f" rows={op.rows_out}" if op.rows_out is not None else ""
            extra = ""
            if op.detail:
                keys = ("lane", "files_scanned", "files_total",
                        "buckets_scanned", "buckets_total", "reused")
                bits = [f"{k}={op.detail[k]}" for k in keys
                        if k in op.detail]
                if bits:
                    extra = " [" + ", ".join(bits) + "]"
            err = f" ERROR={op.error}" if op.error else ""
            lines.append(f"{pad}{op.label}  ({op.wall_s:.4f}s{rows})"
                         f"{extra}{err}")
            for c in children.get(op.op_id, []):
                emit(c, depth + 1)

        for root in children.get(None, []):
            emit(root, 1)
        if self.events:
            lines.append("Events:")
            for e in self.events:
                detail = {k: v for k, v in e.items()
                          if k not in ("category", "name")}
                lines.append(f"  [{e['category']}] {e['name']} "
                             + json.dumps(detail, default=str))
        if self.counters:
            lines.append("Counters:")
            for k in sorted(self.counters):
                v = self.counters[k]
                lines.append(f"  {k} = "
                             + (f"{v:.4f}" if isinstance(v, float)
                                else str(v)))
        if self.peak_hbm_bytes:
            per_dev = ", ".join(
                f"{dev}={_fmt_bytes(b)}"
                for dev, b in sorted(self.peak_hbm_per_device.items()))
            lines.append(f"Peak HBM: {_fmt_bytes(self.peak_hbm_bytes)}"
                         + (f" ({per_dev})" if per_dev else ""))
        comp = self.compile
        if comp["traces"] or comp["cache_hits"]:
            lines.append(f"Compile: {comp['traces']} traces, "
                         f"{comp['cache_hits']} cache hits, "
                         f"{comp['seconds']:.4f}s")
        roof = self.roofline
        if roof["flops"] or roof["dispatch_s"]:
            bits = [f"{roof['flops']:.0f} flops",
                    f"{roof['bytes_accessed']:.0f} B accessed",
                    f"{roof['dispatch_s']:.4f}s dispatch"]
            if roof["device_share"] is not None:
                bits.append(f"device share {roof['device_share']:.1%}")
            if roof["intensity_flops_per_byte"] is not None:
                bits.append(
                    f"{roof['intensity_flops_per_byte']:.2f} flops/B")
            lines.append("Device: " + ", ".join(bits))
        return "\n".join(lines)

    def __repr__(self) -> str:
        return (f"QueryMetrics({len(self.operators)} operators, "
                f"{len(self.events)} events, wall_s={self.wall_s})")
