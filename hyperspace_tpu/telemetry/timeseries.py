"""Rolling time-series telemetry: the operations plane's time axis.

Every telemetry surface before this module is snapshot-shaped — the
registry accumulates since process start, `QueryMetrics` covers one
query, bench artifacts cover one round — so "what is p99 over the last
60 seconds, and is it getting worse?" was unanswerable. This module is
the flight-recorder discipline applied to the registry itself: a
background sampler (one daemon thread, `drain()`-able, atexit-stopped —
the same lifecycle as the slow-dump lane in `telemetry/flight.py`)
snapshots SELECTED registry series on a fixed interval into a bounded
ring, and derives from consecutive samples what cumulative metrics
cannot express:

- **counter rates** — per-interval and trailing-window deltas divided
  by elapsed time (`window.<counter>.rate` gauges; a scraper gets the
  same numbers from `/metrics` cumulative counters, an in-process
  consumer gets them here without one);
- **histogram interval deltas** — the registry's log2-bucketed
  histograms are cumulative; subtracting two samples bucket-by-bucket
  yields the interval's own observation histogram;
- **mergeable sliding-window quantiles** — summing interval deltas
  over the trailing window and walking the cumulative bucket counts
  gives p50/p90/p99 of the last N seconds, published as
  `window.<series>.{p50,p90,p99,count}` gauges. A log2 bucket bounds
  the answer to within 2x: the reported quantile is the UPPER bound of
  the bucket holding the q-th windowed observation, so
  `true <= reported < 2 * true` — exactly the contract
  `tests/test_timeseries.py` pins against a brute-force oracle.

The ring itself is the `/timeseries` payload of the ops server
(`telemetry/ops_server.py`) and the source of `bench_serve.py`'s
per-second QPS/latency timeline. Everything is in-process and
pull-based — the source paper keeps all index state on the lake with
no side services, and the operations plane keeps that discipline: no
agent, no push gateway, nothing to deploy next to the engine.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["TimeSeriesSampler", "get_sampler", "set_sampler",
           "reset_sampler", "quantile_from_buckets", "delta_buckets"]

# Default selection. Histograms get sliding-window quantile gauges;
# counters matching the prefixes ride the ring (rates derivable by any
# consumer); WINDOW_RATE_COUNTERS additionally publish a
# `window.<name>.rate` gauge each tick.
DEFAULT_HISTOGRAMS = ("query.wall_s", "serve.queue_wait_s")
# Histogram names are dynamic when a dimension is embedded in them
# (`tenant.<id>.query_wall_s`): prefixes select those the same way the
# counter prefixes do, since the exact names cannot be enumerated ahead
# of the tenants existing.
DEFAULT_HISTOGRAM_PREFIXES = ("tenant.",)
DEFAULT_COUNTER_PREFIXES = ("queries.", "serve.", "compile.", "link.",
                            "cache.segments.", "resilience.", "flight.",
                            "device.", "rules.served.", "spmd.",
                            "tenant.", "critpath.")
WINDOW_RATE_COUNTERS = ("queries.total", "serve.admitted",
                        "serve.rejected", "serve.slo.violations",
                        "serve.slo.shed", "compile.traces")
DEFAULT_GAUGE_PREFIXES = ("serve.",)
WINDOW_QUANTILES = (0.50, 0.90, 0.99)

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 600          # 10 minutes at 1 Hz
DEFAULT_WINDOW_S = 60.0


def quantile_from_buckets(buckets: Dict[Optional[int], int], q: float
                          ) -> Optional[float]:
    """The q-quantile of a log2-bucket histogram: the UPPER bound of
    the bucket containing the ceil(q * count)-th observation (None =
    empty). Upper bound, deliberately: every observation v in a bucket
    satisfies upper/2 < v <= upper, so the reported quantile never
    understates the true one and overstates it by strictly less than
    2x — the conservative direction for an SLO consumer."""
    count = sum(n for n in buckets.values() if n > 0)
    if count <= 0:
        return None
    target = max(1, math.ceil(q * count))
    cum = 0
    for exp in sorted((e for e in buckets), key=lambda e: (
            -(10 ** 9) if e is None else e)):
        n = buckets.get(exp, 0)
        if n <= 0:
            continue
        cum += n
        if cum >= target:
            return 0.0 if exp is None else float(2 ** exp)
    return None  # unreachable: cum == count >= target


def delta_buckets(new: dict, old: Optional[dict]
                  ) -> Dict[Optional[int], int]:
    """Per-interval observation histogram: `new` minus `old` bucket
    state (both `Histogram.bucket_state()` shapes; old=None means
    everything is new). Negative deltas (a registry reset between
    samples) clamp to zero."""
    nb = new.get("buckets") or {}
    ob = (old or {}).get("buckets") or {}
    return {exp: max(0, n - ob.get(exp, 0)) for exp, n in nb.items()
            if n - ob.get(exp, 0) > 0}


def _merge_buckets(into: Dict[Optional[int], int],
                   more: Dict[Optional[int], int]) -> None:
    for exp, n in more.items():
        into[exp] = into.get(exp, 0) + n


class _Sample:
    """One tick: wall time, the selected cumulative series, and the
    per-interval derivations against the previous tick."""

    __slots__ = ("t", "dt", "counters", "gauges", "hists", "rates",
                 "interval", "seq")

    def __init__(self, t: float, dt: Optional[float], counters, gauges,
                 hists, rates, interval, seq: int = 0):
        self.t = t
        self.dt = dt
        self.counters = counters   # {name: cumulative value}
        self.gauges = gauges       # {name: value}
        self.hists = hists         # {name: bucket_state()}
        self.rates = rates         # {name: per-second rate this interval}
        self.interval = interval   # {name: {count, p50, p99, sum_s}}
        self.seq = seq             # monotonic per-sampler tick number

    def to_dict(self) -> dict:
        hists = {}
        for name, st in self.hists.items():
            hists[name] = {
                "count": st["count"], "sum": round(st["sum"], 6),
                "buckets": {("-inf" if exp is None else str(exp)): n
                            for exp, n in sorted(
                                st["buckets"].items(),
                                key=lambda kv: (-(10 ** 9)
                                                if kv[0] is None
                                                else kv[0]))}}
        return {
            "t": round(self.t, 3),
            "seq": self.seq,
            "dt_s": round(self.dt, 6) if self.dt is not None else None,
            "counters": {k: round(v, 6)
                         for k, v in sorted(self.counters.items())},
            "gauges": {k: round(v, 6)
                       for k, v in sorted(self.gauges.items())},
            "histograms": hists,
            "rates": {k: round(v, 4)
                      for k, v in sorted(self.rates.items())},
            "interval": self.interval,
        }


class TimeSeriesSampler:
    """Background registry sampler + sliding-window math (module
    docstring). One per process (`get_sampler()`); `start()` spawns the
    daemon thread, `tick()` samples once synchronously (what the tests
    and the ops server's freshness path call), `drain()` stops the
    thread and joins it — idempotent, and the atexit hook calls it so
    interpreter teardown never races a mid-tick sampler."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = DEFAULT_CAPACITY,
                 window_s: float = DEFAULT_WINDOW_S,
                 histograms: Tuple[str, ...] = DEFAULT_HISTOGRAMS,
                 counter_prefixes: Tuple[str, ...]
                 = DEFAULT_COUNTER_PREFIXES,
                 gauge_prefixes: Tuple[str, ...]
                 = DEFAULT_GAUGE_PREFIXES,
                 histogram_prefixes: Tuple[str, ...]
                 = DEFAULT_HISTOGRAM_PREFIXES):
        self.interval_s = max(0.01, float(interval_s))
        self.window_s = max(self.interval_s, float(window_s))
        self.histograms = tuple(histograms)
        self.histogram_prefixes = tuple(histogram_prefixes)
        self.counter_prefixes = tuple(counter_prefixes)
        self.gauge_prefixes = tuple(gauge_prefixes)
        self._ring: deque = deque(maxlen=max(2, int(capacity)))
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev: Optional[_Sample] = None
        self._seq = 0              # advances on every tick, never rewinds
        self.conf = None           # set by configure(); the tick hooks'
        #                            conf (alerts/history need one)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> bool:
        """Start the background thread (True iff started now; False =
        already running). Restartable after `drain()`."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="hs-timeseries", daemon=True)
            self._thread.start()
        _registry.get_registry().counter("timeseries.starts").inc()
        return True

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def drain(self) -> None:
        """Stop the sampler thread and join it (idempotent). The ring
        and its derived gauges stay readable after a drain — draining
        stops the clock, it does not erase history."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        with self._lock:
            if self._thread is t:
                self._thread = None

    def clear(self) -> None:
        """Empty the ring and forget the previous sample (test
        isolation)."""
        with self._lock:
            self._ring.clear()
            self._prev = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # The sampler must never take the process down; count
                # and keep ticking.
                _registry.get_registry().counter(
                    "timeseries.tick_errors").inc()

    # -- sampling --------------------------------------------------------

    def _selected(self, snap: dict):
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith(self.counter_prefixes)}
        gauges = {k: v for k, v in snap["gauges"].items()
                  if k.startswith(self.gauge_prefixes)
                  and not k.startswith("window.")}
        hists = {k: v for k, v in snap["histograms"].items()
                 if k in self.histograms
                 or (self.histogram_prefixes
                     and k.startswith(self.histogram_prefixes))}
        return counters, gauges, hists

    def tick(self, t: Optional[float] = None) -> dict:
        """Take one sample NOW: snapshot the selected series, derive
        interval rates/deltas against the previous sample, append to
        the ring, and refresh the `window.*` gauges. Returns the
        sample as a dict (what `/timeseries` serves per entry). `t`
        overrides the wall clock for deterministic tests."""
        now = time.time() if t is None else float(t)
        snap = _registry.get_registry().series_snapshot()
        counters, gauges, hists = self._selected(snap)
        with self._lock:
            prev = self._prev
            dt = (now - prev.t) if prev is not None else None
            rates: Dict[str, float] = {}
            interval: Dict[str, dict] = {}
            if dt is not None and dt > 0:
                for name, v in counters.items():
                    d = v - prev.counters.get(name, 0.0)
                    if d:
                        rates[name] = d / dt
            for name, st in hists.items():
                db = delta_buckets(st, prev.hists.get(name)
                                   if prev is not None else None)
                dc = sum(db.values())
                if dc:
                    interval[name] = {
                        "count": dc,
                        "p50": quantile_from_buckets(db, 0.50),
                        "p99": quantile_from_buckets(db, 0.99),
                    }
            self._seq += 1
            sample = _Sample(now, dt, counters, gauges, hists, rates,
                             interval, seq=self._seq)
            self._ring.append(sample)
            self._prev = sample
        self._publish_window_gauges(now)
        self._post_tick_hooks(now)
        return sample.to_dict()

    def _post_tick_hooks(self, now: float) -> None:
        """Fan the fresh tick out to the incident plane — alert rule
        evaluation and the interval-gated history flush — OUTSIDE the
        sampler lock (both re-enter the window math). A hook failure
        never breaks sampling: counted `timeseries.hook_errors` and
        dropped."""
        reg = _registry.get_registry()
        try:
            from hyperspace_tpu.telemetry import alerts as _alerts
            _alerts.on_tick(self, now=now)
        except Exception:
            reg.counter("timeseries.hook_errors").inc()
        try:
            from hyperspace_tpu.telemetry import history as _history
            _history.on_tick(conf=self.conf, now=now)
        except Exception:
            reg.counter("timeseries.hook_errors").inc()

    # -- window math -----------------------------------------------------

    def _baseline(self, t0: float) -> Optional[_Sample]:
        """The newest sample at or before `t0` (the window's start
        state), or None when the whole ring is younger — the window
        then covers everything recorded (delta against zero)."""
        base = None
        with self._lock:
            for s in self._ring:
                if s.t <= t0:
                    base = s
                else:
                    break
        return base

    def _latest(self) -> Optional[_Sample]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def window_buckets(self, name: str,
                       window_s: Optional[float] = None,
                       since_t: Optional[float] = None
                       ) -> Tuple[Dict[Optional[int], int], float]:
        """(merged observation buckets, covered seconds) of histogram
        `name` over the trailing window — latest cumulative state minus
        the state at the window start (merge = subtract cumulative
        states; summing per-interval deltas gives the identical answer,
        which is the mergeability the gauges rely on). `since_t` pins
        the window start to an absolute time instead (bench drivers
        isolating one phase)."""
        latest = self._latest()
        if latest is None:
            return {}, 0.0
        t0 = since_t if since_t is not None \
            else latest.t - (window_s or self.window_s)
        base = self._baseline(t0)
        new = latest.hists.get(name)
        if new is None:
            return {}, 0.0
        old = base.hists.get(name) if base is not None else None
        covered = latest.t - (base.t if base is not None else t0)
        return delta_buckets(new, old), max(covered, 0.0)

    def window_quantile(self, name: str, q: float,
                        window_s: Optional[float] = None,
                        since_t: Optional[float] = None
                        ) -> Optional[float]:
        """Sliding-window q-quantile of histogram `name` (log2-bucket
        upper bound; None = no observations in the window)."""
        buckets, _cov = self.window_buckets(name, window_s=window_s,
                                            since_t=since_t)
        return quantile_from_buckets(buckets, q)

    def window_rate(self, name: str,
                    window_s: Optional[float] = None,
                    since_t: Optional[float] = None) -> Optional[float]:
        """Trailing-window per-second rate of counter `name` (None =
        the window has no baseline AND no samples)."""
        latest = self._latest()
        if latest is None:
            return None
        t0 = since_t if since_t is not None \
            else latest.t - (window_s or self.window_s)
        base = self._baseline(t0)
        now_v = latest.counters.get(name, 0.0)
        then_v = base.counters.get(name, 0.0) if base is not None else 0.0
        elapsed = latest.t - (base.t if base is not None else t0)
        if elapsed <= 0:
            return None
        return max(0.0, now_v - then_v) / elapsed

    def window_delta(self, name: str,
                     window_s: Optional[float] = None,
                     since_t: Optional[float] = None
                     ) -> Tuple[float, float]:
        """(raw counter delta, covered seconds) of counter `name` over
        the trailing window — the absolute-change primitive the alert
        rules' delta/ratio/trend predicates are built on (a rate hides
        "exactly one breaker opened"). covered == 0 means the ring had
        nothing to diff against."""
        latest = self._latest()
        if latest is None:
            return 0.0, 0.0
        t0 = since_t if since_t is not None \
            else latest.t - (window_s or self.window_s)
        base = self._baseline(t0)
        now_v = latest.counters.get(name, 0.0)
        then_v = base.counters.get(name, 0.0) if base is not None else 0.0
        elapsed = latest.t - (base.t if base is not None else t0)
        return max(0.0, now_v - then_v), max(elapsed, 0.0)

    def window_count(self, name: str,
                     window_s: Optional[float] = None) -> int:
        buckets, _cov = self.window_buckets(name, window_s=window_s)
        return sum(buckets.values())

    def _publish_window_gauges(self, now: float) -> None:
        reg = _registry.get_registry()
        latest = self._latest()
        # The static selection plus whatever dynamic (prefix-selected,
        # e.g. per-tenant) histograms the latest tick actually saw.
        names = list(self.histograms)
        if latest is not None:
            names.extend(k for k in latest.hists
                         if k not in self.histograms)
        for name in names:
            buckets, _cov = self.window_buckets(name)
            count = sum(buckets.values())
            if not count:
                continue
            reg.gauge(f"window.{name}.count").set(count)
            for q in WINDOW_QUANTILES:
                v = quantile_from_buckets(buckets, q)
                if v is not None:
                    reg.gauge(
                        f"window.{name}.p{int(q * 100)}").set(v)
        for name in WINDOW_RATE_COUNTERS:
            r = self.window_rate(name)
            if r is not None:
                reg.gauge(f"window.{name}.rate").set(r)
        reg.gauge("timeseries.samples").set(len(self._ring))
        reg.gauge("timeseries.last_sample_age_s").set(
            max(0.0, time.time() - now))

    # -- export ----------------------------------------------------------

    @property
    def last_seq(self) -> int:
        """Highest tick sequence assigned so far (advances even past
        samples the ring has since rotated out — the same global-cursor
        contract as the flight recorder's `last_seq`)."""
        with self._lock:
            return self._seq

    def samples(self, since_t: Optional[float] = None,
                since_seq: Optional[int] = None) -> List[dict]:
        """The ring as JSON-able dicts, oldest first. `since_t` keeps
        only samples strictly after that time (the bench drivers'
        phase isolation); `since_seq` keeps only ticks with a strictly
        greater sequence (the incremental-scraper cursor)."""
        with self._lock:
            entries = list(self._ring)
        return [s.to_dict() for s in entries
                if (since_t is None or s.t > since_t)
                and (since_seq is None or s.seq > since_seq)]

    def snapshot(self, since_seq: Optional[int] = None) -> dict:
        """The `/timeseries` payload: sampler config + the ring.
        `since_seq` (the `?since=` query parameter) returns only ticks
        newer than the caller's cursor; `last_seq` in the payload is
        the cursor to hand back next poll — the flight recorder's
        `snapshot(since_seq)` contract, applied to the sampler ring."""
        return {
            "interval_s": self.interval_s,
            "window_s": self.window_s,
            "capacity": self._ring.maxlen,
            "running": self.running,
            "histograms": list(self.histograms),
            "last_seq": self.last_seq,
            "samples": self.samples(since_seq=since_seq),
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# ---------------------------------------------------------------------------
# Process-wide sampler
# ---------------------------------------------------------------------------

_sampler: Optional[TimeSeriesSampler] = None
_sampler_lock = threading.Lock()


def get_sampler() -> TimeSeriesSampler:
    """THE process-wide sampler (sessions and the ops server share
    it)."""
    global _sampler
    if _sampler is None:
        with _sampler_lock:
            if _sampler is None:
                _sampler = TimeSeriesSampler()
    return _sampler


def set_sampler(sampler: TimeSeriesSampler) -> TimeSeriesSampler:
    """Install a specific sampler (tests: fresh ring/config); the
    previous one is drained first so no stray thread keeps ticking."""
    global _sampler
    with _sampler_lock:
        old, _sampler = _sampler, sampler
    if old is not None:
        old.drain()
    return sampler


def reset_sampler() -> None:
    global _sampler
    with _sampler_lock:
        old, _sampler = _sampler, None
    if old is not None:
        old.drain()


def configure(conf) -> Optional[TimeSeriesSampler]:
    """Session-init wiring: when the ops port is set, make sure the
    process sampler exists with the conf's interval/capacity/window and
    is running. Returns the sampler when (now) running, else None —
    starting the operations plane is opt-in, never a startup failure."""
    try:
        if conf is None or conf.telemetry_ops_port is None:
            return None
        sampler = get_sampler()
        sampler.conf = conf
        if not sampler.running:
            sampler.interval_s = max(0.01,
                                     conf.timeseries_interval_seconds)
            sampler.window_s = max(sampler.interval_s,
                                   conf.serve_slo_window_seconds)
            cap = max(2, conf.timeseries_capacity)
            if sampler._ring.maxlen != cap:
                with sampler._lock:
                    sampler._ring = deque(sampler._ring, maxlen=cap)
            sampler.start()
        return sampler
    except Exception:
        import logging
        logging.getLogger(__name__).warning(
            "timeseries sampler configuration failed; operations plane "
            "disabled", exc_info=True)
        return None


def _atexit_drain() -> None:
    try:
        if _sampler is not None:
            _sampler.drain()
    except Exception:
        pass


import atexit  # noqa: E402

atexit.register(_atexit_drain)
