"""Span tracer with Chrome trace-event / Perfetto JSON export.

Where the registry (`telemetry/registry.py`) aggregates, the tracer
keeps a TIMELINE: complete-event spans for queries, physical operators,
fusion stages, index-maintenance action phases, and H2D/D2H link
transfers, each stamped with the REAL thread it ran on — so the export
shows the bucketed join's two sides reading concurrently on their pool
threads, and the link transfer that serialized them. Mesh work adds a
synthetic per-device process (`pid=2`) whose tracks carry per-shard row
attribution, making multi-chip skew visible as unequal track labels.

Off by default: every hook starts with one module-global read + None
check (`tracer()`), the same always-off discipline as the query
recorder. `enable_tracing()` installs a bounded ring (old events drop,
never the process); `export_trace(path)` writes the standard
`{"traceEvents": [...]}` JSON object that chrome://tracing and
https://ui.perfetto.dev load directly.

Timestamps are microseconds on the tracer's own perf_counter clock —
the Chrome format needs only internal consistency, and perf_counter is
the engine's timing base everywhere else.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from hyperspace_tpu.telemetry import registry as _registry

__all__ = ["Tracer", "enable_tracing", "disable_tracing",
           "tracing_enabled", "tracer", "span", "link_transfer",
           "record_link_transfer", "export_trace", "PID_ENGINE",
           "PID_MESH"]

# Trace "processes": real engine threads vs the synthetic per-device
# tracks (tid = device ordinal) mesh dispatches attribute work to.
PID_ENGINE = 1
PID_MESH = 2

_tracer: Optional["Tracer"] = None


class Tracer:
    def __init__(self, capacity: int = 200_000):
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self.emitted = 0
        self.t0_s = time.perf_counter()
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._thread_names: Dict[int, str] = {}
        self._device_tracks: set = set()

    def now_us(self) -> float:
        return (time.perf_counter() - self.t0_s) * 1e6

    def complete(self, name: str, cat: str, ts_us: float, dur_us: float,
                 tid: Optional[int] = None, pid: int = PID_ENGINE,
                 args: Optional[dict] = None) -> None:
        """One Chrome "X" (complete) event. Same-thread spans nest by
        ts/dur containment — no explicit parent links needed."""
        if tid is None:
            tid = threading.get_ident()
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(ts_us, 1), "dur": round(max(dur_us, 0.0), 1),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)
            self.emitted += 1

    def counter(self, name: str, values: Dict[str, float],
                pid: int = PID_ENGINE, tid: int = 0) -> None:
        """One Chrome "C" (counter) event: Perfetto renders each
        distinct `name` as its own counter track, plotting the numeric
        `values` series over time — the per-device HBM tracks the
        memory accountant emits (`telemetry/memory.py`)."""
        ev = {"name": name, "cat": "memory", "ph": "C",
              "ts": round(self.now_us(), 1), "pid": pid, "tid": tid,
              "args": {k: float(v) for k, v in values.items()}}
        with self._lock:
            self.events.append(ev)
            self.emitted += 1

    def instant(self, name: str, cat: str,
                args: Optional[dict] = None) -> None:
        tid = threading.get_ident()
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": round(self.now_us(), 1), "pid": PID_ENGINE,
              "tid": tid}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)
            self.emitted += 1

    def device_spans(self, name: str, ts_us: float, rows_per_device,
                     cat: str = "mesh", **common) -> None:
        """One span per mesh device on the synthetic device process.
        SPMD dispatch gives every device the same wall window (the
        jitted step); the per-device ROW attribution in the span args is
        what exposes skew."""
        dur = self.now_us() - ts_us
        for d, rows in enumerate(rows_per_device):
            self._device_tracks.add(d)
            args = {"device": d, "rows": int(rows)}
            args.update(common)
            self.complete(f"{name} [dev{d}]", cat, ts_us, dur,
                          tid=d, pid=PID_MESH, args=args)

    def _metadata_events(self) -> List[dict]:
        out = [
            {"name": "process_name", "ph": "M", "ts": 0,
             "pid": PID_ENGINE, "tid": 0,
             "args": {"name": "hyperspace-engine"}},
        ]
        for tid, tname in sorted(self._thread_names.items()):
            out.append({"name": "thread_name", "ph": "M", "ts": 0,
                        "pid": PID_ENGINE, "tid": tid,
                        "args": {"name": tname}})
        if self._device_tracks:
            out.append({"name": "process_name", "ph": "M", "ts": 0,
                        "pid": PID_MESH, "tid": 0,
                        "args": {"name": "hyperspace-mesh"}})
            for d in sorted(self._device_tracks):
                out.append({"name": "thread_name", "ph": "M", "ts": 0,
                            "pid": PID_MESH, "tid": d,
                            "args": {"name": f"device {d}"}})
        return out

    def export(self, path: str) -> dict:
        with self._lock:
            events = list(self.events)
            emitted = self.emitted
        doc = {
            "traceEvents": self._metadata_events() + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "hyperspace_tpu.telemetry",
                "started_at": self.started_at,
                "events": len(events),
                "dropped": max(emitted - len(events), 0),
            },
        }
        from hyperspace_tpu.utils import file_utils
        file_utils.create_file(path, json.dumps(doc, default=str))
        return {"path": path, "events": len(events),
                "dropped": max(emitted - len(events), 0)}


def enable_tracing(capacity: int = 200_000) -> Tracer:
    """Install (or keep) the process tracer. Idempotent: an already
    running tracer is reused so concurrent enablers don't drop each
    other's spans."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer(capacity)
    return _tracer


def disable_tracing() -> None:
    global _tracer
    _tracer = None


def tracing_enabled() -> bool:
    return _tracer is not None


def tracer() -> Optional[Tracer]:
    """The active tracer, or None — THE always-off check every hook
    makes first."""
    return _tracer


@contextmanager
def span(name: str, cat: str = "engine", **args):
    """Trace the enclosed block as a complete event on this thread.
    No-op (one global read) without an active tracer."""
    t = _tracer
    if t is None:
        yield
        return
    ts = t.now_us()
    try:
        yield
    finally:
        t.complete(name, cat, ts, t.now_us() - ts, args=args or None)


def record_link_transfer(direction: str, nbytes: int, seconds: float,
                         ts_us: Optional[float] = None,
                         chunks: int = 1) -> None:
    """Record one device-link transfer (`direction` = "h2d" | "d2h"):
    registry counters + log-bucketed byte/seconds histograms ALWAYS, a
    per-query counter when a recorder is active, a span when tracing.
    `chunks` is how many pipelined chunk puts the logical transfer
    shipped as (`io/transfer.py`) — `link.<dir>.chunks` vs
    `link.<dir>.transfers` is the chunking ratio. jax dispatch is
    asynchronous — the measured wall is dispatch-side unless the
    measuring code synced; the byte counts are exact either way."""
    reg = _registry.get_registry()
    reg.counter(f"link.{direction}.bytes").inc(nbytes)
    reg.counter(f"link.{direction}.seconds").inc(seconds)
    reg.counter(f"link.{direction}.transfers").inc()
    reg.counter(f"link.{direction}.chunks").inc(max(int(chunks), 1))
    reg.histogram(f"link.{direction}.bytes_per_transfer").observe(nbytes)
    from hyperspace_tpu import telemetry
    # Tenant chargeback at the ONE link seam: mirroring the global inc
    # here keeps per-tenant link-byte sums exactly equal to the global
    # `link.<dir>.bytes` counters.
    telemetry.charge_tenant(f"link.{direction}.bytes", nbytes)
    telemetry.add_seconds(f"link.{direction}_s", seconds)
    telemetry.add_count(f"link.{direction}_bytes", int(nbytes))
    t = _tracer
    if t is not None:
        end = t.now_us()
        start = end - seconds * 1e6 if ts_us is None else ts_us
        t.complete(f"{direction} {int(nbytes):,}B", "link", start,
                   end - start,
                   args={"bytes": int(nbytes), "direction": direction})
    # Every instrumented transfer moves device residency: fold a memory
    # sample (throttled; no-op unless a recorder or tracer is active).
    from hyperspace_tpu.telemetry import memory as _memory
    _memory.maybe_sample()


@contextmanager
def link_transfer(direction: str, nbytes: int, chunks: int = 1):
    """Context-manager form of `record_link_transfer`: times the
    enclosed block as the transfer wall."""
    t = _tracer
    ts = t.now_us() if t is not None else None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_link_transfer(direction, nbytes,
                             time.perf_counter() - t0, ts_us=ts,
                             chunks=chunks)


def export_trace(path: str) -> dict:
    """Write the collected spans as Chrome trace-event JSON at `path`
    (loadable in chrome://tracing and ui.perfetto.dev). Returns
    {path, events, dropped}. Raises if tracing was never enabled —
    silently exporting an empty timeline would mask a missing
    `enable_tracing()` call."""
    t = _tracer
    if t is None:
        from hyperspace_tpu.exceptions import HyperspaceException
        raise HyperspaceException(
            "Tracing is not enabled; call telemetry.enable_tracing() "
            "before the work you want captured.")
    return t.export(path)
