"""TPC-H benchmark — the 22-query suite, three ways, warm best-of-N:
  - rules ON   (index-accelerated framework execution)
  - rules OFF  (framework execution without indexes)
  - pandas     (vectorized CPU oracle — the commodity baseline)
Result equality across all three is asserted before timing is reported
(the reference's E2E guarantee, `E2EHyperspaceRulesTests.scala:330-346`;
its serde layer pins the full TPC-H set, `serde/package.scala:46-49`).

Prints exactly ONE JSON line:
  {"metric": "tpch_22q_wall_s", "value": <rules-on total>,
   "vs_baseline": <pandas total / rules-on total>, "queries": {...}}

BENCH_TPCH_SCALE scales the tables (1.0 ~ 60k lineitem rows).
BENCH_TPCH_QUERIES selects a comma-separated subset.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SCALE = float(os.environ.get("BENCH_TPCH_SCALE", 1.0))
WARM_RUNS = int(os.environ.get("BENCH_WARM_RUNS", 3))
QUERY_FILTER = [q for q in os.environ.get(
    "BENCH_TPCH_QUERIES", "").split(",") if q]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def best_of(fn, runs=WARM_RUNS, label=""):
    best, out = float("inf"), None
    for i in range(runs):
        t0 = time.perf_counter()
        out = fn()
        elapsed = time.perf_counter() - t0
        log(f"  {label} run {i}: {elapsed:.3f}s")
        best = min(best, elapsed)
    return best, out


def norm(df):
    from hyperspace_tpu.tpch.queries import normalize_result
    return normalize_result(df)


def main():
    import pandas as pd
    import pyarrow.parquet as pq

    from hyperspace_tpu import telemetry
    from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
    from hyperspace_tpu.tpch import QUERIES, generate
    from hyperspace_tpu.tpch.queries import create_indexes

    work = tempfile.mkdtemp(prefix="hs_tpch_")
    try:
        t0 = time.perf_counter()
        paths = generate(os.path.join(work, "data"), scale=SCALE)
        log(f"generate (scale={SCALE}): {time.perf_counter() - t0:.1f}s")

        sess = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": os.path.join(work, "wh"),
            "spark.hyperspace.index.num.buckets": "32"}))
        hs = Hyperspace(sess)
        dfs = {n: sess.read_parquet(p) for n, p in paths.items()}
        selected = {n: q for n, q in QUERIES.items()
                    if not QUERY_FILTER or n in QUERY_FILTER}
        t0 = time.perf_counter()
        create_indexes(hs, dfs, queries=list(selected))
        index_build_s = time.perf_counter() - t0
        log(f"index build: {index_build_s:.1f}s")

        pdfs = {n: pq.read_table(os.path.join(p, "part-0.parquet"))
                .to_pandas() for n, p in paths.items()}

        queries = {}
        tot_on = tot_off = tot_cpu = 0.0
        for name, (build, oracle) in selected.items():
            cpu_s, expected = best_of(lambda: oracle(pdfs),
                                      label=f"{name} pandas")
            sess.enable_hyperspace()
            build(dfs).collect()  # warm (compiles, file listings)
            on_s, got_on = best_of(lambda: build(dfs).collect().to_pandas(),
                                   label=f"{name} rules-on")
            qmetrics = sess.last_query_metrics()
            sess.disable_hyperspace()
            off_s, got_off = best_of(lambda: build(dfs).collect().to_pandas(),
                                     label=f"{name} rules-off")
            for got, tag in ((got_on, "rules-on"), (got_off, "rules-off")):
                pd.testing.assert_frame_equal(
                    norm(got), norm(expected), check_dtype=False,
                    check_exact=False, rtol=1e-6, atol=1e-9)
            log(f"{name}: on {on_s:.3f}s off {off_s:.3f}s cpu {cpu_s:.3f}s "
                f"(vs cpu x{cpu_s / on_s:.2f}, "
                f"vs no-index x{off_s / on_s:.2f})")
            queries[name] = {"rules_on_s": round(on_s, 4),
                             "rules_off_s": round(off_s, 4),
                             "pandas_s": round(cpu_s, 4),
                             "vs_baseline": round(cpu_s / on_s, 3),
                             "vs_no_index": round(off_s / on_s, 3),
                             "rows": int(len(expected)),
                             **telemetry.artifact.query_metrics_block(
                                 qmetrics)}
            tot_on += on_s
            tot_off += off_s
            tot_cpu += cpu_s

        # Canonical, versioned artifact — same emitter as bench.py /
        # bench_tpcds.py (telemetry/artifact.py), so TPC-H rounds diff
        # and gate with the same tooling.
        print(json.dumps(telemetry.artifact.make_artifact(
            driver="bench_tpch.py",
            metric=f"tpch_{len(selected)}q_wall_s",
            value=round(tot_on, 3),
            unit="s",
            vs_baseline=round(tot_cpu / tot_on, 3),
            queries=queries,
            extra={"scale": SCALE,
                   "index_build_s": round(index_build_s, 2)})))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
