"""Serving benchmark — closed-loop scaling plus the sustained open-loop
rung the ROADMAP's serving item gates on.

Three phases, one artifact:

1. **AOT replica phase** (runs FIRST, while the process is genuinely
   cold): `engine.batcher.warmup(df)` pre-compiles the batched
   predicate programs for every batchable workload shape across the
   canonical cohort-size buckets — then a concurrent burst must record
   ZERO new `compile.serve.batch.traces` (`serve.aot.warm_traces`,
   gated absolutely). With `spark.hyperspace.compile.cache.dir` set,
   the same warmup on a real fresh replica loads the persisted
   executables instead of compiling.
2. **Closed loop**: K client threads drive the serving mix through ONE
   session, each issuing its next query the moment the previous
   returns. `vs_baseline` is closed-loop QPS at K clients over
   single-client QPS on the same warm mix — with inter-query batched
   execution (`engine/batcher.py`) this must be >= 1.0: concurrency
   WINS (gated absolutely via `scaling_floor`), and
   `serve.batch.members / serve.batch.invocations` (occupancy) must
   exceed 1. Every success is checked against its serial-run oracle.
3. **Open loop**: Poisson arrivals swept across arrival rates to the
   latency knee — queries are dispatched on schedule regardless of
   completions (hundreds of logical clients; latency counts from the
   SCHEDULED arrival, so dispatch queueing is visible, the way real
   traffic experiences it). Reports per-rate achieved QPS and
   p50/p95/p99, and the headline `qps_at_p99_slo`: the highest
   achieved rate whose p99 meets BENCH_SERVE_SLO_MS.

Operations plane (PR 15): the background timeseries sampler runs for
the whole bench — the closed loop commits `window_p99_s` (the
sampler's sliding-window p99 over exactly the timed population, gated
for agreement with the client-measured percentile) and `slo` (the
burn-rate story, SLO window reset at the timed-loop start so it is a
steady-state compliance number), and the open-loop phase commits the
per-second QPS/p50/p99 `timeline` section from the sampler ring.

The workload is the serving shape the batch lane exists for: point
lookups and range/IN filters over a fact table (differing only in
literals — one execution signature each), plus a join and an aggregate
so the mix never degenerates into pure batchable traffic.

Prints exactly ONE JSON line (canonical schema via
`telemetry.artifact.make_artifact`; `scripts/bench_regress.py --serve`
gates scaling ratio + floor, QPS, p50/p99 growth, reject/timeout
rates, batch occupancy, and the AOT warm-trace zero).

PR 16 adds the opt-in `--tenants` chaos rung (phase 4): a hot
point-query victim tenant laps solo and then co-located with a greedy
cold-scan tenant (quota-capped) and an unmeetable-deadline tenant;
the artifact's `serve.tenants` section carries both p99s, the
mismatch/deadlock story, and the `tenant_report()` chargeback
exactness flag — all gated by `bench_regress.py --serve`.

Env knobs: BENCH_SERVE_CLIENTS (8), BENCH_SERVE_QUERIES (240 total),
BENCH_SERVE_ROWS (50000), BENCH_SERVE_BUDGET_BYTES (0 = unlimited),
BENCH_SERVE_TIMEOUT_S (0 = none), BENCH_SERVE_QUEUE_DEPTH (32),
BENCH_SERVE_OPEN_SECONDS (6 per rate; minutes-long soaks raise it),
BENCH_SERVE_OPEN_WORKERS (64 logical clients), BENCH_SERVE_SLO_MS
(150), BENCH_SERVE_RATES (comma fractions of serial QPS,
"0.5,0.75,1.0,1.25,1.5"), BENCH_SERVE_TENANT_QUERIES (240 per
victim lap on the `--tenants` rung).
"""

import json
import os
import queue as queue_mod
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

CLIENTS = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
TOTAL_QUERIES = int(os.environ.get("BENCH_SERVE_QUERIES", 800))
ROWS = int(os.environ.get("BENCH_SERVE_ROWS", 50_000))
BUDGET_BYTES = int(os.environ.get("BENCH_SERVE_BUDGET_BYTES", 0))
TIMEOUT_S = float(os.environ.get("BENCH_SERVE_TIMEOUT_S", 0))
QUEUE_DEPTH = int(os.environ.get("BENCH_SERVE_QUEUE_DEPTH", 32))
OPEN_SECONDS = float(os.environ.get("BENCH_SERVE_OPEN_SECONDS", 6))
OPEN_WORKERS = int(os.environ.get("BENCH_SERVE_OPEN_WORKERS", 64))
SLO_MS = float(os.environ.get("BENCH_SERVE_SLO_MS", 150))
RATES = [float(r) for r in os.environ.get(
    "BENCH_SERVE_RATES", "0.5,0.75,1.0,1.25,1.5").split(",")]

from bench_common import link_probe, log  # noqa: E402
from hyperspace_tpu import telemetry  # noqa: E402


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _counter(name: str) -> float:
    return telemetry.get_registry().counters_dict().get(name, 0)


def build_workload(session, data_dir: str):
    """The serving mix. Deterministic plans — each query's serial
    result is the correctness oracle for its concurrent runs. The
    point/range/IN entries share execution signatures (same shape,
    different literals), which is exactly what the batch lane
    coalesces; the join and aggregate keep the mix honest."""
    from hyperspace_tpu.plan.expr import col, lit

    facts = session.read_parquet(os.path.join(data_dir, "facts"))
    dims = session.read_parquet(os.path.join(data_dir, "dims"))
    workload = []
    for g in range(8):
        workload.append((f"point_g{g}",
                         facts.filter(col("g") == lit(g))
                         .select("k", "g", "v")))
    for i, (lo, hi) in enumerate(((0.90, 0.95), (0.40, 0.45))):
        workload.append((f"range_v{i}",
                         facts.filter((col("v") > lit(lo))
                                      & (col("v") <= lit(hi)))
                         .select("k", "v")))
    workload.append(("in_g0", facts.filter(col("g").isin(3, 11, 19))
                     .select("k", "g")))
    workload.append(("in_g1", facts.filter(col("g").isin(5, 21))
                     .select("k", "g")))
    workload.append(("agg", facts.group_by("g")
                     .agg(("sum", "v", "total"), cnt=("count", "*"))))
    workload.append(("join", facts.join(dims, on="k")
                     .filter(col("w") > lit(0.5))
                     .group_by("g").agg(("avg", "v", "avg_v"))))
    return workload


def generate(data_dir: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    os.makedirs(os.path.join(data_dir, "facts"))
    os.makedirs(os.path.join(data_dir, "dims"))
    n_dims = max(ROWS // 50, 16)
    pq.write_table(pa.table({
        "k": rng.integers(0, n_dims, ROWS).astype(np.int64),
        "g": rng.integers(0, 32, ROWS).astype(np.int64),
        "v": rng.random(ROWS).astype(np.float64),
    }), os.path.join(data_dir, "facts", "part-0.parquet"))
    pq.write_table(pa.table({
        "k": np.arange(n_dims, dtype=np.int64),
        "w": rng.random(n_dims).astype(np.float64),
        "label": pa.array([f"d{i % 100}" for i in range(n_dims)]),
    }), os.path.join(data_dir, "dims", "part-0.parquet"))


def canonical(table):
    names = table.schema.names
    return table.sort_by([(n, "ascending") for n in names])


def aot_replica_phase(workload):
    """Phase 1 (cold process): warm the batched executables, then prove
    a concurrent burst traces NOTHING new on the serve.batch entry."""
    from hyperspace_tpu.engine import batcher

    warmed = 0
    batchable = []
    for name, df in workload:
        sig = batcher.plan_signature(df.session.optimize(df.plan),
                                     id(df.session))
        if sig is not None:
            batchable.append((name, df))
    # One warmup per distinct signature shape (the memo dedups).
    for _name, df in batchable:
        warmed += batcher.warmup(df)
    traces_before = _counter("compile.serve.batch.traces")
    burst_errors = []

    def burst_client(entries):
        for _name, df in entries:
            try:
                df.collect()
            except Exception as exc:  # pragma: no cover
                burst_errors.append(repr(exc))

    per = max(1, 32 // max(1, len(batchable)))
    threads = [threading.Thread(target=burst_client,
                                args=(batchable * per,),
                                name=f"aot-burst-{c}")
               for c in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120)
    warm_traces = _counter("compile.serve.batch.traces") - traces_before
    log(f"aot replica phase: {warmed} programs warmed, "
        f"{len(batchable)} batchable shapes, burst warm traces "
        f"{warm_traces:.0f}, errors {len(burst_errors)}")
    return {
        "programs_warmed": warmed,
        "batchable_shapes": len(batchable),
        "warm_traces": warm_traces,
        "burst_errors": len(burst_errors),
    }


def closed_loop(workload, expected):
    """Phase 2: K closed-loop clients vs the single-client baseline."""
    from hyperspace_tpu.exceptions import (QueryCancelledError,
                                           QueryDeadlineExceededError,
                                           QueryRejectedError)

    # Single-client baseline QPS on the warm mix: median of three
    # laps — this shared container's CPU wobbles run to run, and the
    # scaling ratio is only as trustworthy as its denominator.
    lap_qps = []
    for _lap in range(3):
        t0 = time.perf_counter()
        serial_runs = 0
        while serial_runs < max(len(workload) * 8, 112):
            _name, df = workload[serial_runs % len(workload)]
            df.collect()
            serial_runs += 1
        lap_qps.append(serial_runs / (time.perf_counter() - t0))
    serial_qps = sorted(lap_qps)[1]
    log(f"serial baseline: laps "
        + ", ".join(f"{q:.1f}" for q in lap_qps)
        + f" QPS -> median {serial_qps:.1f}")

    next_q = [0]
    budget = [0]
    take_lock = threading.Lock()
    latencies = []
    outcomes = {"ok": 0, "rejected": 0, "deadline": 0,
                "cancelled": 0, "error": 0}
    mismatches = []
    produced = []
    res_lock = threading.Lock()

    def client(cid: int):
        while True:
            with take_lock:
                if next_q[0] >= budget[0]:
                    return
                qi = next_q[0]
                next_q[0] += 1
            name, df = workload[qi % len(workload)]
            t1 = time.perf_counter()
            try:
                table = df.collect(
                    timeout=TIMEOUT_S if TIMEOUT_S > 0 else None)
            except QueryRejectedError:
                with res_lock:
                    outcomes["rejected"] += 1
                continue
            except QueryDeadlineExceededError:
                with res_lock:
                    outcomes["deadline"] += 1
                continue
            except QueryCancelledError:
                with res_lock:
                    outcomes["cancelled"] += 1
                continue
            except Exception as exc:  # pragma: no cover
                with res_lock:
                    outcomes["error"] += 1
                    mismatches.append(f"{name}: {exc!r}")
                continue
            wall = time.perf_counter() - t1
            # Correctness is verified AFTER the loop (every result,
            # none skipped) — the serial baseline doesn't pay a
            # canonicalize+compare per query, so neither may the
            # concurrent lap it is the denominator for.
            with res_lock:
                latencies.append(wall)
                outcomes["ok"] += 1
                produced.append((name, table))

    # Warm lap (not measured): thread spawn, cohort formation, and any
    # residual compiles settle before the timed loop — the committed
    # number is steady-state serving, matching how the serial baseline
    # ran on the already-warm mix.
    budget[0] = max(CLIENTS * 16, 120)
    warm = [threading.Thread(target=client, args=(c,),
                             name=f"serve-warm-{c}")
            for c in range(CLIENTS)]
    for th in warm:
        th.start()
    for th in warm:
        th.join()
    for name, table in produced:  # warm lap is still correctness-checked
        if not canonical(table).equals(expected[name]):
            mismatches.append(f"{name}: result differs from serial run")
    with res_lock:
        latencies.clear()
        produced.clear()
        for k in outcomes:
            outcomes[k] = 0
    next_q[0] = 0
    budget[0] = TOTAL_QUERIES
    # Steady-state SLO + sliding-window baseline: reset the burn window
    # (the cold AOT/oracle phases' walls are warm-up, not serving
    # compliance) and pin a timeseries sample at the loop start so the
    # committed window-p99 covers exactly the timed population.
    from hyperspace_tpu.engine.scheduler import get_scheduler
    from hyperspace_tpu.telemetry import timeseries
    get_scheduler().slo.reset()
    sampler = timeseries.get_sampler()
    sampler.tick()
    # The false-positive gate's window: a clean closed-loop lap must
    # fire ZERO incidents (`bench_regress.py --serve` gates the delta
    # absolutely). Counted over exactly the timed loop — the open-loop
    # sweep deliberately saturates past the knee, where a burn incident
    # is the alert plane working, not a false positive.
    alerts_fired0 = _counter("alerts.fired")
    t_loop0 = time.time()
    batch0 = {k: _counter(f"serve.batch.{k}")
              for k in ("invocations", "members", "fallbacks", "solo")}
    threads = [threading.Thread(target=client, args=(c,),
                                name=f"serve-client-{c}")
               for c in range(CLIENTS)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    loop_wall = time.perf_counter() - t0
    for name, table in produced:
        if not canonical(table).equals(expected[name]):
            mismatches.append(f"{name}: result differs from serial run")

    if mismatches:
        log("CORRECTNESS FAILURES under concurrency:")
        for m in mismatches[:10]:
            log(f"  {m}")
        raise SystemExit(1)

    batch = {k: _counter(f"serve.batch.{k}") - batch0[k]
             for k in batch0}
    batch["occupancy"] = (round(batch["members"] / batch["invocations"],
                                3) if batch["invocations"] else None)
    # Sliding-window cross-check: the sampler's p99 over exactly the
    # timed population (log2-bucket upper bound) next to the
    # client-measured percentile — `bench_regress.py --serve` gates
    # their agreement.
    sampler.tick()
    window_p99 = sampler.window_quantile("query.wall_s", 0.99,
                                         since_t=t_loop0)
    slo = get_scheduler().slo_snapshot()
    slo["p99_target_s"] = SLO_MS / 1e3
    latencies.sort()
    qps = outcomes["ok"] / loop_wall if loop_wall else 0.0
    return {
        "loop_wall_s": round(loop_wall, 3),
        "qps": round(qps, 2),
        "serial_qps": round(serial_qps, 2),
        "p50_s": round(_percentile(latencies, 0.50) or 0, 5),
        "p95_s": round(_percentile(latencies, 0.95) or 0, 5),
        "p99_s": round(_percentile(latencies, 0.99) or 0, 5),
        "max_s": round(latencies[-1], 5) if latencies else None,
        "window_p99_s": window_p99,
        "slo": slo,
        "outcomes": outcomes,
        "reject_rate": round(outcomes["rejected"] / TOTAL_QUERIES, 5),
        "timeout_rate": round(outcomes["deadline"] / TOTAL_QUERIES, 5),
        "batch": batch,
        "alerts_fired_timed_loop":
            int(_counter("alerts.fired") - alerts_fired0),
    }


def _rate_critpath(seg0: dict, wall0: float, q0: float, seq0: int):
    """The knee-attribution block for one finished arrival rate:
    counter-delta segment shares over the rate's whole window, plus
    the stamped decomposition of the rate's p99 query (by SERVER wall
    — the flight ring's measured wall, which is what the sum-exactness
    contract is against; the client-side open-loop latency additionally
    counts dispatch queueing outside the server)."""
    from hyperspace_tpu.telemetry import critical_path, flight

    wall_d = _counter("critpath.wall.seconds") - wall0
    q_d = _counter("critpath.queries") - q0
    shares = {}
    for seg in critical_path.SEGMENTS:
        d = _counter(f"critpath.{seg}.seconds") - seg0[seg]
        shares[seg] = round(d / wall_d, 4) if wall_d > 0 else 0.0
    out = {
        "queries": int(q_d),
        "wall_seconds": round(wall_d, 4),
        "shares": shares,
        "dominant": (max(shares, key=shares.get)
                     if wall_d > 0 else None),
    }
    # The ring holds the newest 64 entries — a sample of the rate's
    # tail, which is exactly where the p99 lives.
    fresh, _last = flight.get_recorder().snapshot(seq0)
    stamped = sorted((m for m in fresh
                      if getattr(m, "critical_path", None) is not None
                      and m.wall_s is not None),
                     key=lambda m: m.wall_s)
    if stamped:
        cp = _percentile(stamped, 0.99).critical_path
        out["p99_wall_s"] = cp["wall_s"]
        out["p99_segments"] = cp["segments"]
        out["p99_dominant"] = cp["dominant"]
        out["p99_sum_error_s"] = round(
            abs(cp["sum_s"] - cp["wall_s"]), 9)
        out["ring_sampled"] = len(stamped)
    return out


def profiler_overhead_phase(workload):
    """Phase 2.5: the price of always-on visibility. The same
    closed-loop lap with the sampling profiler OFF and ON, interleaved
    (off, on, off, on, ...) so machine drift lands on both sides
    equally; median of three each. `bench_regress.py --serve` gates
    the QPS delta at 2%."""
    from hyperspace_tpu.telemetry import profiler

    lap_queries = max(CLIENTS * 16, 160)

    def lap() -> float:
        next_q = [0]
        take = threading.Lock()

        def client():
            while True:
                with take:
                    if next_q[0] >= lap_queries:
                        return
                    qi = next_q[0]
                    next_q[0] += 1
                _name, df = workload[qi % len(workload)]
                df.collect()

        threads = [threading.Thread(target=client,
                                    name=f"prof-lap-{c}")
                   for c in range(CLIENTS)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        return lap_queries / (time.perf_counter() - t0)

    hz = profiler.DEFAULT_HZ
    off_laps, on_laps, samples = [], [], 0
    for _rep in range(3):
        off_laps.append(lap())
        p = profiler.start_profiler(hz)
        try:
            on_laps.append(lap())
        finally:
            samples += sum(p.snapshot().values())
            profiler.stop_profiler()
    qps_off = sorted(off_laps)[1]
    qps_on = sorted(on_laps)[1]
    overhead = ((qps_off - qps_on) / qps_off) if qps_off else 0.0
    out = {
        "hz": hz,
        "lap_queries": lap_queries,
        "qps_off_laps": [round(q, 2) for q in off_laps],
        "qps_on_laps": [round(q, 2) for q in on_laps],
        "qps_off": round(qps_off, 2),
        "qps_on": round(qps_on, 2),
        "overhead_fraction": round(overhead, 4),
        "samples": samples,
    }
    log(f"profiler overhead @ {hz:.0f} Hz: off {out['qps_off']:.1f} "
        f"QPS vs on {out['qps_on']:.1f} QPS = "
        f"{overhead * 100:+.2f}% ({samples} stack samples)")
    return out


def open_loop(workload, expected, serial_qps):
    """Phase 3: Poisson arrivals swept across rates. Open-loop latency
    counts from the SCHEDULED arrival time — a saturated server shows
    its queueing delay instead of silently slowing the clients. Each
    rate's entry embeds its critical-path decomposition — the sweep
    states numerically what eats p99 as the offered rate climbs."""
    from hyperspace_tpu.telemetry import critical_path, flight, timeseries

    sampler = timeseries.get_sampler()
    sampler.tick()
    t_open0 = time.time()
    rng = np.random.default_rng(23)
    sweep = []
    for frac in RATES:
        rate = max(1.0, frac * serial_qps)
        seg0 = {seg: _counter(f"critpath.{seg}.seconds")
                for seg in critical_path.SEGMENTS}
        wall0 = _counter("critpath.wall.seconds")
        q0 = _counter("critpath.queries")
        seq0 = flight.get_recorder().last_seq
        horizon = OPEN_SECONDS
        gaps = rng.exponential(1.0 / rate, size=int(rate * horizon * 1.2)
                               + 16)
        sched = np.cumsum(gaps)
        sched = sched[sched < horizon]
        work = queue_mod.Queue()
        latencies = []
        outcomes = {"ok": 0, "failed": 0, "mismatch": 0}
        lock = threading.Lock()

        def worker():
            while True:
                item = work.get()
                if item is None:
                    return
                qi, t_sched_abs = item
                name, df = workload[qi % len(workload)]
                try:
                    table = df.collect(
                        timeout=TIMEOUT_S if TIMEOUT_S > 0 else None)
                except Exception:
                    with lock:
                        outcomes["failed"] += 1
                    continue
                done = time.perf_counter()
                ok = canonical(table).equals(expected[name])
                with lock:
                    latencies.append(done - t_sched_abs)
                    outcomes["ok" if ok else "mismatch"] += 1

        workers = [threading.Thread(target=worker,
                                    name=f"open-worker-{w}")
                   for w in range(OPEN_WORKERS)]
        for th in workers:
            th.start()
        t0 = time.perf_counter()
        for qi, t_rel in enumerate(sched):
            now = time.perf_counter() - t0
            if t_rel > now:
                time.sleep(t_rel - now)
            work.put((qi, t0 + t_rel))
        for _ in workers:
            work.put(None)
        for th in workers:
            th.join(300)
        wall = time.perf_counter() - t0
        latencies.sort()
        achieved = outcomes["ok"] / wall if wall else 0.0
        entry = {
            "offered_qps": round(rate, 2),
            "offered_fraction_of_serial": frac,
            "arrivals": int(len(sched)),
            "achieved_qps": round(achieved, 2),
            "p50_s": round(_percentile(latencies, 0.50) or 0, 5),
            "p95_s": round(_percentile(latencies, 0.95) or 0, 5),
            "p99_s": round(_percentile(latencies, 0.99) or 0, 5),
            "outcomes": outcomes,
            "critical_path": _rate_critpath(seg0, wall0, q0, seq0),
        }
        sweep.append(entry)
        cp = entry["critical_path"]
        log(f"open loop @ {rate:7.1f}/s offered: "
            f"{achieved:7.1f}/s achieved, "
            f"p50 {entry['p50_s'] * 1e3:6.1f} ms, "
            f"p99 {entry['p99_s'] * 1e3:6.1f} ms, "
            f"dominant {cp['dominant']}")
        if entry["outcomes"]["mismatch"]:
            log("CORRECTNESS FAILURES in the open loop")
            raise SystemExit(1)
    slo_s = SLO_MS / 1e3
    meeting = [e for e in sweep if e["p99_s"] <= slo_s
               and e["outcomes"]["ok"] > 0]
    qps_at_slo = max((e["achieved_qps"] for e in meeting), default=None)
    # Knee attribution: the HIGHEST rate still meeting the p99 SLO is
    # the knee; its dominant critical-path segment names what the
    # serving plane runs out of first. No rate meeting the SLO = the
    # knee sits below the sweep; attribute the lowest rate instead.
    knee_entry = (max(meeting, key=lambda e: e["achieved_qps"])
                  if meeting else (sweep[0] if sweep else None))
    knee = None
    if knee_entry is not None:
        kcp = knee_entry["critical_path"]
        knee = {
            "offered_qps": knee_entry["offered_qps"],
            "offered_fraction_of_serial":
                knee_entry["offered_fraction_of_serial"],
            "achieved_qps": knee_entry["achieved_qps"],
            "p99_s": knee_entry["p99_s"],
            "dominant_segment": kcp.get("dominant"),
            "p99_dominant_segment": kcp.get("p99_dominant"),
            "shares": kcp.get("shares"),
            "below_sweep": not meeting,
        }
        log(f"knee @ {knee['offered_qps']}/s offered: dominant "
            f"segment {knee['dominant_segment']}"
            + (" (below sweep)" if not meeting else ""))
    # Per-second arrival-rate timeline from the timeseries ring: what
    # the open-loop phase actually looked like over time (QPS from the
    # queries.total rate, per-interval p50/p99 from the query.wall_s
    # histogram deltas — log2-bucket upper bounds).
    sampler.tick()
    timeline = []
    for s in sampler.samples(since_t=t_open0):
        iv = (s.get("interval") or {}).get("query.wall_s") or {}
        timeline.append({
            "t": s["t"],
            "dt_s": s["dt_s"],
            "qps": round((s.get("rates") or {}).get("queries.total",
                                                    0.0), 2),
            "queries": iv.get("count", 0),
            "p50_s": iv.get("p50"),
            "p99_s": iv.get("p99"),
        })
    return {
        "slo_p99_ms": SLO_MS,
        "seconds_per_rate": OPEN_SECONDS,
        "workers": OPEN_WORKERS,
        "sweep": sweep,
        "qps_at_p99_slo": qps_at_slo,
        "knee": knee,
        "timeline": timeline,
    }


def slow_decile_attribution():
    """The p99 diagnosis the flight recorder exists for: diff the
    slowest decile of the ring against the median-wall query so the
    committed artifact carries *why* the tail is slow."""
    from hyperspace_tpu.telemetry import diff, flight

    ring = [q for q in flight.get_recorder().queries()
            if q.wall_s is not None]
    if len(ring) < 10:
        return None
    ring.sort(key=lambda q: q.wall_s)
    median = ring[len(ring) // 2]
    median_tree = median.to_dict()
    out = {
        "ring_queries": len(ring),
        "median_wall_s": round(median.wall_s, 5),
        "queries": [],
    }
    for qm in ring[-max(1, len(ring) // 10):]:
        d = diff.diff_trees(median_tree, qm.to_dict(),
                            name=qm.description or "query")
        out["queries"].append({
            "description": qm.description,
            "wall_s": round(qm.wall_s, 5),
            "vs_median": (round(qm.wall_s / median.wall_s, 2)
                          if median.wall_s else None),
            "dominant_bucket": d.dominant,
            "attribution": d.to_dict(),
        })
    return out


def tenants_phase(session, workload, expected):
    """`--tenants` adversarial chaos rung (the ROADMAP multi-tenant
    mix): three tenants co-located on one scheduler —

    - **hot** (the victim): point lookups, the latency-sensitive
      tenant whose p99 the round is about;
    - **cold** (the greedy tenant): scans/joins/aggregates issued
      back-to-back under a deliberately tiny HBM fraction, so it
      saturates its quota and lives in the weighted-fair queue;
    - **doomed**: queries carrying an unmeetable deadline — every one
      must die with the TYPED deadline error, never a hang or a poison
      of another tenant's slot.

    The victim runs one lap SOLO and one lap co-located with the
    chaos; the committed numbers are both p99s. `bench_regress.py
    --serve` gates the ratio (co-located <= 2x solo), zero mismatches
    (bit-identical results under chaos), zero deadlocks (every thread
    joins), and the chargeback exactness flag from
    `Hyperspace.tenant_report()`."""
    from hyperspace_tpu import Hyperspace
    from hyperspace_tpu.exceptions import (QueryDeadlineExceededError,
                                           QueryRejectedError)

    conf = session.conf
    sched = session.scheduler()
    hot = [(n, df) for n, df in workload if n.startswith("point_")]
    cold = [(n, df) for n, df in workload
            if not n.startswith("point_")]
    hot_clients = 2
    hot_queries = int(os.environ.get("BENCH_SERVE_TENANT_QUERIES", 240))

    def hot_lap():
        """One victim lap: `hot_clients` closed-loop threads drain
        `hot_queries` point queries as tenant "hot". Results are kept
        and oracle-checked AFTER the lap (same reasoning as the closed
        loop: the timed path pays no canonicalize+compare)."""
        lats, produced, errors = [], [], []
        idx = [0]
        lock = threading.Lock()

        def client(cid: int):
            while True:
                with lock:
                    if idx[0] >= hot_queries:
                        return
                    qi = idx[0]
                    idx[0] += 1
                name, df = hot[qi % len(hot)]
                t1 = time.perf_counter()
                try:
                    table = df.collect(tenant="hot")
                except Exception as exc:
                    with lock:
                        errors.append(f"{name}: {exc!r}")
                    continue
                wall = time.perf_counter() - t1
                with lock:
                    lats.append(wall)
                    produced.append((name, table))

        threads = [threading.Thread(target=client, args=(c,),
                                    name=f"tenant-hot-{c}")
                   for c in range(hot_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        stuck = any(th.is_alive() for th in threads)
        mism = list(errors)
        for name, table in produced:
            if not canonical(table).equals(expected[name]):
                mism.append(f"{name}: result differs from serial run")
        lats.sort()
        return lats, mism, stuck

    # Quota/weight knobs for the rung. The global budget is sized off
    # the peak the earlier phases actually admitted, so it never binds
    # on the victim; the cold tenant's 5% fraction DOES bind the
    # moment it has one scan in flight — that is the "greedy tenant
    # saturating its quota" the gate is about. Restored afterwards.
    knobs = {
        "spark.hyperspace.serve.hbm.budget.bytes":
            str(max(int(sched.peak_admitted_bytes), 64 << 20)),
        "spark.hyperspace.serve.tenant.hot.weight": "4",
        "spark.hyperspace.serve.tenant.cold.weight": "1",
        "spark.hyperspace.serve.tenant.cold.hbm.fraction": "0.05",
        "spark.hyperspace.serve.tenant.cold.queue.depth": "4",
        "spark.hyperspace.serve.tenant.doomed.queue.depth": "2",
    }
    saved = {k: conf.get(k) for k in knobs}
    for k, v in knobs.items():
        conf.set(k, v)

    stop = threading.Event()
    chaos = {"cold_ok": 0, "cold_rejected": 0, "cold_deadline": 0,
             "doomed_deadline": 0, "doomed_ok": 0, "unexpected": 0}
    chaos_lock = threading.Lock()

    def cold_client(cid: int):
        i = cid
        while not stop.is_set():
            _name, df = cold[i % len(cold)]
            i += 1
            try:
                df.collect(tenant="cold", timeout=5.0)
                key = "cold_ok"
            except QueryRejectedError:
                key = "cold_rejected"
            except QueryDeadlineExceededError:
                key = "cold_deadline"
            except Exception:
                key = "unexpected"
            with chaos_lock:
                chaos[key] += 1

    def doomed_client():
        i = 0
        while not stop.is_set():
            _name, df = hot[i % len(hot)]
            i += 1
            try:
                # 1 microsecond: expired before the first checkpoint.
                df.collect(tenant="doomed", timeout=1e-6)
                key = "doomed_ok"
            except (QueryRejectedError, QueryDeadlineExceededError):
                key = "doomed_deadline"
            except Exception:
                key = "unexpected"
            with chaos_lock:
                chaos[key] += 1
            time.sleep(0.01)

    tenant_counter_names = [
        f"serve.tenant.{t}.{k}"
        for t in ("hot", "cold", "doomed")
        for k in ("admitted", "rejected", "queued")]
    try:
        solo_lats, solo_mism, solo_stuck = hot_lap()

        before = telemetry.get_registry().counters_dict()
        # One cold client: its 5% HBM fraction already serializes the
        # greedy tenant to one scan in flight, so a second thread
        # would only deepen its own queue — and on a small host the
        # co-located p99 must reflect scheduler isolation, not raw
        # core starvation the admission plane cannot govern.
        chaos_threads = [
            threading.Thread(target=cold_client, args=(0,),
                             name="tenant-cold-0"),
            threading.Thread(target=doomed_client,
                             name="tenant-doomed")]
        for th in chaos_threads:
            th.start()
        coloc_lats, coloc_mism, coloc_stuck = hot_lap()
        stop.set()
        for th in chaos_threads:
            th.join(timeout=60)
        deadlock = (solo_stuck or coloc_stuck
                    or any(th.is_alive() for th in chaos_threads))
        after = telemetry.get_registry().counters_dict()
    finally:
        for k, v in saved.items():
            conf.set(k, v) if v is not None else conf.unset(k)

    rep = Hyperspace(session).tenant_report()
    solo_p99 = _percentile(solo_lats, 0.99)
    coloc_p99 = _percentile(coloc_lats, 0.99)
    return {
        "hot_clients": hot_clients,
        "hot_queries": hot_queries,
        "victim_solo_p50_s": round(_percentile(solo_lats, 0.50), 6),
        "victim_solo_p99_s": round(solo_p99, 6),
        "victim_coloc_p50_s": round(_percentile(coloc_lats, 0.50), 6),
        "victim_coloc_p99_s": round(coloc_p99, 6),
        "victim_isolation_x": (round(coloc_p99 / solo_p99, 3)
                               if solo_p99 else None),
        "mismatches": len(solo_mism) + len(coloc_mism),
        "mismatch_detail": (solo_mism + coloc_mism)[:10],
        "deadlock": deadlock,
        "chaos": chaos,
        "tenant_counters": {
            name: round(after.get(name, 0) - before.get(name, 0), 6)
            for name in tenant_counter_names},
        "chargeback": {
            "exact": rep["exact"],
            "totals": {k: round(v, 6) for k, v in rep["totals"].items()},
            "global": {k: round(v, 6) for k, v in rep["global"].items()},
            "tenants": sorted(rep["tenants"]),
        },
    }


def main():
    from hyperspace_tpu import HyperspaceConf, HyperspaceSession

    work = tempfile.mkdtemp(prefix="hs_serve_")
    try:
        data_dir = os.path.join(work, "data")
        generate(data_dir)
        session = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": os.path.join(work, "wh"),
            "spark.hyperspace.serve.hbm.budget.bytes": str(BUDGET_BYTES),
            "spark.hyperspace.serve.queue.depth": str(QUEUE_DEPTH),
            # SLO tracking on: burn window + violations accumulate so
            # the committed round carries its own compliance story
            # (shedding stays at its off default — a bench must
            # measure the knee, not flinch from it).
            "spark.hyperspace.serve.slo.p99.seconds": str(SLO_MS / 1e3),
        }))
        # Background per-second sampler: the open-loop timeline and the
        # sliding-window p99 cross-check both read its ring.
        from hyperspace_tpu.telemetry import timeseries
        timeseries.get_sampler().start()
        workload = build_workload(session, data_dir)

        # Phase 1 while the process is cold: AOT warm-start proof.
        aot = aot_replica_phase(workload)

        # Correctness oracles (serial run of every query).
        expected = {}
        for name, df in workload:
            expected[name] = canonical(df.collect())

        # Phase 2: closed loop.
        serve = closed_loop(workload, expected)
        qps, serial_qps = serve["qps"], serve["serial_qps"]
        log(f"closed loop: {serve['outcomes']['ok']}/{TOTAL_QUERIES} ok "
            f"in {serve['loop_wall_s']:.2f}s = {qps:.1f} QPS "
            f"(x{qps / serial_qps:.2f} vs 1 client), "
            f"p50 {serve['p50_s'] * 1e3:.1f} ms, "
            f"p99 {serve['p99_s'] * 1e3:.1f} ms, "
            f"batch occupancy {serve['batch']['occupancy']}")

        # Phase 2.5: sampling-profiler overhead, measured not assumed.
        serve["profiler"] = profiler_overhead_phase(workload)

        # Phase 3: open loop to the knee.
        serve["open_loop"] = open_loop(workload, expected, serial_qps)

        # Phase 4 (opt-in): multi-tenant chaos rung.
        if "--tenants" in sys.argv:
            serve["tenants"] = tenants_phase(session, workload, expected)
            tn = serve["tenants"]
            log(f"tenants: victim p99 solo "
                f"{tn['victim_solo_p99_s'] * 1e3:.1f} ms -> co-located "
                f"{tn['victim_coloc_p99_s'] * 1e3:.1f} ms "
                f"(x{tn['victim_isolation_x']}), "
                f"{tn['mismatches']} mismatches, "
                f"deadlock={tn['deadlock']}, "
                f"chargeback exact={tn['chargeback']['exact']}")

        # Incident digest: the whole bench's alert story (the open-loop
        # saturation rates MAY legitimately fire), with the clean-run
        # number scoped to the timed closed loop.
        from hyperspace_tpu.telemetry import alerts as alerts_mod
        alerts_digest = alerts_mod.get_manager().digest()
        alerts_digest["clean_run_fired"] = serve.pop(
            "alerts_fired_timed_loop")
        serve["alerts"] = alerts_digest
        log(f"alerts: {alerts_digest['fired']} fired over the bench "
            f"({alerts_digest['clean_run_fired']} during the clean "
            f"closed loop), {alerts_digest['evaluations']} evaluations")

        sched = session.scheduler()
        counters = telemetry.get_registry().counters_dict()
        serve.update({
            "clients": CLIENTS,
            "queries": TOTAL_QUERIES,
            "rows": ROWS,
            "budget_bytes": BUDGET_BYTES,
            "deadline_s": TIMEOUT_S,
            "aot": aot,
            "peak_admitted_bytes": sched.peak_admitted_bytes,
            "counters": {k: v for k, v in counters.items()
                         if k.startswith(("serve.", "resilience.",
                                          "compile.aot.",
                                          "cache.segments.shared."))},
            "slow_decile": slow_decile_attribution(),
        })
        timeline = serve["open_loop"].pop("timeline", [])
        result = telemetry.artifact.make_artifact(
            driver="bench_serve.py",
            metric="serve_closed_loop_qps",
            value=qps,
            unit="queries/s",
            vs_baseline=round(qps / serial_qps, 3) if serial_qps else None,
            extra={"serve": serve,
                   "timeline": {"source": "open_loop",
                                "interval_s":
                                    timeseries.get_sampler().interval_s,
                                "samples": timeline},
                   "link_probe": link_probe()})
        print(json.dumps(result))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
