"""Closed-loop serving benchmark — the traffic the ROADMAP's serving
item gates on.

K client threads drive a mixed filter / join / aggregate workload
through ONE session (every `collect` routes through the process-wide
`QueryScheduler`), closed-loop: each client issues its next query the
moment the previous one returns. Reported:

  - p50 / p95 / p99 latency over successful queries,
  - QPS (successes / loop wall),
  - typed outcome counts (rejected / deadline-exceeded / cancelled),
  - the scheduler's serve.* counter block and peak admitted bytes.

`vs_baseline` is the concurrency scaling ratio: closed-loop QPS at K
clients over single-client QPS on the same warm mix — the number the
scheduler must not regress (admission overhead, queue convoying, lock
contention all land here). Every successful query's result is compared
against its serial-run table, so a correctness break under concurrency
fails the bench before any number is reported.

Prints exactly ONE JSON line (canonical schema via
`telemetry.artifact.make_artifact`; `scripts/bench_regress.py --serve`
gates p99, reject rate, and QPS from it).

Env knobs: BENCH_SERVE_CLIENTS (8), BENCH_SERVE_QUERIES (200 total),
BENCH_SERVE_ROWS (50000), BENCH_SERVE_BUDGET_BYTES (serving HBM budget;
0 = unlimited), BENCH_SERVE_TIMEOUT_S (per-query deadline; 0 = none),
BENCH_SERVE_QUEUE_DEPTH (32).
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

CLIENTS = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
TOTAL_QUERIES = int(os.environ.get("BENCH_SERVE_QUERIES", 200))
ROWS = int(os.environ.get("BENCH_SERVE_ROWS", 50_000))
BUDGET_BYTES = int(os.environ.get("BENCH_SERVE_BUDGET_BYTES", 0))
TIMEOUT_S = float(os.environ.get("BENCH_SERVE_TIMEOUT_S", 0))
QUEUE_DEPTH = int(os.environ.get("BENCH_SERVE_QUEUE_DEPTH", 32))

from bench_common import link_probe, log  # noqa: E402
from hyperspace_tpu import telemetry  # noqa: E402


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def build_workload(session, data_dir: str):
    """The mixed query set. Deterministic plans — each query's serial
    result is the correctness oracle for its concurrent runs."""
    from hyperspace_tpu.plan.expr import col, lit

    facts = session.read_parquet(os.path.join(data_dir, "facts"))
    dims = session.read_parquet(os.path.join(data_dir, "dims"))
    return [
        ("filter", facts.filter(col("v") > lit(0.9))
         .select("k", "v")),
        ("agg", facts.group_by("g").agg(("sum", "v", "total"),
                                        cnt=("count", "*"))),
        ("join", facts.join(dims, on="k")
         .filter(col("w") > lit(0.5))
         .group_by("g").agg(("avg", "v", "avg_v"))),
        ("filter2", facts.filter((col("g") == lit(7)))
         .select("k", "g", "v")),
        ("join_agg", facts.join(dims, on="k")
         .group_by("label").agg(("sum", "w", "tw"))),
    ]


def generate(data_dir: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    os.makedirs(os.path.join(data_dir, "facts"))
    os.makedirs(os.path.join(data_dir, "dims"))
    n_dims = max(ROWS // 50, 16)
    pq.write_table(pa.table({
        "k": rng.integers(0, n_dims, ROWS).astype(np.int64),
        "g": rng.integers(0, 32, ROWS).astype(np.int64),
        "v": rng.random(ROWS).astype(np.float64),
    }), os.path.join(data_dir, "facts", "part-0.parquet"))
    pq.write_table(pa.table({
        "k": np.arange(n_dims, dtype=np.int64),
        "w": rng.random(n_dims).astype(np.float64),
        "label": pa.array([f"d{i % 100}" for i in range(n_dims)]),
    }), os.path.join(data_dir, "dims", "part-0.parquet"))


def canonical(table):
    names = table.schema.names
    return table.sort_by([(n, "ascending") for n in names])


def slow_decile_attribution():
    """The p99 diagnosis the flight recorder exists for (ROADMAP item):
    pull the slowest DECILE of the ring's completed queries and diff
    each against the ring's median-wall query with the regression
    differ, so the committed artifact carries *why* the tail is slow
    (compute vs link vs compile vs cache vs cancellation), not just
    that it is. The ring holds the most recent completed queries of the
    closed loop — the exact population the p99 is computed over."""
    from hyperspace_tpu.telemetry import diff, flight

    ring = [q for q in flight.get_recorder().queries()
            if q.wall_s is not None]
    if len(ring) < 10:
        return None
    ring.sort(key=lambda q: q.wall_s)
    median = ring[len(ring) // 2]
    median_tree = median.to_dict()
    out = {
        "ring_queries": len(ring),
        "median_wall_s": round(median.wall_s, 5),
        "queries": [],
    }
    for qm in ring[-max(1, len(ring) // 10):]:
        d = diff.diff_trees(median_tree, qm.to_dict(),
                            name=qm.description or "query")
        out["queries"].append({
            "description": qm.description,
            "wall_s": round(qm.wall_s, 5),
            "vs_median": (round(qm.wall_s / median.wall_s, 2)
                          if median.wall_s else None),
            "dominant_bucket": d.dominant,
            "attribution": d.to_dict(),
        })
    return out


def main():
    from hyperspace_tpu import HyperspaceConf, HyperspaceSession
    from hyperspace_tpu.exceptions import (QueryCancelledError,
                                           QueryDeadlineExceededError,
                                           QueryRejectedError)

    work = tempfile.mkdtemp(prefix="hs_serve_")
    try:
        data_dir = os.path.join(work, "data")
        generate(data_dir)
        session = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": os.path.join(work, "wh"),
            "spark.hyperspace.serve.hbm.budget.bytes": str(BUDGET_BYTES),
            "spark.hyperspace.serve.queue.depth": str(QUEUE_DEPTH),
        }))
        workload = build_workload(session, data_dir)

        # Warm + correctness oracles (serial run of every query).
        expected = {}
        for name, df in workload:
            expected[name] = canonical(df.collect())

        # Single-client baseline QPS on the warm mix.
        t0 = time.perf_counter()
        serial_runs = 0
        while serial_runs < max(len(workload) * 4, 20):
            _name, df = workload[serial_runs % len(workload)]
            df.collect()
            serial_runs += 1
        serial_wall = time.perf_counter() - t0
        serial_qps = serial_runs / serial_wall
        log(f"serial baseline: {serial_runs} queries in "
            f"{serial_wall:.2f}s = {serial_qps:.1f} QPS")

        # Closed loop: K clients share one global query budget.
        next_q = [0]
        take_lock = threading.Lock()
        latencies = []
        outcomes = {"ok": 0, "rejected": 0, "deadline": 0,
                    "cancelled": 0, "error": 0}
        mismatches = []
        res_lock = threading.Lock()

        def client(cid: int):
            while True:
                with take_lock:
                    if next_q[0] >= TOTAL_QUERIES:
                        return
                    qi = next_q[0]
                    next_q[0] += 1
                name, df = workload[qi % len(workload)]
                t1 = time.perf_counter()
                try:
                    table = df.collect(
                        timeout=TIMEOUT_S if TIMEOUT_S > 0 else None)
                except QueryRejectedError:
                    with res_lock:
                        outcomes["rejected"] += 1
                    continue
                except QueryDeadlineExceededError:
                    with res_lock:
                        outcomes["deadline"] += 1
                    continue
                except QueryCancelledError:
                    with res_lock:
                        outcomes["cancelled"] += 1
                    continue
                except Exception as exc:  # pragma: no cover
                    with res_lock:
                        outcomes["error"] += 1
                        mismatches.append(f"{name}: {exc!r}")
                    continue
                wall = time.perf_counter() - t1
                ok = canonical(table).equals(expected[name])
                with res_lock:
                    latencies.append(wall)
                    outcomes["ok"] += 1
                    if not ok:
                        mismatches.append(
                            f"{name}: result differs from serial run")

        threads = [threading.Thread(target=client, args=(c,),
                                    name=f"serve-client-{c}")
                   for c in range(CLIENTS)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        loop_wall = time.perf_counter() - t0

        if mismatches:
            log("CORRECTNESS FAILURES under concurrency:")
            for m in mismatches[:10]:
                log(f"  {m}")
            raise SystemExit(1)

        latencies.sort()
        qps = outcomes["ok"] / loop_wall if loop_wall else 0.0
        slow_decile = slow_decile_attribution()
        sched = session.scheduler()
        counters = telemetry.get_registry().counters_dict()
        serve_counters = {k: v for k, v in counters.items()
                          if k.startswith(("serve.", "resilience."))}
        attempted = TOTAL_QUERIES
        serve = {
            "clients": CLIENTS,
            "queries": attempted,
            "rows": ROWS,
            "budget_bytes": BUDGET_BYTES,
            "deadline_s": TIMEOUT_S,
            "loop_wall_s": round(loop_wall, 3),
            "qps": round(qps, 2),
            "serial_qps": round(serial_qps, 2),
            "p50_s": round(_percentile(latencies, 0.50) or 0, 5),
            "p95_s": round(_percentile(latencies, 0.95) or 0, 5),
            "p99_s": round(_percentile(latencies, 0.99) or 0, 5),
            "max_s": round(latencies[-1], 5) if latencies else None,
            "outcomes": outcomes,
            "reject_rate": round(outcomes["rejected"] / attempted, 5),
            "timeout_rate": round(outcomes["deadline"] / attempted, 5),
            "peak_admitted_bytes": sched.peak_admitted_bytes,
            "counters": serve_counters,
            "slow_decile": slow_decile,
        }
        log(f"closed loop: {outcomes['ok']}/{attempted} ok in "
            f"{loop_wall:.2f}s = {qps:.1f} QPS "
            f"(x{qps / serial_qps:.2f} vs 1 client), "
            f"p50 {serve['p50_s'] * 1e3:.1f} ms, "
            f"p99 {serve['p99_s'] * 1e3:.1f} ms, "
            f"rejected {outcomes['rejected']}, "
            f"deadline {outcomes['deadline']}")

        result = telemetry.artifact.make_artifact(
            driver="bench_serve.py",
            metric="serve_closed_loop_qps",
            value=round(qps, 2),
            unit="queries/s",
            vs_baseline=round(qps / serial_qps, 3) if serial_qps else None,
            extra={"serve": serve, "link_probe": link_probe()})
        print(json.dumps(result))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
