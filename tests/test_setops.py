"""INTERSECT / EXCEPT (SQL DISTINCT set semantics, NULL == NULL) and
scalar subqueries — the IR additions closing the reference serde's
query-breadth property (`index/serde/package.scala:46-49`)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.serde import plan_from_json, plan_to_json


@pytest.fixture
def env(tmp_path):
    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    a_dir.mkdir(), b_dir.mkdir()
    pq.write_table(pa.table({
        "k": pa.array([1, 1, 2, 3, None, None, 7], type=pa.int64()),
        "s": pa.array(["x", "x", "y", "z", "n", "n", "q"]),
    }), str(a_dir / "p.parquet"))
    pq.write_table(pa.table({
        "k": pa.array([1, 2, None, 9], type=pa.int64()),
        "s": pa.array(["x", "OTHER", "n", "q"]),
    }), str(b_dir / "p.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(a_dir), str(b_dir)


def norm(df):
    return (df.sort_values(list(df.columns)).reset_index(drop=True))


@pytest.mark.parametrize("device", [False, True])
def test_intersect_and_except(env, device):
    session, a, b = env
    extra = ({"spark.hyperspace.execution.min.device.rows": "0",
              "spark.hyperspace.distribution.enabled": "false"}
             if device else {})
    sess = session(**extra)
    adf, bdf = sess.read_parquet(a), sess.read_parquet(b)

    inter = adf.intersect(bdf).to_pandas()
    # DISTINCT rows of a present in b; (None,"n") == (None,"n") — SQL
    # set ops group NULLs, so the null row IS in the intersection.
    assert sorted(map(tuple, inter.fillna(-99).values)) == sorted(
        [(1, "x"), (-99, "n")])

    exc = adf.except_(bdf).to_pandas()
    assert sorted(map(tuple, exc.fillna(-99).values)) == sorted(
        [(2, "y"), (3, "z"), (7, "q")])


def test_setop_serde_round_trip(env):
    session, a, b = env
    sess = session()
    plan = sess.read_parquet(a).intersect(sess.read_parquet(b)).plan
    again = plan_from_json(plan_to_json(plan))
    assert again.to_dict() == plan.to_dict()
    plan2 = sess.read_parquet(a).except_(sess.read_parquet(b)).plan
    assert plan_from_json(plan_to_json(plan2)).to_dict() == plan2.to_dict()


def test_setop_rejects_misaligned_columns(env):
    session, a, b = env
    sess = session()
    from hyperspace_tpu.exceptions import HyperspaceException
    with pytest.raises(HyperspaceException):
        sess.read_parquet(a).select("k").intersect(
            sess.read_parquet(b).select("s"))


@pytest.mark.parametrize("device", [False, True])
def test_scalar_subquery_in_filter(env, device):
    session, a, b = env
    extra = ({"spark.hyperspace.execution.min.device.rows": "0",
              "spark.hyperspace.distribution.enabled": "false"}
             if device else {})
    sess = session(**extra)
    adf = sess.read_parquet(a)
    # k > avg(k of b where k not null) = (1+2+9)/3 = 4.0
    avg_b = (sess.read_parquet(b).agg(("avg", "k", "a"))).as_scalar()
    out = adf.filter(col("k") > avg_b).to_pandas()
    assert sorted(out["k"].tolist()) == [7]
    # Arithmetic over the scalar: k > 0.5 * avg = 2.0
    out2 = adf.filter(col("k") > lit(0.5) * avg_b).to_pandas()
    assert sorted(out2["k"].tolist()) == [3, 7]


def test_scalar_subquery_empty_is_null(env):
    session, a, b = env
    sess = session()
    adf = sess.read_parquet(a)
    empty = (sess.read_parquet(b).filter(col("k") == lit(-1))
             .agg(("max", "k", "m")).filter(col("m").is_not_null())
             .select("m")).as_scalar()
    # NULL comparison is not-true for every row: empty result.
    assert len(adf.filter(col("k") > empty).to_pandas()) == 0


def test_scalar_subquery_multirow_raises(env):
    session, a, b = env
    sess = session()
    adf = sess.read_parquet(a)
    multi = sess.read_parquet(b).select("k").as_scalar()
    from hyperspace_tpu.exceptions import HyperspaceException
    with pytest.raises(HyperspaceException):
        adf.filter(col("k") > multi).to_pandas()


def test_scalar_subquery_serde_round_trip(env):
    session, a, b = env
    sess = session()
    adf = sess.read_parquet(a)
    avg_b = (sess.read_parquet(b).agg(("avg", "k", "a"))).as_scalar()
    plan = adf.filter(col("k") > avg_b).plan
    again = plan_from_json(plan_to_json(plan))
    # Unresolved round trip (values never serialize into fresh plans).
    text = plan_to_json(again)
    assert "scalar_subquery" in text
    # The deserialized plan executes and resolves independently.
    from hyperspace_tpu.engine.executor import execute_plan
    from hyperspace_tpu.io.columnar import to_arrow
    out = to_arrow(execute_plan(again, conf=sess.conf)).to_pandas()
    assert sorted(out["k"].tolist()) == [7]
