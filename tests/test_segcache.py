"""HBM segment cache correctness suite (ISSUE 8).

The acceptance bar: a warm repeat of an index-served query is
LINK-FREE (`link.h2d.chunks` does not move); version invalidation
tracks the index log FSM (refresh/optimize/vacuum); K concurrent
queries over one cold segment trigger exactly ONE decode+H2D
(single-flight, bit-identical results); a cancellation mid-fill
releases its byte reservation; eviction is LRU under the byte budget
and leaks nothing; and the chaos harness stays deadlock-free with
concurrent fills, cancels, and refreshes.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig, telemetry)
from hyperspace_tpu.exceptions import QueryCancelledError
from hyperspace_tpu.io import parquet, segcache
from hyperspace_tpu.io.segcache import SegmentCache, SegmentRef
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.schema import Schema

from chaos import canonical, run_chaos


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


@pytest.fixture(autouse=True)
def fresh_cache():
    """A fresh process segment cache per test (and after)."""
    segcache.set_cache(SegmentCache())
    yield
    segcache.set_cache(SegmentCache())


@pytest.fixture
def indexed_env(tmp_path):
    """A source dir + session/hs over it with an index created, device
    lane forced."""
    rng = np.random.default_rng(3)
    n = 20_000
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, 200, n).astype(np.int64),
        "val": rng.random(n).astype(np.float64),
    }), str(src / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
                "spark.hyperspace.execution.min.device.rows": "0",
                "spark.hyperspace.distribution.enabled": "false"}
        conf.update({k: str(v) for k, v in extra.items()})
        return HyperspaceSession(HyperspaceConf(conf))

    sess = session()
    hs = Hyperspace(sess)
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("seg_idx", ["key"], ["val"]))
    sess.enable_hyperspace()
    return sess, hs, df, str(src), session


@pytest.fixture
def plain_parquet(tmp_path):
    """One parquet file + its Schema, for direct SegmentCache units."""
    rng = np.random.default_rng(9)
    path = tmp_path / "plain.parquet"
    table = pa.table({
        "a": rng.integers(0, 1000, 5000).astype(np.int64),
        "b": rng.random(5000).astype(np.float64),
    })
    pq.write_table(table, str(path))
    return str(path), Schema.from_arrow(table.schema), table


def _ref(version=0, bucket="all", name="u", root="/idx/u"):
    return SegmentRef(index_name=name, index_root=root, version=version,
                      bucket=bucket)


# ---------------------------------------------------------------------------
# The acceptance bar: warm repeat queries are link-free
# ---------------------------------------------------------------------------


def test_warm_repeat_query_is_link_free(indexed_env):
    sess, hs, df, src, _session = indexed_env
    q = lambda: df.filter(col("key") == lit(7)).select("val")  # noqa: E731
    plan = q()._optimized_plan()
    roots = [p for s in plan.collect_leaves() for p in s.root_paths]
    assert any("v__=" in p for p in roots), "not index-served"
    first = q().collect()
    q().collect()  # settle jit/fusion caches
    h0 = _counter("link.h2d.chunks")
    hits0 = _counter("cache.segments.hits")
    warm = q().collect()
    assert _counter("link.h2d.chunks") == h0, \
        "steady-state repeat query crossed the link"
    assert _counter("cache.segments.hits") > hits0
    assert canonical(warm).equals(canonical(first))


def test_segment_ref_keys_on_committed_version(indexed_env):
    sess, hs, df, src, _session = indexed_env
    plan = df.filter(col("key") == lit(7)).select("val")._optimized_plan()
    scan = next(s for s in plan.collect_leaves() if s.index_name)
    ref = segcache.segment_ref_for_scan(scan)
    assert ref is not None
    assert ref.index_name == "seg_idx"
    assert ref.version == 0
    assert os.path.basename(ref.index_root) == "seg_idx"
    # Source scans (no index_name) are not version-addressable.
    src_scan = next(s for s in df.plan.collect_leaves())
    assert segcache.segment_ref_for_scan(src_scan) is None


# ---------------------------------------------------------------------------
# Version invalidation: refresh + optimize + vacuum (the index log FSM)
# ---------------------------------------------------------------------------


def _index_root(sess, name):
    from hyperspace_tpu.index.path_resolver import PathResolver
    return PathResolver(sess.conf).get_index_path(name)


def _append(src, n=2000, seed=99):
    rng = np.random.default_rng(seed)
    pq.write_table(pa.table({
        "key": rng.integers(0, 200, n).astype(np.int64),
        "val": rng.random(n).astype(np.float64),
    }), os.path.join(src, f"part-extra{seed}.parquet"))


def test_refresh_invalidates_and_serves_new_version(indexed_env):
    sess, hs, df, src, _session = indexed_env
    before = df.filter(col("key") == lit(7)).select("key",
                                                    "val").collect()
    assert segcache.get_cache().bytes_held() > 0
    _append(src, seed=99)
    hs.refresh_index("seg_idx")
    # The commit hook dropped the old version's segments.
    snap = segcache.get_cache().snapshot()
    assert snap["entries"] == 0, snap
    df2 = sess.read_parquet(src)  # re-list: appended file included
    q2 = lambda: df2.filter(col("key") == lit(7)).select("key", "val")  # noqa: E731
    plan = q2()._optimized_plan()
    roots = [p for s in plan.collect_leaves() for p in s.root_paths]
    assert any("v__=1" in p for p in roots), f"not v1-served: {roots}"
    after = q2().collect()
    assert after.num_rows > before.num_rows
    # And the new version's segments are resident + warm-hit now.
    hits0 = _counter("cache.segments.hits")
    q2().collect()
    assert _counter("cache.segments.hits") > hits0


def _index_entries(cache):
    """Count of version-keyed (index) entries resident — path-keyed
    source-scan entries are invalidated by stamps, not the FSM."""
    with cache._cv:
        return sum(1 for e in cache._entries.values()
                   if e.ref is not None)


def test_optimize_and_vacuum_invalidate(indexed_env):
    sess, hs, df, src, _session = indexed_env
    cache = segcache.get_cache()
    df.filter(col("key") == lit(7)).select("val").collect()
    assert _index_entries(cache) > 0  # v__=0 resident
    _append(src, seed=7)
    hs.refresh_index("seg_idx", mode="incremental")
    assert _index_entries(cache) == 0  # commit of v__=1 dropped v0
    df2 = sess.read_parquet(src)
    q2 = lambda: df2.filter(col("key") == lit(7)).select("val")  # noqa: E731
    q2().collect()
    assert _index_entries(cache) > 0  # v__=1 resident
    hs.optimize_index("seg_idx")
    assert _index_entries(cache) == 0  # commit of v__=2 dropped v1
    q2().collect()
    assert _index_entries(cache) > 0  # v__=2 resident
    # delete + vacuum: every segment of the index leaves HBM.
    hs.delete_index("seg_idx")
    assert _index_entries(cache) == 0  # DELETED stable log drops all
    hs.vacuum_index("seg_idx")
    assert _index_entries(cache) == 0


def test_footprint_size_cache_stamp_invalidation(tmp_path):
    from hyperspace_tpu.plan import footprint

    path = tmp_path / "f.parquet"
    t = pa.table({"a": np.arange(100, dtype=np.int64)})
    pq.write_table(t, str(path))
    size1 = footprint._file_size(str(path))
    assert size1 == os.path.getsize(str(path))
    # Rewrite in place with different content: the stamp changes, so
    # admission control must see the NEW size, not the cached one.
    t2 = pa.table({"a": np.arange(50_000, dtype=np.int64)})
    time.sleep(0.01)  # ensure mtime tick on coarse filesystems
    pq.write_table(t2, str(path))
    size2 = footprint._file_size(str(path))
    assert size2 == os.path.getsize(str(path))
    assert size2 != size1
    footprint.invalidate_sizes(str(tmp_path))
    assert str(path) not in footprint._size_cache


def test_invalidate_paths_sweeps_host_caches(tmp_path):
    path = tmp_path / "h.parquet"
    pq.write_table(pa.table({"a": np.arange(64, dtype=np.int64)}),
                   str(path))
    parquet.read_table([str(path)])
    assert any(str(path) in k[0] for k in parquet._read_cache)
    parquet.file_row_counts([str(path)])
    assert str(path) in parquet._count_cache
    parquet.invalidate_paths(str(tmp_path))
    assert not any(str(path) in k[0] for k in parquet._read_cache)
    assert str(path) not in parquet._count_cache


# ---------------------------------------------------------------------------
# Single-flight: one fill for K waiters, bit-identical results
# ---------------------------------------------------------------------------


def test_single_flight_one_fill_for_k_waiters(plain_parquet, monkeypatch):
    path, schema, _table = plain_parquet
    cache = segcache.set_cache(SegmentCache())
    reads = [0]
    real_read = parquet.read_table

    def slow_read(paths, columns=None):
        reads[0] += 1
        time.sleep(0.05)  # hold the fill open so waiters pile up
        return real_read(paths, columns=columns)

    monkeypatch.setattr(parquet, "read_table", slow_read)
    ref = _ref()
    results = [None] * 6
    errors = []

    def worker(i):
        try:
            results[i] = cache.read([path], ["a", "b"], schema, ref=ref)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert reads[0] == 1, f"{reads[0]} fills for 6 concurrent readers"
    # Bit-identical by construction: every waiter got THE batch.
    assert all(r is results[0] for r in results)
    assert cache.snapshot()["fills_in_flight"] == 0


def test_failed_fill_does_not_wedge_waiters(plain_parquet, monkeypatch):
    path, schema, _table = plain_parquet
    cache = segcache.set_cache(SegmentCache())
    real_read = parquet.read_table
    calls = [0]

    def flaky_read(paths, columns=None):
        calls[0] += 1
        if calls[0] == 1:
            time.sleep(0.03)
            raise OSError("injected fill failure")
        return real_read(paths, columns=columns)

    monkeypatch.setattr(parquet, "read_table", flaky_read)
    ref = _ref()
    outcomes = []

    def worker():
        try:
            outcomes.append(cache.read([path], ["a", "b"], schema,
                                       ref=ref))
        except OSError as exc:
            outcomes.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    # The filler got the error; the waiters retried with their own fill
    # and succeeded — nobody hung, and the cache is healthy.
    assert any(isinstance(o, OSError) for o in outcomes)
    assert any(not isinstance(o, OSError) for o in outcomes)
    assert cache.snapshot()["fills_in_flight"] == 0
    assert cache.read([path], ["a", "b"], schema, ref=ref) is not None


# ---------------------------------------------------------------------------
# Byte budget: eviction order, reservations, cancellation, leaks
# ---------------------------------------------------------------------------


def _write_sized(tmp_path, name, rows):
    path = tmp_path / f"{name}.parquet"
    t = pa.table({"a": np.arange(rows, dtype=np.int64)})
    pq.write_table(t, str(path))
    return str(path), Schema.from_arrow(t.schema)


def test_byte_budget_eviction_order_under_concurrent_fills(tmp_path):
    # Each entry is ~8 KB of int64; budget fits two.
    paths = {}
    for name in "abcd":
        paths[name] = _write_sized(tmp_path, name, 1000)
    budget = 20_000
    cache = segcache.set_cache(SegmentCache(budget_bytes=budget))

    def fill(name, version):
        p, schema = paths[name]
        return cache.read([p], ["a"], schema,
                          ref=_ref(version=version, name=name,
                                   root=f"/idx/{name}"))

    threads = [threading.Thread(target=fill, args=(n, i))
               for i, n in enumerate("abc")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    snap = cache.snapshot()
    assert snap["bytes_held"] <= budget
    assert snap["reserved_bytes"] == 0
    assert _counter("cache.segments.evictions") >= 1
    # LRU order: touch the survivors deterministically, then overflow —
    # the LEAST recently used entry must be the victim.
    fill("a", 0)  # a resident (fill or hit), now MRU among residents
    hits_a0 = _counter("cache.segments.hits")
    fill("a", 0)
    assert _counter("cache.segments.hits") > hits_a0  # a is resident
    fill("d", 3)  # evicts the LRU entry, which is NOT a
    hits_a1 = _counter("cache.segments.hits")
    fill("a", 0)
    assert _counter("cache.segments.hits") > hits_a1, \
        "eviction removed the most-recently-used entry"


def test_cancellation_mid_fill_releases_reservation(plain_parquet):
    from hyperspace_tpu.engine.scheduler import Deadline

    path, schema, _table = plain_parquet
    cache = segcache.set_cache(SegmentCache())
    deadline = Deadline("q-cancel")
    deadline.cancel()
    with telemetry.deadline_scope(deadline):
        with pytest.raises(QueryCancelledError):
            cache.read([path], ["a", "b"], schema, ref=_ref())
    snap = cache.snapshot()
    assert snap["reserved_bytes"] == 0, "cancelled fill leaked its " \
        "byte reservation"
    assert snap["fills_in_flight"] == 0
    assert snap["entries"] == 0
    # The key is not wedged: a clean retry fills normally.
    batch = cache.read([path], ["a", "b"], schema, ref=_ref())
    assert batch.num_rows == 5000


def test_leak_sentinel_on_eviction(tmp_path, leak_sentinel):
    pa_, schema_a = _write_sized(tmp_path, "x", 2000)
    pb_, schema_b = _write_sized(tmp_path, "y", 2000)
    budget = 18_000  # fits ONE ~16 KB entry: every fill evicts the other
    cache = segcache.set_cache(SegmentCache(budget_bytes=budget))
    cache.read([pa_], ["a"], schema_a, ref=_ref(name="x", root="/i/x"))
    cache.read([pb_], ["a"], schema_b, ref=_ref(name="y", root="/i/y"))
    with leak_sentinel(tolerance=2):
        for _ in range(4):
            cache.read([pa_], ["a"], schema_a,
                       ref=_ref(name="x", root="/i/x"))
            cache.read([pb_], ["a"], schema_b,
                       ref=_ref(name="y", root="/i/y"))
    assert cache.snapshot()["bytes_held"] <= budget


def test_pinned_index_survives_byte_pressure(tmp_path):
    pa_, schema_a = _write_sized(tmp_path, "pinned", 1000)
    pb_, schema_b = _write_sized(tmp_path, "bulk", 1000)
    conf = HyperspaceConf({
        "spark.hyperspace.cache.segments.pin.indexes": "hot_idx",
    })
    cache = segcache.set_cache(SegmentCache(budget_bytes=12_000))
    cache.read([pa_], ["a"], schema_a, conf=conf,
               ref=_ref(name="hot_idx", root="/i/hot"))
    assert telemetry.get_registry().gauge("cache.segments.pins").value \
        == 1
    for v in range(3):  # pressure: each fill wants the whole budget
        cache.read([pb_], ["a"], schema_b, conf=conf,
                   ref=_ref(version=v, name="bulk", root="/i/bulk"))
    hits0 = _counter("cache.segments.hits")
    cache.read([pa_], ["a"], schema_a, conf=conf,
               ref=_ref(name="hot_idx", root="/i/hot"))
    assert _counter("cache.segments.hits") > hits0, \
        "pinned segment was evicted by byte pressure"
    # Invalidation still drops pinned segments (refresh correctness
    # beats pinning).
    cache.invalidate_index("/i/hot")
    assert cache.snapshot()["pinned_entries"] == 0


def test_unversioned_scan_stamp_validation(tmp_path):
    path, schema = _write_sized(tmp_path, "plainsrc", 1000)
    cache = segcache.set_cache(SegmentCache())
    b1 = cache.read([path], ["a"], schema)  # no ref: stamp-keyed
    misses0 = _counter("cache.segments.misses")
    b2 = cache.read([path], ["a"], schema)
    assert b2 is b1  # stamped hit
    time.sleep(0.01)
    t = pa.table({"a": np.arange(500, dtype=np.int64) * 2})
    pq.write_table(t, path)
    b3 = cache.read([path], ["a"], schema)
    assert b3 is not b1
    assert b3.num_rows == 500
    assert _counter("cache.segments.misses") > misses0


def test_budget_zero_disables_caching(plain_parquet):
    path, schema, _table = plain_parquet
    cache = segcache.set_cache(SegmentCache(budget_bytes=0))
    b1 = cache.read([path], ["a", "b"], schema, ref=_ref())
    b2 = cache.read([path], ["a", "b"], schema, ref=_ref())
    assert b1 is not b2
    assert cache.snapshot()["entries"] == 0


# ---------------------------------------------------------------------------
# Admission-aware coalescing: footprint credit for resident bytes
# ---------------------------------------------------------------------------


def test_footprint_credit_for_resident_segments(indexed_env, monkeypatch):
    from hyperspace_tpu.engine import scheduler as sched_mod
    from hyperspace_tpu.engine.scheduler import QueryScheduler
    from hyperspace_tpu.plan import footprint

    sess, hs, df, src, session = indexed_env
    # Test-scale data sits under the production footprint floor; lower
    # it so the credit clamp has headroom to act on.
    monkeypatch.setattr(footprint, "MIN_FOOTPRINT_BYTES", 1024)
    sched_mod.set_scheduler(QueryScheduler())
    try:
        sess.conf.set("spark.hyperspace.serve.hbm.budget.bytes",
                      str(512 * 1024 * 1024))
        q = lambda: df.filter(col("key") == lit(7)).select("val")  # noqa: E731
        q().collect()  # fills the cache
        assert segcache.get_cache().bytes_held() > 0
        credit0 = _counter("serve.footprint_credit_bytes")
        _, metrics = q().collect(with_metrics=True)
        assert _counter("serve.footprint_credit_bytes") > credit0
        assert metrics.events_of("serve", "footprint_credit")
    finally:
        sched_mod.set_scheduler(QueryScheduler())


# ---------------------------------------------------------------------------
# Chaos: concurrent fills + cancels + refreshes, segment cache enabled
# ---------------------------------------------------------------------------


def test_chaos_with_concurrent_refresh(indexed_env):
    sess, hs, df, src, _session = indexed_env
    filt = df.filter(col("key") == lit(7)).select("key", "val")
    join_like = df.filter(col("key") < lit(20)).select("key", "val")
    workload = [("filt", filt), ("range", join_like)]
    expected = {name: canonical(d.collect())
                for name, d in workload}

    stop = threading.Event()
    refresh_errors = []

    def refresher():
        # Full refreshes of unchanged source data: every commit
        # invalidates + bumps the served version, but the correct
        # ANSWER never changes — the oracle stays valid while the
        # cache churns underneath the queries.
        while not stop.is_set():
            try:
                hs.refresh_index("seg_idx")
            except Exception as exc:  # OCC conflicts are fine
                refresh_errors.append(repr(exc))
            time.sleep(0.01)

    th = threading.Thread(target=refresher, daemon=True)
    th.start()
    try:
        report = run_chaos(
            workload, expected, clients=6, total_queries=90,
            timeout_for=lambda i: 0.002 if i % 9 == 4 else None)
    finally:
        stop.set()
        th.join(timeout=30)
    assert not report.stuck_threads, report.summary()
    assert not report.mismatches, report.mismatches[:3]
    assert report.outcomes["ok"] >= 1
    assert report.outcomes["error"] == 0, report.errors[:3]
    snap = segcache.get_cache().snapshot()
    assert snap["reserved_bytes"] == 0
    assert snap["fills_in_flight"] == 0


# ---------------------------------------------------------------------------
# Tiered cache: host-RAM tier below HBM (ISSUE 11)
# ---------------------------------------------------------------------------


def _two_files(tmp_path):
    rng = np.random.default_rng(21)
    paths = []
    schema = None
    for i in (0, 1):
        t = pa.table({
            "a": rng.integers(0, 1000, 3000).astype(np.int64),
            "b": rng.random(3000).astype(np.float64),
        })
        p = tmp_path / f"tier{i}.parquet"
        pq.write_table(t, str(p))
        paths.append(str(p))
        schema = Schema.from_arrow(t.schema)
    return paths, schema


def _tier_conf(host_bytes):
    return HyperspaceConf({
        "spark.hyperspace.cache.segments.host.bytes": str(host_bytes)})


def test_eviction_demotes_to_host_tier_and_promotes_without_decode(
        tmp_path, monkeypatch):
    """Device-tier eviction lands the victim in the host tier within
    its byte budget; a subsequent read of the demoted key re-promotes
    through the TransferEngine fill lane with cache.segments.host.hits
    moving and NO host-side parquet re-decode."""
    (p1, p2), schema = _two_files(tmp_path)
    conf = _tier_conf(1 << 20)
    # Budget fits exactly one decoded file on device.
    cache = segcache.set_cache(SegmentCache(budget_bytes=60_000))

    before_demote = _counter("cache.segments.host.demotions")
    b1 = cache.read([p1], None, schema, conf=conf)
    cache.read([p2], None, schema, conf=conf)  # evicts+demotes p1
    snap = cache.snapshot()
    assert snap["host_entries"] == 1
    assert 0 < snap["host_bytes_held"] <= (1 << 20)
    assert _counter("cache.segments.host.demotions") == before_demote + 1

    fill_bytes = _counter("transfer.fill.bytes")
    host_hits = _counter("cache.segments.host.hits")

    def boom(*a, **k):
        raise AssertionError("host-side parquet decode on the promote "
                             "path")

    monkeypatch.setattr(parquet, "read_table", boom)
    b1_again = cache.read([p1], None, schema, conf=conf)
    monkeypatch.undo()

    assert _counter("cache.segments.host.hits") == host_hits + 1
    # The promotion crossed the link through the FILL lane.
    assert _counter("transfer.fill.bytes") > fill_bytes
    from hyperspace_tpu.io import columnar
    assert columnar.to_arrow(b1_again).equals(columnar.to_arrow(b1))
    # p1 is back on device; p2 was demoted to make room.
    snap = cache.snapshot()
    assert snap["entries"] == 1 and snap["host_entries"] == 1


def test_host_tier_byte_accounting_and_budget(tmp_path):
    """Host-tier LRU honors its own byte budget (a tier smaller than
    one entry holds nothing), and the snapshot's byte accounting stays
    exact across demote/evict cycles."""
    (p1, p2), schema = _two_files(tmp_path)
    cache = segcache.set_cache(SegmentCache(budget_bytes=60_000))

    # Tier too small for any entry: demotion degrades to a plain drop.
    tiny = _tier_conf(1024)
    cache.read([p1], None, schema, conf=tiny)
    cache.read([p2], None, schema, conf=tiny)
    snap = cache.snapshot()
    assert snap["host_entries"] == 0 and snap["host_bytes_held"] == 0

    # Tier fits ONE entry: the second demotion evicts the first.
    cache.clear()
    one = _tier_conf(50_000)
    evictions = _counter("cache.segments.host.evictions")
    cache.read([p1], None, schema, conf=one)
    cache.read([p2], None, schema, conf=one)   # p1 -> host
    cache.read([p1], None, schema, conf=one)   # p1 promoted, p2 -> host
    snap = cache.snapshot()
    assert snap["host_entries"] == 1
    assert snap["host_bytes_held"] <= 50_000
    assert _counter("cache.segments.host.evictions") >= evictions


def test_host_tier_demote_promote_leaks_nothing(tmp_path, leak_sentinel):
    """Steady-state demote/promote ping-pong accretes no device
    arrays (the leak_sentinel contract: warm first, then repeat)."""
    (p1, p2), schema = _two_files(tmp_path)
    conf = _tier_conf(1 << 20)
    cache = segcache.set_cache(SegmentCache(budget_bytes=60_000))
    # Warm one full cycle (jit constants, staging pools).
    cache.read([p1], None, schema, conf=conf)
    cache.read([p2], None, schema, conf=conf)
    cache.read([p1], None, schema, conf=conf)
    with leak_sentinel(tolerance=2):
        for _ in range(3):
            cache.read([p2], None, schema, conf=conf)
            cache.read([p1], None, schema, conf=conf)
    snap = cache.snapshot()
    assert snap["entries"] == 1 and snap["host_entries"] == 1


def test_invalidation_sweeps_host_tier(tmp_path):
    """FSM invalidation reaches demoted entries too: a version commit
    drops the old version's host-tier copies."""
    (p1, p2), schema = _two_files(tmp_path)
    conf = _tier_conf(1 << 20)
    cache = segcache.set_cache(SegmentCache(budget_bytes=60_000))
    root = str(tmp_path / "idx")
    ref1 = SegmentRef("t_idx", root, 0, 0)
    cache.read([p1], None, schema, ref=ref1, conf=conf)
    cache.read([p2], None, schema,
               ref=SegmentRef("t_idx", root, 0, 1), conf=conf)
    assert cache.snapshot()["host_entries"] == 1
    cache.invalidate_index(root, keep_version=7)
    snap = cache.snapshot()
    assert snap["entries"] == 0 and snap["host_entries"] == 0
    assert snap["host_bytes_held"] == 0 and snap["bytes_held"] == 0


# ---------------------------------------------------------------------------
# Bucket-scoped invalidation (ISSUE 11 satellite)
# ---------------------------------------------------------------------------


def test_rekey_carried_keeps_untouched_buckets(tmp_path):
    """`on_version_committed(touched_buckets=..., carried_from=...)`
    rekeys carried-forward entries of untouched buckets to the new
    version (same batch object — no refill) and drops touched /
    unknowable ones."""
    (p1, p2), schema = _two_files(tmp_path)
    cache = segcache.set_cache(SegmentCache(budget_bytes=1 << 30))
    root = str(tmp_path / "idx")
    batch0 = cache.read([p1], None, schema,
                        ref=SegmentRef("t_idx", root, 0, 0))
    cache.read([p2], None, schema, ref=SegmentRef("t_idx", root, 0, 1))
    cache.read([p1], None, schema, ref=SegmentRef("t_idx", root, 0,
                                                  "all"))
    assert cache.snapshot()["entries"] == 3
    rekeyed_before = _counter("cache.segments.rekeyed")

    segcache.on_version_committed(root, 1, touched_buckets={1},
                                  carried_from=0)

    # Bucket 0 survived under the NEW version — the same batch object,
    # zero fills; bucket 1 (touched) and "all" (unknowable) dropped.
    assert cache.snapshot()["entries"] == 1
    assert _counter("cache.segments.rekeyed") == rekeyed_before + 1
    fills = _counter("cache.segments.fills")
    again = cache.read([p1], None, schema,
                       ref=SegmentRef("t_idx", root, 1, 0))
    assert again is batch0
    assert _counter("cache.segments.fills") == fills


def test_incremental_refresh_commits_bucket_scoped(tmp_path,
                                                   monkeypatch):
    """The incremental-refresh action reports the buckets it touched
    and hands them to the commit hook — an append that lands in a few
    buckets no longer torches the whole warm set."""
    rng = np.random.default_rng(5)
    src = tmp_path / "incsrc"
    src.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, 100, 4000).astype(np.int64),
        "val": rng.random(4000).astype(np.float64),
    }), str(src / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.index.num.buckets": "4"}))
    hs = Hyperspace(sess)
    hs.create_index(sess.read_parquet(str(src)),
                    IndexConfig("inc_idx", ["key"], ["val"]))

    calls = []
    real = segcache.on_version_committed

    def capture(root, version, touched_buckets=None, carried_from=None):
        calls.append((version, touched_buckets, carried_from))
        return real(root, version, touched_buckets=touched_buckets,
                    carried_from=carried_from)

    monkeypatch.setattr(segcache, "on_version_committed", capture)
    # Appended rows: a handful of keys -> a strict subset of buckets.
    pq.write_table(pa.table({
        "key": np.asarray([3, 3, 3, 7], dtype=np.int64),
        "val": rng.random(4).astype(np.float64),
    }), str(src / "part-1.parquet"))
    hs.refresh_index("inc_idx", mode="incremental")

    assert calls, "incremental commit never reached the cache hook"
    version, touched, carried = calls[-1]
    assert carried == version - 1
    assert touched is not None and 0 < len(touched) < 4
