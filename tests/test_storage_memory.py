"""Storage seam: the metadata layer and the full index lifecycle running
against fsspec `memory://` (VERDICT r1 #10 — L0 must not be local-only;
reference parity: Hadoop FileSystem API, `util/FileUtils.scala:37-116`)."""

import uuid

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig)
from hyperspace_tpu.constants import States
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
from hyperspace_tpu.index.data_manager import IndexDataManagerImpl
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.utils import file_utils


@pytest.fixture
def mem_root():
    root = f"memory://hs-{uuid.uuid4().hex}"
    yield root
    file_utils.delete(root)


def test_log_manager_occ_on_memory(mem_root):
    from fakes import make_entry
    mgr = IndexLogManagerImpl(mem_root + "/idx")
    e = make_entry(state=States.CREATING)
    assert mgr.write_log(0, e)
    # OCC: second writer for the same id loses.
    assert not mgr.write_log(0, e)
    assert mgr.get_latest_id() == 0
    e2 = mgr.get_log(0)
    assert e2.state == States.CREATING
    e2.state = States.ACTIVE
    assert mgr.write_log(1, e2)
    mgr.create_latest_stable_log(1)
    assert mgr.get_latest_stable_log().state == States.ACTIVE
    mgr.delete_latest_stable_log()
    # Falls back to scanning ids downward.
    assert mgr.get_latest_stable_log().state == States.ACTIVE


def test_data_manager_versions_on_memory(mem_root):
    dm = IndexDataManagerImpl(mem_root + "/idx")
    assert dm.get_latest_version_id() is None
    for v in (0, 1, 5):
        file_utils.create_file(dm.get_path(v) + "/data.txt", "x")
        dm.commit(v)
    assert dm.get_latest_version_id() == 5
    assert dm.next_version_id() == 6
    dm.delete(5)
    assert dm.get_latest_version_id() == 1
    # An uncommitted (partial) dir is skipped by readers but seen by the
    # version allocator and vacuum's enumeration.
    file_utils.create_file(dm.get_path(7) + "/data.txt", "x")
    assert dm.get_latest_version_id() == 1
    assert dm.next_version_id() == 8
    assert dm.all_version_ids() == [0, 1, 7]


def test_full_lifecycle_and_query_on_memory_warehouse(mem_root, tmp_path):
    """create -> query (rules on == off) -> delete/restore/vacuum, with the
    index warehouse AND the source data living on memory://."""
    rng = np.random.default_rng(23)
    n = 5000
    table = pa.table({"k": rng.integers(0, 200, n).astype(np.int64),
                      "x": np.arange(n, dtype=np.int64)})
    src = mem_root + "/src"
    # Write source parquet onto the memory filesystem.
    local = tmp_path / "p.parquet"
    pq.write_table(table, str(local))
    file_utils.save_byte_array(src + "/part-0.parquet",
                               local.read_bytes())

    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": mem_root + "/wh",
        "spark.hyperspace.index.num.buckets": "8"}))
    hs = Hyperspace(sess)
    df = sess.read_parquet(src)
    hs.create_index(df, IndexConfig("memIdx", ["k"], ["x"]))
    assert list(hs.indexes()["name"]) == ["memIdx"]

    q = lambda: df.filter(col("k") == lit(7)).select("x")
    sess.enable_hyperspace()
    roots = [p for s in q()._optimized_plan().collect_leaves()
             for p in s.root_paths]
    assert any("v__=" in p and p.startswith("memory://") for p in roots), roots
    got = q().collect().to_pandas().sort_values("x").reset_index(drop=True)
    sess.disable_hyperspace()
    want = q().collect().to_pandas().sort_values("x").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want)
    kk = table.column("k").to_numpy()
    assert len(got) == int((kk == 7).sum())

    hs.delete_index("memIdx")
    hs.restore_index("memIdx")
    hs.delete_index("memIdx")
    hs.vacuum_index("memIdx")
    remaining = hs.indexes()
    assert len(remaining) == 0
    assert not file_utils.is_dir(mem_root + "/wh/indexes/memIdx/v__=0")


# -- object-store OCC preconditions (VERDICT r3 #6) -----------------------


class _NoPreconditionFS:
    """Minimal fsspec-shaped backend with NO create precondition."""

    protocol = "fakeobj"

    def __init__(self):
        self.files = {}

    def makedirs(self, path, exist_ok=False):
        pass

    def exists(self, path):
        return path in self.files

    def open(self, path, mode="rb"):
        import io
        fs = self

        class W(io.BytesIO):
            def __exit__(self, *exc):
                fs.files[path] = self.getvalue()
                return False
        if "w" in mode:
            return W()
        import io as _io
        return _io.BytesIO(self.files[path])


class _FakeGCS(_NoPreconditionFS):
    """GCS-shaped backend honoring if_generation_match=0."""

    protocol = "gs"

    def pipe_file(self, path, data, if_generation_match=None, **kw):
        if if_generation_match == 0 and path in self.files:
            raise RuntimeError("412 PreconditionFailed: object exists")
        self.files[path] = data


def test_exclusive_create_raises_without_precondition(monkeypatch):
    """A backend with no atomic create must RAISE from write_log — silent
    check-then-create would corrupt the op log under concurrency — unless
    spark.hyperspace.single.writer accepts the risk explicitly."""
    from hyperspace_tpu.exceptions import HyperspaceException
    from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
    from hyperspace_tpu.utils import storage
    from fakes import make_entry

    fake = _NoPreconditionFS()
    monkeypatch.setattr(storage, "get_fs",
                        lambda path: (fake, path.split("://", 1)[1]))
    mgr = IndexLogManagerImpl("fakeobj://idx")
    with pytest.raises(HyperspaceException, match="single.writer"):
        mgr.write_log(0, make_entry(state=States.CREATING))
    assert not fake.files  # nothing was written

    allowed = IndexLogManagerImpl(
        "fakeobj://idx",
        conf=HyperspaceConf({"spark.hyperspace.single.writer": "true"}))
    assert allowed.write_log(0, make_entry(state=States.CREATING))
    assert not allowed.write_log(0, make_entry(state=States.CREATING))


def test_exclusive_create_gcs_generation_precondition(monkeypatch):
    """The gs:// dispatch uses if_generation_match=0; a 412 maps to
    'lost the race' (False), not an error."""
    from hyperspace_tpu.utils import storage

    fake = _FakeGCS()
    monkeypatch.setattr(storage, "get_fs",
                        lambda path: (fake, path.split("://", 1)[1]))
    assert storage.exclusive_create("gs://bkt/log/0", b"a")
    assert not storage.exclusive_create("gs://bkt/log/0", b"b")
    assert fake.files["bkt/log/0"] == b"a"  # first writer's bytes survive
