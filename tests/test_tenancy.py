"""Multi-tenant serving (ISSUE 16): the tenant contextvar seam and
its propagation, tenant resolution/stamping through `collect`, the
weighted-fair (deficit-round-robin) wait queue, per-tenant HBM/queue
quotas, shed-the-burning-tenant-first, the flight ring's `tenant=`
filter (cursor-stable across rotation, composable with `replica=`),
`/healthz` tenant-section error isolation, Prometheus exposition
conformance under metric-hostile tenant ids, and the chargeback
exactness contract behind `Hyperspace.tenant_report()`.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            telemetry)
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.engine.scheduler import (Deadline, QueryScheduler,
                                             _QueryEntry)
from hyperspace_tpu.exceptions import QueryRejectedError
from hyperspace_tpu.telemetry import flight

MIB = 1024 * 1024


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


@pytest.fixture
def fresh_scheduler():
    """A scheduler with clean budgets/queues for this test; a fresh one
    is installed again on teardown so no state leaks either way."""
    sch = sched_mod.set_scheduler(QueryScheduler())
    yield sch
    sched_mod.set_scheduler(QueryScheduler())


@pytest.fixture
def sales_env(tmp_path):
    rng = np.random.default_rng(7)
    n = 3000
    data_dir = tmp_path / "sales"
    data_dir.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, 50, n).astype(np.int64),
        "qty": rng.integers(1, 10, n).astype(np.int64),
    }), str(data_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update({k: str(v) for k, v in extra.items()})
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(data_dir)


def _entry(qid, nbytes, tenant="default", timeout_s=None):
    ent = _QueryEntry(qid, Deadline(qid, timeout_s), nbytes, None)
    ent.tenant = tenant
    return ent


def _hold(sch, nbytes, qid="blocker", tenant="holder"):
    """Occupy `nbytes` of the serving budget (a stand-in for a
    long-running admitted query). Returns the entry for `_release`."""
    ent = _entry(qid, nbytes, tenant)
    with sch._cv:
        sch._active[qid] = ent
        sch._grant(ent, telemetry.get_registry())
    return ent


def _finished_metrics(tag, tenant=None, replica=None):
    qm = telemetry.QueryMetrics(description=tag)
    op = qm.start_operator("Scan")
    qm.finish_operator(op, rows_out=5)
    qm.tenant = tenant
    qm.replica = replica
    qm.finish()
    return qm


# ---------------------------------------------------------------------------
# The contextvar seam
# ---------------------------------------------------------------------------


def test_tenant_scope_and_charge_mirror():
    """`tenant_scope` is the billing seam: inside it `charge_tenant`
    mirrors onto the scoped tenant's series, outside onto "default"
    (never dropped), and `propagating` carries the scope to pool
    threads exactly as it carries the recorder and deadline."""
    assert telemetry.current_tenant() == telemetry.DEFAULT_TENANT
    reg = telemetry.get_registry()
    before = _counter("tenant.t-scope.device.flops")
    with telemetry.tenant_scope("t-scope"):
        assert telemetry.current_tenant() == "t-scope"
        # The contract shape: global inc + mirror at the same site.
        reg.counter("device.flops").inc(5)
        assert telemetry.charge_tenant("device.flops", 5) == "t-scope"
        # None is a no-op carrier: the surrounding scope survives.
        with telemetry.tenant_scope(None):
            assert telemetry.current_tenant() == "t-scope"
    assert _counter("tenant.t-scope.device.flops") == before + 5
    assert telemetry.current_tenant() == telemetry.DEFAULT_TENANT
    d0 = _counter("tenant.default.cache.segments.fills")
    reg.counter("cache.segments.fills").inc()
    telemetry.charge_tenant("cache.segments.fills")
    assert _counter("tenant.default.cache.segments.fills") == d0 + 1
    assert "t-scope" in telemetry.known_tenants()

    seen = []
    with telemetry.tenant_scope("t-pool"):
        wrapped = telemetry.propagating(
            lambda: seen.append(telemetry.current_tenant()))
    t = threading.Thread(target=wrapped)
    t.start()
    t.join(5)
    assert seen == ["t-pool"]


def test_tenant_digest_covers_every_charge_family():
    with telemetry.tenant_scope("t-digest"):
        for name in telemetry.TENANT_CHARGE_COUNTERS:
            telemetry.get_registry().counter(name).inc(2)
            telemetry.charge_tenant(name, 2)
    digest = telemetry.tenant_digest()
    assert set(digest["t-digest"]) == set(telemetry.TENANT_CHARGE_COUNTERS)
    assert all(v >= 2 for v in digest["t-digest"].values())
    # Zero-usage tenants still appear (exactness sums need every row).
    assert telemetry.DEFAULT_TENANT in digest


# ---------------------------------------------------------------------------
# Tenant resolution + stamping through collect
# ---------------------------------------------------------------------------


def test_collect_tenant_resolution_and_stamping(sales_env,
                                                fresh_scheduler):
    """Resolution order: explicit `collect(tenant=)` > the session's
    sticky `session.tenant(...)` > "default" — and the EFFECTIVE tenant
    is stamped on the recorder and billed the admission counters."""
    session, data_dir = sales_env
    sess = session()
    df = sess.read_parquet(data_dir).select("key")

    _t, qm = df.collect(with_metrics=True)
    assert qm.tenant == "default"

    sess.tenant("sticky")
    a0 = _counter("serve.tenant.sticky.admitted")
    _t, qm = df.collect(with_metrics=True)
    assert qm.tenant == "sticky"
    assert _counter("serve.tenant.sticky.admitted") == a0 + 1

    e0 = _counter("serve.tenant.explicit.admitted")
    _t, qm = df.collect(with_metrics=True, tenant="explicit")
    assert qm.tenant == "explicit"
    assert _counter("serve.tenant.explicit.admitted") == e0 + 1

    sess.tenant(None)
    _t, qm = df.collect(with_metrics=True)
    assert qm.tenant == "default"

    # The tenant-dimensioned wall histogram observed each query.
    hists = telemetry.get_registry().to_dict()["histograms"]
    assert hists["tenant.sticky.query_wall_s"]["count"] >= 1
    assert hists["tenant.explicit.query_wall_s"]["count"] >= 1


def test_instrumented_jit_charges_active_tenant():
    """Every device dispatch bills the ACTIVE tenant scope: the warm
    dispatch's measured seconds (and modeled flops/bytes when the HLO
    cost is known) land on `tenant.<id>.device.*` at the same site as
    the global inc — so the deltas are exactly equal by construction."""
    import jax.numpy as jnp

    fn = telemetry.instrumented_jit("test.tenancy_kernel",
                                    lambda x: x * 2 + 1)
    x = jnp.arange(64)
    fn(x)  # cold: compile (compile time stays in the compile bucket)

    t0 = {n: _counter(f"tenant.t-bill.{n}")
          for n in telemetry.TENANT_CHARGE_COUNTERS}
    g0 = {n: _counter(n) for n in telemetry.TENANT_CHARGE_COUNTERS}
    with telemetry.tenant_scope("t-bill"):
        fn(x)  # warm: dispatch-seconds charged to the scope
    t1 = {n: _counter(f"tenant.t-bill.{n}")
          for n in telemetry.TENANT_CHARGE_COUNTERS}
    g1 = {n: _counter(n) for n in telemetry.TENANT_CHARGE_COUNTERS}

    assert t1["device.dispatch.seconds"] > t0["device.dispatch.seconds"]
    for n in telemetry.TENANT_CHARGE_COUNTERS:
        assert t1[n] - t0[n] == pytest.approx(g1[n] - g0[n]), n


def test_tenant_report_exactness(sales_env, fresh_scheduler):
    """`Hyperspace.tenant_report()`: per-tenant sums equal the global
    charge counters (bit-exact for the integer families, a few ulps
    for dispatch-seconds), every observed tenant appears, and the
    serving snapshot rides along."""
    session, data_dir = sales_env
    sess = session()
    hs = Hyperspace(sess)
    df = sess.read_parquet(data_dir).select("key")
    df.collect(tenant="rep-a")
    df.collect(tenant="rep-b")
    df.collect()

    rep = hs.tenant_report()
    assert rep["exact"] is True
    for name in telemetry.TENANT_CHARGE_COUNTERS:
        assert rep["totals"][name] == pytest.approx(
            rep["global"][name], rel=1e-9)
    for t in ("rep-a", "rep-b", "default"):
        assert t in rep["tenants"]
        assert set(rep["tenants"][t]["usage"]) == \
            set(telemetry.TENANT_CHARGE_COUNTERS)


# ---------------------------------------------------------------------------
# Weighted-fair admission (unit level: deterministic DRR semantics)
# ---------------------------------------------------------------------------


def _drain_order(sch, conf, n):
    """Selection order of the next `n` dequeues, simulating each
    selected waiter admitting and leaving the queue."""
    order = []
    with sch._cv:
        for _ in range(n):
            ent = sch._drr_select(conf)
            if ent is None:
                break
            order.append(ent.tenant)
            sch._remove_waiter(ent)
    return order


def test_drr_weighted_fairness_and_no_starvation(fresh_scheduler):
    """A weight-2 tenant drains twice per round; a weight-1/2 tenant
    every other round; and a one-tenant burst cannot starve another
    tenant's head the way the old global FIFO could."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.tenant.heavy.weight": "2",
        "spark.hyperspace.serve.tenant.light.weight": "0.5"})
    with sch._cv:
        for i in range(8):
            sch._enqueue_waiter(_entry(f"h{i}", 1, "heavy"))
        for i in range(4):
            sch._enqueue_waiter(_entry(f"n{i}", 1, "normal"))
        for i in range(2):
            sch._enqueue_waiter(_entry(f"l{i}", 1, "light"))
    order = _drain_order(sch, conf, 14)
    assert len(order) == 14
    # Per full round: heavy 2, normal 1, light 1/2 — so in the first
    # 7 dequeues heavy got 4, normal 2, light 1 (2x the weight ratio).
    first = order[:7]
    assert first.count("heavy") == 4
    assert first.count("normal") == 2
    assert first.count("light") == 1
    # The burst did not starve anyone: every tenant appears early.
    assert set(order[:4]) >= {"heavy", "normal"}

    # FIFO within a tenant: heavy's own entries drain in arrival order.
    with sch._cv:
        assert not sch._waiters


def test_drr_selection_is_pinned_across_wakeups(fresh_scheduler):
    """The selected head stays selected until it admits or leaves —
    repeated `_drr_select` calls (spurious cv wakeups) must not rotate
    past the pick, or waiters livelock."""
    sch = fresh_scheduler
    conf = HyperspaceConf({})
    with sch._cv:
        sch._enqueue_waiter(_entry("a1", 1, "a"))
        sch._enqueue_waiter(_entry("b1", 1, "b"))
        first = sch._drr_select(conf)
        assert sch._drr_select(conf) is first
        assert sch._drr_select(conf) is first
        sch._remove_waiter(first)
        second = sch._drr_select(conf)
        assert second is not first
        sch._remove_waiter(second)
        assert sch._drr_select(conf) is None


def test_tenant_hbm_fraction_quota_with_progress(fresh_scheduler,
                                                 monkeypatch):
    """`serve.tenant.<id>.hbm.fraction` caps a tenant's CONCURRENT
    admitted bytes at its fraction of the budget — with the progress
    guarantee: a tenant with nothing in flight always admits one."""
    sch = fresh_scheduler
    # `_fits` also charges LIVE device bytes against the budget; any
    # suite that ran real queries before this one leaves cached device
    # buffers that dwarf the toy 1000-byte budget here. Pin that term
    # to zero — this test is about the per-tenant fraction math only.
    monkeypatch.setattr(sch, "_live_device_bytes", lambda: 0)
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "1000",
        "spark.hyperspace.serve.tenant.capped.hbm.fraction": "0.2"})
    other = _hold(sch, 10, qid="other", tenant="other")
    try:
        # Progress: capped has nothing in flight — even an entry far
        # over its 200-byte share fits.
        with sch._cv:
            assert sch._fits(_entry("big", 500, "capped"), 1000, conf)
        big = _hold(sch, 500, qid="big", tenant="capped")
        with sch._cv:
            # With 500 B in flight the quota now binds: +100 > 200.
            assert not sch._fits(_entry("more", 100, "capped"),
                                 1000, conf)
            # Another tenant is untouched by capped's quota.
            assert sch._fits(_entry("free", 100, "other"), 1000, conf)
        sch._release(big)
        with sch._cv:
            assert sch._fits(_entry("more", 100, "capped"), 1000, conf)
    finally:
        sch._release(other)


def test_tenant_queue_depth_rejects_only_that_tenant(fresh_scheduler):
    """`serve.tenant.<id>.queue.depth` backpressures the tenant's OWN
    burst before it can occupy the shared queue; other tenants keep
    queueing under the global depth."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "100",
        "spark.hyperspace.serve.queue.depth": "10",
        "spark.hyperspace.serve.tenant.noisy.queue.depth": "1"})
    holder = _hold(sch, 100)
    results = []

    def waiter(qid, tenant):
        ent = _entry(qid, 60, tenant)
        try:
            sch._admit(ent, conf)
            results.append((qid, "admitted"))
            sch._release(ent)
        except QueryRejectedError:
            results.append((qid, "rejected"))

    threads = [threading.Thread(target=waiter, args=("n1", "noisy")),
               threading.Thread(target=waiter, args=("q1", "quiet"))]
    for t in threads:
        t.start()
    for _ in range(400):
        with sch._cv:
            if len(sch._waiters) == 2:
                break
        time.sleep(0.005)
    with sch._cv:
        assert len(sch._waiters) == 2

    r0 = _counter("serve.tenant.noisy.rejected")
    with pytest.raises(QueryRejectedError) as ei:
        sch._admit(_entry("n2", 60, "noisy"), conf)
    assert ei.value.phase == "queue"
    assert _counter("serve.tenant.noisy.rejected") == r0 + 1

    sch._release(holder)
    for t in threads:
        t.join(5)
    assert sorted(results) == [("n1", "admitted"), ("q1", "admitted")]


def test_shed_evicts_burning_tenants_queue_first(fresh_scheduler):
    """With SLO shedding active, the tightened queue sheds the BURNING
    tenant's newest waiter to make room for the arriver — the burning
    tenant's burst pays for its own burn, not everyone else."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "100",
        "spark.hyperspace.serve.queue.depth": "2",
        "spark.hyperspace.serve.slo.p99.seconds": "0.001",
        "spark.hyperspace.serve.slo.window.seconds": "60",
        "spark.hyperspace.serve.slo.shed.enabled": "true"})
    # Burn both the global window and the burning tenant's own window
    # far past the shed threshold.
    for _ in range(20):
        sch.slo.record(1.0, conf)
        sch._tenant_slo_for("burny").record(1.0, conf)
    assert sch.slo.burn_rate(conf) > sched_mod.SLO_SHED_BURN_THRESHOLD

    holder = _hold(sch, 100)
    outcomes = {}

    def waiter(qid, tenant):
        ent = _entry(qid, 60, tenant)
        try:
            sch._admit(ent, conf)
            outcomes[qid] = "admitted"
            sch._release(ent)
        except QueryRejectedError as exc:
            outcomes[qid] = f"rejected:{exc.phase}"

    burny = threading.Thread(target=waiter, args=("b1", "burny"))
    burny.start()
    for _ in range(400):
        with sch._cv:
            if sch._waiters:
                break
        time.sleep(0.005)

    # Effective depth is 2 // 2 = 1 while shedding: the arriving calm
    # tenant finds the queue "full", the shed hook evicts burny's
    # newest waiter, and the calm query queues in its place.
    shed0 = _counter("serve.slo.shed")
    rej0 = _counter("serve.tenant.burny.rejected")
    calm = threading.Thread(target=waiter, args=("c1", "calm"))
    calm.start()
    burny.join(5)
    assert outcomes.get("b1") == "rejected:queue"
    assert _counter("serve.slo.shed") == shed0 + 1
    assert _counter("serve.tenant.burny.rejected") == rej0 + 1

    sch._release(holder)
    calm.join(5)
    assert outcomes.get("c1") == "admitted"


# ---------------------------------------------------------------------------
# Flight ring: tenant filter + cursor stability (mirrors the PR-11
# rotation pin in test_flight_recorder.py::test_snapshot_incremental_cursor)
# ---------------------------------------------------------------------------


def test_snapshot_tenant_filter_cursor_stable_across_rotation():
    """`snapshot(tenant=)` narrows to one tenant's entries while the
    cursor stays GLOBAL: it advances past other tenants' entries and
    past rotated-out entries, so a filtered consumer skips, never
    stalls — and the filter composes with `replica=`."""
    rec = flight.FlightRecorder(capacity=4)
    for i in range(3):
        rec.record(_finished_metrics(
            f"q{i}", tenant=("acme" if i % 2 == 0 else "zen")))
    fresh, cursor = rec.snapshot(0, tenant="acme")
    assert [m.description for m in fresh] == ["q0", "q2"]
    assert cursor == rec.last_seq  # advanced past zen's q1 too
    again, cursor2 = rec.snapshot(cursor, tenant="acme")
    assert again == [] and cursor2 == cursor

    # More entries than capacity arrive between polls: the filtered
    # consumer gets acme's survivors, cursor jumps past the rotated.
    for i in range(3, 10):
        rec.record(_finished_metrics(
            f"q{i}", tenant=("acme" if i % 2 == 0 else "zen"),
            replica=i % 2))
    fresh, cursor3 = rec.snapshot(cursor, tenant="acme")
    assert [m.description for m in fresh] == ["q6", "q8"]
    assert cursor3 == cursor + 7
    # Composition: acme AND replica 0 (acme entries all landed on 0).
    both, _ = rec.snapshot(cursor, tenant="acme", replica=0)
    assert [m.description for m in both] == ["q6", "q8"]
    none, _ = rec.snapshot(cursor, tenant="acme", replica=1)
    assert none == []
    # A different tenant's view over the same cursor: disjoint entries,
    # identical cursor arithmetic.
    zen, zcur = rec.snapshot(cursor, tenant="zen")
    assert [m.description for m in zen] == ["q7", "q9"]
    assert zcur == cursor3


def test_flight_tenant_filter_e2e(sales_env, fresh_scheduler):
    """Scheduled collects land in the ring with their effective tenant
    stamped; the recorder-level filter sees exactly them."""
    session, data_dir = sales_env
    sess = session()
    rec = sess.flight_recorder()
    cursor = rec.last_seq
    df = sess.read_parquet(data_dir).select("key")
    df.collect(tenant="flt-a")
    df.collect()
    df.collect(tenant="flt-a")
    mine, _ = rec.snapshot(cursor, tenant="flt-a")
    assert len(mine) == 2
    assert all(m.tenant == "flt-a" for m in mine)
    other, _ = rec.snapshot(cursor, tenant="default")
    assert len(other) == 1


# ---------------------------------------------------------------------------
# /healthz tenant section: error isolation
# ---------------------------------------------------------------------------


def test_healthz_tenant_section_error_isolated(fresh_scheduler,
                                               monkeypatch):
    """A tenants-section failure degrades to an `{"error": ...}` stub;
    the rest of the health document is intact (a health endpoint that
    500s because one subsystem is mid-teardown lies about the rest)."""
    from hyperspace_tpu.telemetry import ops_server

    doc = ops_server.healthz_doc()
    assert doc["status"] == "ok"
    assert "tenants" in doc and "error" not in doc["tenants"]

    monkeypatch.setattr(
        QueryScheduler, "tenant_snapshot",
        lambda self, conf=None: (_ for _ in ()).throw(
            RuntimeError("mid-teardown")))
    doc = ops_server.healthz_doc()
    assert doc["status"] == "ok"
    assert "error" in doc["tenants"]
    assert "mid-teardown" in doc["tenants"]["error"]
    for section in ("scheduler", "breakers", "flight"):
        assert "error" not in doc[section], section


def test_healthz_groups_flight_by_tenant(sales_env, fresh_scheduler):
    from hyperspace_tpu.telemetry import ops_server

    session, data_dir = sales_env
    sess = session()
    df = sess.read_parquet(data_dir).select("key")
    df.collect(tenant="hz-a")
    df.collect(tenant="hz-a")
    doc = ops_server.healthz_doc()
    assert doc["flight"]["by_tenant"].get("hz-a", 0) >= 2
    assert "hz-a" in doc["tenants"]
    assert "usage" in doc["tenants"]["hz-a"]


# ---------------------------------------------------------------------------
# Prometheus exposition under metric-hostile tenant ids
# ---------------------------------------------------------------------------


def test_prometheus_conformance_hostile_tenant_ids():
    """Tenant ids are user-supplied strings that land inside metric
    names: exposition must sanitize every id to the Prometheus grammar,
    keep HELP/TYPE per family, and disambiguate ids that COLLIDE after
    sanitization (`a.b` vs `a/b`) with a numeric serial instead of
    emitting a duplicate family."""
    import re

    from hyperspace_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    hostile = ['acme corp/eu-1', 'acme"corp"eu 1', 'acme.corp.eu.1',
               'über-mieter', '1st-tenant', 'tab\ttenant']
    for t in hostile:
        reg.counter(f"tenant.{t}.device.flops").inc(3)
        reg.counter(f"serve.tenant.{t}.admitted").inc()
    text = reg.to_text()

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    families = []
    for line in text.splitlines():
        assert line == line.strip()
        if line.startswith("# HELP "):
            families.append(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            assert line.split()[2] == families[-1], \
                "TYPE must follow its family's HELP"
            continue
        sample_name = line.split("{")[0].split()[0]
        assert name_re.match(sample_name), sample_name
    assert all(name_re.match(f) for f in families)
    # One family per dotted source metric: the two colliding ids map
    # to distinct (serial-suffixed) families, never a repeated TYPE.
    assert len(families) == len(set(families))
    assert len(families) == 2 * len(hostile)
    # The HELP line carries the original dotted name for reverse
    # mapping, correctly escaped (the tab rides through as-is; the
    # newline rules are pinned by test_artifact_diff's conformance).
    assert 'acme"corp"eu 1' in text


# ---------------------------------------------------------------------------
# tenant_snapshot: the serving-side view
# ---------------------------------------------------------------------------


def test_tenant_snapshot_reports_knobs_and_slo(fresh_scheduler):
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.slo.p99.seconds": "10",
        "spark.hyperspace.serve.slo.window.seconds": "60",
        "spark.hyperspace.serve.tenant.snap.weight": "3",
        "spark.hyperspace.serve.tenant.snap.hbm.fraction": "0.5",
        "spark.hyperspace.serve.tenant.snap.queue.depth": "4"})
    ent = _hold(sch, 128, qid="s1", tenant="snap")
    try:
        sch._tenant_slo_for("snap").record(0.5, conf)
        snap = sch.tenant_snapshot(conf)["snap"]
        assert snap["admitted_bytes"] == 128
        assert snap["inflight"] == 1
        assert snap["queued"] == 0
        assert snap["weight"] == 3.0
        assert snap["hbm_fraction"] == 0.5
        assert snap["queue_depth"] == 4
        assert snap["slo"]["window_queries"] == 1
        assert snap["slo"]["burn_rate"] == 0.0  # 0.5 s under 10 s p99
    finally:
        sch._release(ent)
