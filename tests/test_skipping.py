"""Data-skipping index subsystem: config, serde through the log FSM,
plan-time pruning (zones + blooms, conjunction-aware), rule interplay
with the covering index, degradation on corrupt/missing sketch blobs,
the Z-order build option, snapshot-pinned reads, the commit-time
source-cache sweep, and the no-false-negative property."""

import json
import os
import shutil

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import (DataSkippingIndexConfig,
                                               IndexConfig)
from hyperspace_tpu.index.log_entry import (DataSkippingIndex,
                                            IndexLogEntry, LogEntry)
from hyperspace_tpu.index.sketch import (SKETCH_BLOB, clear_sketch_cache,
                                         load_sketches)
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.nodes import Scan


def _reg(name):
    return telemetry.get_registry().counter(name).value


@pytest.fixture(autouse=True)
def _fresh_sketch_cache():
    clear_sketch_cache()
    yield
    clear_sketch_cache()


@pytest.fixture
def env(tmp_path):
    """(session, hs, df, src_dir): an 8-file source whose files hold
    disjoint key ranges — zones are tight, so selective predicates can
    refute whole files."""
    src = tmp_path / "src"
    src.mkdir()
    rng = np.random.default_rng(7)
    for i in range(8):
        t = pa.table({
            "key": np.arange(i * 100, (i + 1) * 100, dtype=np.int64),
            "val": rng.random(100),
            "s": pa.array([f"s{i}_{j % 10}" for j in range(100)]),
        })
        pq.write_table(t, str(src / f"f{i}.parquet"))
    sess = HyperspaceSession(HyperspaceConf(
        {"hyperspace.warehouse.dir": str(tmp_path / "wh")}))
    hs = Hyperspace(sess)
    return sess, hs, sess.read_parquet(str(src)), str(src)


def _sorted(table):
    return table.sort_by([(n, "ascending") for n in table.column_names])


def _collect_both(sess, q_df):
    """(rules-on table, rules-off table, on-run metrics)."""
    sess.enable_hyperspace()
    try:
        on, metrics = q_df.collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    off = q_df.collect()
    return on, off, metrics


# -- config + serde --------------------------------------------------------


def test_config_validation():
    with pytest.raises(HyperspaceException):
        DataSkippingIndexConfig("", ["a"])
    with pytest.raises(HyperspaceException):
        DataSkippingIndexConfig("x", [])
    with pytest.raises(HyperspaceException):
        DataSkippingIndexConfig("x", ["a", "A"])
    with pytest.raises(HyperspaceException):
        DataSkippingIndexConfig("x", ["a"], sketch_types=["zonemap", "hll"])
    with pytest.raises(HyperspaceException):
        DataSkippingIndexConfig("x", ["a"], zorder_by=["b", "B"])
    cfg = (DataSkippingIndexConfig.builder().index_name("x")
           .skip_by("a", "b").sketches("zonemap").zorder_by("a").create())
    assert cfg == DataSkippingIndexConfig("X", ["a", "b"], ["zonemap"],
                                          ["a"])
    assert cfg != DataSkippingIndexConfig("X", ["a", "b"])


def test_log_entry_serde_round_trip(env):
    """A DataSkippingIndex entry written through the real log manager
    reads back equal — the second index kind flows through the SAME
    LogEntry serde as the covering index."""
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("skA", ["key", "s"],
                                                zorder_by=["key"]))
    manager = Hyperspace.get_context(sess).index_collection_manager
    (entry,) = manager.get_indexes(["ACTIVE"])
    assert entry.kind == "DataSkippingIndex"
    back = LogEntry.from_json(entry.to_json())
    assert isinstance(back, IndexLogEntry)
    assert isinstance(back.derived_dataset, DataSkippingIndex)
    assert back == entry
    assert back.derived_dataset.skipped_columns == ["key", "s"]
    assert back.derived_dataset.zorder_by == ["key"]
    # Catalog surface shared with the covering kind.
    cat = hs.indexes()
    assert list(cat["kind"]) == ["DataSkippingIndex"]
    assert list(cat["state"]) == ["ACTIVE"]


# -- pruning end to end ----------------------------------------------------


def test_prune_eq_bit_identical_with_counters(env):
    sess, hs, df, src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key", "s"]))
    q = df.filter(col("key") == lit(250)).select("key", "val")
    pruned0 = _reg("skipping.files_pruned")
    on, off, metrics = _collect_both(sess, q)
    assert _sorted(on).equals(_sorted(off))
    assert on.num_rows == 1
    # 7 of 8 files refuted; the per-query counters and the process
    # counters agree; the usage record carries the prune detail.
    assert metrics.counters.get("skipping.files_pruned") == 7
    assert metrics.counters.get("skipping.bytes_pruned", 0) > 0
    assert _reg("skipping.files_pruned") - pruned0 >= 7
    (use,) = [u for u in metrics.index_usage()
              if u.get("side") == "skipping"]
    assert use["name"] == "sk" and use["files_pruned"] == 7
    assert use["files_considered"] == 8 and use["served"] == "source"
    assert use["files_scanned"] == 1


def test_prune_range_in_null_and_string(env):
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key", "s"]))
    cases = [
        (col("key") > lit(699)) & (col("key") <= lit(750)),
        col("key").isin(5, 105, 710),
        col("s") == lit("s3_4"),          # bloom + string zones
        col("key").between(199, 202),
        col("s").is_null(),               # no nulls anywhere: all refuted
    ]
    for cond in cases:
        q = df.filter(cond).select("key", "val", "s")
        on, off, metrics = _collect_both(sess, q)
        assert _sorted(on).equals(_sorted(off)), repr(cond)
        assert metrics.counters.get("skipping.files_pruned", 0) > 0, \
            repr(cond)


def test_conjunction_prunes_more_than_either(env):
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key", "s"]))
    sess.enable_hyperspace()
    try:
        _, m_and = df.filter((col("key") < lit(100))
                             & (col("s") == lit("s3_0"))) \
            .select("key").collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    # key<100 alone refutes 7; s=='s3_0' alone refutes 7 (other files'
    # dictionaries miss it); together every file is refuted.
    assert m_and.counters.get("skipping.files_pruned") == 8


def test_covering_index_wins_when_both_apply(env):
    sess, hs, df, _src = env
    hs.create_index(df, IndexConfig("cov", ["key"], ["val"]))
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    sess.enable_hyperspace()
    try:
        plan = df.filter(col("key") == lit(250)).select("key", "val") \
            ._optimized_plan()
    finally:
        sess.disable_hyperspace()
    (leaf,) = plan.collect_leaves()
    assert leaf.index_name == "cov"
    assert "cov" in leaf.root_paths[0] and "v__=" in leaf.root_paths[0]


def test_no_prune_no_rewrite(env):
    """A predicate the sketches cannot refute anywhere leaves the plan
    untouched (no churn rewrite to an identical explicit listing)."""
    sess, hs, df, src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    sess.enable_hyperspace()
    try:
        plan = df.filter(col("val") < lit(2.0)).select("key") \
            ._optimized_plan()  # val is unsketched; nothing refutable
    finally:
        sess.disable_hyperspace()
    (leaf,) = plan.collect_leaves()
    assert not leaf._explicit_files
    assert leaf.root_paths == [src]


def test_skipping_disabled_conf(env):
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    sess.conf.set("spark.hyperspace.index.skipping.enabled", "false")
    sess.enable_hyperspace()
    try:
        _, metrics = df.filter(col("key") == lit(3)).select("key") \
            .collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    assert "skipping.files_pruned" not in metrics.counters


def test_corrupt_and_missing_blob_degrade_unpruned(env):
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    manager = Hyperspace.get_context(sess).index_collection_manager
    (entry,) = manager.get_indexes(["ACTIVE"])
    blob = os.path.join(entry.content.root, SKETCH_BLOB)
    q = df.filter(col("key") == lit(250)).select("key", "val")
    baseline = _sorted(q.collect())

    with open(blob, "wb") as f:
        f.write(b"not parquet at all")
    clear_sketch_cache()
    on, off, metrics = _collect_both(sess, q)
    assert _sorted(on).equals(baseline) and _sorted(off).equals(baseline)
    assert "skipping.files_pruned" not in metrics.counters

    os.remove(blob)
    clear_sketch_cache()
    on, _off, metrics = _collect_both(sess, q)
    assert _sorted(on).equals(baseline)
    assert "skipping.files_pruned" not in metrics.counters


def test_rewritten_source_file_not_pruned(env):
    """Stamp revalidation: a file rewritten after sketching is UNKNOWN
    — kept — so stale sketches can never drop fresh matching rows."""
    sess, hs, df, src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    # Rewrite f0 (keys 0..99) to now hold key 777 — its OLD sketch says
    # max=99 and would refute key==777.
    t = pa.table({"key": np.array([777], dtype=np.int64),
                  "val": np.array([0.5]),
                  "s": pa.array(["zz"])})
    pq.write_table(t, os.path.join(src, "f0.parquet"))
    from hyperspace_tpu.io.parquet import clear_read_cache
    clear_read_cache()
    df2 = sess.read_parquet(src)
    q = df2.filter(col("key") == lit(777)).select("key", "val")
    on, off, _m = _collect_both(sess, q)
    assert on.num_rows == off.num_rows == 2  # rewritten f0 + original f7
    assert _sorted(on).equals(_sorted(off))


def test_hybrid_remainder_pruned_by_sketches(env):
    """The covering index's SOURCE-FILE REMAINDER: with hybrid scan on,
    appended files ride the union — unless a skipping index's sketches
    refute the predicate for them, in which case the appended branch
    thins (here: to nothing — no Union in the plan at all)."""
    from hyperspace_tpu.plan.nodes import Union as UnionNode

    sess, hs, df, src = env
    hs.create_index(df, IndexConfig("cov", ["key"], ["val"]))
    # Append a file with a DISJOINT key range, then sketch the grown
    # source: the appended file has a sketch row that refutes key==250.
    pq.write_table(pa.table({
        "key": np.arange(5000, 5100, dtype=np.int64),
        "val": np.zeros(100), "s": pa.array(["a"] * 100)}),
        os.path.join(src, "f_app.parquet"))
    df2 = sess.read_parquet(src)
    hs.create_index(df2, DataSkippingIndexConfig("sk", ["key"]))
    sess.conf.set("hyperspace.index.hybridscan.enabled", "true")
    q = df2.filter(col("key") == lit(250)).select("key", "val")
    sess.enable_hyperspace()
    try:
        plan = q._optimized_plan()
        on, metrics = q.collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    off = q.collect()
    assert _sorted(on).equals(_sorted(off)) and on.num_rows == 1
    unions = []
    plan.transform_up(lambda n: (unions.append(n), n)[1]
                      if isinstance(n, UnionNode) else n)
    assert not unions  # appended branch fully pruned away
    assert any(u.get("served") == "hybrid-remainder"
               for e in metrics.events_of("rule", "FilterIndexRule")
               if e.get("action") == "applied"
               for u in e.get("indexes", []))
    # The index scan itself still serves the query.
    assert any(leaf.index_name == "cov"
               for leaf in plan.collect_leaves())


# -- refresh / lifecycle ---------------------------------------------------


def test_refresh_resketches_appended_files(env):
    sess, hs, df, src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    pq.write_table(pa.table({
        "key": np.arange(800, 900, dtype=np.int64),
        "val": np.zeros(100), "s": pa.array(["n"] * 100)}),
        os.path.join(src, "f8.parquet"))
    df2 = sess.read_parquet(src)
    q = df2.filter(col("key") == lit(850)).select("key")
    sess.enable_hyperspace()
    try:
        _, m_before = q.collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    # The appended file has no sketch row yet: kept, old files pruned.
    assert m_before.counters.get("skipping.files_pruned") == 8
    hs.refresh_index("sk")
    manager = Hyperspace.get_context(sess).index_collection_manager
    (entry,) = manager.get_indexes(["ACTIVE"])
    assert entry.content.root.endswith("v__=1")
    on, off, m_after = _collect_both(sess, q)
    assert m_after.counters.get("skipping.files_pruned") == 8
    assert _sorted(on).equals(_sorted(off)) and on.num_rows == 1


def test_incremental_refresh_dispatches_and_optimize_declines(env):
    """mode='incremental' on a skipping index now takes the
    sketch-append delta path (tests/test_ingest.py covers its
    semantics); Z-ordered configs and optimize still decline typed."""
    sess, hs, df, _src = env
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    hs.refresh_index("sk", mode="incremental")  # no-op append: succeeds
    with pytest.raises(HyperspaceException, match="skipping"):
        hs.optimize_index("sk")
    hs.create_index(df, DataSkippingIndexConfig(
        "zk", ["key"], zorder_by=["key"]))
    with pytest.raises(HyperspaceException, match="full"):
        hs.refresh_index("zk", mode="incremental")
    assert sorted(hs.indexes()["state"]) == ["ACTIVE", "ACTIVE"]


def test_lifecycle_round_trip_with_crash_recovery(env, fault_injector):
    """create -> (injected crash mid-create; recover) -> create ->
    refresh -> delete -> vacuum through the shared FSM."""
    from hyperspace_tpu.utils.faults import FaultRule, InjectedCrash

    sess, hs, df, _src = env
    inj = fault_injector(
        FaultRule("action.CreateSkippingIndexAction.op", kind="crash"))
    with pytest.raises(InjectedCrash):
        hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    assert inj.fired("action.*") == 1
    from hyperspace_tpu.utils import faults
    faults.uninstall()
    assert hs.recover_index("sk") is True  # stranded CREATING unwound
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    # Crash a refresh BETWEEN op and end (data committed, final log
    # entry never written): recovery unwinds to ACTIVE-at-v0 and the
    # next refresh skips the orphaned version number.
    inj2 = fault_injector(FaultRule("action.RefreshAction.end",
                                    kind="crash"))
    with pytest.raises(InjectedCrash):
        hs.refresh_index("sk")
    assert inj2.fired("action.*") == 1
    faults.uninstall()
    assert hs.recover_index("sk") is True
    hs.refresh_index("sk")
    q = df.filter(col("key") == lit(5)).select("key")
    on, off, m = _collect_both(sess, q)
    assert _sorted(on).equals(_sorted(off))
    assert m.counters.get("skipping.files_pruned", 0) > 0
    hs.delete_index("sk")
    hs.vacuum_index("sk")
    assert len(hs.indexes()) == 0
    manager = Hyperspace.get_context(sess).index_collection_manager
    index_path = manager.path_resolver.get_index_path("sk")
    assert not any(n.startswith("v__=") for n in os.listdir(index_path))


# -- Z-order ---------------------------------------------------------------


def _zorder_env(tmp_path, n=4000, files=4):
    """Source with SHUFFLED keys: per-file zones are full-width, so
    only the Z-order rewrite can prune."""
    src = tmp_path / "zsrc"
    src.mkdir()
    rng = np.random.default_rng(3)
    keys = rng.permutation(n).astype(np.int64)
    k2 = rng.integers(0, 50, n).astype(np.int64)
    per = n // files
    for i in range(files):
        sl = slice(i * per, (i + 1) * per)
        pq.write_table(pa.table({"key": keys[sl], "k2": k2[sl],
                                 "val": rng.random(per)}),
                       str(src / f"f{i}.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "zwh"),
        "spark.hyperspace.index.skipping.zorder.files": "8"}))
    return sess, Hyperspace(sess), sess.read_parquet(str(src))


def test_zorder_serves_pruned_copy(tmp_path):
    sess, hs, df = _zorder_env(tmp_path)
    hs.create_index(df, DataSkippingIndexConfig(
        "z", ["key", "k2"], zorder_by=["key", "k2"]))
    q = df.filter((col("key") < lit(400)) & (col("k2") < lit(8))) \
        .select("key", "k2", "val")
    sess.enable_hyperspace()
    try:
        plan = q._optimized_plan()
        on, metrics = q.collect(with_metrics=True)
    finally:
        sess.disable_hyperspace()
    off = q.collect()
    (leaf,) = plan.collect_leaves()
    assert leaf.index_name == "z" and "v__=0" in leaf.root_paths[0]
    assert leaf.pinned_version == 0
    assert leaf._explicit_files and 0 < len(leaf.files()) < 8
    assert _sorted(on).equals(_sorted(off))
    (use,) = [u for u in metrics.index_usage()
              if u.get("side") == "skipping"]
    assert use["served"] == "zorder-copy" and use["files_pruned"] > 0


def test_zorder_requires_signature_match(tmp_path):
    """Source changed after the Z-order build: the copy no longer
    represents it — the entry must NOT serve."""
    sess, hs, df = _zorder_env(tmp_path)
    hs.create_index(df, DataSkippingIndexConfig(
        "z", ["key"], zorder_by=["key"]))
    src = df.plan.root_paths[0]
    pq.write_table(pa.table({"key": np.array([9999], dtype=np.int64),
                             "k2": np.array([1], dtype=np.int64),
                             "val": np.array([0.5])}),
                   os.path.join(src, "extra.parquet"))
    df2 = sess.read_parquet(src)
    q = df2.filter(col("key") == lit(9999)).select("key", "val")
    on, off, _m = _collect_both(sess, q)
    assert on.num_rows == 1
    assert _sorted(on).equals(_sorted(off))


def test_zorder_missing_data_degrades_and_trips_breaker(tmp_path):
    """Copy data deleted out-of-band: execution raises the typed
    IndexDataUnavailableError, the query falls back to the source plan
    bit-identically, and repeated failures open the per-index breaker
    (the PR-4/PR-7 degradation path)."""
    from hyperspace_tpu.engine import scheduler as sched_mod
    from hyperspace_tpu.engine.scheduler import QueryScheduler

    sess, hs, df = _zorder_env(tmp_path)
    sess.conf.set("spark.hyperspace.serve.breaker.failures", "1")
    hs.create_index(df, DataSkippingIndexConfig(
        "z", ["key"], zorder_by=["key"]))
    q = df.filter(col("key") < lit(50)).select("key", "val")
    baseline = _sorted(q.collect())
    manager = Hyperspace.get_context(sess).index_collection_manager
    (entry,) = manager.get_indexes(["ACTIVE"])
    # Corrupt the copy's row files PRESERVING (size, mtime) — the
    # stamps still validate, so the rule keeps serving the copy, and
    # the failure surfaces at SCAN time as the typed error (deleting
    # the files instead would flunk stamp revalidation and degrade at
    # plan time — also correct, but not the path under test).
    from hyperspace_tpu.io.parquet import clear_read_cache
    sess.enable_hyperspace()
    try:
        q._optimized_plan()
        for name in os.listdir(entry.content.root):
            if name.endswith(".parquet"):
                p = os.path.join(entry.content.root, name)
                st = os.stat(p)
                with open(p, "wb") as f:
                    f.write(b"\x00" * st.st_size)
                os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
        clear_read_cache()
        sched_mod.set_scheduler(QueryScheduler())
        try:
            fb0 = _reg("resilience.fallbacks")
            t1 = q.collect()
            assert _reg("resilience.fallbacks") == fb0 + 1
            sc0 = _reg("resilience.breaker.short_circuits")
            t2 = q.collect()  # breaker open: straight to source
            assert _reg("resilience.breaker.short_circuits") == sc0 + 1
        finally:
            sched_mod.set_scheduler(QueryScheduler())
    finally:
        sess.disable_hyperspace()
    assert _sorted(t1).equals(baseline) and _sorted(t2).equals(baseline)


# -- snapshot-pinned reads -------------------------------------------------


def test_snapshot_pin_freezes_listing_against_racing_writer(env):
    """ROADMAP serving item 1: the committed v__=N is resolved ONCE at
    plan time and the listing frozen — a file landing in the version
    dir between plan and execution (a racing/stale writer) is invisible
    to the already-planned query, and a refresh committing v__=N+1
    cannot redirect it."""
    from hyperspace_tpu.engine.executor import execute_plan
    from hyperspace_tpu.io.columnar import to_arrow

    sess, hs, df, src = env
    hs.create_index(df, IndexConfig("cov", ["key"], ["val"]))
    q = df.filter(col("key") > lit(750)).select("key", "val")
    sess.enable_hyperspace()
    try:
        plan = q._optimized_plan()
    finally:
        sess.disable_hyperspace()
    (leaf,) = plan.collect_leaves()
    assert leaf.index_name == "cov" and leaf.pinned_version == 0
    before = _sorted(to_arrow(execute_plan(plan, conf=sess.conf)))

    # Concurrent refresher: source grows, refresh commits v__=1 ...
    pq.write_table(pa.table({
        "key": np.arange(900, 950, dtype=np.int64),
        "val": np.zeros(50), "s": pa.array(["r"] * 50)}),
        os.path.join(src, "f9.parquet"))
    hs.refresh_index("cov")
    # ... and a stale/racing writer drops a matching-keyed bucket file
    # INTO the pinned v__=0 dir (what an unpinned execution-time
    # re-listing would pick up).
    foreign = pa.table({"key": np.array([800] * 5, dtype=np.int64),
                        "val": np.zeros(5)})
    pq.write_table(foreign, os.path.join(
        os.path.dirname(leaf.root_paths[0]), "v__=0",
        "part-99999.parquet"))

    after = _sorted(to_arrow(execute_plan(plan, conf=sess.conf)))
    assert after.equals(before)  # neither v__=1 nor the foreign file

    # A FRESH plan resolves (and pins) the new committed version.
    sess.enable_hyperspace()
    try:
        plan2 = sess.read_parquet(src).filter(col("key") > lit(750)) \
            .select("key", "val")._optimized_plan()
    finally:
        sess.disable_hyperspace()
    (leaf2,) = plan2.collect_leaves()
    assert leaf2.pinned_version == 1


# -- admission interplay ---------------------------------------------------


def test_commit_sweeps_source_root_caches(env):
    from hyperspace_tpu.plan import footprint

    sess, hs, df, src = env
    footprint.projected_bytes(df.plan)  # populate the size cache
    assert any(p.startswith(src) for p in footprint._size_cache)
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    # Skipping-index commit sweeps SOURCE roots, not just index roots.
    assert not any(p.startswith(src) for p in footprint._size_cache)


def test_footprint_reprojection_credit(env, monkeypatch):
    from hyperspace_tpu.engine import scheduler as sched_mod
    from hyperspace_tpu.engine.scheduler import QueryScheduler
    from hyperspace_tpu.plan import footprint

    sess, hs, df, _src = env
    monkeypatch.setattr(footprint, "MIN_FOOTPRINT_BYTES", 1024)
    hs.create_index(df, DataSkippingIndexConfig("sk", ["key"]))
    sched_mod.set_scheduler(QueryScheduler())
    try:
        sess.enable_hyperspace()
        try:
            c0 = _reg("serve.footprint_credit_bytes")
            _, metrics = df.filter(col("key") == lit(250)).select("key") \
                .collect(with_metrics=True)
        finally:
            sess.disable_hyperspace()
        assert _reg("serve.footprint_credit_bytes") > c0
        assert metrics.events_of("serve", "footprint_reprojected")
    finally:
        sched_mod.set_scheduler(QueryScheduler())


# -- kernels ---------------------------------------------------------------


def test_host_device_sketch_identity():
    """Host and device lanes must produce bit-identical blooms and
    equal zones — the blob a query probes must not depend on which lane
    built it."""
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import sketch as ops_sketch
    from hyperspace_tpu.plan.schema import Schema

    t = pa.table({
        "a": pa.array([1, 5, None, 7, 5, -3], type=pa.int64()),
        "s": pa.array(["x", "y", None, "zz", "x", ""]),
        "f": pa.array([1.5, float("nan"), None, -0.0, 2.5, -9.75],
                      type=pa.float64()),
        "g": pa.array(np.arange(6, dtype=np.float32)),
        "b": pa.array([True, False, None, True, True, False]),
    })
    schema = Schema.from_arrow(t.schema)
    bh = columnar.from_arrow(t, schema, device=False)
    bd = columnar.from_arrow(t, schema, device=True)
    for name in t.column_names:
        zh = ops_sketch.zones(bh.column(name))
        zd = ops_sketch.zones(bd.column(name))
        assert zh == zd, (name, zh, zd)
        wh = ops_sketch.bloom_build(bh.column(name), 512)
        wd = ops_sketch.bloom_build(bd.column(name), 512)
        assert np.array_equal(wh, wd), name


def test_bloom_membership_and_sizing():
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.sketch import (bloom_build, bloom_maybe_contains,
                                           bloom_num_bits, probe_hash_pair)
    from hyperspace_tpu.plan.schema import Schema

    assert bloom_num_bits(1000, 0.01, 64 * 1024) % 256 == 0
    assert bloom_num_bits(10 ** 9, 0.01, 64 * 1024) == 64 * 1024 * 8
    values = np.arange(0, 5000, 7, dtype=np.int64)
    t = pa.table({"k": values})
    batch = columnar.from_arrow(t, Schema.from_arrow(t.schema),
                                device=False)
    words = bloom_build(batch.column("k"),
                        bloom_num_bits(len(values), 0.01, 64 * 1024))
    for v in values[::50]:  # members: NEVER a false negative
        assert bloom_maybe_contains(words, *probe_hash_pair(int(v),
                                                            "int64"))
    misses = sum(
        bloom_maybe_contains(words, *probe_hash_pair(int(v), "int64"))
        for v in range(1, 5000, 7))  # all non-members
    assert misses / (5000 // 7) < 0.05  # ~fpp with headroom


def test_zorder_permutation_clusters():
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.sketch import zorder_permutation
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(0)
    n = 4096
    t = pa.table({"x": rng.permutation(n).astype(np.int64),
                  "y": rng.permutation(n).astype(np.int64)})
    batch = columnar.from_arrow(t, Schema.from_arrow(t.schema),
                                device=False)
    perm = zorder_permutation(batch, ["x", "y"])
    assert sorted(perm) == list(range(n))  # a permutation
    x = t.column("x").to_numpy()[perm]
    y = t.column("y").to_numpy()[perm]
    # Z-order clustering: each quarter of the output spans far less
    # than the full range in BOTH dimensions on average.
    spans = []
    for i in range(4):
        sl = slice(i * n // 4, (i + 1) * n // 4)
        spans.append((x[sl].max() - x[sl].min())
                     * (y[sl].max() - y[sl].min()))
    assert np.mean(spans) < 0.5 * (n - 1) ** 2


# -- the property: pruning never drops a matching row ----------------------


def test_property_no_false_negatives(tmp_path):
    """Randomized predicates over files with nulls, NaNs, negatives,
    strings, and int32 — every PRUNED file must contain ZERO rows the
    ENGINE's own predicate compiler marks true."""
    from hyperspace_tpu.engine.compiler import compile_predicate
    from hyperspace_tpu.io import columnar, parquet as pio
    from hyperspace_tpu.plan import expr as E
    from hyperspace_tpu.plan.rules.skipping import prune_files
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(42)
    src = tmp_path / "prop"
    src.mkdir()
    n_files, per = 6, 60

    def maybe_null(arr, p=0.15):
        mask = rng.random(len(arr)) < p
        return pa.array([None if m else v
                         for v, m in zip(arr.tolist(), mask)])

    files = []
    for i in range(n_files):
        base = rng.integers(-50, 400)
        i64 = rng.integers(base, base + rng.integers(5, 120),
                           per).astype(np.int64)
        f64 = np.where(rng.random(per) < 0.1, np.nan,
                       rng.normal(base, 30, per))
        s = [f"v{int(v)}" for v in rng.integers(base, base + 40, per)]
        i32 = rng.integers(-5, 5, per).astype(np.int32)
        t = pa.table({
            "i64": maybe_null(i64),
            "f64": pa.array(f64, type=pa.float64()),  # NaN, no nulls
            "s": maybe_null(np.asarray(s, dtype=object), p=0.1),
            "i32": pa.array(i32, type=pa.int32()),
        }).cast(pa.schema([("i64", pa.int64()), ("f64", pa.float64()),
                           ("s", pa.string()), ("i32", pa.int32())]))
        path = str(src / f"f{i}.parquet")
        pq.write_table(t, path)
        files.append(path)

    sess = HyperspaceSession(HyperspaceConf(
        {"hyperspace.warehouse.dir": str(tmp_path / "pwh")}))
    hs = Hyperspace(sess)
    df = sess.read_parquet(str(src))
    hs.create_index(df, DataSkippingIndexConfig(
        "prop", ["i64", "f64", "s", "i32"]))
    manager = Hyperspace.get_context(sess).index_collection_manager
    (entry,) = manager.get_indexes(["ACTIVE"])
    sketches = load_sketches(entry.content.root)
    schema = df.schema

    def leaf():
        name = rng.choice(["i64", "f64", "s", "i32"])
        c = E.col(name)
        kind = rng.choice(["eq", "ne", "lt", "le", "gt", "ge", "in",
                           "null", "notnull"])
        if name == "s":
            vals = [f"v{int(v)}" for v in rng.integers(-60, 460, 3)]
        elif name == "f64":
            vals = [float(v) for v in rng.normal(150, 120, 3)]
        elif name == "i32":
            vals = [int(v) for v in rng.integers(-6, 6, 3)]
        else:
            vals = [int(v) for v in rng.integers(-60, 520, 3)]
        v = vals[0]
        return {"eq": c == E.lit(v), "ne": c != E.lit(v),
                "lt": c < E.lit(v), "le": c <= E.lit(v),
                "gt": c > E.lit(v), "ge": c >= E.lit(v),
                "in": c.isin(*vals), "null": c.is_null(),
                "notnull": c.is_not_null()}[kind]

    def predicate(depth=2):
        if depth == 0 or rng.random() < 0.4:
            return leaf()
        a, b = predicate(depth - 1), predicate(depth - 1)
        return (a & b) if rng.random() < 0.5 else (a | b)

    batches = {f: columnar.from_arrow(pio.read_table([f]), schema,
                                      device=False) for f in files}
    checked = 0
    for _trial in range(120):
        cond = predicate()
        survivors, pruned, _bytes = prune_files(cond, files, sketches)
        assert sorted(survivors + pruned) == sorted(files)
        for f in pruned:
            mask = np.asarray(compile_predicate(cond, batches[f]))
            assert not mask.any(), (
                f"false negative: {cond!r} pruned {os.path.basename(f)} "
                f"which holds {int(mask.sum())} matching row(s)")
            checked += 1
    assert checked > 50  # the trials actually pruned files
