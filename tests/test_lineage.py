"""Per-row lineage: hybrid scan and incremental refresh over DELETED
source files (extension; the surveyed reference stores bare paths — per-
file stamps + a `_hs_file_id` row column are its v0.2 lineage direction).

Layers mirror the suite's test strategy: metadata round-trip pinning,
rule-level behavior via explain plans, and E2E rules-on == rules-off
equality over mutated sources.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import LINEAGE_COLUMN
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col


def _write_part(src, i, n=100):
    ids = np.arange(i * 1000, i * 1000 + n, dtype=np.int64)
    table = pa.table({
        "k": (ids % 17).astype(np.int64),
        "id": ids,
        "val": (ids * 2).astype(np.int64),
    })
    pq.write_table(table, os.path.join(src, f"part-{i}.parquet"))


@pytest.fixture
def env(tmp_path):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
        "spark.hyperspace.index.lineage.enabled": "true",
        "spark.hyperspace.index.hybridscan.enabled": "true",
    })
    session = HyperspaceSession(conf)
    src = str(tmp_path / "src")
    os.makedirs(src)
    for i in range(3):
        _write_part(src, i)
    return session, Hyperspace(session), src


def _sorted(df):
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _assert_equal_on_off(session, query):
    session.enable_hyperspace()
    on = _sorted(query.to_pandas())
    session.disable_hyperspace()
    off = _sorted(query.to_pandas())
    pd.testing.assert_frame_equal(on, off, check_dtype=False)
    return on


def _index_roots(session, query):
    session.enable_hyperspace()
    _, optimized, _ = query.explain_plans()
    return [r for leaf in optimized.collect_leaves()
            for r in leaf.root_paths]


# -- build-time metadata ---------------------------------------------------

def test_lineage_build_metadata_and_column(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    [entry] = [e for e in
               Hyperspace.get_context(session).index_collection_manager
               .get_indexes(["ACTIVE"])]
    infos = entry.source_file_infos()
    assert infos is not None and len(infos) == 3
    assert sorted(fi.id for fi in infos.values()) == [0, 1, 2]
    for path, fi in infos.items():
        assert os.path.isfile(path)
        assert fi.size == os.stat(path).st_size
    assert entry.has_lineage

    # Every index data file carries the lineage column; its values are
    # exactly the stored file ids.
    root = entry.content.root
    seen = set()
    for f in os.listdir(root):
        if f.endswith(".parquet"):
            t = pq.read_table(os.path.join(root, f))
            assert LINEAGE_COLUMN in t.column_names
            seen |= set(t.column(LINEAGE_COLUMN).to_pylist())
    assert seen == {0, 1, 2}

    # The internal column never leaks into query results.
    query = session.read_parquet(src).filter(col("k") == 3)
    session.enable_hyperspace()
    got = query.to_pandas()
    assert LINEAGE_COLUMN not in got.columns
    assert list(got.columns) == ["k", "id", "val"]


def test_lineage_metadata_roundtrip():
    from hyperspace_tpu.index.log_entry import (Directory, FileInfo,
                                                IndexLogEntry)
    d = Directory(path="/d", files=["a", "b"],
                  file_infos=[FileInfo("a", 10, "123", 0),
                              FileInfo("b", 20, "456", 1)])
    back = Directory.from_dict(d.to_dict())
    assert back == d
    # Stampless directories keep the reference-parity wire shape.
    bare = Directory(path="/d", files=["a"])
    assert "fileInfos" not in bare.to_dict()


# -- filter path -----------------------------------------------------------

def test_filter_hybrid_scan_survives_delete(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    os.remove(os.path.join(src, "part-1.parquet"))

    query = session.read_parquet(src).filter(col("k") == 3).select("id", "val")
    roots = _index_roots(session, query)
    assert len(roots) == 1 and "v__=0" in roots[0], \
        "deletion should stay index-served via lineage exclusion"
    on = _assert_equal_on_off(session, query)
    assert (on["id"] // 1000 != 1).all()


def test_filter_hybrid_scan_delete_plus_append(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    os.remove(os.path.join(src, "part-0.parquet"))
    _write_part(src, 7)  # appended after build

    query = session.read_parquet(src).filter(col("k") == 5).select("id")
    roots = _index_roots(session, query)
    assert any("v__=0" in r for r in roots)  # index branch
    assert any("src" in r for r in roots)    # appended branch
    _assert_equal_on_off(session, query)


def test_modified_file_declines_hybrid(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    _write_part(src, 1, n=50)  # in-place rewrite: same path, new content

    query = session.read_parquet(src).filter(col("k") == 3).select("id")
    roots = _index_roots(session, query)
    assert all("v__=0" not in r for r in roots), \
        "an in-place rewrite must not be index-served"
    _assert_equal_on_off(session, query)


# -- join path -------------------------------------------------------------

def test_join_hybrid_scan_survives_delete(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("jl", ["k"], ["id"]))
    hs.create_index(df, IndexConfig("jr", ["k"], ["val"]))
    os.remove(os.path.join(src, "part-2.parquet"))

    df2 = session.read_parquet(src)
    query = df2.select("k", "id").join(df2.select("k", "val"), on="k")
    roots = _index_roots(session, query)
    assert any("v__=0" in r for r in roots), \
        "join over a deleted source should stay index-served"
    _assert_equal_on_off(session, query)


def test_join_exact_match_lineage_not_leaked(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("jl", ["k"], ["id"]))
    hs.create_index(df, IndexConfig("jr", ["k"], ["val"]))

    df2 = session.read_parquet(src)
    query = df2.select("k", "id").join(df2.select("k", "val"), on="k")
    roots = _index_roots(session, query)
    assert any("v__=0" in r for r in roots)
    session.enable_hyperspace()
    got = query.to_pandas()
    assert LINEAGE_COLUMN not in got.columns
    _assert_equal_on_off(session, query)


# -- incremental refresh ---------------------------------------------------

def test_incremental_refresh_deletion(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    os.remove(os.path.join(src, "part-1.parquet"))
    hs.refresh_index("lin", mode="incremental")

    v1 = os.path.join(session.conf.system_path, "lin", "v__=1")
    assert os.path.isdir(v1)
    # The new version's rows exclude exactly the deleted file's id.
    ids = set()
    for f in os.listdir(v1):
        if f.endswith(".parquet"):
            ids |= set(pq.read_table(os.path.join(v1, f))
                       .column(LINEAGE_COLUMN).to_pylist())
    assert ids == {0, 2}

    query = session.read_parquet(src).filter(col("k") == 4).select("id")
    roots = _index_roots(session, query)
    assert len(roots) == 1 and "v__=1" in roots[0]
    _assert_equal_on_off(session, query)


def test_incremental_refresh_delete_and_append(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    os.remove(os.path.join(src, "part-0.parquet"))
    _write_part(src, 9)
    hs.refresh_index("lin", mode="incremental")

    [entry] = [e for e in
               Hyperspace.get_context(session).index_collection_manager
               .get_indexes(["ACTIVE"])]
    infos = entry.source_file_infos()
    by_name = {os.path.basename(p): fi.id for p, fi in infos.items()}
    # Survivors keep their build-time ids; the appended file gets a fresh
    # one PAST the previous maximum (deleted ids are never reused — rows
    # carrying them were just filtered out).
    assert by_name["part-1.parquet"] == 1
    assert by_name["part-2.parquet"] == 2
    assert by_name["part-9.parquet"] == 3

    query = session.read_parquet(src).filter(col("k") == 2).select("id", "val")
    roots = _index_roots(session, query)
    assert len(roots) == 1 and "v__=1" in roots[0]
    _assert_equal_on_off(session, query)


def test_incremental_refresh_without_lineage_still_rejects_delete(tmp_path):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
    })
    session = HyperspaceSession(conf)
    src = str(tmp_path / "src")
    os.makedirs(src)
    for i in range(2):
        _write_part(src, i)
    hs = Hyperspace(session)
    hs.create_index(session.read_parquet(src),
                    IndexConfig("nolin", ["k"], ["id"]))
    os.remove(os.path.join(src, "part-0.parquet"))
    with pytest.raises(HyperspaceException, match="lineage"):
        hs.refresh_index("nolin", mode="incremental")


def test_full_refresh_preserves_lineage(env):
    session, hs, src = env
    hs.create_index(session.read_parquet(src),
                    IndexConfig("lin", ["k"], ["id", "val"]))
    # Conf flips off — the index property is sticky across full refresh.
    session.conf.set("spark.hyperspace.index.lineage.enabled", "false")
    os.remove(os.path.join(src, "part-1.parquet"))
    hs.refresh_index("lin")
    [entry] = [e for e in
               Hyperspace.get_context(session).index_collection_manager
               .get_indexes(["ACTIVE"])]
    assert entry.has_lineage
    infos = entry.source_file_infos()
    assert infos is not None and len(infos) == 2
