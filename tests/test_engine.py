"""Engine tests: predicate compilation vs pandas, projection pushdown,
physical planning (Exchange/Sort insertion and elision)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.physical import (ExchangeExec, ScanExec,
                                            SortMergeJoinExec)
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col, lit


@pytest.fixture
def session():
    return HyperspaceSession(HyperspaceConf())


@pytest.fixture
def df(session, sample_parquet):
    return session.read_parquet(sample_parquet)


@pytest.fixture
def pdf(sample_parquet):
    import glob, os
    files = glob.glob(os.path.join(sample_parquet, "*.parquet"))
    return pq.read_table(files[0]).to_pandas()


@pytest.mark.parametrize("predicate,pandas_query", [
    (col("clicks") == 42, lambda d: d[d.clicks == 42]),
    (col("clicks") != 42, lambda d: d[d.clicks != 42]),
    (col("clicks") > 90, lambda d: d[d.clicks > 90]),
    (col("clicks") >= 90, lambda d: d[d.clicks >= 90]),
    (col("clicks") < 5, lambda d: d[d.clicks < 5]),
    (col("score") <= 0.1, lambda d: d[d.score <= 0.1]),
    ((col("clicks") > 50) & (col("score") < 0.5),
     lambda d: d[(d.clicks > 50) & (d.score < 0.5)]),
    ((col("clicks") < 5) | (col("clicks") > 95),
     lambda d: d[(d.clicks < 5) | (d.clicks > 95)]),
    (~(col("clicks") > 10), lambda d: d[~(d.clicks > 10)]),
    (col("clicks").isin(1, 2, 3), lambda d: d[d.clicks.isin([1, 2, 3])]),
    ((col("clicks") + 1) * 2 > 150, lambda d: d[(d.clicks + 1) * 2 > 150]),
    (col("query") == "q7", lambda d: d[d["query"] == "q7"]),
    (col("query") > "q40", lambda d: d[d["query"] > "q40"]),
    (col("query") <= "q11", lambda d: d[d["query"] <= "q11"]),
    (col("query") >= "nonexistent", lambda d: d[d["query"] >= "nonexistent"]),
])
def test_filter_parity_with_pandas(df, pdf, predicate, pandas_query):
    out = df.filter(predicate).to_pandas().sort_values("id").reset_index(drop=True)
    ref = pandas_query(pdf).sort_values("id").reset_index(drop=True)
    assert len(out) == len(ref)
    pd.testing.assert_frame_equal(out, ref[out.columns])


def test_filter_on_nullable_column(session, tmp_path):
    table = pa.table({"x": pa.array([1, None, 3, None, 5], type=pa.int64()),
                      "y": pa.array([10, 20, 30, 40, 50], type=pa.int64())})
    d = tmp_path / "nulls"
    d.mkdir()
    pq.write_table(table, str(d / "part-0.parquet"))
    df = session.read_parquet(str(d))
    # SQL semantics: null fails comparisons
    assert df.filter(col("x") > 0).count() == 3
    assert df.filter(col("x").is_null()).count() == 2
    assert df.filter(col("x").is_not_null()).count() == 3


def test_not_predicate_three_valued_null_semantics(session, tmp_path):
    """Regression: NOT over a nullable comparison must keep NULL rows
    filtered (SQL: NOT(NULL = 5) is NULL, which is not-true). The old
    compiler folded NULL to False and ~mask let those rows through."""
    table = pa.table({"x": pa.array([1, None, 5], type=pa.int64()),
                      "s": pa.array(["a", None, "c"])})
    d = tmp_path / "nulls3v"
    d.mkdir()
    pq.write_table(table, str(d / "part-0.parquet"))
    df = session.read_parquet(str(d))

    assert df.filter(~(col("x") == 5)).to_pandas()["x"].tolist() == [1]
    assert df.filter(~(col("x") != 5)).to_pandas()["x"].tolist() == [5]
    assert df.filter(~col("x").isin(1, 2)).to_pandas()["x"].tolist() == [5]
    # Double negation keeps NULL out too.
    assert df.filter(~~(col("x") == 5)).to_pandas()["x"].tolist() == [5]
    # NOT over string comparisons rides the same validity.
    assert df.filter(~(col("s") == "c")).to_pandas()["s"].tolist() == ["a"]
    # IS NULL under NOT is always known.
    assert df.filter(~col("x").is_null()).count() == 2


def test_kleene_and_or_with_nulls(session, tmp_path):
    """Kleene logic: FALSE AND NULL = FALSE (known), TRUE OR NULL = TRUE
    (known) — so NOT over those combinations behaves like SQL/Spark."""
    table = pa.table({"x": pa.array([1, None, 5], type=pa.int64()),
                      "y": pa.array([7, 8, None], type=pa.int64())})
    d = tmp_path / "kleene"
    d.mkdir()
    pq.write_table(table, str(d / "part-0.parquet"))
    df = session.read_parquet(str(d))

    # NOT(x=5 AND y=9): row x=1 -> NOT(F AND ?)=T; x=None -> NOT(NULL AND F)
    # = NOT F = T (y=8 makes the AND definitely false); x=5,y=None ->
    # NOT(T AND NULL) = NULL -> filtered.
    out = df.filter(~((col("x") == 5) & (col("y") == 9)))
    assert out.to_pandas()["y"].tolist() == [7, 8]
    # NOT(x=1 OR y=8): x=1 -> NOT T = F; None,8 -> NOT(NULL OR T)=NOT T=F;
    # 5,None -> NOT(F OR NULL) = NULL -> filtered. Nothing passes.
    assert df.filter(~((col("x") == 1) | (col("y") == 8))).count() == 0
    # And the positive forms still work.
    assert df.filter((col("x") == 1) | (col("y") == 8)).count() == 2


def test_select_and_projection_pushdown(df):
    q = df.filter(col("clicks") > 50).select("id", "score")
    _, _, physical = q.explain_plans()
    scans = [n for n in physical.collect() if isinstance(n, ScanExec)]
    assert len(scans) == 1
    # Only the needed columns are read from parquet.
    assert set(scans[0].columns) == {"id", "score", "clicks"}
    out = q.to_pandas()
    assert list(out.columns) == ["id", "score"]


def test_unbucketed_join_plans_exchange_and_sort(session, sample_parquet):
    # Disable broadcast to exercise the Exchange+Sort machinery on these
    # tiny fixtures — the reference E2E suite pins
    # autoBroadcastJoinThreshold=-1 for the same reason.
    session.conf.set("hyperspace.broadcast.threshold", -1)
    df = session.read_parquet(sample_parquet)
    q = df.select("id", "clicks").join(df.select("id", "score"), on="id")
    _, _, physical = q.explain_plans()
    names = [type(n).__name__ for n in physical.collect()]
    assert names.count("ExchangeExec") == 2
    assert names.count("SortExec") == 2
    smj = [n for n in physical.collect() if isinstance(n, SortMergeJoinExec)]
    assert len(smj) == 1 and not smj[0].bucketed


def test_join_requires_equi_condition(session, sample_parquet):
    df = session.read_parquet(sample_parquet)
    q = df.join(df, on=col("clicks") > col("imprs"))
    with pytest.raises(HyperspaceException):
        q.collect()


def test_count_and_collect(df, pdf):
    assert df.count() == len(pdf)
    table = df.collect()
    assert table.num_rows == len(pdf)


def test_empty_filter_result(df):
    out = df.filter(col("clicks") > 1000).to_pandas()
    assert len(out) == 0


# ---------------------------------------------------------------------------
# Bucket pruning (point filters over bucketed index layouts)
# ---------------------------------------------------------------------------


def _bucketed_source(tmp_path, n=5000, num_buckets=8, with_strings=False):
    """Write a bucketed layout via the product build and return a Scan."""
    from hyperspace_tpu.io.builder import write_bucketed_table
    from hyperspace_tpu.plan.nodes import BucketSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(5)
    cols = {"k": rng.integers(0, 500, n).astype(np.int64),
            "v": np.arange(n, dtype=np.int64)}
    if with_strings:
        cols["s"] = np.array(["name_%d" % (i % 97) for i in range(n)])
    table = pa.table(cols)
    out = str(tmp_path / "bucketed")
    keys = ["k"] if not with_strings else ["s"]
    write_bucketed_table(table, keys, num_buckets, out)
    schema = Schema.from_arrow(table.schema)
    spec = BucketSpec(num_buckets, tuple(keys), tuple(keys))
    return Scan([out], schema, bucket_spec=spec), table


def test_bucket_pruning_point_filter_correct_and_pruned(session, tmp_path):
    from hyperspace_tpu.engine.physical import plan_physical
    from hyperspace_tpu.plan.nodes import Filter, Project

    scan, table = _bucketed_source(tmp_path)
    plan = Project(["v"], Filter(col("k") == lit(123), scan))
    phys = plan_physical(plan)
    scans = [n for n in phys.collect() if isinstance(n, ScanExec)]
    assert scans and scans[0].allowed_buckets is not None
    assert len(scans[0].allowed_buckets) == 1
    assert "prunedBuckets=1/8" in scans[0].simple_string()

    got = np.sort(np.asarray(phys.execute().column("v").data))
    k = table.column("k").to_numpy()
    expected = np.sort(table.column("v").to_numpy()[k == 123])
    assert (got == expected).all()


def test_bucket_pruning_in_list_and_unprunable_predicates(session, tmp_path):
    from hyperspace_tpu.engine.physical import plan_physical, _prune_buckets
    from hyperspace_tpu.plan.nodes import Filter

    scan, table = _bucketed_source(tmp_path)
    # IN list prunes to <= 3 buckets.
    allowed = _prune_buckets(col("k").isin(7, 8, 9), scan)
    assert allowed is not None and 1 <= len(allowed) <= 3
    k = table.column("k").to_numpy()
    phys = plan_physical(Filter(col("k").isin(7, 8, 9), scan))
    got = np.sort(np.asarray(phys.execute().column("v").data))
    expected = np.sort(table.column("v").to_numpy()[np.isin(k, [7, 8, 9])])
    assert (got == expected).all()

    # Range predicates and disjunctions must NOT prune.
    assert _prune_buckets(col("k") > lit(5), scan) is None
    assert _prune_buckets((col("k") == lit(1)) | (col("k") == lit(2)),
                          scan) is None
    # Conjunct with extra terms still prunes on the key equality.
    assert _prune_buckets((col("k") == lit(1)) & (col("v") > lit(10)),
                          scan) is not None


def test_bucket_pruning_string_key(session, tmp_path):
    from hyperspace_tpu.engine.physical import plan_physical
    from hyperspace_tpu.plan.nodes import Filter

    scan, table = _bucketed_source(tmp_path, with_strings=True)
    phys = plan_physical(Filter(col("s") == lit("name_13"), scan))
    scans = [n for n in phys.collect() if isinstance(n, ScanExec)]
    assert scans[0].allowed_buckets is not None
    got = np.sort(np.asarray(phys.execute().column("v").data))
    s = np.array(table.column("s").to_pylist())
    expected = np.sort(table.column("v").to_numpy()[s == "name_13"])
    assert (got == expected).all()


def test_bucket_pruning_e2e_filter_rule(tmp_path):
    """FilterIndexRule swap + pruning end to end: results equal rules-off."""
    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.physical import ScanExec as SE

    conf = HyperspaceConf({"hyperspace.warehouse.dir": str(tmp_path / "wh")})
    sess = HyperspaceSession(conf)
    hs = Hyperspace(sess)
    rng = np.random.default_rng(11)
    src = tmp_path / "src"
    src.mkdir()
    table = pa.table({"k": rng.integers(0, 100, 3000).astype(np.int64),
                      "x": np.arange(3000, dtype=np.int64)})
    pq.write_table(table, str(src / "part-0.parquet"))
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("pidx", ["k"], ["x"]))

    q = lambda: df.filter(col("k") == lit(17)).select("x")
    sess.enable_hyperspace()
    phys = q().explain_plans()[2]
    scans = [n for n in phys.collect() if isinstance(n, SE)]
    assert any(s.allowed_buckets is not None for s in scans)
    with_idx = q().collect().to_pandas().sort_values("x").reset_index(drop=True)
    sess.disable_hyperspace()
    without = q().collect().to_pandas().sort_values("x").reset_index(drop=True)
    assert with_idx.equals(without)


# ---------------------------------------------------------------------------
# Real ExchangeExec (hash repartition)
# ---------------------------------------------------------------------------


def test_exchange_materializes_hash_partitioning(tmp_path):
    """Exchange output must be grouped by THE hash identity's partition id
    (so it matches index bucket layouts), on both lanes."""
    from hyperspace_tpu.engine.physical import ExchangeExec, ScanExec
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.host_hash import host_bucket_ids
    from hyperspace_tpu.plan.nodes import Scan
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(2)
    table = pa.table({"k": rng.integers(0, 100, 2000).astype(np.int64),
                      "v": np.arange(2000, dtype=np.int64)})
    src = tmp_path / "x"
    src.mkdir()
    pq.write_table(table, str(src / "p.parquet"))
    scan = Scan([str(src)], Schema.from_arrow(table.schema))

    class _Lane(ScanExec):
        def __init__(self, scan, device):
            super().__init__(scan, ["k", "v"])
            self._device = device

        def execute(self, bucket=None):
            return columnar.from_arrow(
                pq.read_table(str(src / "p.parquet")), self.out_schema,
                device=self._device)

    for device in (False, True):
        ex = ExchangeExec(["k"], 16, _Lane(scan, device))
        out, lengths = ex.execute_partitioned()
        k = np.asarray(out.column("k").data)
        expected_ids = host_bucket_ids([k], ["int64"], 16)
        assert (np.diff(expected_ids) >= 0).all(), f"device={device}"
        assert lengths.sum() == 2000
        bounds = np.concatenate([[0], np.cumsum(lengths)])
        for b in range(16):
            seg = expected_ids[bounds[b]:bounds[b + 1]]
            assert (seg == b).all()
        # Multiset of values preserved.
        assert sorted(np.asarray(out.column("v").data).tolist()) == \
            sorted(table.column("v").to_pylist())


def test_unindexed_device_join_via_partitioned_exchange(session, tmp_path):
    """Device-lane unindexed join runs the co-partitioned path and matches
    the pandas result."""
    import pandas as pd
    rng = np.random.default_rng(4)
    lt = pa.table({"k": rng.integers(0, 500, 5000).astype(np.int64),
                   "x": np.arange(5000, dtype=np.int64)})
    rt = pa.table({"k": rng.integers(0, 500, 800).astype(np.int64),
                   "y": np.arange(800, dtype=np.int64)})
    lp, rp = tmp_path / "l", tmp_path / "r"
    lp.mkdir(); rp.mkdir()
    pq.write_table(lt, str(lp / "p.parquet"))
    pq.write_table(rt, str(rp / "p.parquet"))
    session.conf.set("spark.hyperspace.execution.min.device.rows", "0")
    try:
        ldf = session.read_parquet(str(lp))
        rdf = session.read_parquet(str(rp))
        got = (ldf.join(rdf, on="k").select("x", "y").collect().to_pandas()
               .sort_values(["x", "y"]).reset_index(drop=True))
    finally:
        session.conf.unset("spark.hyperspace.execution.min.device.rows")
    want = (lt.to_pandas().merge(rt.to_pandas(), on="k")[["x", "y"]]
            .sort_values(["x", "y"]).reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want)


def test_cross_dtype_key_join_correct_on_device(session, tmp_path):
    """int32 x int64 join keys must not take the co-partitioned Exchange
    branch (each side would hash with its own lane decomposition); the
    general promoting path must return the correct matches."""
    import pandas as pd
    lt = pa.table({"k": pa.array(np.arange(100, dtype=np.int32)),
                   "x": np.arange(100, dtype=np.int64)})
    rt = pa.table({"k": pa.array(np.arange(50, dtype=np.int64)),
                   "y": np.arange(50, dtype=np.int64)})
    lp, rp = tmp_path / "cl", tmp_path / "cr"
    lp.mkdir(); rp.mkdir()
    pq.write_table(lt, str(lp / "p.parquet"))
    pq.write_table(rt, str(rp / "p.parquet"))
    session.conf.set("spark.hyperspace.execution.min.device.rows", "0")
    try:
        got = (session.read_parquet(str(lp))
               .join(session.read_parquet(str(rp)), on="k")
               .select("x", "y").collect().to_pandas()
               .sort_values(["x", "y"]).reset_index(drop=True))
    finally:
        session.conf.unset("spark.hyperspace.execution.min.device.rows")
    assert len(got) == 50
    assert (got.x == got.y).all()


def test_trace_dir_captures_profile(session, sample_parquet, tmp_path):
    """hyperspace.trace.dir: one XLA profiler capture per executed query."""
    import glob
    import os
    df = session.read_parquet(sample_parquet)
    trace_root = str(tmp_path / "traces")
    session.conf.set("spark.hyperspace.trace.dir", trace_root)
    try:
        df.filter(col("clicks") > lit(1)).select("id").collect()
    finally:
        session.conf.unset("spark.hyperspace.trace.dir")
    captures = glob.glob(os.path.join(trace_root, "query-*", "**", "*"),
                         recursive=True)
    assert captures, "no profiler artifacts written"


def test_mismatched_bucket_counts_rebucket_one_side(tmp_path):
    """Index pair with different bucket counts (the ranker's fallback):
    the planner re-buckets ONLY the coarser side through Exchange and
    runs the bucketed SMJ at the finer count; results equal rules-off."""
    import pandas as pd
    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.physical import (ExchangeExec,
                                                SortMergeJoinExec)

    conf = HyperspaceConf({"hyperspace.warehouse.dir": str(tmp_path / "wh")})
    sess = HyperspaceSession(conf)
    hs = Hyperspace(sess)
    rng = np.random.default_rng(19)
    lt = pa.table({"k": rng.integers(0, 300, 4000).astype(np.int64),
                   "x": np.arange(4000, dtype=np.int64)})
    rt = pa.table({"k": rng.integers(0, 300, 900).astype(np.int64),
                   "y": np.arange(900, dtype=np.int64)})
    lp, rp = tmp_path / "l", tmp_path / "r"
    lp.mkdir(); rp.mkdir()
    pq.write_table(lt, str(lp / "p.parquet"))
    pq.write_table(rt, str(rp / "p.parquet"))
    ldf, rdf = sess.read_parquet(str(lp)), sess.read_parquet(str(rp))
    sess.conf.set("spark.hyperspace.index.num.buckets", "16")
    hs.create_index(ldf, IndexConfig("ml", ["k"], ["x"]))
    sess.conf.set("spark.hyperspace.index.num.buckets", "4")
    hs.create_index(rdf, IndexConfig("mr", ["k"], ["y"]))

    q = lambda: (ldf.select("k", "x").join(rdf.select("k", "y"), on="k")
                 .select("x", "y"))
    sess.enable_hyperspace()
    phys = q().explain_plans()[2]
    smjs = [n for n in phys.collect() if isinstance(n, SortMergeJoinExec)]
    assert smjs and smjs[0].bucketed and smjs[0].num_buckets == 16
    exchanges = [n for n in phys.collect() if isinstance(n, ExchangeExec)]
    assert len(exchanges) == 1 and exchanges[0].num_partitions == 16
    # The exchanged side is the coarser (right) index.
    assert any("mr" in p for s in exchanges[0].collect()
               if hasattr(s, "scan") for p in s.scan.root_paths)

    got = (q().collect().to_pandas().sort_values(["x", "y"])
           .reset_index(drop=True))
    sess.disable_hyperspace()
    want = (q().collect().to_pandas().sort_values(["x", "y"])
            .reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want)


def test_cross_dtype_indexed_join_takes_general_path(tmp_path):
    """Indexes bucketed over different key dtypes (int64 vs int32) must
    NOT co-partition — their on-disk layouts hash with different lane
    structures. The planner must fall to the promoting general path and
    return correct results (with equal AND mismatched bucket counts)."""
    import pandas as pd
    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.physical import SortMergeJoinExec

    for lbuckets, rbuckets in ((8, 8), (16, 4)):
        conf = HyperspaceConf({
            "hyperspace.warehouse.dir": str(tmp_path / f"wh{lbuckets}"
                                            / str(rbuckets)),
            # The small right side would broadcast; this test exercises
            # the promoting GENERAL path (reference analog: E2E pins
            # autoBroadcastJoinThreshold=-1).
            "hyperspace.broadcast.threshold": -1})
        sess = HyperspaceSession(conf)
        hs = Hyperspace(sess)
        rng = np.random.default_rng(7)
        lt = pa.table({"k": rng.integers(0, 200, 3000).astype(np.int64),
                       "x": np.arange(3000, dtype=np.int64)})
        rt = pa.table({"k": pa.array(rng.integers(0, 200, 500)
                                     .astype(np.int32)),
                       "y": np.arange(500, dtype=np.int64)})
        lp = tmp_path / f"l{lbuckets}_{rbuckets}"
        rp = tmp_path / f"r{lbuckets}_{rbuckets}"
        lp.mkdir(); rp.mkdir()
        pq.write_table(lt, str(lp / "p.parquet"))
        pq.write_table(rt, str(rp / "p.parquet"))
        ldf, rdf = sess.read_parquet(str(lp)), sess.read_parquet(str(rp))
        sess.conf.set("spark.hyperspace.index.num.buckets", str(lbuckets))
        hs.create_index(ldf, IndexConfig("xl", ["k"], ["x"]))
        sess.conf.set("spark.hyperspace.index.num.buckets", str(rbuckets))
        hs.create_index(rdf, IndexConfig("xr", ["k"], ["y"]))

        q = lambda: (ldf.select("k", "x").join(rdf.select("k", "y"), on="k")
                     .select("x", "y"))
        sess.enable_hyperspace()
        phys = q().explain_plans()[2]
        smjs = [n for n in phys.collect()
                if isinstance(n, SortMergeJoinExec)]
        assert smjs and not smjs[0].bucketed, (lbuckets, rbuckets)
        got = (q().collect().to_pandas().sort_values(["x", "y"])
               .reset_index(drop=True))
        sess.disable_hyperspace()
        want = (q().collect().to_pandas().sort_values(["x", "y"])
                .reset_index(drop=True))
        pd.testing.assert_frame_equal(got, want)
        assert len(got) > 0


def test_common_subplan_reuse(tmp_path):
    """An identical subtree referenced twice (q64-style self-join of an
    aggregate) compiles to ONE shared ReusedExec and executes once."""
    import pandas as pd
    from hyperspace_tpu.engine.executor import compile_plan
    from hyperspace_tpu.engine.physical import ReusedExec

    sess = HyperspaceSession(HyperspaceConf())
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 50, 2000).astype(np.int64),
                  "v": rng.random(2000)})
    src = tmp_path / "s"
    src.mkdir()
    pq.write_table(t, str(src / "p.parquet"))
    df = sess.read_parquet(str(src))
    agg = df.group_by("k").agg(("sum", "v", "sv"), ("count", "*", "cnt"))
    joined = agg.join(agg, on="k").select("k", "sv", "sv_r", "cnt", "cnt_r")

    phys = compile_plan(joined.plan, conf=sess.conf)
    reused = [n for n in phys.collect() if isinstance(n, ReusedExec)]
    assert reused, "no shared subplan detected"
    # Both join sides route through the SAME instance.
    ids = {id(n) for n in reused
           if any("Aggregate" in c.simple_string() for c in n.collect())}
    assert len(ids) == 1, f"aggregate subplan not shared: {len(ids)}"

    got = joined.collect().to_pandas().sort_values("k").reset_index(drop=True)
    ref = (t.to_pandas().groupby("k")
           .agg(sv=("v", "sum"), cnt=("k", "size")).reset_index())
    assert np.allclose(got.sv, ref.sv) and np.allclose(got.sv_r, ref.sv)
    assert (got.cnt.to_numpy() == ref.cnt.to_numpy()).all()
    assert (got.cnt_r.to_numpy() == ref.cnt.to_numpy()).all()


def test_descending_sort_both_lanes(session, tmp_path):
    """df.sort("-col"): descending with nulls LAST (Spark's desc default),
    identical on host and device lanes, mixed asc/desc."""
    t = pa.table({
        "a": pa.array([3, 1, None, 2, 1], type=pa.int64()),
        "b": pa.array([1.5, None, 2.5, 0.5, 3.5], type=pa.float64()),
        "s": pa.array(["x", "b", "m", "b", None]),
    })
    src = tmp_path / "ds"
    src.mkdir()
    pq.write_table(t, str(src / "p.parquet"))
    pdf = t.to_pandas()

    for min_dev in ("1000000", "0"):  # host lane, then device lane
        session.conf.set("spark.hyperspace.execution.min.device.rows",
                         min_dev)
        try:
            df = session.read_parquet(str(src))
            got = df.sort("-a", "b").collect().to_pandas()
            want = pdf.sort_values(["a", "b"],
                                   ascending=[False, True],
                                   na_position="last").reset_index(drop=True)
            # pandas sorts nulls-last on BOTH here; our asc 'b' is
            # nulls-first — compare on 'a' order (nan-aware).
            assert np.array_equal(got.a.to_numpy(), want.a.to_numpy(),
                                  equal_nan=True), min_dev
            got2 = df.sort("-s").collect().to_pandas()
            vals = got2.s.tolist()
            non_null = [v for v in vals if isinstance(v, str)]
            assert non_null == sorted(non_null, reverse=True)
            assert not isinstance(vals[-1], str)  # nulls last on desc
            got3 = df.sort("a").collect().to_pandas()
            assert got3.a.tolist()[0] is None or np.isnan(got3.a[0])  # nulls first on asc
        finally:
            session.conf.unset("spark.hyperspace.execution.min.device.rows")


def test_bucketed_join_key_order_insensitive(tmp_path):
    """A join condition written in a different conjunct order than the
    index's bucket columns must still take the shuffle-free bucketed
    path (no Exchange in the physical plan)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import (Hyperspace, HyperspaceConf,
                                HyperspaceSession, IndexConfig)
    from hyperspace_tpu.plan.expr import col

    rng = np.random.default_rng(9)
    n = 2000
    lt = pa.table({"a": rng.integers(0, 50, n), "b": rng.integers(0, 7, n),
                   "x": rng.random(n)})
    rt = pa.table({"a": rng.integers(0, 50, 400),
                   "b": rng.integers(0, 7, 400),
                   "y": rng.random(400)})
    lp, rp = str(tmp_path / "lt"), str(tmp_path / "rt")
    for p, t in ((lp, lt), (rp, rt)):
        import os
        os.makedirs(p)
        pq.write_table(t, p + "/p.parquet")
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4}))
    hs = Hyperspace(sess)
    hs.create_index(sess.read_parquet(lp), IndexConfig("l", ["a", "b"], ["x"]))
    hs.create_index(sess.read_parquet(rp), IndexConfig("r", ["a", "b"], ["y"]))
    sess.enable_hyperspace()
    l, r = sess.read_parquet(lp), sess.read_parquet(rp)
    # condition deliberately ordered (b, a) against the (a, b) layout
    q = l.join(r, on=(col("b") == col("b")) & (col("a") == col("a")))
    import pandas as pd
    _, _, physical = q.explain_plans()
    ops = [n.name for n in physical.collect()]
    assert "Exchange" not in ops, ops
    got = q.to_pandas().sort_values(["a", "b", "x", "y"]).reset_index(drop=True)
    lpd, rpd = lt.to_pandas(), rt.to_pandas()
    exp = (lpd.merge(rpd, on=["a", "b"])
           .sort_values(["a", "b", "x", "y"]).reset_index(drop=True))
    pd.testing.assert_frame_equal(got[exp.columns], exp, check_dtype=False)
