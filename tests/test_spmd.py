"""Born-sharded SPMD execution (`parallel/spmd.py`): bit-identity with
the single-device operators at 1/2/4/8 virtual devices, the in-program
mismatched-bucket repartition, static-capacity overflow recovery, the
per-device segment-cache read path, and the device-resident stage-flow
telemetry contract (zero D2H between stages of a warm two-stage SMJ)."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.io import columnar
from hyperspace_tpu.parallel import spmd
from hyperspace_tpu.parallel.build import distributed_build
from hyperspace_tpu.parallel.mesh import (bucket_owner, bucket_ranges,
                                          make_mesh, shard_row_segments)


def make_batch(n, seed=0, keyspace=None):
    rng = np.random.default_rng(seed)
    return columnar.from_arrow(pa.table({
        "k": rng.integers(0, keyspace or max(4, n // 8),
                          n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }))


def sharded_pair(n=1200, m=500, buckets=16, n_dev=8, seed=1,
                 keyspace=None):
    mesh = make_mesh(n_dev)
    left = make_batch(n, seed=seed, keyspace=keyspace)
    right = make_batch(m, seed=seed + 1, keyspace=keyspace)
    lb, ll = distributed_build(left, ["k"], buckets, mesh)
    rb, rl = distributed_build(right, ["k"], buckets, mesh)
    return (mesh, spmd.shard_bucket_ordered(lb, ll, mesh),
            spmd.shard_bucket_ordered(rb, rl, mesh), lb, rb, ll, rl)


def pairs_frame(lsh, rsh, li, ri):
    lk = np.asarray(lsh.batch.column("k").data)
    rk = np.asarray(rsh.batch.column("k").data)
    li, ri = np.asarray(li), np.asarray(ri)
    return pd.DataFrame({
        "lk": np.where(li >= 0, lk[np.clip(li, 0, None)], -1),
        "rk": np.where(ri >= 0, rk[np.clip(ri, 0, None)], -1),
    }).sort_values(["lk", "rk"]).reset_index(drop=True)


def oracle_frame(lb, rb, how):
    lpd = pd.DataFrame({"lk": np.asarray(lb.column("k").data)})
    rpd = pd.DataFrame({"rk": np.asarray(rb.column("k").data)})
    merged = lpd.assign(j=lpd.lk).merge(
        rpd.assign(j=rpd.rk), on="j",
        how={"inner": "inner", "left_outer": "left",
             "full_outer": "outer"}[how]).drop(columns="j")
    return (merged.fillna(-1).astype(np.int64)
            .sort_values(["lk", "rk"]).reset_index(drop=True))


def test_bucket_range_map_is_exact_inverse():
    for B, n in ((16, 8), (64, 8), (5, 2), (7, 3), (8, 1)):
        ranges = bucket_ranges(B, n)
        assert ranges[0][0] == 0 and ranges[-1][1] == B
        for s, (lo, hi) in enumerate(ranges):
            for b in range(lo, hi):
                assert bucket_owner(b, B, n) == s
        # contiguous, non-overlapping
        for s in range(1, n):
            assert ranges[s][0] == ranges[s - 1][1]


def test_shard_row_segments_cover_rows():
    lengths = np.asarray([3, 0, 5, 2, 7, 1, 0, 4], dtype=np.int64)
    segs = shard_row_segments(lengths, 4)
    assert segs[0][0] == 0 and segs[-1][1] == int(lengths.sum())
    for s in range(1, 4):
        assert segs[s][0] == segs[s - 1][1]


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_join_bit_identity_across_device_counts(n_dev):
    """SMJ over the born-sharded layout equals the single-chip bucketed
    join for every pair type, at every mesh size."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_join_indices

    mesh, lsh, rsh, lb, rb, ll, rl = sharded_pair(n_dev=n_dev)
    for how in ("inner", "left_outer", "full_outer"):
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                           how=how)
        got = pairs_frame(lsh, rsh, li, ri)
        pd.testing.assert_frame_equal(got, oracle_frame(lb, rb, how))
    # membership
    lk = np.asarray(lb.column("k").data)
    member = np.isin(lk, np.asarray(rb.column("k").data))
    for anti in (False, True):
        idx = np.asarray(spmd.sharded_semi_anti_indices(
            lsh, rsh, ["k"], ["k"], anti=anti))
        keys = np.sort(np.asarray(lsh.batch.column("k").data)[idx])
        exp = np.sort(lk[~member if anti else member])
        assert (keys == exp).all(), f"anti={anti}"


@pytest.mark.parametrize("n_dev", [2, 8])
def test_filter_and_aggregate_bit_identity(n_dev):
    from hyperspace_tpu.engine.compiler import apply_filter
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.expr import col, lit
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    mesh = make_mesh(n_dev)
    batch = make_batch(2000, seed=7)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    sh = spmd.shard_bucket_ordered(built, lengths, mesh)

    pred = col("k") < lit(60)
    got = columnar.to_arrow(spmd.sharded_filter(sh, pred)).to_pandas()
    want = columnar.to_arrow(apply_filter(built, pred)).to_pandas()
    cols = list(got.columns)
    pd.testing.assert_frame_equal(
        got.sort_values(cols).reset_index(drop=True),
        want.sort_values(cols).reset_index(drop=True))

    schema = Schema.from_arrow(pa.table(
        {"k": np.zeros(1, np.int64), "v": np.zeros(1)}).schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("sum", "v", "sv"),
             AggSpec("min", "v", "mn"), AggSpec("max", "v", "mx")]
    out_schema = Aggregate(["k"], specs, Scan(["/nx"], schema)).schema
    agg = spmd.sharded_group_aggregate(sh, ["k"], specs, out_schema)
    single = group_aggregate(built, ["k"], specs, out_schema)
    g = columnar.to_arrow(agg).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    s = columnar.to_arrow(single).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(g, s, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_mismatched_bucket_counts_repartition_in_program():
    """The ranker's fallback: the right side arrives at HALF the bucket
    count and re-buckets over ICI inside the jitted program; results
    equal the equal-bucket join."""
    mesh = make_mesh(8)
    left = make_batch(900, seed=3)
    right = make_batch(400, seed=4)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb8, rl8 = distributed_build(right, ["k"], 8, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    rsh8 = spmd.shard_bucket_ordered(rb8, rl8, mesh)
    assert rsh8.num_buckets != lsh.num_buckets
    for how in ("inner", "left_outer"):
        li, ri = spmd.sharded_join_indices(lsh, rsh8, ["k"], ["k"],
                                           how=how)
        got = pairs_frame(lsh, rsh8, li, ri)
        pd.testing.assert_frame_equal(got, oracle_frame(lb, rb8, how))
    idx = np.asarray(spmd.sharded_semi_anti_indices(
        lsh, rsh8, ["k"], ["k"], anti=True))
    lk = np.asarray(lb.column("k").data)
    member = np.isin(lk, np.asarray(rb8.column("k").data))
    assert len(idx) == int((~member).sum())


def test_skewed_overflow_retries_exactly():
    """A hot key whose match expansion blows past the first-attempt
    static capacity must be recovered EXACTLY by the on-device overflow
    detection + doubled retry — never silently truncated."""
    mesh = make_mesh(4)
    n = 2000
    rng = np.random.default_rng(9)
    hot = np.where(rng.random(n) < 0.7, 7, rng.integers(0, 64, n))
    left = columnar.from_arrow(pa.table({
        "k": hot.astype(np.int64), "v": rng.random(n)}))
    right = columnar.from_arrow(pa.table({
        "k": np.where(rng.random(300) < 0.5, 7,
                      rng.integers(0, 64, 300)).astype(np.int64),
        "v": rng.random(300)}))
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    rsh = spmd.shard_bucket_ordered(rb, rl, mesh)
    spmd._CAP_MEMO.clear()
    before = telemetry.get_registry().counters_dict().get(
        "mesh.spmd.overflow_retries", 0)
    # Tiny first-attempt capacity forces the overflow path.
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                       capacity_factor=0.01)
    after = telemetry.get_registry().counters_dict().get(
        "mesh.spmd.overflow_retries", 0)
    assert after > before, "overflow retry never fired"
    got = pairs_frame(lsh, rsh, li, ri)
    pd.testing.assert_frame_equal(got, oracle_frame(lb, rb, "inner"))
    spmd._CAP_MEMO.clear()


def test_pad_blowup_guard():
    lengths = np.zeros(16, dtype=np.int64)
    lengths[3] = 1 << 17  # one hot bucket
    lengths[4:] = 1
    assert spmd.pad_blowup(lengths, 8)
    even = np.full(16, 1 << 13, dtype=np.int64)
    assert not spmd.pad_blowup(even, 8)


def test_warm_two_stage_smj_zero_d2h_between_stages():
    """Device-resident stage flow: join -> in-program repartition ->
    second join -> SPMD aggregate, with ZERO D2H link crossings across
    the whole pipeline (the engine-counted `link.d2h.*` series stays
    flat until result materialization)."""
    from hyperspace_tpu.ops.bucketed_join import assemble_join_output
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    mesh, lsh, rsh, lb, rb, ll, rl = sharded_pair(n=1500, m=700,
                                                  seed=21)

    def pipeline():
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
        joined = assemble_join_output(lsh.batch, rsh.batch, li, ri,
                                      how="inner")
        stage2 = spmd.repartition_sharded(joined, ["k"], 16, mesh)
        li2, ri2 = spmd.sharded_join_indices(stage2, rsh, ["k"], ["k"])
        j2 = assemble_join_output(stage2.batch, rsh.batch, li2, ri2,
                                  how="inner",
                                  columns=["k", "v", "v_r"])
        stage3 = spmd.repartition_sharded(j2, ["k"], 16, mesh)
        schema = Schema.from_arrow(pa.table(
            {"k": np.zeros(1, np.int64), "v": np.zeros(1),
             "v_r": np.zeros(1)}).schema)
        specs = [AggSpec("count", "*", "cnt"),
                 AggSpec("sum", "v", "sv")]
        out_schema = Aggregate(["k"], specs,
                               Scan(["/nx"], schema)).schema
        return spmd.sharded_group_aggregate(stage3, ["k"], specs,
                                            out_schema)

    cold = columnar.to_arrow(pipeline()).to_pandas()
    reg = telemetry.get_registry()
    before = dict(reg.counters_dict())
    warm_out = pipeline()  # stop BEFORE materialization
    after = dict(reg.counters_dict())
    assert after.get("link.d2h.chunks", 0) == \
        before.get("link.d2h.chunks", 0), "a stage crossed D2H"
    assert after.get("link.d2h.bytes", 0) == \
        before.get("link.d2h.bytes", 0)
    warm = columnar.to_arrow(warm_out).to_pandas()
    pd.testing.assert_frame_equal(
        cold.sort_values("k").reset_index(drop=True),
        warm.sort_values("k").reset_index(drop=True))


@pytest.fixture
def born_sharded_env(tmp_path, sample_parquet):
    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace

    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 8,
        "hyperspace.distribution.enabled": "true",
        "hyperspace.broadcast.threshold": -1,
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def test_born_sharded_build_layout_and_log_entry(born_sharded_env):
    """The mesh build writes per-device parquet shards (contiguous
    bucket ranges, shard-tagged filenames), the `_shard_layout.json`
    record, and the log entry carries the layout."""
    session, hs, src = born_sharded_env
    from hyperspace_tpu.index.index_config import IndexConfig
    from hyperspace_tpu.io.builder import read_shard_layout

    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("born", ["clicks"], ["id"]))
    vdir = os.path.join(session.conf.system_path, "born", "v__=0")
    files = [os.path.basename(f)
             for f in glob.glob(os.path.join(vdir, "part-*.parquet"))]
    assert files and all("-s0" in f for f in files), files
    layout = read_shard_layout(vdir)
    assert layout is not None and layout["numShards"] == 8
    assert layout["bucketRanges"] == [[s, s + 1] for s in range(8)]
    entry = next(e for e in hs._manager.get_indexes()
                 if e.name == "born")
    assert entry.shard_layout == layout
    # Shard tag s matches the contiguous-range owner of the bucket id.
    from hyperspace_tpu.io.parquet import bucket_of_file
    for f in files:
        b = bucket_of_file(f)
        s = int(f.split("-s")[1][:2])
        assert bucket_owner(b, 8, 8) == s, f


def test_engine_smj_spmd_lane_and_warm_link_free(born_sharded_env):
    """The planner-selected bucketed SMJ rides the SPMD lane (counter
    pinned), warm repeats read per-device from the segment cache with
    ZERO H2D chunks, and results equal rules-off."""
    session, hs, src = born_sharded_env
    from hyperspace_tpu.index.index_config import IndexConfig
    from hyperspace_tpu.io import segcache

    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("sjl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("sjr", ["imprs"], ["score"]))
    left = df.select("imprs", "id", "clicks")
    right = df.select("imprs", "score")
    query = left.join(right, on="imprs")
    sort_cols = ["imprs", "id", "score"]

    session.disable_hyperspace()
    plain = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    session.enable_hyperspace()
    segcache.clear()
    reg = telemetry.get_registry()

    def counters():
        c = reg.counters_dict()
        return {k: c.get(k, 0) for k in
                ("mesh.spmd.join_execs", "link.h2d.chunks",
                 "cache.segments.hits")}

    c0 = counters()
    cold = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    c1 = counters()
    warm = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    c2 = counters()
    session.disable_hyperspace()

    assert c1["mesh.spmd.join_execs"] > c0["mesh.spmd.join_execs"], \
        "SPMD lane not taken"
    assert c2["link.h2d.chunks"] == c1["link.h2d.chunks"], \
        "warm per-device read crossed the link"
    assert c2["cache.segments.hits"] > c1["cache.segments.hits"]
    pd.testing.assert_frame_equal(plain, cold)
    pd.testing.assert_frame_equal(plain, warm)


def test_engine_string_smj_spmd_lane_fallback_free(born_sharded_env):
    """A STRING-keyed planner-selected SMJ runs the SPMD lane end to
    end — no per-query placement, no host fallback (`spmd.fallbacks`
    delta is 0), warm repeats link-free with remap tables served from
    the segment cache — and equals rules-off bit for bit."""
    session, hs, src = born_sharded_env
    from hyperspace_tpu.index.index_config import IndexConfig
    from hyperspace_tpu.io import segcache

    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("strl", ["query"],
                                    ["id", "clicks"]))
    hs.create_index(df, IndexConfig("strr", ["query"], ["score"]))
    left = df.select("query", "id", "clicks")
    right = df.select("query", "score")
    query = left.join(right, on="query")
    sort_cols = ["query", "id", "score"]

    session.disable_hyperspace()
    plain = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    session.enable_hyperspace()
    segcache.clear()
    reg = telemetry.get_registry()

    def counters():
        c = reg.counters_dict()
        return {k: c.get(k, 0) for k in
                ("mesh.spmd.join_execs", "spmd.fallbacks",
                 "link.h2d.chunks", "spmd.strings.remap_cache_hits")}

    c0 = counters()
    cold = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    c1 = counters()
    warm = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    c2 = counters()
    session.disable_hyperspace()

    assert c1["mesh.spmd.join_execs"] > c0["mesh.spmd.join_execs"], \
        "string SMJ did not take the SPMD lane"
    assert c2["spmd.fallbacks"] == c0["spmd.fallbacks"], \
        "string join fell off the SPMD lane"
    assert c2["link.h2d.chunks"] == c1["link.h2d.chunks"], \
        "warm string join crossed the link"
    assert c2["spmd.strings.remap_cache_hits"] > \
        c1["spmd.strings.remap_cache_hits"]
    pd.testing.assert_frame_equal(plain, cold)
    pd.testing.assert_frame_equal(plain, warm)


def test_spmd_disabled_falls_back_to_single_chip(born_sharded_env):
    """`spark.hyperspace.distribution.spmd.enabled=false` is the
    operational escape hatch: with the legacy mesh path deleted, the
    bucketed SMJ runs single-chip, identical results."""
    session, hs, src = born_sharded_env
    from hyperspace_tpu.index.index_config import IndexConfig

    session.conf.set("spark.hyperspace.distribution.spmd.enabled",
                     "false")
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("nsl", ["imprs"], ["id"]))
    hs.create_index(df, IndexConfig("nsr", ["imprs"], ["score"]))
    query = df.select("imprs", "id").join(df.select("imprs", "score"),
                                          on="imprs")
    session.disable_hyperspace()
    plain = query.to_pandas().sort_values(["imprs", "id", "score"]) \
        .reset_index(drop=True)
    session.enable_hyperspace()
    reg = telemetry.get_registry()
    before = reg.counters_dict().get("mesh.spmd.join_execs", 0)
    indexed = query.to_pandas().sort_values(["imprs", "id", "score"]) \
        .reset_index(drop=True)
    session.disable_hyperspace()
    assert reg.counters_dict().get("mesh.spmd.join_execs", 0) == before
    pd.testing.assert_frame_equal(plain, indexed)


def make_string_batch(n, seed=0, keyspace=80, null_frac=0.0,
                      prefix="key"):
    """String-keyed batch; `null_frac` > 0 inserts NULL keys,
    `keyspace` controls dictionary cardinality."""
    rng = np.random.default_rng(seed)
    keys = np.array([f"{prefix}{int(x):07d}"
                     for x in rng.integers(0, keyspace, n)])
    if null_frac:
        keys = np.where(rng.random(n) < null_frac, None, keys)
    return columnar.from_arrow(pa.table({
        "k": pa.array(list(keys)),
        "v": rng.random(n).astype(np.float64),
    }))


def string_sharded_pair(n_dev, n=900, m=400, buckets=16, seed=5,
                        keyspace=80, null_frac=0.0):
    mesh = make_mesh(n_dev)
    left = make_string_batch(n, seed=seed, keyspace=keyspace,
                             null_frac=null_frac)
    right = make_string_batch(m, seed=seed + 1, keyspace=keyspace)
    lb, ll = distributed_build(left, ["k"], buckets, mesh)
    rb, rl = distributed_build(right, ["k"], buckets, mesh)
    return (mesh, spmd.shard_bucket_ordered(lb, ll, mesh),
            spmd.shard_bucket_ordered(rb, rl, mesh), lb, rb, ll, rl)


def _string_values(batch, name="k"):
    col = batch.column(name)
    vals = np.asarray(col.dictionary)[np.asarray(col.data)]
    ok = (np.asarray(col.validity) if col.validity is not None
          else np.ones(len(vals), bool))
    return vals, ok


def string_pairs_frame(lsh, rsh, li, ri):
    lv, lo = _string_values(lsh.batch)
    rv, ro = _string_values(rsh.batch)
    li, ri = np.asarray(li), np.asarray(ri)
    lk = np.where(li >= 0,
                  np.where(lo[np.clip(li, 0, None)],
                           lv[np.clip(li, 0, None)], "~null"), "~none")
    rk = np.where(ri >= 0,
                  np.where(ro[np.clip(ri, 0, None)],
                           rv[np.clip(ri, 0, None)], "~null"), "~none")
    return pd.DataFrame({"lk": lk, "rk": rk}) \
        .sort_values(["lk", "rk"]).reset_index(drop=True)


def string_oracle_frame(lb, rb, how):
    lv, lo = _string_values(lb)
    rv, ro = _string_values(rb)
    lpd = pd.DataFrame({
        "lk": np.where(lo, lv, "~null"),
        "j": np.where(lo, lv, [f"__null{i}" for i in range(len(lv))])})
    rpd = pd.DataFrame({
        "rk": np.where(ro, rv, "~null"),
        "j": np.where(ro, rv,
                      [f"__rnull{i}" for i in range(len(rv))])})
    merged = lpd.merge(rpd, on="j", how={
        "inner": "inner", "left_outer": "left",
        "full_outer": "outer"}[how]).drop(columns="j")
    merged["lk"] = merged["lk"].fillna("~none")
    merged["rk"] = merged["rk"].fillna(
        "~none" if how == "left_outer" else "~none")
    # left_outer/full_outer: unmatched rows carry "~none" on the
    # missing side, EXCEPT null-key left rows which legitimately pair
    # with right "~none" too — the spmd frame reports unmatched as
    # "~none", so align: any row whose rk is NaN means no match.
    return merged.sort_values(["lk", "rk"]).reset_index(drop=True)


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_string_join_bit_identity_across_device_counts(n_dev):
    """String-keyed SMJ over born-sharded sides — per-range
    dictionaries unified by in-program rank remaps — equals the pandas
    oracle at every mesh size, NULL-bearing keys included."""
    mesh, lsh, rsh, lb, rb, ll, rl = string_sharded_pair(
        n_dev, null_frac=0.08)
    for how in ("inner", "left_outer", "full_outer"):
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                           how=how)
        got = string_pairs_frame(lsh, rsh, li, ri)
        want = string_oracle_frame(lb, rb, how)
        pd.testing.assert_frame_equal(got, want), how
    # membership (anti emits null-key left rows — NOT EXISTS)
    lv, lo = _string_values(lb)
    rv, _ro = _string_values(rb)
    member = np.isin(lv, rv) & lo
    for anti in (False, True):
        idx = np.asarray(spmd.sharded_semi_anti_indices(
            lsh, rsh, ["k"], ["k"], anti=anti))
        exp = int((~member).sum()) if anti else int(member.sum())
        assert len(idx) == exp, f"anti={anti}"


@pytest.mark.parametrize("n_dev", [2, 8])
def test_string_filter_and_aggregate_bit_identity(n_dev):
    """String predicate (code-space range test against the GLOBAL
    dictionary) and group-by-string aggregation over the sharded layout
    equal the single-device operators."""
    from hyperspace_tpu.engine.compiler import apply_filter
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.expr import col, lit
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    mesh = make_mesh(n_dev)
    batch = make_string_batch(1500, seed=11, keyspace=60,
                              null_frac=0.05)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    sh = spmd.shard_bucket_ordered(built, lengths, mesh)

    for pred in (col("k") < lit("key0000030"),
                 col("k") == lit("key0000007"),
                 col("k").isin("key0000001", "key0000002",
                               "no-such-key")):
        got = columnar.to_arrow(spmd.sharded_filter(sh, pred)) \
            .to_pandas()
        want = columnar.to_arrow(apply_filter(built, pred)).to_pandas()
        cols = list(got.columns)
        pd.testing.assert_frame_equal(
            got.sort_values(cols).reset_index(drop=True),
            want.sort_values(cols).reset_index(drop=True))

    schema = Schema.from_arrow(pa.table(
        {"k": np.array(["x"]), "v": np.zeros(1)}).schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("sum", "v", "sv"),
             AggSpec("min", "v", "mn")]
    out_schema = Aggregate(["k"], specs, Scan(["/nx"], schema)).schema
    agg = spmd.sharded_group_aggregate(sh, ["k"], specs, out_schema)
    single = group_aggregate(built, ["k"], specs, out_schema)
    g = columnar.to_arrow(agg).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    s = columnar.to_arrow(single).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(g, s, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_string_high_cardinality_dictionaries():
    """A dictionary with one entry per row (worst case for the remap
    tables) still joins exactly, through the in-program repartition
    path too (value-hash routing, not rank routing)."""
    mesh = make_mesh(4)
    _m, lsh, rsh, lb, rb, _ll, _rl = string_sharded_pair(
        4, n=1200, m=600, keyspace=1 << 20, seed=31)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
    got = string_pairs_frame(lsh, rsh, li, ri)
    pd.testing.assert_frame_equal(got,
                                  string_oracle_frame(lb, rb, "inner"))
    # mismatched bucket counts: right re-buckets in-program by VALUE
    # hash (the rank lanes are pair-local and must not route)
    right2 = make_string_batch(600, seed=32, keyspace=1 << 20)
    rb8, rl8 = distributed_build(right2, ["k"], 8, mesh)
    rsh8 = spmd.shard_bucket_ordered(rb8, rl8, mesh)
    li2, ri2 = spmd.sharded_join_indices(lsh, rsh8, ["k"], ["k"])
    got2 = string_pairs_frame(lsh, rsh8, li2, ri2)
    pd.testing.assert_frame_equal(got2,
                                  string_oracle_frame(lb, rb8, "inner"))


def test_string_warm_repeat_remaps_from_cache_zero_h2d(tmp_path):
    """The warm-repeat contract for strings: a second born-sharded read
    + string-keyed join serves BOTH the global dictionaries and the
    join's rank-remap tables from the segment cache — zero H2D chunks,
    `spmd.strings.remap_cache_hits` advancing, results identical."""
    from hyperspace_tpu.io import builder, parquet, segcache
    from hyperspace_tpu.io.segcache import SegmentRef
    from hyperspace_tpu.parallel.mesh import bucket_ranges

    mesh = make_mesh(4)
    left = make_string_batch(800, seed=41, keyspace=120,
                             null_frac=0.05)
    right = make_string_batch(300, seed=42, keyspace=120)
    roots = {}
    lengths_map = {}
    for tag, batch in (("l", left), ("r", right)):
        built, lengths = distributed_build(batch, ["k"], 16, mesh)
        root = str(tmp_path / tag)
        builder.write_bucket_ordered(built, lengths, 16, root,
                                     mesh=mesh)
        roots[tag] = root
        lengths_map[tag] = (lengths, built.schema)
        layout = builder.read_shard_layout(root)
        assert layout is not None and "dictionaries" in layout
        assert len(layout["dictionaries"]["k"]) == 4  # one per range

    segcache.clear()

    def read(tag):
        lengths, schema = lengths_map[tag]
        per_bucket = parquet.bucket_files(roots[tag])
        per_shard = [[f for b in range(lo, hi)
                      for f in per_bucket.get(b, [])]
                     for lo, hi in bucket_ranges(16, 4)]
        ref = SegmentRef(index_name=f"str_{tag}", index_root=roots[tag],
                         version=0, bucket="t")
        return spmd.read_sharded(per_shard, lengths,
                                 [f.name for f in schema.fields],
                                 schema, mesh, base_ref=ref)

    def join_once():
        lsh = read("l")
        rsh = read("r")
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
        return string_pairs_frame(lsh, rsh, li, ri)

    reg = telemetry.get_registry()
    cold = join_once()
    c0 = dict(reg.counters_dict())
    warm = join_once()
    c1 = dict(reg.counters_dict())
    assert c1.get("link.h2d.chunks", 0) == c0.get("link.h2d.chunks", 0), \
        "warm string read/join crossed the link"
    assert c1.get("spmd.strings.remap_cache_hits", 0) > \
        c0.get("spmd.strings.remap_cache_hits", 0), \
        "remap tables not served from the segment cache"
    pd.testing.assert_frame_equal(cold, warm)


def test_segcache_get_or_fill_invalidation():
    """Per-range entries ride the index-FSM invalidation hooks: a
    version commit under the same root drops them; the single-flight
    contract serves concurrent fills one decode."""
    import threading

    from hyperspace_tpu.io import segcache

    cache = segcache.SegmentCache(budget_bytes=1 << 30)
    ref = segcache.SegmentRef("idx", "/tmp/idx_root", 0, "mc")
    fills = []

    def fill():
        fills.append(1)
        return {"columns": {}, "rows": 1}, 1024

    key = ref.key + (("spmd", 0, 4, 4, 10),)
    results = []

    def worker():
        results.append(cache.get_or_fill(key, fill, ref=ref))

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(fills) == 1, "single-flight violated"
    assert all(r is results[0] for r in results)
    assert cache.get_or_fill(key, fill, ref=ref) is results[0]
    assert len(fills) == 1
    # FSM hook: a new committed version under the root evicts the range.
    cache.invalidate_index("/tmp/idx_root", keep_version=1)
    cache.get_or_fill(key, fill, ref=ref)
    assert len(fills) == 2


# ---------------------------------------------------------------------------
# Multi-slice (slice, device) topologies — PR 14
# ---------------------------------------------------------------------------


def topo_mesh(slices, ici):
    return make_mesh(slices * ici, dcn_size=slices if slices > 1 else None)


def test_slice_hierarchy_nests_exactly():
    """`slice_bucket_ranges` equals the union of each slice's flat shard
    ranges — the nesting identity layout v3 and replica residency rely
    on — and `slice_submesh` carves the right device rows."""
    from hyperspace_tpu.parallel.mesh import (mesh_device_list,
                                              slice_bucket_ranges,
                                              slice_submesh)

    for B, slices, ici in ((64, 2, 4), (64, 4, 2), (16, 2, 4), (7, 2, 2)):
        flat = bucket_ranges(B, slices * ici)
        for d, (lo, hi) in enumerate(slice_bucket_ranges(B, slices, ici)):
            assert lo == flat[d * ici][0]
            assert hi == flat[(d + 1) * ici - 1][1]
    mesh = topo_mesh(2, 4)
    full = mesh_device_list(mesh)
    for idx in range(2):
        sub = slice_submesh(mesh, idx)
        assert mesh_device_list(sub) == full[idx * 4:(idx + 1) * 4]


@pytest.mark.parametrize("slices,ici", [(1, 8), (2, 4), (4, 2)])
def test_multislice_join_bit_identity(slices, ici):
    """Join/semi/anti over a (slice, device) topology equal the flat
    oracle at every hierarchy shape — the flat mesh is the degenerate
    1-slice case, bit-identical."""
    mesh = topo_mesh(slices, ici)
    left = make_batch(1200, seed=1)
    right = make_batch(500, seed=2)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    rsh = spmd.shard_bucket_ordered(rb, rl, mesh)
    for how in ("inner", "left_outer", "full_outer"):
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                           how=how)
        got = pairs_frame(lsh, rsh, li, ri)
        pd.testing.assert_frame_equal(got, oracle_frame(lb, rb, how))
    lk = np.asarray(lb.column("k").data)
    member = np.isin(lk, np.asarray(rb.column("k").data))
    for anti in (False, True):
        idx = np.asarray(spmd.sharded_semi_anti_indices(
            lsh, rsh, ["k"], ["k"], anti=anti))
        exp = int((~member).sum()) if anti else int(member.sum())
        assert len(idx) == exp, f"anti={anti}"


@pytest.mark.parametrize("slices,ici", [(2, 4), (4, 2)])
def test_multislice_repartition_crosses_dcn(slices, ici):
    """Mismatched bucket counts on a 2-axis mesh: the in-program
    repartition routes key lanes hierarchically (ICI within the slice,
    one DCN hop across), results equal the co-bucketed join, and the
    exchange volume is attributed to BOTH axes with the DCN share at
    the per-row hierarchy bound (~1/2, each row crosses DCN at most
    once)."""
    mesh = topo_mesh(slices, ici)
    left = make_batch(900, seed=3)
    right = make_batch(400, seed=4)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb8, rl8 = distributed_build(right, ["k"], 8, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    rsh8 = spmd.shard_bucket_ordered(rb8, rl8, mesh)
    reg = telemetry.get_registry()
    before = {k: reg.counters_dict().get(k, 0)
              for k in ("spmd.repartition.ici.bytes",
                        "spmd.repartition.dcn.bytes")}
    li, ri = spmd.sharded_join_indices(lsh, rsh8, ["k"], ["k"])
    got = pairs_frame(lsh, rsh8, li, ri)
    pd.testing.assert_frame_equal(got, oracle_frame(lb, rb8, "inner"))
    after = {k: reg.counters_dict().get(k, 0)
             for k in ("spmd.repartition.ici.bytes",
                       "spmd.repartition.dcn.bytes")}
    ici_b = after["spmd.repartition.ici.bytes"] \
        - before["spmd.repartition.ici.bytes"]
    dcn_b = after["spmd.repartition.dcn.bytes"] \
        - before["spmd.repartition.dcn.bytes"]
    assert ici_b > 0 and dcn_b > 0
    assert dcn_b / (ici_b + dcn_b) <= 0.6


@pytest.mark.parametrize("slices,ici", [(2, 4), (4, 2)])
def test_multislice_string_filter_aggregate(slices, ici):
    """String-keyed SMJ, predicate filter, and group aggregate over a
    2-axis mesh equal the single-device operators (string keys ride the
    same hierarchy: rank remaps in-program, value-hash routing across
    DCN)."""
    from hyperspace_tpu.engine.compiler import apply_filter
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.expr import col, lit
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    mesh = topo_mesh(slices, ici)
    left = make_string_batch(900, seed=5, keyspace=80, null_frac=0.08)
    right = make_string_batch(400, seed=6, keyspace=80)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    rsh = spmd.shard_bucket_ordered(rb, rl, mesh)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
    got = string_pairs_frame(lsh, rsh, li, ri)
    pd.testing.assert_frame_equal(got,
                                  string_oracle_frame(lb, rb, "inner"))

    batch = make_batch(2000, seed=7)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    sh = spmd.shard_bucket_ordered(built, lengths, mesh)
    pred = col("k") < lit(60)
    gotf = columnar.to_arrow(spmd.sharded_filter(sh, pred)).to_pandas()
    want = columnar.to_arrow(apply_filter(built, pred)).to_pandas()
    cols = list(gotf.columns)
    pd.testing.assert_frame_equal(
        gotf.sort_values(cols).reset_index(drop=True),
        want.sort_values(cols).reset_index(drop=True))
    schema = Schema.from_arrow(pa.table(
        {"k": np.zeros(1, np.int64), "v": np.zeros(1)}).schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("sum", "v", "sv")]
    out_schema = Aggregate(["k"], specs, Scan(["/nx"], schema)).schema
    g = columnar.to_arrow(spmd.sharded_group_aggregate(
        sh, ["k"], specs, out_schema)).to_pandas() \
        .sort_values("k").reset_index(drop=True)
    s = columnar.to_arrow(group_aggregate(
        built, ["k"], specs, out_schema)).to_pandas() \
        .sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(g, s, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_shard_layout_v3_records_hierarchy(tmp_path):
    """A multi-slice build's `_shard_layout.json` records the
    hierarchy: version 3, numSlices, and slice-level ranges that nest
    exactly over the flat shard map."""
    from hyperspace_tpu.io import builder
    from hyperspace_tpu.parallel.mesh import slice_bucket_ranges

    mesh = topo_mesh(2, 4)
    batch = make_batch(800, seed=9)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    root = str(tmp_path / "ms")
    builder.write_bucket_ordered(built, lengths, 16, root, mesh=mesh)
    layout = builder.read_shard_layout(root)
    assert layout["version"] == 3
    assert layout["numSlices"] == 2
    assert layout["numShards"] == 8
    assert layout["sliceBucketRanges"] == \
        [[lo, hi] for lo, hi in slice_bucket_ranges(16, 2, 4)]


# ---------------------------------------------------------------------------
# Virtual sub-shards (hot-bucket skew) — PR 14
# ---------------------------------------------------------------------------


def test_subshard_plan_geometry():
    """Segments tile the row space; every row's bucket lies inside its
    shard's bucket span (the alignment invariant the replicated right
    read relies on)."""
    lengths = np.asarray([3, 0, 120, 5, 2, 0, 7, 1], dtype=np.int64)
    plan = spmd.subshard_plan(lengths, 4)
    total = int(lengths.sum())
    assert plan.segments[0][0] == 0
    assert plan.segments[-1][1] == total
    cum = np.concatenate([[0], np.cumsum(lengths)])
    for (lo, hi), (b_lo, b_hi) in zip(plan.segments, plan.bucket_spans):
        for s in range(1, 4):
            assert plan.segments[s][0] == plan.segments[s - 1][1]
        for row in range(lo, hi):
            b = int(np.searchsorted(cum, row, side="right")) - 1
            assert b_lo <= b < b_hi


def test_skewed_key_subshard_join_bit_identity(tmp_path):
    """THE skew pin: a hot key holding most of the rows trips
    `pad_blowup`, the read splits the hot range into virtual sub-shards
    (aligned right side replicating split buckets), and
    inner/left_outer/semi/anti all equal the pandas oracle — the lane
    that used to decline to single-chip now stays SPMD and exact."""
    from hyperspace_tpu.io import builder, parquet

    mesh = make_mesh(8)
    rng = np.random.default_rng(11)
    n = 24_000
    hot = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 4096, n))
    left = columnar.from_arrow(pa.table({
        "k": hot.astype(np.int64), "v": rng.random(n)}))
    right = columnar.from_arrow(pa.table({
        "k": np.concatenate([np.full(3, 7),
                             rng.integers(0, 4096, 300)]).astype(np.int64),
        "v": rng.random(303)}))
    data = {}
    for tag, batch in (("l", left), ("r", right)):
        built, lengths = distributed_build(batch, ["k"], 16, mesh)
        root = str(tmp_path / tag)
        builder.write_bucket_ordered(built, lengths, 16, root, mesh=mesh)
        data[tag] = (root, lengths, built)
    l_root, l_lengths, l_built = data["l"]
    r_root, r_lengths, r_built = data["r"]
    assert spmd.pad_blowup(l_lengths, 8)

    plan, l_specs = spmd.plan_skew_read(
        parquet.bucket_files(l_root), l_lengths, 8)
    r_specs = spmd.plan_aligned_read(
        parquet.bucket_files(r_root), r_lengths, plan)
    cols = [f.name for f in l_built.schema.fields]
    lsh = spmd.read_sharded([], l_lengths, cols, l_built.schema, mesh,
                            shard_specs=l_specs, split_plan=plan)
    rsh = spmd.read_sharded([], r_lengths, cols, r_built.schema, mesh,
                            shard_specs=r_specs)
    assert lsh.split_plan is plan
    # The split layout stays near the true rows instead of padding out
    # to the hot range (the decline the sub-shards exist to remove).
    assert lsh.rows_per_shard * 8 <= 2 * n

    for how in ("inner", "left_outer"):
        li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                           how=how)
        got = pairs_frame(lsh, rsh, li, ri)
        pd.testing.assert_frame_equal(got,
                                      oracle_frame(l_built, r_built, how))
    lk = np.asarray(l_built.column("k").data)
    member = np.isin(lk, np.asarray(r_built.column("k").data))
    for anti in (False, True):
        idx = np.asarray(spmd.sharded_semi_anti_indices(
            lsh, rsh, ["k"], ["k"], anti=anti))
        exp = int((~member).sum()) if anti else int(member.sum())
        assert len(idx) == exp, f"anti={anti}"


def test_smj_right_only_skew_side_swap(tmp_path):
    """Right-side-ONLY skew (ISSUE 16 satellite): the planner-selected
    bucketed SMJ used to decline the SPMD lane when only the RIGHT
    scan's hot bucket tripped `pad_blowup` (replicating the left breaks
    outer/membership semantics). INNER has no unmatched-row semantics
    on either side, so the engine now swaps roles — re-reads the left
    aligned to the right's split and keeps the lane — bit-identical to
    rules-off, `mesh.spmd.side_swapped` pinned; a left_outer over the
    same shape still declines (`spmd.fallbacks`), identically correct."""
    import pyarrow.parquet as pq

    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace
    from hyperspace_tpu.index.index_config import IndexConfig

    rng = np.random.default_rng(19)
    left_dir = tmp_path / "left"
    right_dir = tmp_path / "right"
    left_dir.mkdir()
    right_dir.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 4096, 2000).astype(np.int64),
        "v": rng.random(2000),
    }), str(left_dir / "part-0.parquet"))
    n = 24_000  # 90% on one hot key: C*S far past PAD_BLOWUP_FACTOR*n
    hot = np.where(rng.random(n) < 0.9, 7,
                   rng.integers(0, 4096, n)).astype(np.int64)
    pq.write_table(pa.table({
        "k": hot, "w": rng.random(n),
    }), str(right_dir / "part-0.parquet"))

    session = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 8,
        "hyperspace.distribution.enabled": "true",
        "hyperspace.broadcast.threshold": -1,
    }))
    hs = Hyperspace(session)
    left = session.read_parquet(str(left_dir))
    right = session.read_parquet(str(right_dir))
    hs.create_index(left, IndexConfig("swl", ["k"], ["v"]))
    hs.create_index(right, IndexConfig("swr", ["k"], ["w"]))
    reg = telemetry.get_registry()
    sort_cols = ["k", "v", "w"]

    def run(how):
        q = left.join(right, on="k", how=how)
        session.disable_hyperspace()
        plain = q.to_pandas().sort_values(sort_cols) \
            .reset_index(drop=True)
        session.enable_hyperspace()
        got = q.to_pandas().sort_values(sort_cols) \
            .reset_index(drop=True)
        session.disable_hyperspace()
        session.enable_hyperspace()
        return plain, got

    c0 = reg.counters_dict().get("mesh.spmd.side_swapped", 0)
    plain, got = run("inner")
    c1 = reg.counters_dict().get("mesh.spmd.side_swapped", 0)
    assert c1 > c0, "inner right-skew join did not swap sides"
    pd.testing.assert_frame_equal(plain, got)

    f0 = reg.counters_dict().get("spmd.fallbacks", 0)
    plain, got = run("left")
    c2 = reg.counters_dict().get("mesh.spmd.side_swapped", 0)
    assert c2 == c1, "left_outer must not take the swapped lane"
    assert reg.counters_dict().get("spmd.fallbacks", 0) > f0
    pd.testing.assert_frame_equal(plain, got)


# ---------------------------------------------------------------------------
# String LIKE on the SPMD lane — PR 14
# ---------------------------------------------------------------------------


def test_sharded_filter_like_warm_link_free():
    """LIKE over the sharded layout: the dictionary-membership mask is
    computed once, cached in the segment cache, and a warm repeat is
    link-free with `spmd.strings.like_mask_cache_hits` advancing —
    results equal the host regex path bit for bit."""
    from hyperspace_tpu.engine.compiler import apply_filter
    from hyperspace_tpu.io import segcache
    from hyperspace_tpu.plan.expr import col

    mesh = make_mesh(4)
    batch = make_string_batch(1200, seed=13, keyspace=90,
                              null_frac=0.05)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    sh = spmd.shard_bucket_ordered(built, lengths, mesh)
    segcache.clear()
    pred = col("k").like("key00000_%")
    reg = telemetry.get_registry()

    want = columnar.to_arrow(apply_filter(built, pred)).to_pandas()
    cold = columnar.to_arrow(spmd.sharded_filter(sh, pred)).to_pandas()
    c0 = dict(reg.counters_dict())
    warm = columnar.to_arrow(spmd.sharded_filter(sh, pred)).to_pandas()
    c1 = dict(reg.counters_dict())
    assert c1.get("link.h2d.chunks", 0) == c0.get("link.h2d.chunks", 0), \
        "warm LIKE crossed the link"
    assert c1.get("spmd.strings.like_mask_cache_hits", 0) > \
        c0.get("spmd.strings.like_mask_cache_hits", 0)
    cols = list(want.columns)

    def norm(df):
        return df.sort_values(cols).reset_index(drop=True)

    pd.testing.assert_frame_equal(norm(cold), norm(want))
    pd.testing.assert_frame_equal(norm(warm), norm(want))


# ---------------------------------------------------------------------------
# Replica routing & coherence — PR 14
# ---------------------------------------------------------------------------


def test_replica_scope_confines_distribution_mesh():
    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.parallel import context
    from hyperspace_tpu.parallel.mesh import (dcn_size, mesh_device_list,
                                              total_shards)

    conf = HyperspaceConf({"hyperspace.distribution.enabled": "true",
                           "hyperspace.distribution.slices": 2})
    full = context.distribution_mesh(conf)
    assert dcn_size(full) == 2 and total_shards(full) == 8
    devices = mesh_device_list(full)
    with context.replica_scope(1):
        sub = context.distribution_mesh(conf)
        assert total_shards(sub) == 4
        assert mesh_device_list(sub) == devices[4:]
    assert context.active_replica() is None


def test_replica_residency_coherent_under_refresh(tmp_path):
    """Two replica slices fill INDEPENDENT cache entries for the same
    bucket ranges (device-tagged keys — no aliasing), a version
    invalidation sweeps BOTH replicas (coherence by construction), and
    re-reads serve identical data."""
    from hyperspace_tpu.io import builder, parquet, segcache
    from hyperspace_tpu.io.segcache import SegmentRef
    from hyperspace_tpu.parallel.mesh import slice_submesh

    mesh = topo_mesh(2, 4)
    batch = make_batch(1600, seed=17)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    root = str(tmp_path / "rep")
    builder.write_bucket_ordered(built, lengths, 16, root, mesh=mesh)
    per_bucket = parquet.bucket_files(root)
    cols = [f.name for f in built.schema.fields]
    segcache.clear()
    cache = segcache.get_cache()
    ref = SegmentRef(index_name="rep", index_root=root, version=0,
                     bucket="all")

    def read(slice_idx):
        sub = slice_submesh(mesh, slice_idx)
        per_shard = [[f for b in range(lo, hi)
                      for f in per_bucket.get(b, [])]
                     for lo, hi in bucket_ranges(16, 4)]
        sh = spmd.read_sharded(per_shard, lengths, cols, built.schema,
                               sub, base_ref=ref)
        df = columnar.to_arrow(
            spmd.sharded_filter(sh, _k_lt_60())).to_pandas()
        return df.sort_values(list(df.columns)).reset_index(drop=True)

    def _k_lt_60():
        from hyperspace_tpu.plan.expr import col, lit
        return col("k") < lit(60)

    r0 = read(0)
    r1 = read(1)
    pd.testing.assert_frame_equal(r0, r1)
    residency = cache.replica_residency(root)
    assert len(residency) == 2, residency  # one device tag per replica
    assert all(v == 4 for v in residency.values())  # 4 shards each
    # A committed refresh invalidates EVERY replica's entries.
    cache.invalidate_index(root, keep_version=1)
    assert cache.replica_residency(root) == {}
    pd.testing.assert_frame_equal(read(0), read(1))
    assert len(cache.replica_residency(root)) == 2


def test_least_loaded_routing_distribution_under_chaos(fault_injector):
    """Concurrent routed traffic balances across replicas (no replica
    past the 70% bar) and stays exact — including with transient faults
    injected at the parquet-read seam (the PR-7 chaos discipline): a
    retried read changes nothing about where queries land or what they
    return."""
    import threading

    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.scheduler import QueryScheduler
    from hyperspace_tpu.parallel import replica as replica_mod
    from hyperspace_tpu.utils.faults import FaultRule

    conf = HyperspaceConf({"hyperspace.distribution.enabled": "true",
                           "hyperspace.distribution.slices": 2})
    mesh = topo_mesh(2, 4)
    left = make_batch(1000, seed=19)
    right = make_batch(400, seed=20)
    replica_mod.reset_router()
    router = replica_mod.get_router()
    sched = QueryScheduler()

    import tempfile

    from hyperspace_tpu.io import builder, parquet, segcache
    from hyperspace_tpu.io.segcache import SegmentRef
    from hyperspace_tpu.parallel.mesh import slice_submesh

    work = tempfile.mkdtemp(prefix="hs_chaos_route_")
    roots = {}
    for tag, batch in (("l", left), ("r", right)):
        built, lengths = distributed_build(batch, ["k"], 16, mesh)
        root = f"{work}/{tag}"
        builder.write_bucket_ordered(built, lengths, 16, root,
                                     mesh=mesh)
        roots[tag] = (root, lengths, built)
    segcache.clear()

    def read_pair(slice_idx):
        sub = slice_submesh(mesh, slice_idx)
        out = []
        for tag in ("l", "r"):
            root, lengths, built = roots[tag]
            per_bucket = parquet.bucket_files(root)
            per_shard = [[f for b in range(lo, hi)
                          for f in per_bucket.get(b, [])]
                         for lo, hi in bucket_ranges(16, 4)]
            ref = SegmentRef(index_name=f"cr_{tag}", index_root=root,
                             version=0, bucket="cr")
            out.append(spmd.read_sharded(
                per_shard, lengths,
                [f.name for f in built.schema.fields], built.schema,
                sub, base_ref=ref))
        return tuple(out)

    # Transient read faults bite the COLD per-device fills (retried by
    # the PR-4 policy); warm routed traffic then never re-pays them.
    inj = fault_injector(FaultRule("parquet.read", kind="transient",
                                   probability=0.3, times=8))
    oracle = oracle_frame(roots["l"][2], roots["r"][2], "inner")
    results = []
    errors = []

    def client(i):
        try:
            for _q in range(4):
                rep = router.route(None, conf, sched)
                assert rep in (0, 1)
                lsh, rsh = read_pair(rep)
                li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"],
                                                   ["k"])
                results.append(pairs_frame(lsh, rsh, li, ri))
        except Exception as exc:  # pragma: no cover - fail loudly
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(results) == 32
    for frame in results:
        pd.testing.assert_frame_equal(frame, oracle)
    routed = router.routed_counts()
    assert sum(routed.values()) == 32
    assert max(routed.values()) / 32 <= 0.70, routed
    assert inj.fired("parquet.read") > 0, \
        "chaos seam never fired — the test lost its teeth"
    import shutil
    shutil.rmtree(work, ignore_errors=True)


def test_engine_multislice_replica_routing(tmp_path, sample_parquet):
    """End to end through the serving plane: on a 2-slice topology the
    scheduler routes each collect to a replica slice
    (`serve.replica.<i>.routed`, per-replica admitted-byte gauges),
    execution is confined to the routed slice's submesh, and concurrent
    replica-routed joins equal the rules-off run bit for bit."""
    import threading

    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace
    from hyperspace_tpu.index.index_config import IndexConfig
    from hyperspace_tpu.io import segcache
    from hyperspace_tpu.parallel import replica as replica_mod

    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 8,
        "hyperspace.distribution.enabled": "true",
        "hyperspace.distribution.slices": 2,
        "hyperspace.broadcast.threshold": -1,
    })
    session = HyperspaceSession(conf)
    hs = Hyperspace(session)
    df = session.read_parquet(sample_parquet)
    hs.create_index(df, IndexConfig("msl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("msr", ["imprs"], ["score"]))
    query = df.select("imprs", "id", "clicks").join(
        df.select("imprs", "score"), on="imprs")
    sort_cols = ["imprs", "id", "score"]

    session.disable_hyperspace()
    plain = query.to_pandas().sort_values(sort_cols) \
        .reset_index(drop=True)
    session.enable_hyperspace()
    segcache.clear()
    replica_mod.reset_router()
    reg = telemetry.get_registry()
    before = {k: reg.counters_dict().get(k, 0)
              for k in ("serve.replica.0.routed",
                        "serve.replica.1.routed")}
    results = []
    errors = []

    def client():
        try:
            results.append(query.to_pandas().sort_values(sort_cols)
                           .reset_index(drop=True))
        except Exception as exc:  # pragma: no cover - fail loudly
            errors.append(exc)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    session.disable_hyperspace()
    assert not errors, errors
    for frame in results:
        pd.testing.assert_frame_equal(frame, plain)
    after = {k: reg.counters_dict().get(k, 0)
             for k in ("serve.replica.0.routed",
                       "serve.replica.1.routed")}
    routed = sum(after.values()) - sum(before.values())
    assert routed >= 4, (before, after)


def test_repartition_sharded_routes_all_rows():
    """Every input row survives the in-program re-bucket, lands on its
    bucket's contiguous-range owner, and a join over the repartitioned
    layout equals the oracle."""
    mesh = make_mesh(8)
    batch = make_batch(1000, seed=31)
    sh = spmd.repartition_sharded(batch, ["k"], 16, mesh)
    assert sh.num_rows == 1000
    rsh_mesh, lsh, rsh, lb, rb, ll, rl = sharded_pair(n_dev=8, seed=31)
    li, ri = spmd.sharded_join_indices(sh, rsh, ["k"], ["k"])
    lk = np.asarray(sh.batch.column("k").data)
    rk = np.asarray(rsh.batch.column("k").data)
    li, ri = np.asarray(li), np.asarray(ri)
    assert (lk[li] == rk[ri]).all()
    exp = pd.DataFrame({"k": np.asarray(batch.column("k").data)}).merge(
        pd.DataFrame({"k": np.asarray(rb.column("k").data)}), on="k")
    assert len(exp) == len(li)
