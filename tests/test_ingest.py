"""Continuous-ingest plane: the delta-sketch append path, kind
dispatch for mode='incremental', the `IngestCoordinator` tick (append /
pressure gate / lease-path refresh / staleness accounting), typed
conflict concession against a manual refresher, the crash-point matrix
for both incremental refresh actions under concurrent serving, the
segment-cache warm-set story under sustained append, vacuum-vs-pin
safety, and the default staleness alert rule."""

import os
import shutil
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from chaos import canonical, run_chaos
from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index import pins
from hyperspace_tpu.index.index_config import (DataSkippingIndexConfig,
                                               IndexConfig)
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.sketch import clear_sketch_cache, load_sketches
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.utils.faults import FaultRule, InjectedCrash


def _reg(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


def _gauge(name):
    return telemetry.get_registry().gauge(name).value


@pytest.fixture(autouse=True)
def _fresh_sketch_cache():
    clear_sketch_cache()
    yield
    clear_sketch_cache()


def _write_facts(directory, name, lo, n=80, g=None):
    """One facts file: k sequential from `lo`, g = k % 4 (or pinned to
    a single value so a refresh touches at most one bucket)."""
    k = np.arange(lo, lo + n, dtype=np.int64)
    gv = (k % 4) if g is None else np.full(n, g, dtype=np.int64)
    path = os.path.join(directory, name)
    pq.write_table(pa.table({
        "k": k, "g": gv,
        "v": np.linspace(0.0, 1.0, n)}), path)
    return path


def _session(tmp_path, **extra):
    conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
            "spark.hyperspace.index.num.buckets": "4",
            "spark.hyperspace.index.hybridscan.enabled": "true",
            "spark.hyperspace.io.retry.base.ms": "1",
            "spark.hyperspace.io.retry.max.ms": "4"}
    conf.update(extra)
    return HyperspaceSession(HyperspaceConf(conf))


@pytest.fixture
def env(tmp_path):
    """(session, hs, facts_dir): 4-file facts source, hybrid scan on."""
    facts = tmp_path / "facts"
    facts.mkdir()
    for i in range(4):
        _write_facts(str(facts), f"f{i}.parquet", i * 80)
    sess = _session(tmp_path)
    return sess, Hyperspace(sess), str(facts)


def _managers(sess, name):
    mgr = Hyperspace.get_context(sess).index_collection_manager
    return mgr._managers(name)


def _latest_version_dir(sess, name):
    _, dm = _managers(sess, name)
    return dm.get_path(dm.get_latest_version_id())


# -- delta-sketch append path ----------------------------------------------


def test_incremental_refresh_dispatches_sketch_append(env):
    """mode='incremental' on a data-skipping index takes the
    sketch-append path: the new version's blob covers appended files,
    and every pre-existing file's row is CARRIED (bit-identical),
    not re-sketched."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    DataSkippingIndexConfig("sk", ["k"]))
    before = dict(load_sketches(_latest_version_dir(sess, "sk")).files)
    _write_facts(facts, "a0.parquet", 10_000)
    hs.refresh_index("sk", mode="incremental")
    after_dir = _latest_version_dir(sess, "sk")
    after = dict(load_sketches(after_dir).files)
    assert len(after) == len(before) + 1
    for path, prev in before.items():
        got = after[path]
        assert (got.size, got.stamp, got.rows) == (
            prev.size, prev.stamp, prev.rows), (
            f"carried sketch row for {path} changed across append")
        for name, prev_col in prev.columns.items():
            got_col = got.columns[name]
            assert (got_col.min, got_col.max, got_col.ok) == (
                prev_col.min, prev_col.max, prev_col.ok)
    appended = [p for p in after if p not in before]
    assert len(appended) == 1 and appended[0].endswith("a0.parquet")


def test_sketch_append_unit_carry_resketch_drop(env):
    """`append_file_sketches` unit semantics: unchanged files carry,
    rewritten files re-sketch, vanished files drop — and the detail
    counts say exactly which happened."""
    from hyperspace_tpu.index import sketch as sketch_io

    sess, hs, facts = env
    df = sess.read_parquet(facts)
    hs.create_index(df, DataSkippingIndexConfig("sk", ["k"]))
    v0 = _latest_version_dir(sess, "sk")
    files = sorted(os.path.join(facts, f) for f in os.listdir(facts))

    new = _write_facts(facts, "a0.parquet", 20_000)
    merged, detail = sketch_io.append_file_sketches(
        v0, files + [new], ["k"], df.schema, sess.conf)
    assert [detail["files_carried"], detail["files_sketched"],
            detail["files_dropped"]] == [4, 1, 0]
    assert len(merged) == 5

    _write_facts(facts, "f0.parquet", 30_000)  # rewrite: stamp changes
    merged, detail = sketch_io.append_file_sketches(
        v0, files, ["k"], df.schema, sess.conf)
    assert [detail["files_carried"], detail["files_sketched"],
            detail["files_dropped"]] == [3, 1, 0]

    merged, detail = sketch_io.append_file_sketches(
        v0, files[:2], ["k"], df.schema, sess.conf)
    assert detail["files_dropped"] == 2
    assert len(merged) == 2


def test_zorder_skipping_declines_incremental(env):
    """Z-ordered skipping indexes decline the append path with a typed
    error naming the remedy — the clustered copy needs a full
    re-cluster, not a carry."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    DataSkippingIndexConfig("zk", ["k"], zorder_by=["k"]))
    _write_facts(facts, "a0.parquet", 10_000)
    with pytest.raises(HyperspaceException, match="mode='full'"):
        hs.refresh_index("zk", mode="incremental")


# -- coordinator tick ------------------------------------------------------


def test_tick_appends_refreshes_both_kinds_and_staleness_drains(env):
    """One tick lands the producer's micro-batch and refreshes both
    index kinds through the lease path; afterwards staleness is 0 and a
    fresh query sees the appended rows."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))
    hs.create_index(sess.read_parquet(facts),
                    DataSkippingIndexConfig("sk", ["k"]))
    appended = []

    def producer():
        appended.append(_write_facts(facts, f"a{len(appended)}.parquet",
                                     10_000 + 100 * len(appended)))
        return appended[-1:]

    coord = hs.ingest(producer=producer, indexes=["cov", "sk"])
    t0 = {n: _reg(n) for n in ("ingest.ticks", "ingest.appends",
                               "ingest.refreshes", "ingest.failures")}
    decision = coord.run_once()
    assert decision["action"] == "refreshed"
    assert decision["appended"] == 1
    assert [r["action"] for r in decision["refreshes"]] == [
        "refreshed", "refreshed"]
    assert _reg("ingest.ticks") == t0["ingest.ticks"] + 1
    assert _reg("ingest.appends") == t0["ingest.appends"] + 1
    assert _reg("ingest.refreshes") == t0["ingest.refreshes"] + 2
    assert _reg("ingest.failures") == t0["ingest.failures"]
    assert coord.staleness_s() == 0.0
    assert _gauge("ingest.staleness.seconds") == 0.0
    # The appended file is in the new skipping blob, and a fresh reader
    # sees its rows.
    blob = set(load_sketches(_latest_version_dir(sess, "sk")).files)
    assert appended[0] in blob
    got = sess.read_parquet(facts).filter(
        col("k") >= lit(10_000)).collect()
    assert got.num_rows == 80


def test_staleness_tracks_uncovered_appends(env):
    """`ingest.staleness.seconds` = now − newest UNcovered append: it
    ages while no refresh lands, and a successful tick (refresh started
    after the append) drains it to 0."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))
    coord = hs.ingest(indexes=["cov"])
    path = _write_facts(facts, "a0.parquet", 10_000)
    coord.record_append([path], at=time.time() - 7.0)
    assert 6.5 <= coord.staleness_s() <= 30.0
    assert _gauge("ingest.staleness.seconds") >= 6.5
    decision = coord.run_once()
    assert decision["refreshes"][0]["action"] == "refreshed"
    assert coord.staleness_s() == 0.0
    assert _gauge("ingest.staleness.seconds") == 0.0


def test_serve_pressure_defers_refresh_not_appends(env):
    """Under queue pressure the tick still lands appends (staleness
    accounting stays truthful) but defers the refresh with a reason."""
    from hyperspace_tpu.engine import scheduler as sched_mod

    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))

    class _Pressured:
        def pressure(self):
            return {"queue_depth": 3, "admitted_bytes": 0}

    coord = hs.ingest(
        producer=lambda: [_write_facts(facts, "a0.parquet", 10_000)],
        indexes=["cov"])
    d0, r0 = _reg("ingest.deferred"), _reg("ingest.refreshes")
    prev = sched_mod.get_scheduler()
    sched_mod.set_scheduler(_Pressured())
    try:
        decision = coord.run_once()
    finally:
        sched_mod.set_scheduler(prev)
    assert decision["action"] == "deferred"
    assert "3 queries waiting" in decision["reason"]
    assert decision["appended"] == 1
    assert _reg("ingest.deferred") == d0 + 1
    assert _reg("ingest.refreshes") == r0
    assert coord.staleness_s() > 0.0  # the un-refreshed append ages
    # Quiet again: the next tick picks the backlog up.
    assert coord.run_once()["refreshes"][0]["action"] == "refreshed"
    assert coord.staleness_s() == 0.0


def test_producer_failure_is_contained(env):
    """A producer exception fails the TICK (typed, counted), never
    crashes the owner or half-refreshes."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))

    def bad_producer():
        raise OSError("source landing zone unreachable")

    coord = hs.ingest(producer=bad_producer, indexes=["cov"])
    f0, r0 = _reg("ingest.failures"), _reg("ingest.refreshes")
    decision = coord.run_once()
    assert decision["action"] == "failed"
    assert "landing zone" in decision["reason"]
    assert _reg("ingest.failures") == f0 + 1
    assert _reg("ingest.refreshes") == r0


# -- conflict concession ---------------------------------------------------


def test_conflict_concession_exactly_one_winner(env):
    """Racing a manual refresher: the op-log slot has one winner. The
    coordinator retries under the shared backoff policy and CONCEDES
    (typed decision + `ingest.conflicts`), then wins cleanly next tick
    once the manual writer committed."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))
    lm, _ = _managers(sess, "cov")
    base = lm.get_latest_log()
    # A fresh transient entry = a LIVE manual refresher mid-flight (too
    # young for lease recovery to touch).
    rival = IndexLogEntry.from_dict(base.to_dict())
    rival.state = States.REFRESHING
    assert lm.write_log(base.id + 1, rival)

    coord = hs.ingest(indexes=["cov"])
    c0, f0, retries0 = (_reg("ingest.conflicts"), _reg("ingest.failures"),
                        _reg("io.retries"))
    decision = coord.run_once()
    assert decision["refreshes"][0]["action"] == "conceded"
    assert _reg("ingest.conflicts") == c0 + 1
    assert _reg("ingest.failures") == f0  # a concession is NOT a failure
    assert _reg("io.retries") > retries0  # bounded backoff, not a spin

    # The manual writer commits; the next tick wins the slot.
    winner = IndexLogEntry.from_dict(base.to_dict())
    winner.state = States.ACTIVE
    assert lm.write_log(base.id + 2, winner)
    assert coord.run_once()["refreshes"][0]["action"] == "refreshed"
    assert lm.get_latest_log().state == States.ACTIVE


# -- crash-point matrix under concurrent serving ---------------------------


@pytest.mark.parametrize("kind,phase", [
    ("covering", "begin"), ("covering", "op"), ("covering", "end"),
    ("skipping", "begin"), ("skipping", "op"), ("skipping", "end"),
])
def test_crash_matrix_refresh_recovers_next_tick(tmp_path, fault_injector,
                                                 kind, phase):
    """Crash the incremental refresh at each phase boundary. The torn
    op-log entry must not corrupt concurrent serving (chaos lap against
    the serial oracle), and the NEXT tick's lease recovery heals the
    log and completes the refresh."""
    facts = tmp_path / "facts"
    facts.mkdir()
    for i in range(4):
        _write_facts(str(facts), f"f{i}.parquet", i * 80)
    sess = _session(tmp_path,
                    **{"spark.hyperspace.maintenance.lease.seconds": "0"})
    hs = Hyperspace(sess)
    if kind == "covering":
        hs.create_index(sess.read_parquet(str(facts)),
                        IndexConfig("cov", ["g"], ["k", "v"]))
        action = "RefreshIncrementalAction"
    else:
        hs.create_index(sess.read_parquet(str(facts)),
                        DataSkippingIndexConfig("sk", ["k"]))
        action = "RefreshSkippingAppendAction"
    name = "cov" if kind == "covering" else "sk"
    _write_facts(str(facts), "a0.parquet", 10_000)

    coord = hs.ingest(indexes=[name])
    inj = fault_injector(
        FaultRule(f"action.{action}.{phase}", kind="crash", times=1))
    with pytest.raises(InjectedCrash):
        coord.run_once()
    assert inj.fired("action.*") == 1
    lm, _ = _managers(sess, name)
    # The fault fires BEFORE the phase runs: a crash at `begin` dies
    # before the transient entry is written (log untouched); `op`/`end`
    # crashes leave the torn REFRESHING entry recovery must heal.
    if phase == "begin":
        assert lm.get_latest_log().state == States.ACTIVE
    else:
        assert lm.get_latest_log().state != States.ACTIVE

    # Concurrent serving against the torn log: correctness holds (the
    # planner uses the last COMMITTED version or falls back).
    sess.enable_hyperspace()
    try:
        workload, expected = [], {}
        for g in range(4):
            df = sess.read_parquet(str(facts)).filter(
                col("g") == lit(g)).select("k", "g", "v")
            workload.append((f"g{g}", df))
            expected[f"g{g}"] = canonical(df.collect())
        report = run_chaos(workload, expected, clients=4,
                           total_queries=16)
    finally:
        sess.disable_hyperspace()
    assert report.mismatches == []
    assert report.stuck_threads == []
    assert report.outcomes["error"] == 0

    # Next tick: lease recovery (Cancel FSM) + the refresh completes.
    fault_injector()  # disarm
    rec0 = _reg("resilience.recoveries")
    decision = coord.run_once()
    assert decision["refreshes"][0]["action"] == "refreshed"
    if phase != "begin":  # begin crash left nothing to recover
        assert _reg("resilience.recoveries") >= rec0 + 1
    assert lm.get_latest_log().state == States.ACTIVE
    assert coord.staleness_s() == 0.0
    got = sess.read_parquet(str(facts)).filter(
        col("k") >= lit(10_000)).collect()
    assert got.num_rows == 80


# -- segment-cache warm set under sustained append -------------------------


def test_warm_hit_rate_held_under_append(tmp_path):
    """Bucket-scoped incremental commit REKEYS warm untouched-bucket
    segments to the new version instead of dumping them: after an
    append+refresh, repeat queries keep a warm hit rate above the floor
    and `cache.segments.rekeyed` moves."""
    facts = tmp_path / "facts"
    facts.mkdir()
    for i in range(4):
        _write_facts(str(facts), f"f{i}.parquet", i * 80)
    sess = _session(
        tmp_path,
        **{"spark.hyperspace.execution.min.device.rows": "0",
           "spark.hyperspace.distribution.enabled": "false"})
    hs = Hyperspace(sess)
    hs.create_index(sess.read_parquet(str(facts)),
                    IndexConfig("cov", ["g"], ["k", "v"]))

    def run_lap():
        out = {}
        for g in range(4):
            out[g] = canonical(
                sess.read_parquet(str(facts))
                .filter(col("g") == lit(g)).select("k", "g", "v")
                .collect())
        return out

    sess.enable_hyperspace()
    try:
        before = run_lap()
        run_lap()  # warm the segment cache
        # Appended file pins a single OUT-OF-WORKLOAD g value: the
        # refresh touches at most one bucket, answers stay invariant.
        coord = hs.ingest(
            producer=lambda: [_write_facts(str(facts), "a0.parquet",
                                           10_000, g=7)],
            indexes=["cov"])
        rekeyed0 = _reg("cache.segments.rekeyed")
        assert coord.run_once()["action"] == "refreshed"
        assert _reg("cache.segments.rekeyed") > rekeyed0

        h0, m0 = _reg("cache.segments.hits"), _reg("cache.segments.misses")
        after = run_lap()
        hits = _reg("cache.segments.hits") - h0
        misses = _reg("cache.segments.misses") - m0
    finally:
        sess.disable_hyperspace()
    for g in range(4):
        assert after[g].equals(before[g])
    assert hits + misses > 0
    assert hits / (hits + misses) >= 0.5, (
        f"warm set collapsed across the version flip: "
        f"{hits} hits / {misses} misses")


# -- vacuum vs pinned reads ------------------------------------------------


def test_vacuum_defers_behind_pin_then_collects(env):
    """A vacuum racing a pinned in-flight read backs off and SKIPS the
    pinned version (counted deferral) — the reader finishes unharmed;
    an unpinned retry collects the garbage."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov2", ["g"], ["k"]))
    vdir = _latest_version_dir(sess, "cov")
    hs.delete_index("cov")
    d0 = _reg("resilience.vacuum.deferred")
    with pins.pinned([vdir]):
        hs.vacuum_index("cov")
        assert os.path.isdir(vdir), "vacuum deleted a pinned version"
    assert _reg("resilience.vacuum.deferred") == d0 + 1
    assert not pins.is_pinned(vdir)
    # The skipped version is orphaned garbage — recoverable, unlike a
    # reader crashed mid-file; the vacuum itself still completed.
    assert os.path.isdir(vdir)
    # Control: with no pin held, vacuum hard-deletes the version dir.
    vdir2 = _latest_version_dir(sess, "cov2")
    hs.delete_index("cov2")
    hs.vacuum_index("cov2")
    assert not os.path.isdir(vdir2)
    assert _reg("resilience.vacuum.deferred") == d0 + 1


def test_lost_version_surfaces_typed_fallback_not_file_error(env):
    """If a delete wins anyway (other-process vacuum), the in-flight
    read surfaces as the typed unavailable→fallback path and the query
    still answers from source — never a raw FileNotFoundError."""
    sess, hs, facts = env
    hs.create_index(sess.read_parquet(facts),
                    IndexConfig("cov", ["g"], ["k", "v"]))
    truth = canonical(sess.read_parquet(facts)
                      .filter(col("g") == lit(2)).select("k", "g", "v")
                      .collect())
    shutil.rmtree(_latest_version_dir(sess, "cov"))
    f0 = _reg("resilience.fallbacks")
    sess.enable_hyperspace()
    try:
        got = (sess.read_parquet(facts)
               .filter(col("g") == lit(2)).select("k", "g", "v")
               .collect())
    finally:
        sess.disable_hyperspace()
    assert canonical(got).equals(truth)
    assert _reg("resilience.fallbacks") == f0 + 1


# -- staleness alert rule --------------------------------------------------


def test_ingest_staleness_default_rule_fires_and_resolves():
    """The shipped `ingest_staleness` rule: gauge > 30 sustained 5 s
    fires, hysteresis holds until < 10."""
    from hyperspace_tpu.telemetry.alerts import (DEFAULT_RULES,
                                                 AlertManager)

    rule = next(r for r in DEFAULT_RULES if r.name == "ingest_staleness")
    assert rule.series == "ingest.staleness.seconds"
    g = telemetry.get_registry().gauge("ingest.staleness.seconds")
    m = AlertManager(rules=[rule])
    g.set(45.0)
    assert m.evaluate(now=100.0) == []          # not yet sustained
    fired = m.evaluate(now=105.1)
    assert len(fired) == 1 and fired[0]["rule"] == "ingest_staleness"
    g.set(20.0)                                  # hysteresis band
    assert m.evaluate(now=106.0) == []
    assert m.active_count() == 1
    g.set(0.0)
    resolved = m.evaluate(now=107.0)
    assert len(resolved) == 1 and resolved[0]["state"] == "resolved"
    assert m.active_count() == 0
