"""Self-driving index advisor suite (ISSUE 11).

The acceptance bar: a recurring un-indexed filter+join workload makes
the advisor recommend AND auto-build at least one index under the
maintenance lease; the repeat workload is served by it (rule-usage
telemetry), reads strictly fewer bytes, and returns bit-identical
results. Plus: deterministic rankings over a fixed recorded workload,
clean one-winner behavior against a concurrent/stranded manual create,
deferral under serving pressure, and the persisted advisor state.
"""

import json
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig, telemetry)
from hyperspace_tpu.advisor import STATE_FILE, IndexAdvisor
from hyperspace_tpu.advisor.miner import WorkloadMiner
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.io import segcache
from hyperspace_tpu.plan.expr import col

from chaos import canonical


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


def _scan_bytes(metrics) -> int:
    return sum(op.detail.get("bytes_scanned", 0)
               for op in metrics.operators if op.name == "Scan")


@pytest.fixture(autouse=True)
def fresh_ring_and_cache():
    """Advisor tests read the PROCESS flight ring: empty it first so
    other suites' queries (over now-deleted tmp dirs) are not mined."""
    telemetry.get_recorder().clear()
    segcache.set_cache(segcache.SegmentCache())
    yield
    telemetry.get_recorder().clear()
    segcache.set_cache(segcache.SegmentCache())


@pytest.fixture
def workload_env(tmp_path):
    """Facts+dims source dirs and a rules-enabled session, no indexes."""
    rng = np.random.default_rng(11)
    n = 6000
    facts_dir = tmp_path / "facts"
    facts_dir.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, n // 8, n).astype(np.int64),
        "v": rng.random(n),
        "tag": rng.integers(0, 40, n).astype(np.int32),
    }), str(facts_dir / "part-0.parquet"))
    dims_dir = tmp_path / "dims"
    dims_dir.mkdir()
    pq.write_table(pa.table({
        "k": np.arange(n // 8, dtype=np.int64),
        "label": rng.integers(0, 9, n // 8).astype(np.int64),
    }), str(dims_dir / "part-0.parquet"))

    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.index.num.buckets": "4",
        # One cycle may build every winner (filter covering, skipping,
        # and the join PAIR) — the default of 2 spreads them over runs,
        # which is production-sane but makes "second run is a no-op"
        # assertions noisy.
        "spark.hyperspace.advisor.max.builds": "6"})
    sess = HyperspaceSession(conf).enable_hyperspace()
    return sess, str(facts_dir), str(dims_dir)


def _run_filter_workload(sess, facts, repeats=3):
    df = sess.read_parquet(facts)
    q = df.filter(col("tag") == 7).select("k", "v", "tag")
    table = None
    for _ in range(repeats):
        table = q.collect()
    return q, table


# ---------------------------------------------------------------------------
# End-to-end: the acceptance criterion
# ---------------------------------------------------------------------------


def test_e2e_recurring_workload_auto_builds_and_serves(workload_env):
    sess, facts, dims = workload_env
    hs = Hyperspace(sess)
    df = sess.read_parquet(facts)
    d = sess.read_parquet(dims)
    filter_q = df.filter(col("tag") == 7).select("k", "v", "tag")
    join_q = df.join(d, on="k").select("k", "v", "label")

    before_tables = []
    before_bytes = 0
    for _ in range(3):
        before_tables = [filter_q.collect(), join_q.collect()]
        m = sess.last_query_metrics()
    for q in (filter_q, join_q):
        q.collect()
        before_bytes += _scan_bytes(sess.last_query_metrics())

    advisor = hs.advisor()
    builds_before = _counter("advisor.builds")
    summary = advisor.run_once()

    # At least one recommendation became a real ACTIVE index through
    # the lease path (CreateAction emits its report; state says so).
    built = [dec for dec in summary["decisions"]
             if dec.get("action") == "built"]
    assert built, summary["decisions"]
    assert _counter("advisor.builds") >= builds_before + 1
    catalog = hs.indexes()
    assert (catalog["state"] == "ACTIVE").all()
    assert any(name.startswith("adv_")
               for name in catalog["name"])

    # The repeat workload is SERVED by the new index...
    after_bytes = 0
    applied = 0
    after_tables = []
    for q in (filter_q, join_q):
        after_tables.append(q.collect())
        m = sess.last_query_metrics()
        after_bytes += _scan_bytes(m)
        applied += sum(1 for e in m.events
                       if e.get("category") == "rule"
                       and e.get("action") == "applied")
    assert applied >= 1
    # ...reads strictly fewer bytes...
    assert after_bytes < before_bytes
    # ...and answers bit-identically (row order is not part of the
    # contract; canonical() sorts, as everywhere in this repo).
    for want, got in zip(before_tables, after_tables):
        assert canonical(got).equals(canonical(want))

    # Persisted state round-trips and records the decisions.
    state = advisor.state()
    assert state is not None
    assert state["kind"] == "hyperspace-advisor-state"
    assert state["last_run"]["decisions"] == summary["decisions"]
    assert os.path.exists(os.path.join(sess.conf.system_path,
                                       STATE_FILE))

    # A second cycle over the same ring is a no-op: the built shapes
    # are served now (rule applied -> no misses) and already-built
    # candidates are recognized by their deterministic names.
    second = advisor.run_once()
    assert not [dec for dec in second["decisions"]
                if dec.get("action") == "built"]


# ---------------------------------------------------------------------------
# Determinism
# ---------------------------------------------------------------------------


def test_same_recorded_workload_same_ranked_recommendations(workload_env):
    """Two independent advisors polling the same ring must mine the
    same signatures and rank the same candidates with the same scores
    — and scoring twice must be idempotent."""
    sess, facts, dims = workload_env
    _run_filter_workload(sess, facts)
    df = sess.read_parquet(facts)
    d = sess.read_parquet(dims)
    for _ in range(3):
        df.join(d, on="k").select("k", "v", "label").collect()

    def ranked():
        hs = Hyperspace(sess)
        adv = IndexAdvisor(sess)
        adv.observe()
        from hyperspace_tpu.advisor import score_signatures
        cands = score_signatures(sess, adv.miner.recurring(), sess.conf)
        return [(c.name, c.kind, c.score,
                 c.est_bytes_avoided_per_query) for c in cands]

    first = ranked()
    second = ranked()
    assert first, "no candidates mined from a recurring workload"
    assert first == second
    kinds = {k for _n, k, _s, _b in first}
    assert "covering" in kinds


def test_miner_counts_and_ignores_served_queries(workload_env):
    sess, facts, _dims = workload_env
    _run_filter_workload(sess, facts, repeats=4)
    miner = WorkloadMiner(min_repeats=2)
    assert miner.poll() == 4
    sigs = miner.recurring()
    assert len(sigs) == 1
    assert sigs[0].kind == "filter"
    assert sigs[0].count == 4
    assert sigs[0].filter_columns == ("tag",)
    assert "tag" in sigs[0].eq_columns
    assert sigs[0].total_scan_bytes > 0
    # Incremental: nothing new -> nothing re-mined.
    assert miner.poll() == 0
    assert miner.recurring()[0].count == 4


# ---------------------------------------------------------------------------
# Lease contention: advisor vs manual create — one winner, clean
# recovery
# ---------------------------------------------------------------------------


def test_lease_contention_one_winner_clean_recovery(workload_env,
                                                    monkeypatch):
    sess, facts, _dims = workload_env
    hs = Hyperspace(sess)
    _run_filter_workload(sess, facts)
    advisor = hs.advisor()
    advisor.observe()
    from hyperspace_tpu.advisor import score_signatures
    cands = score_signatures(sess, advisor.miner.recurring(), sess.conf)
    cov = next(c for c in cands if c.kind == "covering")

    # A "manual create" that crashed between begin and end holds the
    # transient op-log slot for the advisor's own candidate name.
    from hyperspace_tpu.index.factories import IndexLogManagerFactory
    from hyperspace_tpu.index.path_resolver import PathResolver
    path = PathResolver(sess.conf).get_index_path(cov.name)
    log_manager = IndexLogManagerFactory().create(path, conf=sess.conf)
    import time as _time

    from hyperspace_tpu.index.log_entry import IndexLogEntry
    stranded = IndexLogEntry.from_dict(json.loads(json.dumps({
        "version": "0.1", "id": 0, "state": "CREATING",
        # FRESH timestamp: the writer is presumed LIVE within the
        # maintenance lease — the advisor must concede, not auto-recover.
        "timestamp": int(_time.time() * 1000),
        "name": cov.name,
        "derivedDataset": {"kind": "CoveringIndex", "properties": {
            "columns": {"indexed": ["tag"], "included": []},
            "schemaString": "{}", "numBuckets": 4}},
        "content": {"root": path, "directories": []},
        "source": {"plan": {"properties": {
            "rawPlan": "{}",
            "fingerprint": {"properties": {"signatures": []}}},
            "kind": "Spark"}, "data": []},
        "extra": {},
    })))
    assert log_manager.write_log(0, stranded)

    conflicts_before = _counter("advisor.build_conflicts")
    summary = advisor.run_once()
    decisions = {d["name"]: d for d in summary["decisions"]}
    assert decisions[cov.name]["action"] == "conflict"
    assert _counter("advisor.build_conflicts") == conflicts_before + 1
    # The stranded writer still owns the slot; the catalog is intact.
    assert log_manager.get_latest_log().state == "CREATING"

    # Clean recovery (the lease path's Cancel FSM), then the next run
    # builds for real.
    assert hs.recover_index(cov.name) is True
    summary2 = advisor.run_once()
    built = {name for d in summary2["decisions"]
             if d.get("action") == "built"
             for name in d.get("indexes", ())}
    assert cov.name in built
    states = dict(zip(hs.indexes()["name"], hs.indexes()["state"]))
    assert states[cov.name] == "ACTIVE"


def test_concurrent_manual_create_races_cleanly(workload_env):
    """A racing manual create of the advisor's candidate: exactly one
    writer wins the op-log slot, the loser concedes, and the index ends
    ACTIVE exactly once."""
    sess, facts, _dims = workload_env
    hs = Hyperspace(sess)
    _run_filter_workload(sess, facts)
    advisor = hs.advisor()
    advisor.observe()
    from hyperspace_tpu.advisor import score_signatures
    cov = next(c for c in score_signatures(sess,
                                           advisor.miner.recurring(),
                                           sess.conf)
               if c.kind == "covering")

    barrier = threading.Barrier(2)
    manual_error = []

    def manual():
        barrier.wait()
        try:
            hs.create_index(
                sess.read_parquet(facts),
                IndexConfig(cov.name, list(cov.configs[0].indexed_columns),
                            list(cov.configs[0].included_columns)))
        except Exception as exc:
            manual_error.append(repr(exc))

    summaries = []

    def advised():
        barrier.wait()
        summaries.append(advisor.run_once())

    threads = [threading.Thread(target=manual),
               threading.Thread(target=advised)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    decisions = {d["name"]: d["action"]
                 for d in summaries[0]["decisions"]}
    advisor_built = decisions.get(cov.name) == "built"
    manual_won = not manual_error
    assert advisor_built or manual_won  # somebody built it
    states = dict(zip(hs.indexes()["name"], hs.indexes()["state"]))
    assert states.get(cov.name) == "ACTIVE"
    # The repeat workload is served regardless of who won.
    _q, _t = _run_filter_workload(sess, facts, repeats=1)
    m = sess.last_query_metrics()
    assert any(e.get("action") == "applied" for e in m.events
               if e.get("category") == "rule")


# ---------------------------------------------------------------------------
# Budget starvation: advisor yields to serving
# ---------------------------------------------------------------------------


class _PressuredScheduler(sched_mod.QueryScheduler):
    def __init__(self, pressure):
        super().__init__()
        self._fake_pressure = pressure

    def pressure(self):
        return dict(self._fake_pressure)


def test_advisor_defers_under_serving_pressure(workload_env):
    sess, facts, _dims = workload_env
    hs = Hyperspace(sess)
    _run_filter_workload(sess, facts)
    advisor = hs.advisor()

    old = sched_mod.get_scheduler()
    try:
        # Queued queries: every build defers, nothing is created.
        sched_mod.set_scheduler(_PressuredScheduler(
            {"queue_depth": 3, "admitted_bytes": 0, "inflight": 3}))
        deferred_before = _counter("advisor.deferred")
        summary = advisor.run_once()
        assert summary["recommendations"], "nothing recommended"
        assert all(d["action"] == "deferred"
                   for d in summary["decisions"])
        assert _counter("advisor.deferred") == deferred_before + 1
        assert len(hs.indexes()) == 0

        # Admitted bytes past the headroom fraction of the serving
        # budget: same deferral.
        sess.conf.set("spark.hyperspace.serve.hbm.budget.bytes", 1000)
        sched_mod.set_scheduler(_PressuredScheduler(
            {"queue_depth": 0, "admitted_bytes": 900, "inflight": 1}))
        summary = advisor.run_once()
        assert all(d["action"] == "deferred"
                   for d in summary["decisions"])
        assert len(hs.indexes()) == 0

        # Pressure clears: the SAME advisor builds on the next cycle.
        sched_mod.set_scheduler(_PressuredScheduler(
            {"queue_depth": 0, "admitted_bytes": 0, "inflight": 0}))
        summary = advisor.run_once()
        assert any(d["action"] == "built" for d in summary["decisions"])
    finally:
        sched_mod.set_scheduler(old)
        sess.conf.unset("spark.hyperspace.serve.hbm.budget.bytes")


def test_build_budget_rejects_past_cap(workload_env):
    sess, facts, _dims = workload_env
    hs = Hyperspace(sess)
    _run_filter_workload(sess, facts)
    sess.conf.set("spark.hyperspace.advisor.build.budget.bytes", 1)
    rejected_before = _counter("advisor.rejected_budget")
    summary = hs.advisor().run_once()
    assert summary["recommendations"]
    assert all(d["action"] == "rejected_budget"
               for d in summary["decisions"])
    assert _counter("advisor.rejected_budget") > rejected_before
    assert len(hs.indexes()) == 0


def test_advisor_disabled_knob(workload_env):
    sess, facts, _dims = workload_env
    sess.conf.set("spark.hyperspace.advisor.enabled", "false")
    hs = Hyperspace(sess)
    _run_filter_workload(sess, facts)
    summary = hs.advisor().run_once()
    assert summary["recommendations"]
    assert all(d["action"] == "disabled" for d in summary["decisions"])
    assert len(hs.indexes()) == 0


# ---------------------------------------------------------------------------
# Warm-start compilation knob (satellite)
# ---------------------------------------------------------------------------


def test_compile_cache_dir_wires_persistent_cache(tmp_path, monkeypatch):
    import jax

    from hyperspace_tpu.telemetry import compilation

    cache_dir = tmp_path / "jitcache"
    monkeypatch.setattr(compilation, "_persistent_dir", None)
    before = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        sess = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": str(tmp_path / "wh"),
            "spark.hyperspace.compile.cache.dir": str(cache_dir)}))
        assert compilation.persistent_cache_dir() == str(cache_dir)
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
        assert _counter("compile.persistent_cache.configured") >= 1
        # Unset knob: configure is a no-op, not a reset.
        HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": str(tmp_path / "wh2")}))
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
        sess.close()
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
        monkeypatch.setattr(compilation, "_persistent_dir", None)


# ---------------------------------------------------------------------------
# Measured prune fraction closes the what-if loop (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


def test_measured_prune_fraction_drives_skipping_rank(workload_env):
    """The measured per-index prune gauge overrides the conf
    assumption and deterministically flips the skipping candidate's
    rank against the covering candidate for the same signature."""
    sess, facts, _dims = workload_env
    _run_filter_workload(sess, facts)
    from hyperspace_tpu.advisor import score_signatures
    adv = IndexAdvisor(sess)
    adv.observe()
    sigs = adv.miner.recurring()

    def ranked():
        cands = score_signatures(sess, sigs, sess.conf)
        return cands, [c.name for c in cands]

    cands, _names = ranked()
    sk = next(c for c in cands if c.kind == "skipping")
    cov = next(c for c in cands if c.kind == "covering")
    # Nothing measured for THIS index yet (the suite's global
    # histogram may already hold other workloads' measurements).
    assert sk.detail["prune_fraction_source"] in ("assumed",
                                                  "measured:global")

    gauge = telemetry.get_registry().gauge(
        f"skipping.{sk.name}.measured_prune_fraction")

    # Reality says the sketches prune (nearly) everything: skipping
    # outranks the replay-verified covering index.
    gauge.set(1.0)
    cands, names = ranked()
    sk_hi = next(c for c in cands if c.kind == "skipping")
    assert sk_hi.detail["prune_fraction_source"] == "measured:index"
    assert sk_hi.detail["prune_fraction"] == 1.0
    assert names.index(sk_hi.name) < names.index(cov.name)
    assert sk_hi.est_bytes_avoided_per_query > \
        cov.est_bytes_avoided_per_query

    # Reality says they barely prune: the SAME candidate sinks below
    # the covering index instead.
    gauge.set(0.001)
    cands, names = ranked()
    sk_lo = next(c for c in cands if c.kind == "skipping")
    assert sk_lo.detail["prune_fraction_source"] == "measured:index"
    assert names.index(sk_lo.name) > names.index(cov.name)
    assert sk_lo.score < cov.score
