"""Signature provider tests (reference `FileBasedSignatureProviderTests`):
signature changes iff file length/path/set changes; provider reconstructable
by name."""

import os
import time

from hyperspace_tpu.index.signature import (FileBasedSignatureProvider,
                                            SignatureProviderFactory)
from hyperspace_tpu.plan.nodes import Filter, Scan
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.schema import Field, Schema


def make_scan(root):
    return Scan([str(root)], Schema([Field("a", "int64")]))


def write(root, name, contents):
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, name), "w") as f:
        f.write(contents)


def test_signature_stable(tmp_path):
    root = tmp_path / "data"
    write(str(root), "f1.parquet", "aaa")
    provider = FileBasedSignatureProvider()
    s1 = provider.signature(make_scan(root))
    s2 = provider.signature(make_scan(root))
    assert s1 is not None and s1 == s2


def test_signature_changes_on_new_file(tmp_path):
    root = tmp_path / "data"
    write(str(root), "f1.parquet", "aaa")
    provider = FileBasedSignatureProvider()
    s1 = provider.signature(make_scan(root))
    write(str(root), "f2.parquet", "bbb")
    s2 = provider.signature(make_scan(root))
    assert s1 != s2


def test_signature_changes_on_content_change(tmp_path):
    root = tmp_path / "data"
    write(str(root), "f1.parquet", "aaa")
    provider = FileBasedSignatureProvider()
    s1 = provider.signature(make_scan(root))
    time.sleep(0.01)  # ensure mtime tick
    write(str(root), "f1.parquet", "aaaa")
    s2 = provider.signature(make_scan(root))
    assert s1 != s2


def test_signature_covers_whole_plan(tmp_path):
    root = tmp_path / "data"
    write(str(root), "f1.parquet", "aaa")
    provider = FileBasedSignatureProvider()
    scan_sig = provider.signature(make_scan(root))
    filter_sig = provider.signature(Filter(col("a") > 1, make_scan(root)))
    # File-based signature ignores plan structure (the reference's known
    # limitation, `JoinIndexRule.scala:194-205`).
    assert scan_sig == filter_sig


def test_provider_factory_roundtrip(tmp_path):
    provider = FileBasedSignatureProvider()
    recreated = SignatureProviderFactory.create(provider.name())
    root = tmp_path / "data"
    write(str(root), "f1.parquet", "aaa")
    assert recreated.signature(make_scan(root)) == provider.signature(make_scan(root))
