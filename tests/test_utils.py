"""Pure unit tests for utilities (reference test layer 1: HashingUtilsTests,
IndexNameUtilsTests, JsonUtilsTests)."""

import os
import threading

from hyperspace_tpu.utils import file_utils
from hyperspace_tpu.utils.hashing import md5_hex
from hyperspace_tpu.utils.name_utils import normalize_index_name


def test_md5_hex_stable():
    assert md5_hex("hyperspace") == md5_hex("hyperspace")
    assert md5_hex("a") != md5_hex("b")
    assert len(md5_hex("x")) == 32


def test_normalize_index_name():
    assert normalize_index_name("  my index ") == "my_index"
    assert normalize_index_name("plain") == "plain"


def test_file_roundtrip(tmp_path):
    path = str(tmp_path / "a" / "b.txt")
    file_utils.create_file(path, "hello")
    assert file_utils.read_contents(path) == "hello"
    file_utils.delete(path)
    assert not os.path.exists(path)


def test_directory_size(tmp_path):
    file_utils.create_file(str(tmp_path / "d" / "x"), "12345")
    file_utils.create_file(str(tmp_path / "d" / "y"), "123")
    assert file_utils.get_directory_size(str(tmp_path / "d")) == 8


def test_atomic_write_if_absent_single_winner(tmp_path):
    target = str(tmp_path / "log" / "0")
    results = []

    def attempt(tag):
        results.append((tag, file_utils.atomic_write_if_absent(target, tag)))

    threads = [threading.Thread(target=attempt, args=(f"w{i}",)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [tag for tag, won in results if won]
    assert len(winners) == 1
    # The file holds exactly the winner's contents.
    assert file_utils.read_contents(target) == winners[0]


def test_atomic_write_if_absent_existing(tmp_path):
    target = str(tmp_path / "f")
    assert file_utils.atomic_write_if_absent(target, "first")
    assert not file_utils.atomic_write_if_absent(target, "second")
    assert file_utils.read_contents(target) == "first"
