"""Broadcast-style dimension joins: plan shape (no Exchange/Sort on
either side), result parity with the general join across join types,
run-time fallback for ineligible keys, and the disable conf — the
engine's analog of Spark's BroadcastHashJoin, which the reference's E2E
suite must disable to exercise SMJ (`E2EHyperspaceRulesTests.scala:42`).
"""

import numpy as np
import pandas as pd
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.physical import BroadcastHashJoinExec
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit


def norm(d):
    out = d.sort_values(list(d.columns)).reset_index(drop=True)
    return out.astype({c: "float64" for c in out.columns
                       if out[c].dtype.kind in "fi"})


@pytest.fixture(params=["host", "device"])
def sess(request, tmp_path):
    conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
    if request.param == "device":
        conf["spark.hyperspace.execution.min.device.rows"] = "0"
    return HyperspaceSession(HyperspaceConf(conf))


@pytest.fixture
def fact_dim(sess):
    rng = np.random.default_rng(11)
    n = 4000
    fact = pd.DataFrame({
        "sk": rng.integers(100, 160, n).astype(np.int64),  # some miss dim
        "qty": rng.integers(1, 9, n).astype(np.int64),
        "amt": rng.random(n),
    })
    dim = pd.DataFrame({
        "d_sk": np.arange(100, 150, dtype=np.int64),
        "d_year": (1998 + (np.arange(50) % 4)).astype(np.int64),
        "d_name": pd.array([f"day{i:02d}" for i in range(50)]),
    })
    return (sess.create_dataframe(fact), sess.create_dataframe(dim),
            fact, dim)


def _physical_names(q):
    _, _, physical = q.explain_plans()
    return [type(n).__name__ for n in physical.collect()]


def test_broadcast_plan_has_no_exchange_or_sort(fact_dim):
    f, d, _, _ = fact_dim
    q = f.join(d, on=col("sk") == col("d_sk"))
    names = _physical_names(q)
    assert names.count("BroadcastHashJoinExec") == 1
    assert names.count("ExchangeExec") == 0
    assert names.count("SortExec") == 0


def test_broadcast_disabled_by_threshold(fact_dim):
    f, d, _, _ = fact_dim
    f.session.conf.set("hyperspace.broadcast.threshold", -1)
    names = _physical_names(f.join(d, on=col("sk") == col("d_sk")))
    assert names.count("BroadcastHashJoinExec") == 0
    assert names.count("ExchangeExec") == 2


@pytest.mark.parametrize("how", ["inner", "left_outer", "right_outer"])
def test_broadcast_join_matches_pandas(fact_dim, how):
    f, d, fact, dim = fact_dim
    q = f.join(d, on=col("sk") == col("d_sk"), how=how)
    assert _physical_names(q).count("BroadcastHashJoinExec") == 1
    got = q.to_pandas()
    exp = fact.merge(dim, left_on="sk", right_on="d_sk",
                     how={"inner": "inner", "left_outer": "left",
                          "right_outer": "right"}[how])
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False,
                                  atol=1e-9)


def test_broadcast_left_build_side(fact_dim):
    """dim JOIN fact right_outer keeps every fact row, so the broadcast
    build side must be the (small) LEFT dim."""
    f, d, fact, dim = fact_dim
    q = d.join(f, on=col("d_sk") == col("sk"), how="right_outer")
    _, _, physical = q.explain_plans()
    nodes = [n for n in physical.collect()
             if isinstance(n, BroadcastHashJoinExec)]
    assert len(nodes) == 1 and nodes[0].build_side == "left"
    got = q.to_pandas()
    exp = dim.merge(fact, left_on="d_sk", right_on="sk", how="right")
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False,
                                  atol=1e-9)


def test_broadcast_semi_anti(fact_dim):
    f, d, fact, dim = fact_dim
    for how, expect in (("left_semi", fact[fact.sk.isin(dim.d_sk)]),
                        ("left_anti", fact[~fact.sk.isin(dim.d_sk)])):
        q = f.join(d, on=col("sk") == col("d_sk"), how=how)
        assert _physical_names(q).count("BroadcastHashJoinExec") == 1
        got = q.to_pandas()
        pd.testing.assert_frame_equal(norm(got), norm(expect),
                                      check_dtype=False, atol=1e-9)


def test_broadcast_null_keys_match_nothing(sess):
    fact = pd.DataFrame({"k": pd.array([1, 2, None, 4], dtype="Int64"),
                         "v": [10, 20, 30, 40]})
    dim = pd.DataFrame({"k2": pd.array([1, None, 4], dtype="Int64"),
                        "w": [100, 200, 300]})
    q = sess.create_dataframe(fact).join(
        sess.create_dataframe(dim), on=col("k") == col("k2"),
        how="left_outer")
    assert _physical_names(q).count("BroadcastHashJoinExec") == 1
    got = q.to_pandas().sort_values("v").reset_index(drop=True)
    assert list(got["w"].fillna(-1)) == [100, -1, -1, 300]


def test_broadcast_duplicate_build_keys_fall_back_correctly(sess):
    """Duplicate build keys are ineligible for the unique-table path —
    execution must fall back to the counting join with identical
    results (including the expansion)."""
    fact = pd.DataFrame({"k": np.arange(2000, dtype=np.int64) % 7,
                         "v": np.arange(2000, dtype=np.int64)})
    dim = pd.DataFrame({"k2": np.asarray([0, 1, 1, 3], dtype=np.int64),
                        "w": np.asarray([9, 8, 7, 6], dtype=np.int64)})
    q = sess.create_dataframe(fact).join(
        sess.create_dataframe(dim), on=col("k") == col("k2"))
    assert _physical_names(q).count("BroadcastHashJoinExec") == 1
    got = q.to_pandas()
    exp = fact.merge(dim, left_on="k", right_on="k2")
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_broadcast_multi_key(sess):
    fact = pd.DataFrame({"a": np.arange(3000, dtype=np.int64) % 5,
                         "b": np.arange(3000, dtype=np.int64) % 11,
                         "v": np.arange(3000, dtype=np.float64)})
    dim = pd.DataFrame({"a2": np.asarray([0, 1, 2, 3], dtype=np.int64),
                        "b2": np.asarray([3, 4, 5, 6], dtype=np.int64),
                        "w": np.asarray([1, 2, 3, 4], dtype=np.int64)})
    q = sess.create_dataframe(fact).join(
        sess.create_dataframe(dim),
        on=(col("a") == col("a2")) & (col("b") == col("b2")))
    assert _physical_names(q).count("BroadcastHashJoinExec") == 1
    got = q.to_pandas()
    exp = fact.merge(dim, left_on=["a", "b"], right_on=["a2", "b2"])
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_broadcast_string_keys_fall_back_correctly(sess):
    """String keys are ineligible for the direct-address table; the node
    still answers correctly through the counting-join fallback."""
    fact = pd.DataFrame({"s": pd.array([f"u{i % 6}" for i in range(500)]),
                         "v": np.arange(500, dtype=np.int64)})
    dim = pd.DataFrame({"s2": pd.array(["u0", "u2", "u4"]),
                        "w": np.asarray([7, 8, 9], dtype=np.int64)})
    q = sess.create_dataframe(fact).join(
        sess.create_dataframe(dim), on=col("s") == col("s2"))
    got = q.to_pandas()
    exp = fact.merge(dim, left_on="s", right_on="s2")
    pd.testing.assert_frame_equal(norm(got[["v", "w"]]),
                                  norm(exp[["v", "w"]]), check_dtype=False)


def test_broadcast_estimator_excludes_aggregates(sess, tmp_path):
    """An aggregate side has no static bound -> never broadcast."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import os
    d = tmp_path / "t"
    os.makedirs(d)
    pq.write_table(pa.table({"k": np.arange(100, dtype=np.int64),
                             "v": np.arange(100, dtype=np.int64)}),
                   str(d / "p.parquet"))
    df = sess.read_parquet(str(d))
    agg = df.group_by("k").agg(("sum", "v", "sv"))
    # Aggregate on the right, tiny scan on the left: the unbounded
    # aggregate must not qualify, so the planner builds LEFT instead.
    from hyperspace_tpu.engine.physical import BroadcastHashJoinExec
    _, _, physical = df.join(agg, on="k").explain_plans()
    nodes = [n for n in physical.collect()
             if isinstance(n, BroadcastHashJoinExec)]
    assert len(nodes) == 1 and nodes[0].build_side == "left"
    # Aggregates on BOTH sides: no static bound anywhere -> no broadcast.
    agg2 = df.group_by("k").agg(("count", "*", "c"))
    q = agg.join(agg2, on="k")
    assert _physical_names(q).count("BroadcastHashJoinExec") == 0
