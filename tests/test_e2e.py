"""End-to-end tests (reference test layer 5: `E2EHyperspaceRulesTests`,
`IndexManagerTests`): real indexes over real parquet, real queries with
rules toggled, asserting (a) scan root paths point at `v__=N` index dirs and
(b) sorted-result equality between index and no-index runs."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.physical import SortMergeJoinExec
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col


@pytest.fixture
def env(tmp_path, sample_parquet):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def run_with_and_without(session, query_df, sort_cols):
    session.disable_hyperspace()
    plain = query_df.to_pandas().sort_values(sort_cols).reset_index(drop=True)
    session.enable_hyperspace()
    indexed = query_df.to_pandas().sort_values(sort_cols).reset_index(drop=True)
    session.disable_hyperspace()
    return plain, indexed


def scan_roots(query_df, session, enabled=True):
    if enabled:
        session.enable_hyperspace()
    _, optimized, _ = query_df.explain_plans()
    session.disable_hyperspace()
    return [root for leaf in optimized.collect_leaves()
            for root in leaf.root_paths]


def test_e2e_filter_query(env):
    """Parity: reference 'E2E test for filter query'
    (`E2EHyperspaceRulesTests.scala:87-96`)."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("filterIdx", ["clicks"], ["id", "score"]))

    query = df.filter(col("clicks") == 42).select("id", "score")
    plain, indexed = run_with_and_without(session, query, ["id"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)
    roots = scan_roots(query, session)
    assert len(roots) == 1 and "filterIdx" in roots[0] and "v__=0" in roots[0]


def test_e2e_filter_string_key(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("strIdx", ["query"], ["id"]))
    query = df.filter(col("query") == "q7").select("id", "query")
    plain, indexed = run_with_and_without(session, query, ["id"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)
    assert "strIdx" in scan_roots(query, session)[0]


def test_e2e_join_query(env):
    """Parity: reference join e2e — bucketed SMJ with no Exchange/Sort."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("jl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("jr", ["imprs"], ["score"]))

    left = df.select("imprs", "id", "clicks")
    right = df.select("imprs", "score")
    query = left.join(right, on="imprs")

    plain, indexed = run_with_and_without(
        session, query, ["imprs", "id", "score"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)

    session.enable_hyperspace()
    _, optimized, physical = query.explain_plans()
    session.disable_hyperspace()
    names = [type(n).__name__ for n in physical.collect()]
    assert names.count("ExchangeExec") == 0
    assert names.count("SortExec") == 0
    smj = [n for n in physical.collect() if isinstance(n, SortMergeJoinExec)]
    assert smj[0].bucketed and smj[0].num_buckets == 4
    roots = [r for leaf in optimized.collect_leaves() for r in leaf.root_paths]
    assert any("jl" in r for r in roots) and any("jr" in r for r in roots)


def test_e2e_filter_under_join(env):
    """Mixed shape: filters over scans below a join (reference covers
    mixed filter-under-join plans)."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("fl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("fr", ["imprs"], ["score"]))
    left = df.select("imprs", "id", "clicks").filter(col("clicks") > 50)
    right = df.select("imprs", "score")
    query = left.join(right, on="imprs")
    plain, indexed = run_with_and_without(
        session, query, ["imprs", "id", "score"])
    pd.testing.assert_frame_equal(plain, indexed)


def test_index_lifecycle_and_catalog(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("lc", ["clicks"], ["id"]))
    cat = hs.indexes()
    assert list(cat["name"]) == ["lc"] and list(cat["state"]) == ["ACTIVE"]
    # queryPlan carries the logged source plan's pretty string (reference
    # `IndexCollectionManager.scala:151-173` — the round-3 gap).
    assert "queryPlan" in cat.columns
    assert "Scan" in cat["queryPlan"][0] and src in cat["queryPlan"][0]

    hs.delete_index("lc")
    assert list(hs.indexes()["state"]) == ["DELETED"]
    hs.restore_index("lc")
    assert list(hs.indexes()["state"]) == ["ACTIVE"]
    hs.delete_index("lc")
    hs.vacuum_index("lc")
    assert len(hs.indexes()) == 0
    index_dir = os.path.join(session.conf.system_path, "lc")
    assert not glob.glob(os.path.join(index_dir, "v__=*"))

    # create again after vacuum (DOESNOTEXIST allows re-create)
    hs.create_index(df, IndexConfig("lc", ["clicks"], ["id"]))
    assert list(hs.indexes()["state"]) == ["ACTIVE"]


def test_view_query_is_index_served(env):
    """Reference E2E view cases (`E2EHyperspaceRulesTests` temp-view
    tests): a filter query over a NAMED view resolves to the same
    underlying relation, so the index rule fires and results match."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("viewIdx", ["clicks"], ["id"]))
    df.create_or_replace_temp_view("sampleView")

    query = session.table("sampleView").filter(
        col("clicks") == 42).select("id")
    plain, indexed = run_with_and_without(session, query, ["id"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)
    roots = scan_roots(query, session)
    assert len(roots) == 1 and "viewIdx" in roots[0]

    # Views layer over arbitrary queries too; rules still fire on the
    # expanded relation underneath.
    (df.filter(col("imprs") > 0)
       .create_or_replace_temp_view("filteredView"))
    q2 = session.table("filteredView").filter(col("clicks") == 42)
    assert session.table("filteredView").count() > 0
    session.disable_hyperspace()
    a = q2.select("id").to_pandas().sort_values("id").reset_index(drop=True)
    session.enable_hyperspace()
    b = q2.select("id").to_pandas().sort_values("id").reset_index(drop=True)
    session.disable_hyperspace()
    pd.testing.assert_frame_equal(a, b)
    assert session.drop_temp_view("sampleView")
    with pytest.raises(HyperspaceException):
        session.table("sampleView")


def test_create_stamps_index_stats(env):
    """Every data-writing action persists on-disk size + row count in the
    log entry (`extra.stats`) at build time, so rule ranking never walks
    the filesystem at query time (round-4 review item 6)."""
    from hyperspace_tpu.utils.file_utils import get_directory_size

    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("st", ["clicks"], ["id"]))
    manager = Hyperspace.get_context(session).index_collection_manager

    def entry_of(name):
        (e,) = [x for x in manager.get_indexes() if x.name == name]
        return e

    entry = entry_of("st")
    stats = entry.extra["stats"]
    assert stats["rowCount"] == 1000
    assert stats["dataSizeBytes"] == get_directory_size(entry.content.root)
    assert stats["dataSizeBytes"] > 0

    hs.refresh_index("st")
    manager.clear_cache()
    entry = entry_of("st")
    stats = entry.extra["stats"]
    assert stats["rowCount"] == 1000
    assert "v__=1" in entry.content.root
    assert stats["dataSizeBytes"] == get_directory_size(entry.content.root)


def test_create_validations(env):
    session, hs, src = env
    df = session.read_parquet(src)
    with pytest.raises(HyperspaceException):
        hs.create_index(df.filter(col("clicks") > 1),
                        IndexConfig("bad", ["clicks"], []))
    with pytest.raises(HyperspaceException):
        hs.create_index(df, IndexConfig("bad2", ["missing_col"], []))
    hs.create_index(df, IndexConfig("dup", ["clicks"], []))
    with pytest.raises(HyperspaceException):
        hs.create_index(df, IndexConfig("dup", ["imprs"], []))


def test_refresh_picks_up_appended_data(env):
    """Parity: reference `IndexManagerTests.scala:189-224` — refresh writes
    v__=1 reflecting new source data; queries then use it."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("rf", ["clicks"], ["id"]))

    # Append rows with a brand-new clicks value (200).
    rng = np.random.default_rng(9)
    extra = pa.table({
        "id": np.arange(10_000, 10_100, dtype=np.int64),
        "clicks": np.full(100, 200, dtype=np.int32),
        "score": rng.random(100),
        "imprs": rng.integers(0, 10, 100),
        "query": pa.array(["qNEW"] * 100),
    })
    pq.write_table(extra, os.path.join(src, "part-1.parquet"))

    # Stale index: signature mismatch -> rule must NOT fire.
    query = session.read_parquet(src).filter(col("clicks") == 200).select("id")
    roots = scan_roots(query, session)
    assert all("rf" not in r for r in roots)
    session.disable_hyperspace()
    assert query.count() == 100

    hs.refresh_index("rf")
    index_dir = os.path.join(session.conf.system_path, "rf")
    assert os.path.isdir(os.path.join(index_dir, "v__=0"))
    assert os.path.isdir(os.path.join(index_dir, "v__=1"))

    fresh = session.read_parquet(src).filter(col("clicks") == 200).select("id")
    roots = scan_roots(fresh, session)
    assert len(roots) == 1 and "v__=1" in roots[0]
    session.enable_hyperspace()
    assert fresh.count() == 100
    session.disable_hyperspace()


def test_bucketed_layout_on_disk(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("bk", ["clicks"], ["id"]))
    data_dir = os.path.join(session.conf.system_path, "bk", "v__=0")
    files = sorted(glob.glob(os.path.join(data_dir, "part-*.parquet")))
    assert 1 <= len(files) <= 4
    # within-bucket sortedness
    for f in files:
        clicks = pq.read_table(f).column("clicks").to_pylist()
        assert clicks == sorted(clicks)
    # total rows preserved
    total = sum(pq.read_table(f).num_rows for f in files)
    assert total == df.count()


def test_filter_rewrite_preserves_column_order(env):
    """Enabling indexes must not change result column order, even for a
    bare Filter(Scan) with no Project on top."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("ord", ["clicks"],
                                    ["id", "score", "imprs", "query"]))
    query = df.filter(col("clicks") == 2)
    plain, indexed = run_with_and_without(session, query, ["id"])
    assert list(plain.columns) == list(indexed.columns) == df.columns
    pd.testing.assert_frame_equal(plain, indexed)


def test_stale_hash_version_layout_reads_unbucketed(env, tmp_path):
    """An index data dir written under an older bucket-hash identity must
    be served UNBUCKETED (correct results, no pruning) rather than
    mis-bucketing point lookups against the new hash."""
    import json

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.plan.expr import col, lit
    from hyperspace_tpu.plan.rules import base as rules_base
    from hyperspace_tpu.utils import file_utils

    sess, hs, _ = env
    src = tmp_path / "src_stale"
    src.mkdir()
    pq.write_table(pa.table({"id": np.arange(500, dtype=np.int64),
                             "v": np.arange(500, dtype=np.int64) * 3}),
                   str(src / "p.parquet"))
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("idx_stale", ["id"], ["v"]))

    # Forge an OLD hash version into the sidecar.
    entry = [e for e in hs.indexes().to_dict("records")
             if e["name"] == "idx_stale"][0]
    root = entry["indexLocation"]
    spec_path = root + "/_bucket_spec.json"
    payload = json.loads(file_utils.read_contents(spec_path))
    payload["hashVersion"] = 1
    file_utils.delete(spec_path)
    file_utils.create_file(spec_path, json.dumps(payload))
    rules_base._layout_hash_current.cache_clear()

    sess.enable_hyperspace()
    q = df.filter(col("id") == lit(123)).select("id", "v")
    opt = q._optimized_plan()
    scans = [leaf for leaf in opt.collect_leaves()]
    assert any("v__=" in p for s in scans for p in s.root_paths)
    assert all(s.bucket_spec is None for s in scans)  # stale -> unbucketed
    got = q.collect().to_pandas()
    assert got.values.tolist() == [[123, 369]]
    rules_base._layout_hash_current.cache_clear()
