"""Process-wide observability (PR 2): metrics registry, trace-span
export (Chrome trace-event schema round-trip), structured action
reports, and mesh-path telemetry on the virtual 8-device mesh."""

import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, IndexConfig, telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col, lit

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracing():
    """Enable the process tracer for one test, always tearing it back
    down (the tracer is process-global)."""
    tracer = telemetry.enable_tracing()
    try:
        yield tracer
    finally:
        telemetry.disable_tracing()


@pytest.fixture
def sales_env(tmp_path):
    """Two joinable tables + a session factory with a tmp warehouse."""
    rng = np.random.default_rng(23)
    n, n_dim = 5000, 200
    fact_dir = tmp_path / "fact"
    dim_dir = tmp_path / "dim"
    fact_dir.mkdir()
    dim_dir.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, n_dim, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": rng.random(n) * 100,
    }), str(fact_dir / "part-0.parquet"))
    pq.write_table(pa.table({
        "key": np.arange(n_dim, dtype=np.int64),
        "grp": rng.integers(0, 10, n_dim).astype(np.int64),
    }), str(dim_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
                "spark.hyperspace.index.num.buckets": "8"}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(fact_dir), str(dim_dir)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = telemetry.MetricsRegistry()
    reg.counter("a.b").inc()
    reg.counter("a.b").inc(2.5)
    reg.gauge("g").set(7)
    for v in (1, 3, 1000, 0.25, 0):
        reg.histogram("h.bytes").observe(v)
    assert reg.counter("a.b").value == 3.5
    assert reg.gauge("g").value == 7
    h = reg.histogram("h.bytes").to_dict()
    assert h["count"] == 5 and h["min"] == 0 and h["max"] == 1000
    # log2 buckets: 1 -> le 1, 3 -> le 4, 1000 -> le 1024, 0.25 -> le
    # 0.25, 0 -> the "0" bucket.
    assert h["buckets"]["1024.0"] == 1 and h["buckets"]["0"] == 1
    snap = reg.to_dict()
    assert snap["counters"]["a.b"] == 3.5
    assert "h.bytes" in snap["histograms"]
    # name collisions across types are an error, not silent aliasing
    with pytest.raises(TypeError):
        reg.gauge("a.b")


def test_registry_prometheus_text():
    reg = telemetry.MetricsRegistry()
    reg.counter("fusion.stage_execs").inc(4)
    reg.gauge("mesh.devices").set(8)
    reg.histogram("link.h2d.bytes_per_transfer").observe(100)
    reg.histogram("link.h2d.bytes_per_transfer").observe(5000)
    text = reg.to_text()
    assert "# TYPE hs_fusion_stage_execs counter" in text
    assert "hs_fusion_stage_execs 4" in text
    assert "hs_mesh_devices 8" in text
    # histogram exposition: cumulative buckets, +Inf, sum, count
    assert 'hs_link_h2d_bytes_per_transfer_bucket{le="128"} 1' in text
    assert 'hs_link_h2d_bytes_per_transfer_bucket{le="+Inf"} 2' in text
    assert "hs_link_h2d_bytes_per_transfer_count 2" in text


def test_process_registry_shared_across_sessions(sales_env):
    session, fact_dir, _dim = sales_env
    s1, s2 = session(), session()
    assert s1.metrics_registry() is s2.metrics_registry()
    assert s1.metrics_registry() is telemetry.get_registry()
    before = s1.metrics_registry().counter("queries.total").value
    s1.read_parquet(fact_dir).select("key").collect()
    s2.read_parquet(fact_dir).select("qty").collect()
    reg = s1.metrics_registry()
    assert reg.counter("queries.total").value == before + 2
    assert reg.counter("queries.seconds").value > 0


def test_fusion_stats_is_registry_view(sales_env):
    from hyperspace_tpu.engine import fusion

    session, fact_dir, _dim = sales_env
    sess = session(**{
        "spark.hyperspace.execution.min.device.rows": "0",
        "spark.hyperspace.distribution.enabled": "false"})
    for k in fusion.STATS:
        fusion.STATS[k] = 0 if isinstance(fusion.STATS[k], int) else 0.0
    q = sess.read_parquet(fact_dir).filter(
        col("qty") > lit(10)).select("key")
    q.collect()
    # One storage, two views: the dict-shaped consumer contract and the
    # registry counter agree exactly.
    reg = telemetry.get_registry()
    assert fusion.STATS["stage_execs"] >= 1
    assert reg.counter("fusion.stage_execs").value \
        == fusion.STATS["stage_execs"]
    assert reg.counter("fusion.dispatch_s").value \
        == fusion.STATS["dispatch_s"]
    # Fused device lane promoted host batches over the link — the
    # transfer histograms saw it.
    assert reg.counter("link.h2d.bytes").value > 0
    assert reg.histogram("link.h2d.bytes_per_transfer").count > 0


# ---------------------------------------------------------------------------
# Action reports
# ---------------------------------------------------------------------------


def test_action_reports_full_maintenance_cycle(sales_env, tmp_path):
    session, fact_dir, _dim = sales_env
    sess = session()
    hs = Hyperspace(sess)
    reg = sess.metrics_registry()

    def runs(name):
        return reg.counter(f"actions.{name}.runs").value

    base = {n: runs(n) for n in ("CreateAction", "RefreshAction",
                                 "OptimizeAction")}
    fact = sess.read_parquet(fact_dir)
    hs.create_index(fact, IndexConfig("sales_key", ["key"],
                                      ["qty", "price"]))
    hs.refresh_index("sales_key", mode="full")
    hs.optimize_index("sales_key")

    # The acceptance surface: nonzero action-report counters after a
    # create+refresh+optimize cycle, via session.metrics_registry().
    assert runs("CreateAction") == base["CreateAction"] + 1
    assert runs("RefreshAction") == base["RefreshAction"] + 1
    assert runs("OptimizeAction") == base["OptimizeAction"] + 1
    assert reg.counter("actions.rows_indexed").value > 0
    assert reg.counter("actions.bytes_written").value > 0

    # The report ring holds the structured reports, newest last.
    report = reg.last_action_report()
    assert report["action"] == "OptimizeAction"
    assert report["ok"] is True and report["index"] == "sales_key"
    assert set(report["phases"]) == {"validate", "begin", "op", "end"}
    assert all(v >= 0 for v in report["phases"].values())
    assert report["detail"]["rows"] > 0 and report["detail"]["bytes"] > 0
    assert report["detail"]["files_written"] > 0

    # Persisted alongside the final log entry, keyed by its id.
    log_dir = os.path.join(sess.conf.system_path, "sales_key",
                           "_hyperspace_log")
    sidecars = sorted(f for f in os.listdir(log_dir)
                      if f.endswith(".report.json"))
    assert len(sidecars) == 3  # create, refresh, optimize
    with open(os.path.join(log_dir, sidecars[0])) as f:
        persisted = json.load(f)
    assert persisted["action"] == "CreateAction"
    assert persisted["log_id"] == int(sidecars[0].split(".")[0])
    # ...and readable back through the log manager API.
    from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
    lm = IndexLogManagerImpl(os.path.join(sess.conf.system_path,
                                          "sales_key"))
    assert lm.get_action_report(persisted["log_id"])["action"] \
        == "CreateAction"
    # The sidecars never perturb log-id resolution.
    assert lm.get_latest_id() == persisted["log_id"] + 4


def test_failed_action_reports_failure_counter(sales_env):
    session, fact_dir, _dim = sales_env
    sess = session()
    hs = Hyperspace(sess)
    reg = sess.metrics_registry()
    fact = sess.read_parquet(fact_dir)
    hs.create_index(fact, IndexConfig("dupe", ["key"], ["qty"]))
    before = reg.counter("actions.CreateAction.failures").value
    with pytest.raises(HyperspaceException):
        hs.create_index(fact, IndexConfig("dupe", ["key"], ["qty"]))
    assert reg.counter("actions.CreateAction.failures").value \
        == before + 1
    report = reg.last_action_report()
    assert report["ok"] is False and "error" in report
    assert "log_id" not in report  # nothing was committed


# ---------------------------------------------------------------------------
# Trace export
# ---------------------------------------------------------------------------


def test_export_trace_requires_enable(tmp_path):
    assert not telemetry.tracing_enabled()
    with pytest.raises(HyperspaceException):
        telemetry.export_trace(str(tmp_path / "t.json"))


def test_trace_export_roundtrip_chrome_schema(sales_env, tmp_path,
                                              tracing):
    session, fact_dir, dim_dir = sales_env
    sess = session(**{
        "spark.hyperspace.execution.min.device.rows": "0",
        "spark.hyperspace.distribution.enabled": "false"})
    hs = Hyperspace(sess)
    fact = sess.read_parquet(fact_dir)
    dim = sess.read_parquet(dim_dir)
    hs.create_index(fact, IndexConfig("tr_fact", ["key"],
                                      ["qty", "price"]))
    hs.create_index(dim, IndexConfig("tr_dim", ["key"], ["grp"]))
    sess.enable_hyperspace()
    # Bucketed SMJ: both sides read concurrently on pool threads.
    (fact.join(dim, on="key").select("qty", "grp")).collect()
    # Fused filter on the forced device lane: link-transfer spans.
    fact.filter(col("qty") > lit(5)).select("price").collect()

    path = str(tmp_path / "trace.json")
    info = telemetry.export_trace(path)
    assert info["path"] == path and info["events"] > 0

    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0

    spans = [e for e in events if e["ph"] == "X"]
    cats = {e.get("cat") for e in spans}
    assert {"query", "operator", "fusion", "link", "action"} <= cats
    # Spans from at least two REAL threads (join sides on the pool).
    op_tids = {e["tid"] for e in spans if e.get("cat") == "operator"}
    assert len(op_tids) >= 2
    # Nesting: an operator span contained within a query span on the
    # same thread (Chrome nests by ts/dur containment).
    queries = [e for e in spans if e.get("cat") == "query"]
    nested = [
        (q, o) for q in queries
        for o in spans if o.get("cat") == "operator"
        and o["tid"] == q["tid"] and o["ts"] >= q["ts"]
        and o["ts"] + o["dur"] <= q["ts"] + q["dur"] + 1.0]
    assert nested, "no operator span nested inside a query span"
    # ...and a link transfer nested inside the query window too.
    links = [e for e in spans if e.get("cat") == "link"]
    assert links and all(e["args"]["bytes"] >= 0 for e in links)
    # Thread-name metadata present for the engine process.
    metas = [e for e in events if e["ph"] == "M"
             and e["name"] == "thread_name"]
    assert metas


def test_facade_export_trace(sales_env, tmp_path, tracing):
    session, fact_dir, _dim = sales_env
    sess = session()
    hs = Hyperspace(sess)
    sess.read_parquet(fact_dir).select("key").collect()
    out = hs.export_trace(str(tmp_path / "t.json"))
    assert os.path.exists(out["path"])
    assert hs.metrics_registry() is telemetry.get_registry()


# ---------------------------------------------------------------------------
# Mesh-path telemetry (virtual 8-device mesh; conftest ensures devices)
# ---------------------------------------------------------------------------


def test_mesh_build_telemetry_and_device_spans(tmp_path, sales_env,
                                               tracing):
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.parallel.build import distributed_build
    from hyperspace_tpu.parallel.mesh import make_mesh
    from hyperspace_tpu.telemetry.trace import PID_MESH

    mesh = make_mesh(8)
    reg = telemetry.get_registry()
    assert reg.gauge("mesh.devices").value == 8
    execs_before = reg.counter("mesh.build.execs").value

    rng = np.random.default_rng(5)
    batch = columnar.from_arrow(pa.table({
        "k": rng.integers(0, 100, 2000).astype(np.int64),
        "v": rng.random(2000)}))
    # Recorder propagation: the mesh path attributes its events and
    # sync seconds to the active per-query recorder.
    rec = telemetry.QueryMetrics("mesh build")
    with telemetry.recording(rec):
        built, lengths = distributed_build(batch, ["k"], 16, mesh)
    assert built.num_rows == 2000

    assert reg.counter("mesh.build.execs").value == execs_before + 1
    assert reg.counter("mesh.build.dispatch_s").value > 0
    assert reg.histogram("mesh.build.shard_rows").count >= 8
    mesh_events = rec.events_of("mesh", "build")
    assert mesh_events and mesh_events[0]["shards"] == 8
    assert sum(mesh_events[0]["shard_rows"]) == 2000
    assert rec.counters["mesh.sync_s"] >= 0
    # Per-device span attribution on the synthetic mesh process: one
    # track per device, rows in args.
    dev_spans = [e for e in tracing.events
                 if e.get("pid") == PID_MESH and e["ph"] == "X"]
    assert {e["tid"] for e in dev_spans} == set(range(8))
    assert sum(e["args"]["rows"] for e in dev_spans
               if e["name"].startswith("build")) == 2000


def test_mesh_join_query_attributes_to_recorder(sales_env, tracing):
    """A distributed bucketed join inside collect(): mesh events, shard
    attribution, and link bytes all land on THAT query's recorder
    (propagation across the join's pool threads included)."""
    session, fact_dir, dim_dir = sales_env
    sess = session(**{"spark.hyperspace.distribution.enabled": "true"})
    hs = Hyperspace(sess)
    fact = sess.read_parquet(fact_dir)
    dim = sess.read_parquet(dim_dir)
    hs.create_index(fact, IndexConfig("mj_fact", ["key"],
                                      ["qty", "price"]))
    hs.create_index(dim, IndexConfig("mj_dim", ["key"], ["grp"]))
    sess.enable_hyperspace()
    _, m = (fact.join(dim, on="key").select("qty", "grp")).collect(
        with_metrics=True)
    joins = m.events_of("mesh", "join")
    assert joins, f"no mesh join events; got {m.events}"
    assert joins[0]["shards"] == 8
    assert len(joins[0]["shard_rows"]) == 8
    assert m.counters.get("link.h2d_bytes", 0) > 0
    reg = telemetry.get_registry()
    assert reg.counter("mesh.join.execs").value >= 1
    assert reg.histogram("mesh.join.shard_rows").count >= 8


# ---------------------------------------------------------------------------
# bench_regress gate
# ---------------------------------------------------------------------------


def _write_artifact(path, ratios, wrap_parsed=False):
    # Canonical-schema fixture (telemetry/artifact.py): bench_regress
    # refuses legacy shapes outright, so gate fixtures carry the
    # required stamp fields.
    doc = {"schema_version": 1, "metric": "fixture", "value": 1.0,
           "process_metrics": {},
           "vs_baseline": ratios.get("headline", 1.0),
           "rungs": {k: {"vs_baseline": v} for k, v in ratios.items()
                     if k != "headline"}}
    if wrap_parsed:
        doc = {"parsed": doc, "rc": 0, "cmd": "python bench.py"}
    with open(path, "w") as f:
        json.dump(doc, f)


def test_bench_regress_gate(tmp_path):
    script = os.path.join(REPO_ROOT, "scripts", "bench_regress.py")
    old = str(tmp_path / "BENCH_r01.json")
    ok = str(tmp_path / "BENCH_r02.json")
    bad = str(tmp_path / "BENCH_r03.json")
    _write_artifact(old, {"headline": 2.0, "1_build": 2.0,
                          "2_filter": 100.0})
    # within 15%: passes (one rung only present in new: never gates)
    _write_artifact(ok, {"headline": 1.8, "1_build": 1.8,
                         "2_filter": 90.0, "9_new": 1.0},
                    wrap_parsed=True)
    # 2_filter drops 40%: fails
    _write_artifact(bad, {"headline": 2.0, "1_build": 2.0,
                          "2_filter": 60.0})
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    good = subprocess.run([sys.executable, script, old, ok],
                          capture_output=True, text=True, env=env)
    assert good.returncode == 0, good.stdout + good.stderr
    assert "bench_regress: OK" in good.stdout
    regress = subprocess.run([sys.executable, script, old, bad],
                             capture_output=True, text=True, env=env)
    assert regress.returncode == 1
    assert "2_filter" in regress.stderr
