"""Regression attribution (PR 6): the canonical bench-artifact schema
(`telemetry/artifact.py`), the telemetry differ (`telemetry/diff.py`),
the `bench_diff` CLI, the TPC-DS gate + legacy refusal in
`bench_regress.py`, and the Prometheus exposition-format conformance
of `registry.to_text()`."""

import json
import os
import re
import subprocess
import sys

import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.telemetry import artifact, diff

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


# ---------------------------------------------------------------------------
# Canonical schema
# ---------------------------------------------------------------------------


def test_make_artifact_is_canonical():
    doc = artifact.make_artifact(driver="test", metric="m", value=1.0,
                                 unit="s", vs_baseline=2.0,
                                 queries={"q1": {"rules_on_s": 0.5}})
    assert artifact.is_canonical(doc)
    assert doc["schema_version"] == artifact.SCHEMA_VERSION
    # The emitter attaches the process digests UNCONDITIONALLY — a
    # driver cannot produce a canonical artifact missing them.
    assert "process_metrics" in doc
    assert "memory" in doc
    assert set(doc["transfer"]) >= {"h2d_bytes", "d2h_bytes",
                                    "overlap_saved_seconds"}


def test_validate_flags_legacy_shapes():
    legacy = {"metric": "m", "value": 1, "vs_baseline": 2.0}
    missing = artifact.validate(legacy)
    assert "schema_version" in missing and "process_metrics" in missing
    migrated = artifact.migrate(legacy)
    assert artifact.is_canonical(migrated)
    assert migrated["legacy"] is True
    # lossless: every legacy field survives
    assert migrated["metric"] == "m" and migrated["vs_baseline"] == 2.0
    # canonical input passes through unchanged
    assert artifact.migrate(migrated) is migrated


def test_unwrap_driver_envelope():
    inner = {"schema_version": 1, "metric": "m", "value": 1,
             "vs_baseline": 1.0, "process_metrics": {}}
    wrapped = {"n": 5, "cmd": "python bench.py", "rc": 0,
               "tail": "...", "parsed": inner}
    assert artifact.unwrap(wrapped) == inner
    assert artifact.is_canonical(wrapped)


def test_load_refuses_legacy_then_migrates(tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"metric": "m", "value": 1,
                             "vs_baseline": 1.0}))
    with pytest.raises(artifact.LegacyArtifactError) as exc:
        artifact.load(str(p))
    assert "migrate" in str(exc.value)
    doc = artifact.load(str(p), migrate_legacy=True)
    assert doc["legacy"] and artifact.is_canonical(doc)


def test_migrate_file_preserves_envelope(tmp_path):
    p = tmp_path / "BENCH_r05.json"
    p.write_text(json.dumps({"n": 5, "cmd": "python bench.py", "rc": 0,
                             "tail": "t",
                             "parsed": {"metric": "m", "value": 1,
                                        "vs_baseline": 1.0}}))
    assert artifact.migrate_file(str(p))
    outer = json.loads(p.read_text())
    assert outer["cmd"] == "python bench.py"  # envelope survives
    assert artifact.is_canonical(outer["parsed"])
    assert not artifact.migrate_file(str(p))  # idempotent


def test_committed_artifacts_are_canonical():
    """Every committed bench round must load without legacy migration
    — the repo's own artifacts obey the repo's own schema."""
    import glob
    for path in sorted(glob.glob(os.path.join(REPO_ROOT,
                                              "BENCH_*r*.json"))):
        doc = artifact.load(path)  # raises LegacyArtifactError if not
        assert doc["schema_version"] == artifact.SCHEMA_VERSION, path


# ---------------------------------------------------------------------------
# The differ: telemetry-based attribution
# ---------------------------------------------------------------------------


def _tree(wall, op_walls, counters=None, events=None):
    """A minimal QueryMetrics.to_dict()-shaped tree: a linear chain of
    operators (parent -> child) with the given walls."""
    ops = []
    cum = list(op_walls)
    # wall of node i includes its children: accumulate from the leaf.
    for i, name_wall in enumerate(op_walls):
        name, self_s = name_wall
        total = sum(w for _, w in op_walls[i:])
        ops.append({"op_id": i, "parent_id": i - 1 if i else None,
                    "name": name, "label": name, "wall_s": total,
                    "rows_out": 100})
    del cum
    return {"description": "t", "wall_s": wall, "operators": ops,
            "events": events or [], "counters": counters or {},
            "index_usage": [], "peak_hbm_bytes": 0,
            "peak_hbm_per_device": {}, "compile": {}}


def test_diff_trees_attributes_compile_regression():
    """Synthetic retrace regression: same operator work, +2s of
    compile — the compile bucket must dominate and carry the cause."""
    old = _tree(1.0, [("Project", 0.2), ("Filter", 0.3), ("Scan", 0.4)],
                counters={"compile.seconds": 0.0, "plan_s": 0.05})
    new = _tree(3.1, [("Project", 0.2), ("Filter", 2.4), ("Scan", 0.4)],
                counters={"compile.seconds": 2.0, "compile.traces": 3,
                          "plan_s": 0.05},
                events=[{"category": "compile", "name": "retrace",
                         "target": "fusion.run_stage",
                         "cause": "shape/dtype: f64[4000] -> f64[8000]"}])
    qd = diff.diff_trees(old, new, name="q_retrace")
    assert qd.dominant == "compile"
    buckets = {b.name: b for b in qd.buckets}
    assert buckets["compile"].seconds == pytest.approx(2.0)
    assert buckets["compile"].detail["traces"] == 3
    assert buckets["compile"].detail["retrace_causes"][0]["cause"] \
        .startswith("shape/dtype")
    # the +2.1s of operator movement nets out the compile seconds: the
    # compute bucket holds only the genuine +0.1s
    assert buckets["compute"].seconds == pytest.approx(0.1)
    # decomposition sums exactly to the wall delta
    total = sum(b.seconds for b in qd.buckets)
    assert total == pytest.approx(qd.delta)


def test_diff_trees_attributes_link_regression():
    old = _tree(1.0, [("Join", 0.5), ("Scan", 0.4)],
                counters={"link.h2d_s": 0.1, "link.h2d_bytes": 1000})
    new = _tree(2.5, [("Join", 0.5), ("Scan", 1.9)],
                counters={"link.h2d_s": 1.6, "link.h2d_bytes": 9000})
    qd = diff.diff_trees(old, new, name="q_link")
    assert qd.dominant == "link"
    buckets = {b.name: b for b in qd.buckets}
    assert buckets["link"].seconds == pytest.approx(1.5)
    assert buckets["link"].detail["link.h2d_bytes"] == 8000


def test_diff_trees_cache_and_fallback_evidence():
    old = _tree(1.0, [("Scan", 0.9)],
                counters={"cache.parquet_read.hits": 10})
    new = _tree(1.1, [("Scan", 1.0)],
                counters={"cache.parquet_read.hits": 2,
                          "cache.parquet_read.misses": 8,
                          "resilience.fallbacks": 1},
                events=[{"category": "resilience", "name": "degraded",
                         "index": "idx", "reason": "gone"}])
    qd = diff.diff_trees(old, new, name="q_cache")
    buckets = {b.name: b for b in qd.buckets}
    assert buckets["cache"].detail["cache.parquet_read.misses"] == 8
    assert buckets["cache"].detail["cache.parquet_read.hits"] == -8
    assert buckets["fallback"].detail["fallbacks"] == 1
    # evidence buckets never claim seconds (their cost is already in
    # compute/link — no double counting)
    assert buckets["cache"].seconds == 0.0
    assert buckets["fallback"].seconds == 0.0


def test_diff_live_query_metrics_round_trip(tmp_path):
    """diff_trees accepts live QueryMetrics objects, not just dicts."""
    qm_old = telemetry.QueryMetrics("a")
    op = qm_old.start_operator("Scan")
    qm_old.finish_operator(op, rows_out=10)
    qm_old.add_seconds("plan_s", 0.01)
    qm_old.finish()
    qm_new = telemetry.QueryMetrics("a")
    op = qm_new.start_operator("Scan")
    qm_new.finish_operator(op, rows_out=10)
    qm_new.add_seconds("plan_s", 0.02)
    qm_new.finish()
    qd = diff.diff_trees(qm_old, qm_new)
    assert qd.old_wall is not None and qd.new_wall is not None
    assert {b.name for b in qd.buckets} >= {"compute", "link",
                                            "compile", "residual"}


# ---------------------------------------------------------------------------
# The differ: legacy per-lane attribution + the committed r03/r04 pair
# ---------------------------------------------------------------------------


def test_legacy_lane_attribution_names_framework_common():
    """When no telemetry exists (legacy rounds), the slowdown the
    rules-OFF lane also paid is attributed as framework/environment-
    common — only the remainder can be index-path work."""
    old = artifact.migrate({"metric": "m", "value": 25.6,
                            "vs_baseline": 3.3, "queries": {
                                "q64": {"rules_on_s": 25.0,
                                        "rules_off_s": 33.0,
                                        "pandas_s": 84.0}}})
    new = artifact.migrate({"metric": "m", "value": 137.8,
                            "vs_baseline": 0.45, "queries": {
                                "q64": {"rules_on_s": 138.0,
                                        "rules_off_s": 142.0,
                                        "pandas_s": 61.0}}})
    d = diff.diff_artifacts(old, new)
    (qd,) = d.queries
    assert qd.dominant == "framework_common"
    buckets = {b.name: b for b in qd.buckets}
    # 25.0 * (142/33 - 1) ~ +82.6s of the +113s is lane-common
    assert buckets["framework_common"].seconds == pytest.approx(
        25.0 * (142.0 / 33.0 - 1.0))
    assert buckets["framework_common"].seconds \
        + buckets["residual"].seconds == pytest.approx(qd.delta)


def test_committed_r03_r04_pair_attributes_q64():
    """THE acceptance pair: the migrated r03/r04 TPC-DS artifacts must
    diff mechanically, and q64's slowdown must name a dominant
    bucket."""
    old = artifact.load(os.path.join(REPO_ROOT, "BENCH_TPCDS_r03.json"))
    new = artifact.load(os.path.join(REPO_ROOT, "BENCH_TPCDS_r04.json"))
    d = diff.diff_artifacts(old, new, "r03", "r04")
    q64 = next(q for q in d.queries if q.name == "q64")
    assert q64.ratio > 2.0  # the regression is real in the artifacts
    assert q64.dominant == "framework_common"
    tree = d.format_tree()
    assert "q64" in tree and "dominant: framework_common" in tree
    # machine form round-trips
    doc = json.loads(d.to_json())
    assert doc["queries"][0]["query"] == "q64"  # ranked: biggest first


def test_rung_artifacts_diff_via_device_walls():
    old = artifact.migrate({"metric": "m", "value": 1, "vs_baseline": 2,
                            "rungs": {"2_filter_query":
                                      {"device_s": 0.1, "cpu_s": 0.3,
                                       "vs_baseline": 3.0}}})
    new = artifact.migrate({"metric": "m", "value": 1, "vs_baseline": 1,
                            "rungs": {"2_filter_query":
                                      {"device_s": 0.4, "cpu_s": 0.3,
                                       "vs_baseline": 0.75}}})
    d = diff.diff_artifacts(old, new)
    (qd,) = d.queries
    assert qd.name == "2_filter_query"
    assert qd.delta == pytest.approx(0.3)


# ---------------------------------------------------------------------------
# bench_diff CLI
# ---------------------------------------------------------------------------


def test_bench_diff_cli_on_committed_pair():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "bench_diff.py"),
         os.path.join(REPO_ROOT, "BENCH_TPCDS_r03.json"),
         os.path.join(REPO_ROOT, "BENCH_TPCDS_r04.json")],
        capture_output=True, text=True, env=_ENV)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "dominant: framework_common" in out.stdout
    assert "q64" in out.stdout
    js = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "bench_diff.py"),
         os.path.join(REPO_ROOT, "BENCH_TPCDS_r03.json"),
         os.path.join(REPO_ROOT, "BENCH_TPCDS_r04.json"),
         "--json", "--query", "q64"],
        capture_output=True, text=True, env=_ENV)
    assert js.returncode == 0
    doc = json.loads(js.stdout)
    assert doc["queries"][0]["query"] == "q64"
    assert doc["queries"][0]["dominant"] == "framework_common"


# ---------------------------------------------------------------------------
# bench_regress: TPC-DS gate, attribution on failure, legacy refusal
# ---------------------------------------------------------------------------


def _regress(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "bench_regress.py"), *args],
        capture_output=True, text=True, env=_ENV)


def test_bench_regress_replays_tpcds_regression_with_attribution():
    """THE acceptance replay: gating the committed r03 -> r04 pair
    exits nonzero AND prints the attribution tree."""
    out = _regress(os.path.join(REPO_ROOT, "BENCH_TPCDS_r03.json"),
                   os.path.join(REPO_ROOT, "BENCH_TPCDS_r04.json"))
    assert out.returncode == 1
    assert "q64" in out.stdout and "REGRESSION" in out.stdout
    assert "Attribution:" in out.stdout
    assert "dominant: framework_common" in out.stdout
    assert "FAILED" in out.stderr


def test_bench_regress_gates_per_query(tmp_path):
    def write(path, agg, q_ratios):
        doc = {"schema_version": 1, "metric": "tpcds", "value": 1.0,
               "process_metrics": {}, "vs_baseline": agg,
               "queries": {q: {"vs_baseline": r, "rules_on_s": 1.0,
                               "rules_off_s": 1.0}
                           for q, r in q_ratios.items()}}
        path.write_text(json.dumps(doc))

    old, new = tmp_path / "a.json", tmp_path / "b.json"
    write(old, 3.0, {"q17": 3.0, "q64": 3.0})
    # aggregate holds, ONE query tanks: the per-query gate must fire
    write(new, 2.9, {"q17": 3.2, "q64": 1.0})
    out = _regress(str(old), str(new))
    assert out.returncode == 1
    assert "q64" in out.stderr
    write(new, 2.9, {"q17": 3.0, "q64": 2.8})
    assert _regress(str(old), str(new)).returncode == 0


def test_bench_regress_refuses_legacy_schema(tmp_path):
    legacy = tmp_path / "BENCH_TPCDS_r01.json"
    legacy.write_text(json.dumps({"metric": "m", "value": 1,
                                  "vs_baseline": 3.0, "queries": {}}))
    good = tmp_path / "BENCH_TPCDS_r02.json"
    good.write_text(json.dumps({"schema_version": 1, "metric": "m",
                                "value": 1, "vs_baseline": 3.0,
                                "process_metrics": {}, "queries": {}}))
    out = _regress(str(legacy), str(good))
    assert out.returncode == 2
    assert "legacy-schema" in out.stderr
    assert "migrate" in out.stderr


def test_pick_latest_two_numeric_round_ordering(tmp_path, monkeypatch):
    """`_r9` vs `_r10`: lexicographic sort would pick r9 as newest."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)
    for n in (1, 2, 9, 10):
        (tmp_path / f"BENCH_r{n}.json").write_text("{}")
    monkeypatch.setattr(bench_regress, "REPO_ROOT", str(tmp_path))
    old, new = bench_regress.pick_latest_two("BENCH_r*.json")
    assert os.path.basename(old) == "BENCH_r9.json"
    assert os.path.basename(new) == "BENCH_r10.json"
    # zero-padded and unpadded rounds interleave numerically too
    (tmp_path / "BENCH_r04.json").write_text("{}")
    old, new = bench_regress.pick_latest_two("BENCH_r*.json")
    assert os.path.basename(new) == "BENCH_r10.json"


def test_check_metrics_coverage_bench_artifact_seam(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import check_metrics_coverage as cmc
    finally:
        sys.path.pop(0)
    # the real drivers all route through the emitter
    assert cmc.check_bench_artifact_seam(REPO_ROOT) == []
    # a rogue driver printing its own top-level JSON fails the lint
    (tmp_path / "bench_rogue.py").write_text(
        "import json\nprint(json.dumps({'metric': 'm'}))\n")
    failures = cmc.check_bench_artifact_seam(str(tmp_path))
    assert len(failures) == 1 and "bench_rogue.py" in failures[0]
    (tmp_path / "bench_ok.py").write_text(
        "from hyperspace_tpu.telemetry.artifact import make_artifact\n"
        "print(make_artifact(driver='x', metric='m', value=1,\n"
        "                    unit='s', vs_baseline=1))\n")
    failures = cmc.check_bench_artifact_seam(str(tmp_path))
    assert len(failures) == 1  # bench_ok passes, rogue still fails


# ---------------------------------------------------------------------------
# Prometheus exposition-format conformance (registry.to_text)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"            # metric name
    r"(?:\{([a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\")*)\})?"
    r" (NaN|[+-]?(?:Inf|[0-9.eE+-]+))$")      # value


def test_prometheus_conformance():
    reg = telemetry.MetricsRegistry()
    reg.counter("fusion.stage_execs").inc(4)
    reg.counter("link.h2d.bytes").inc(1 << 20)
    reg.gauge("mesh.devices").set(8)
    reg.gauge("cache.device_batch.bytes_held").set(12345)
    h = reg.histogram("link.h2d.bytes_per_transfer")
    h.observe(100)
    h.observe(5000)
    h.observe(0)  # the "0" bucket — a label value worth escaping rules
    text = reg.to_text()
    assert text.endswith("\n")

    seen_type = {}
    seen_help = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert _NAME_RE.fullmatch(name), line
            assert name not in seen_help, f"duplicate HELP: {line}"
            seen_help.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert _NAME_RE.fullmatch(name), line
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in seen_type, f"duplicate TYPE: {line}"
            # HELP precedes TYPE for every family
            assert name in seen_help, f"TYPE before HELP: {line}"
            seen_type[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        base = m.group(1)
        family = re.sub(r"_(bucket|sum|count)$", "", base)
        assert family in seen_type or base in seen_type, \
            f"sample before its TYPE: {line!r}"

    # dotted names map to legal names, deterministically
    assert "# TYPE hs_fusion_stage_execs counter" in text
    assert "# HELP hs_fusion_stage_execs" in text
    assert "hyperspace metric 'fusion.stage_execs'" in text
    # histogram invariants: cumulative buckets, +Inf == count
    bucket_counts = [int(line.rsplit(" ", 1)[1])
                     for line in text.splitlines()
                     if line.startswith(
                         "hs_link_h2d_bytes_per_transfer_bucket")]
    assert bucket_counts == sorted(bucket_counts)
    assert bucket_counts[-1] == 3
    assert "hs_link_h2d_bytes_per_transfer_count 3" in text


def test_prometheus_label_escaping():
    from hyperspace_tpu.telemetry.registry import (_escape_help,
                                                   _escape_label_value)
    assert _escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert _escape_help("back\\slash\nline") == "back\\\\slash\\nline"


def test_prometheus_name_collision_disambiguated():
    reg = telemetry.MetricsRegistry()
    reg.counter("a.b").inc()
    reg.counter("a_b").inc()  # same name after sanitization
    text = reg.to_text()
    types = [line for line in text.splitlines()
             if line.startswith("# TYPE ")]
    names = [line.split()[2] for line in types]
    assert len(names) == len(set(names)), names
    assert "hs_a_b" in names and "hs_a_b_2" in names
