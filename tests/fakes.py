"""In-memory fakes for FSM/manager unit tests.

Parity with the reference's test strategy layer 2 (SURVEY.md §4): mockito
mocks of IndexLogManager/IndexDataManager verifying state transitions; here,
recording in-memory fakes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu import constants
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import (Content, CoveringIndex, Directory,
                                            Hdfs, IndexLogEntry,
                                            LogicalPlanFingerprint,
                                            NoOpFingerprint, PlanSource,
                                            Signature, Source)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.plan.schema import Field, Schema


def make_entry(name: str = "idx", state: str = "ACTIVE",
               indexed=("clicks",), included=("score",),
               num_buckets: int = 8, root: str = "/tmp/idx/v__=0",
               raw_plan: str = "{}",
               signature_provider: str = "test.Provider",
               signature_value: str = "sig") -> IndexLogEntry:
    schema = Schema([Field(c, "int64") for c in (*indexed, *included)])
    entry = IndexLogEntry(
        name=name,
        derived_dataset=CoveringIndex(list(indexed), list(included),
                                      schema.to_json(), num_buckets),
        content=Content(root=root, directories=[]),
        source=Source(
            plan=PlanSource(raw_plan, LogicalPlanFingerprint(
                [Signature(signature_provider, signature_value)])),
            data=[Hdfs(Content("", [Directory("", ["f1", "f2"],
                                              NoOpFingerprint())]))]),
        extra={})
    entry.state = state
    return entry


class FakeLogManager(IndexLogManager):
    def __init__(self):
        self.logs: Dict[int, IndexLogEntry] = {}
        self.stable_id: Optional[int] = None
        self.writes: List[Tuple[int, str]] = []  # (id, state) audit trail

    def get_log(self, log_id):
        return self.logs.get(log_id)

    def get_latest_id(self):
        return max(self.logs) if self.logs else None

    def get_latest_log(self):
        latest = self.get_latest_id()
        return self.logs[latest] if latest is not None else None

    def get_latest_stable_log(self):
        if self.stable_id is not None:
            return self.logs.get(self.stable_id)
        for log_id in sorted(self.logs, reverse=True):
            if self.logs[log_id].state in constants.STABLE_STATES:
                return self.logs[log_id]
        return None

    def create_latest_stable_log(self, log_id):
        if log_id in self.logs and self.logs[log_id].state in constants.STABLE_STATES:
            self.stable_id = log_id
            return True
        return False

    def delete_latest_stable_log(self):
        self.stable_id = None
        return True

    def write_log(self, log_id, entry):
        if log_id in self.logs:
            return False
        entry.id = log_id
        self.logs[log_id] = entry
        self.writes.append((log_id, entry.state))
        return True


class FakeDataManager(IndexDataManager):
    def __init__(self, versions=()):
        self.versions = set(versions)
        self.deleted: List[int] = []
        self.committed: List[int] = []

    def get_latest_version_id(self):
        return max(self.versions) if self.versions else None

    def all_version_ids(self):
        # Real listing semantics: only versions that exist — sparse sets
        # enumerate as-is (vacuum must not assume a dense 0..latest).
        return sorted(self.versions)

    def get_path(self, version_id):
        return f"/fake/v__={version_id}"

    def commit(self, version_id):
        self.committed.append(version_id)

    def delete(self, version_id):
        self.versions.discard(version_id)
        self.deleted.append(version_id)


from hyperspace_tpu.index.signature import LogicalPlanSignatureProvider


class TestSignatureProvider(LogicalPlanSignatureProvider):
    """Root-path-based signature, injectable by reflection like the
    reference's RuleTestHelper.TestSignatureProvider
    (`index/rules/RuleTestHelper.scala:26-35`): lets rule tests fabricate
    matching indexes without building real ones."""

    def signature(self, plan):
        from hyperspace_tpu.plan.nodes import Scan
        roots = []
        for leaf in plan.collect_leaves():
            if not isinstance(leaf, Scan):
                return None
            roots.extend(leaf.root_paths)
        return "|".join(sorted(roots))
