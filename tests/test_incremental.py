"""Incremental refresh, optimize (merge-compaction), and hybrid scan —
the beyond-reference ladder items (BASELINE.md configs 4-5)."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col


@pytest.fixture
def env(tmp_path, sample_parquet):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def append_rows(src, clicks_value=200, n=50, id_start=10_000):
    rng = np.random.default_rng(11)
    extra = pa.table({
        "id": np.arange(id_start, id_start + n, dtype=np.int64),
        "clicks": np.full(n, clicks_value, dtype=np.int32),
        "score": rng.random(n),
        "imprs": rng.integers(0, 10, n),
        "query": pa.array(["qNEW"] * n),
    })
    pq.write_table(extra, os.path.join(
        src, f"part-extra-{id_start}.parquet"))


def test_incremental_refresh_links_and_deltas(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("inc", ["clicks"], ["id"]))
    base_files = set(os.listdir(os.path.join(session.conf.system_path,
                                             "inc", "v__=0")))

    append_rows(src)
    hs.refresh_index("inc", mode="incremental")

    v1 = os.path.join(session.conf.system_path, "inc", "v__=1")
    assert os.path.isdir(v1)
    v1_files = set(os.listdir(v1))
    # previous runs carried forward + delta runs added
    assert base_files <= v1_files
    assert any("delta1" in f for f in v1_files)

    # queries over the new data served from the index
    query = session.read_parquet(src).filter(col("clicks") == 200).select("id")
    session.enable_hyperspace()
    _, optimized, _ = query.explain_plans()
    roots = [r for leaf in optimized.collect_leaves() for r in leaf.root_paths]
    assert len(roots) == 1 and "v__=1" in roots[0]
    assert query.count() == 50
    session.disable_hyperspace()
    assert query.count() == 50


def test_incremental_refresh_join_still_correct(env):
    """Multi-run buckets (base + delta) must join correctly — the batched
    join re-sorts per-bucket ids, so file order must not matter."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("ja", ["imprs"], ["id"]))
    hs.create_index(df, IndexConfig("jb", ["imprs"], ["score"]))
    append_rows(src, clicks_value=7)
    hs.refresh_index("ja", mode="incremental")
    hs.refresh_index("jb", mode="incremental")

    df2 = session.read_parquet(src)
    query = df2.select("imprs", "id").join(df2.select("imprs", "score"),
                                           on="imprs")
    session.disable_hyperspace()
    plain = query.to_pandas().sort_values(["imprs", "id", "score"]).reset_index(drop=True)
    session.enable_hyperspace()
    _, optimized, physical = query.explain_plans()
    indexed = query.to_pandas().sort_values(["imprs", "id", "score"]).reset_index(drop=True)
    session.disable_hyperspace()
    names = [type(n).__name__ for n in physical.collect()]
    assert names.count("ExchangeExec") == 0
    pd.testing.assert_frame_equal(plain, indexed)


def test_incremental_refresh_rejects_deletion(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("del", ["clicks"], ["id"]))
    os.remove(sorted(glob.glob(os.path.join(src, "*.parquet")))[0])
    with pytest.raises(HyperspaceException, match="full refresh"):
        hs.refresh_index("del", mode="incremental")
    # index remains usable state-wise (validation failed before begin)
    assert list(hs.indexes()["state"]) == ["ACTIVE"]


def test_optimize_compacts_delta_runs(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("opt", ["clicks"], ["id"]))
    append_rows(src)
    hs.refresh_index("opt", mode="incremental")
    v1 = os.path.join(session.conf.system_path, "opt", "v__=1")
    assert any("delta" in f for f in os.listdir(v1))

    hs.optimize_index("opt")
    v2 = os.path.join(session.conf.system_path, "opt", "v__=2")
    assert os.path.isdir(v2)
    files = [f for f in os.listdir(v2) if f.endswith(".parquet")]
    assert files and not any("delta" in f for f in files)
    # one file per non-empty bucket, each sorted
    for f in files:
        clicks = pq.read_table(os.path.join(v2, f)).column("clicks").to_pylist()
        assert clicks == sorted(clicks)
    # row totals preserved
    total = sum(pq.read_table(os.path.join(v2, f)).num_rows for f in files)
    assert total == session.read_parquet(src).count()
    # queries use v__=2
    query = session.read_parquet(src).filter(col("clicks") == 200).select("id")
    session.enable_hyperspace()
    _, optimized, _ = query.explain_plans()
    session.disable_hyperspace()
    roots = [r for leaf in optimized.collect_leaves() for r in leaf.root_paths]
    assert "v__=2" in roots[0]


def test_hybrid_scan(env):
    """Stale index + appended files: with hybridscan enabled the filter is
    served from index UNION appended — correct rows, no refresh."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("hyb", ["clicks"], ["id"]))
    append_rows(src, clicks_value=42, n=30, id_start=20_000)

    query = session.read_parquet(src).filter(col("clicks") == 42).select("id")
    session.disable_hyperspace()
    expected = query.to_pandas().sort_values("id").reset_index(drop=True)

    index_loc = os.path.join(session.conf.system_path, "hyb")

    # hybrid disabled: stale signature -> no rewrite
    session.enable_hyperspace()
    _, optimized, _ = query.explain_plans()
    assert all(not r.startswith(index_loc)
               for leaf in optimized.collect_leaves()
               for r in leaf.root_paths)

    session.conf.set("hyperspace.index.hybridscan.enabled", "true")
    _, optimized, _ = query.explain_plans()
    roots = [r for leaf in optimized.collect_leaves() for r in leaf.root_paths]
    assert any(r.startswith(index_loc) for r in roots)       # index side
    assert any(not r.startswith(index_loc) for r in roots)   # appended side
    got = query.to_pandas().sort_values("id").reset_index(drop=True)
    session.disable_hyperspace()
    pd.testing.assert_frame_equal(expected, got)
    assert (got["id"] >= 20_000).sum() == 30  # appended rows present


def test_refresh_unknown_mode(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("m", ["clicks"], []))
    with pytest.raises(HyperspaceException, match="mode"):
        hs.refresh_index("m", mode="bogus")


def test_hybrid_scan_rejects_inplace_rewrite(env):
    """A source file rewritten in place (same path, new content) must NOT be
    served from stale index data, even with hybrid scan enabled."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("hw", ["clicks"], ["id"]))
    # rewrite part-0 in place AND append a new file
    first = sorted(glob.glob(os.path.join(src, "*.parquet")))[0]
    t = pq.read_table(first)
    pq.write_table(t.slice(0, t.num_rows // 2), first)
    append_rows(src, clicks_value=42, n=10, id_start=30_000)

    session.conf.set("hyperspace.index.hybridscan.enabled", "true")
    session.enable_hyperspace()
    query = session.read_parquet(src).filter(col("clicks") == 42).select("id")
    _, optimized, _ = query.explain_plans()
    index_loc = os.path.join(session.conf.system_path, "hw")
    assert all(not r.startswith(index_loc)
               for leaf in optimized.collect_leaves()
               for r in leaf.root_paths)
    session.disable_hyperspace()


def test_incremental_refresh_rejects_inplace_rewrite(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("iw", ["clicks"], ["id"]))
    first = sorted(glob.glob(os.path.join(src, "*.parquet")))[0]
    t = pq.read_table(first)
    pq.write_table(t.slice(0, t.num_rows // 2), first)
    append_rows(src, clicks_value=7, n=10, id_start=40_000)
    with pytest.raises(HyperspaceException, match="full refresh"):
        hs.refresh_index("iw", mode="incremental")


def test_hybrid_plan_roundtrips_file_restriction(env):
    """Scan file restrictions must survive plan serde (hybrid correctness)."""
    from hyperspace_tpu.plan.nodes import Scan
    from hyperspace_tpu.plan.schema import Field, Schema
    from hyperspace_tpu.plan.serde import plan_from_json, plan_to_json
    _session, _hs, src = env
    files = sorted(glob.glob(os.path.join(src, "*.parquet")))[:1]
    scan = Scan([src], Schema([Field("id", "int64")]), files=files)
    restored = plan_from_json(plan_to_json(scan))
    assert restored.files() == files


def test_optimize_is_one_batched_program_and_matches_rebuild(env, tmp_path,
                                                             monkeypatch):
    """The VERDICT round-3 'done' bar for OptimizeAction: a 64-bucket
    index with 4 delta runs compacts through ONE compiled device program
    (no per-bucket dispatch), and the output layout is byte-equal to a
    full rebuild of the same source."""
    import hyperspace_tpu.io.builder as builder_mod
    import hyperspace_tpu.ops.merge as merge_mod

    session, hs, _ = env
    rng = np.random.default_rng(5)
    src = tmp_path / "opt64_src"
    src.mkdir()

    def rows(start, n, seed):
        r = np.random.default_rng(seed)
        return pa.table({
            "k": r.integers(0, 40, n).astype(np.int64),
            "v": r.random(n),
            "id": np.arange(start, start + n, dtype=np.int64)})

    pq.write_table(rows(0, 600, 1), str(src / "part-0-base.parquet"))
    session.conf.set("hyperspace.index.num.buckets", 64)
    df = session.read_parquet(str(src))
    hs.create_index(df, IndexConfig("opt64", ["k"], ["v", "id"]))
    for i in range(4):  # 4 appended slices -> 4 incremental delta runs
        pq.write_table(rows(1000 * (i + 1), 150, 10 + i),
                       str(src / f"part-1-extra{i}.parquet"))
        hs.refresh_index("opt64", mode="incremental")

    # Force the device lane and count compiled-program entry points.
    calls = {"device": 0, "host": 0}
    real_dev = merge_mod.bucket_sort_permutation
    real_host = merge_mod.host_bucket_sort_permutation

    def count_dev(*a, **k):
        calls["device"] += 1
        return real_dev(*a, **k)

    def count_host(*a, **k):
        calls["host"] += 1
        return real_host(*a, **k)

    monkeypatch.setattr(builder_mod, "BUILD_MIN_DEVICE_ROWS", 0)
    # Residency routing prefers the native host lane for host tables;
    # bypass it so this test exercises the batched DEVICE program.
    monkeypatch.setattr(builder_mod, "_host_lane_preferred",
                        lambda rows: False)
    # Disable the host MERGE fast path (single-int-key compactions take
    # it; a separate test pins its output) so this test exercises the
    # batched device program.
    monkeypatch.setattr(builder_mod, "_merge_path_permutation",
                        lambda *a, **k: None)
    monkeypatch.setattr(merge_mod, "bucket_sort_permutation", count_dev)
    monkeypatch.setattr(merge_mod, "host_bucket_sort_permutation",
                        count_host)

    hs.optimize_index("opt64")
    assert calls == {"device": 1, "host": 0}, calls

    # Byte-equality with a full rebuild over the identical source (a
    # FRESH DataFrame: the original one's scan caches the pre-append
    # file listing).
    hs.create_index(session.read_parquet(str(src)),
                    IndexConfig("opt64_rebuild", ["k"], ["v", "id"]))
    opt_dir = os.path.join(session.conf.system_path, "opt64", "v__=5")
    reb_dir = os.path.join(session.conf.system_path, "opt64_rebuild",
                           "v__=0")
    opt_files = sorted(f for f in os.listdir(opt_dir)
                       if f.endswith(".parquet"))
    reb_files = sorted(f for f in os.listdir(reb_dir)
                       if f.endswith(".parquet"))
    assert opt_files == reb_files and opt_files
    for f in opt_files:
        with open(os.path.join(opt_dir, f), "rb") as a, \
                open(os.path.join(reb_dir, f), "rb") as b:
            assert a.read() == b.read(), f"byte mismatch in {f}"


def test_hybrid_scan_join(env):
    """VERDICT r2 next-5: a join over an appended source stays
    index-accelerated — the rule serves index UNION appended files on the
    grown side, the planner re-buckets the appended slice through
    ExchangeExec, and the bucketed SMJ still fires. Results equal
    rules-off and pandas."""
    session, hs, _ = env
    import tempfile
    rng = np.random.default_rng(21)
    base = tempfile.mkdtemp()
    lsrc, rsrc = os.path.join(base, "hl"), os.path.join(base, "hr")
    os.makedirs(lsrc), os.makedirs(rsrc)
    pq.write_table(pa.table({
        "k": rng.integers(0, 40, 800).astype(np.int64),
        "x": rng.random(800)}), os.path.join(lsrc, "part-0.parquet"))
    pq.write_table(pa.table({
        "k": rng.integers(0, 40, 300).astype(np.int64),
        "y": rng.random(300)}), os.path.join(rsrc, "part-0.parquet"))
    l = session.read_parquet(lsrc)
    r = session.read_parquet(rsrc)
    hs.create_index(l, IndexConfig("hj_l", ["k"], ["x"]))
    hs.create_index(r, IndexConfig("hj_r", ["k"], ["y"]))
    # Source grows AFTER the build.
    pq.write_table(pa.table({
        "k": rng.integers(0, 40, 200).astype(np.int64),
        "x": rng.random(200)}), os.path.join(lsrc, "part-1.parquet"))
    session.conf.set("hyperspace.index.hybridscan.enabled", "true")

    l2 = session.read_parquet(lsrc)  # fresh listing
    q = l2.join(r, on=col("k") == col("k")).select("x", "y")
    session.enable_hyperspace()
    optimized = q._optimized_plan()
    from hyperspace_tpu.plan.nodes import Union as UnionNode
    unions = []
    optimized.transform_up(
        lambda n: (unions.append(n), n)[1] if isinstance(n, UnionNode)
        else n)
    assert unions, "left side not hybrid-served"
    roots = [p for s in optimized.collect_leaves() for p in s.root_paths]
    assert any("v__=" in p for p in roots)
    # the physical join is the bucketed SMJ (no global Exchange+Sort on
    # the index side; appended slice rides one Exchange inside the Union)
    from hyperspace_tpu.engine.physical import SortMergeJoinExec
    _, _, physical = q.explain_plans()
    smj = [n for n in physical.collect()
           if isinstance(n, SortMergeJoinExec)]
    assert smj and smj[0].bucketed

    on = q.collect().to_pandas()
    session.disable_hyperspace()
    off = q.collect().to_pandas()

    def norm(d):
        return (d.sort_values(list(d.columns)).reset_index(drop=True)
                .astype("float64"))

    pd.testing.assert_frame_equal(norm(on), norm(off), check_dtype=False)
    lt = pq.read_table(lsrc).to_pandas()
    rt = pq.read_table(rsrc).to_pandas()
    exp = lt.merge(rt, on="k")[["x", "y"]]
    pd.testing.assert_frame_equal(norm(on), norm(exp), check_dtype=False)


def test_optimize_merge_fast_path_matches_rebuild(env, tmp_path):
    """Single-int-key compaction takes the TRUE merge path (no re-sort of
    the base run) and its output is byte-equal to a full rebuild."""
    import hyperspace_tpu.io.builder as builder_mod
    import hyperspace_tpu.ops.merge as merge_mod

    session, hs, _ = env
    src = tmp_path / "mergefast_src"
    src.mkdir()

    def rows(start, n, seed):
        r = np.random.default_rng(seed)
        return pa.table({
            "k": r.integers(0, 30, n).astype(np.int64),
            "v": r.random(n),
            "id": np.arange(start, start + n, dtype=np.int64)})

    pq.write_table(rows(0, 500, 2), str(src / "part-0-base.parquet"))
    session.conf.set("hyperspace.index.num.buckets", 16)
    df = session.read_parquet(str(src))
    hs.create_index(df, IndexConfig("mf", ["k"], ["v", "id"]))
    for i in range(3):
        pq.write_table(rows(1000 * (i + 1), 120, 20 + i),
                       str(src / f"part-1-extra{i}.parquet"))
        hs.refresh_index("mf", mode="incremental")

    used = {"merge": 0}
    real = merge_mod.host_merge_runs_permutation

    def counting(*a, **k):
        used["merge"] += 1
        return real(*a, **k)

    merge_mod.host_merge_runs_permutation = counting
    builder_path = builder_mod._merge_path_permutation
    try:
        import hyperspace_tpu.io.builder as b
        # _merge_path_permutation imports host_merge_runs_permutation
        # lazily from merge_mod, so the counter above is seen.
        hs.optimize_index("mf")
    finally:
        merge_mod.host_merge_runs_permutation = real
    assert used["merge"] == 1

    hs.create_index(session.read_parquet(str(src)),
                    IndexConfig("mf_rebuild", ["k"], ["v", "id"]))
    opt_dir = os.path.join(session.conf.system_path, "mf", "v__=4")
    reb_dir = os.path.join(session.conf.system_path, "mf_rebuild", "v__=0")
    opt_files = sorted(f for f in os.listdir(opt_dir)
                       if f.endswith(".parquet"))
    reb_files = sorted(f for f in os.listdir(reb_dir)
                       if f.endswith(".parquet"))
    assert opt_files == reb_files and opt_files
    for f in opt_files:
        with open(os.path.join(opt_dir, f), "rb") as a, \
                open(os.path.join(reb_dir, f), "rb") as b2:
            assert a.read() == b2.read(), f"byte mismatch in {f}"
