"""Relational IR tests: expression serde, plan serde, traversal helpers."""

import json

import pytest

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import (And, Column, EqualTo, Expression, In,
                                      Literal, col, lit, split_conjunctive)
from hyperspace_tpu.plan.nodes import (BucketSpec, Filter, Join, Project, Scan)
from hyperspace_tpu.plan.schema import Field, Schema
from hyperspace_tpu.plan.serde import plan_from_json, plan_to_json


def sample_schema():
    return Schema([Field("id", "int64"), Field("clicks", "int32"),
                   Field("score", "float64"), Field("query", "string")])


def test_schema_json_roundtrip():
    s = sample_schema()
    assert Schema.from_json(s.to_json()) == s


def test_schema_case_insensitive_lookup():
    s = sample_schema()
    assert s.field("CLICKS").name == "clicks"
    assert s.contains("Id")
    with pytest.raises(HyperspaceException):
        s.field("missing")


def test_expression_sugar_and_references():
    e = (col("a") > 5) & (col("b") == "x")
    assert isinstance(e, And)
    assert e.references() == {"a", "b"}


def test_expression_serde_roundtrip():
    exprs = [
        (col("a") > 5) & ~(col("b") == lit("x")),
        col("c").isin(1, 2, 3),
        col("d").is_null() | col("e").is_not_null(),
        (col("f") + 1) * (col("g") - 2) / lit(4),
    ]
    for e in exprs:
        round_tripped = Expression.from_dict(json.loads(json.dumps(e.to_dict())))
        assert round_tripped.to_dict() == e.to_dict()


def test_split_conjunctive():
    e = (col("a") == 1) & (col("b") == 2) & (col("c") == 3)
    parts = split_conjunctive(e)
    assert len(parts) == 3
    assert all(isinstance(p, EqualTo) for p in parts)


def test_plan_serde_roundtrip(tmp_path):
    scan = Scan([str(tmp_path)], sample_schema(),
                bucket_spec=BucketSpec(8, ("clicks",), ("clicks",)))
    plan = Project(["id", "clicks"], Filter(col("clicks") > 10, scan))
    restored = plan_from_json(plan_to_json(plan))
    assert restored.to_dict() == plan.to_dict()
    assert isinstance(restored, Project)
    assert restored.schema.names == ["id", "clicks"]


def test_join_plan_serde(tmp_path):
    left = Scan([str(tmp_path / "l")], sample_schema())
    right = Scan([str(tmp_path / "r")],
                 Schema([Field("clicks", "int32"), Field("other", "int64")]))
    plan = Join(left, right, col("clicks") == col("clicks"))
    restored = plan_from_json(plan_to_json(plan))
    assert restored.to_dict() == plan.to_dict()


def test_linearity(tmp_path):
    scan = Scan([str(tmp_path)], sample_schema())
    assert Filter(col("clicks") > 1, scan).is_linear()
    join = Join(scan, Scan([str(tmp_path)], sample_schema()),
                col("id") == col("id"))
    assert not join.is_linear()


def test_transform_up_replaces_scan(tmp_path):
    scan = Scan([str(tmp_path / "base")], sample_schema())
    new_scan = Scan([str(tmp_path / "index")], sample_schema())
    plan = Project(["id"], Filter(col("clicks") > 1, scan))

    def swap(node):
        if isinstance(node, Scan):
            return new_scan
        return node

    out = plan.transform_up(swap)
    leaf = out.collect_leaves()[0]
    assert leaf.root_paths == new_scan.root_paths
    # Original untouched (immutability).
    assert plan.collect_leaves()[0].root_paths == scan.root_paths
