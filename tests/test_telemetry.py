"""Query-level telemetry (hyperspace_tpu/telemetry): per-operator
records, rule/lane decision events, per-query isolation, and the
metrics-coverage lint."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, IndexConfig, telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tpch_shaped(tmp_path):
    """A TPC-H-shaped pair: a lineitem-like fact and an orders-like
    dimension, plus a session factory."""
    rng = np.random.default_rng(11)
    n, n_ord = 4000, 400
    li_dir = tmp_path / "lineitem"
    ord_dir = tmp_path / "orders"
    li_dir.mkdir()
    ord_dir.mkdir()
    pq.write_table(pa.table({
        "l_orderkey": rng.integers(0, n_ord, n).astype(np.int64),
        "l_quantity": rng.integers(1, 50, n).astype(np.int64),
        "l_extendedprice": rng.random(n) * 1000,
    }), str(li_dir / "part-0.parquet"))
    pq.write_table(pa.table({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, 100, n_ord).astype(np.int64),
        "o_totalprice": rng.random(n_ord) * 10000,
    }), str(ord_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(li_dir), str(ord_dir)


def _tpch_query(sess, li_dir, ord_dir):
    li = sess.read_parquet(li_dir)
    orders = sess.read_parquet(ord_dir)
    return (li.filter(col("l_quantity") > lit(10))
            .join(orders, on=col("l_orderkey") == col("o_orderkey"))
            .group_by("o_custkey")
            .agg(("sum", "l_extendedprice", "revenue"),
                 ("count", "*", "cnt")))


def test_per_operator_rows_and_timings(tpch_shaped):
    session, li_dir, ord_dir = tpch_shaped
    sess = session()
    table, m = _tpch_query(sess, li_dir, ord_dir).collect(
        with_metrics=True)
    assert table.num_rows > 0
    assert m.wall_s is not None and m.wall_s > 0
    names = {op.name for op in m.operators}
    # The executed operator walk: scans feed a join feeding the
    # aggregate (fusion may group filter/project regions).
    assert "Scan" in names
    assert "Aggregate" in names
    aggs = [op for op in m.operators if op.name == "Aggregate"]
    assert aggs[0].rows_out == table.num_rows
    for op in m.operators:
        assert op.wall_s >= 0
    scans = [op for op in m.operators if op.name == "Scan"]
    assert sum(op.rows_out for op in scans) >= 4000  # fact rows read
    # rows_in derives from the parent/child linkage.
    assert m.rows_in(aggs[0]) is not None
    # Reports round-trip.
    parsed = json.loads(m.to_json())
    assert parsed["operators"] and parsed["counters"]["plan_s"] >= 0
    tree = m.format_tree()
    assert "Aggregate" in tree and "rows=" in tree
    summary = m.summary()
    assert summary["operators"]["Scan"]["count"] == len(scans)
    # The session keeps the recorder of the last query.
    assert sess.last_query_metrics() is m


def test_rule_and_lane_events_match_executed_plan(tpch_shaped, tmp_path):
    session, li_dir, ord_dir = tpch_shaped
    sess = session()
    hs = Hyperspace(sess)
    li = sess.read_parquet(li_dir)
    hs.create_index(li, IndexConfig("li_qty", ["l_quantity"],
                                    ["l_orderkey", "l_extendedprice"]))
    sess.enable_hyperspace()
    q = (li.filter(col("l_quantity") == lit(20))
         .select("l_orderkey", "l_extendedprice"))
    _, m = q.collect(with_metrics=True)
    applied = [e for e in m.events_of("rule", "FilterIndexRule")
               if e["action"] == "applied"]
    assert len(applied) == 1
    index_root = applied[0]["indexes"][0]["root"]
    # The event's index root IS a root the executed scan actually read.
    scan_roots = [r for op in m.operators if op.name == "Scan"
                  for r in op.detail.get("roots", [])]
    assert index_root in scan_roots
    # Index usage joins the rule event with the scan record.
    usage = m.index_usage()
    assert usage and usage[0]["name"] == "li_qty"
    assert usage[0]["files_scanned"] <= usage[0]["files_total"]
    assert usage[0]["buckets_scanned"] <= usage[0]["buckets_total"]
    # Lane events name the fusion decision actually taken.
    lanes = m.events_of("fusion", "lane")
    assert lanes and all(
        e["lane"] in ("masked-device", "eager-host", "eager")
        for e in lanes)
    # Rules disabled -> a skipped/no events query, and fresh metrics.
    sess.disable_hyperspace()
    _, m2 = q.collect(with_metrics=True)
    assert not [e for e in m2.events_of("rule") if e["action"] == "applied"]
    # explain renders the runtime numbers next to the plan diff.
    captured = []
    hs.explain(q, redirect=captured.append, metrics=m)
    text = captured[0]
    assert "Runtime metrics" in text and "Plan with indexes" in text
    assert "li_qty" in text  # indexes-used section still names the index


def test_metrics_isolated_across_concurrent_sessions(tpch_shaped):
    session, li_dir, ord_dir = tpch_shaped
    results = {}
    barrier = threading.Barrier(2)

    def run(tag, n_filter):
        sess = session()
        li = sess.read_parquet(li_dir)
        q = (li.filter(col("l_quantity") > lit(n_filter))
             .select("l_orderkey"))
        barrier.wait()
        for _ in range(3):
            _, m = q.collect(with_metrics=True)
        results[tag] = (sess, m)

    threads = [threading.Thread(target=run, args=("a", 10)),
               threading.Thread(target=run, args=("b", 45))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    (sess_a, m_a), (sess_b, m_b) = results["a"], results["b"]
    assert m_a is not m_b
    assert sess_a.last_query_metrics() is m_a
    assert sess_b.last_query_metrics() is m_b
    rows_a = [op.rows_out for op in m_a.operators
              if op.name == "Project"]
    rows_b = [op.rows_out for op in m_b.operators
              if op.name == "Project"]
    # The selective filter (>45 of 1..49) must see far fewer rows than
    # the loose one — cross-query leakage would smear them together.
    assert min(rows_a) > max(rows_b)
    # No operator ended up in both recorders.
    ids_a = {id(op) for op in m_a.operators}
    assert not ids_a & {id(op) for op in m_b.operators}


def test_fusion_stats_consumers_and_per_query_scoping(tpch_shaped):
    from hyperspace_tpu.engine import fusion

    session, li_dir, ord_dir = tpch_shaped
    # Device lane forced (CPU backend): the masked path runs and syncs.
    sess = session(**{
        "spark.hyperspace.execution.min.device.rows": "0",
        "spark.hyperspace.distribution.enabled": "false"})
    li = sess.read_parquet(li_dir)
    q = li.filter(col("l_quantity") > lit(10)).select("l_orderkey")

    # The module-global consumer contract (scripts/profile_tpcds.py):
    # reset by key, read after runs (registry-backed since PR 2).
    for k in fusion.STATS:
        fusion.STATS[k] = 0 if isinstance(fusion.STATS[k], int) else 0.0
    _, m1 = q.collect(with_metrics=True)
    _, m2 = q.collect(with_metrics=True)
    assert fusion.STATS["stage_execs"] >= 2
    assert set(fusion.STATS) == {"stage_execs", "trace_misses", "sync_s",
                                 "dispatch_s"}
    # Per-query counters: each recorder saw only its own execution.
    assert m1.counters["fusion.stage_execs"] == 1
    assert m2.counters["fusion.stage_execs"] == 1
    assert m1.counters["fusion.dispatch_s"] >= 0
    # Warm second run hits the trace cache.
    cache_events = m2.events_of("fusion", "trace-cache")
    assert cache_events and cache_events[-1]["hit"] is True
    lanes = m2.events_of("fusion", "lane")
    assert any(e["lane"] == "masked-device" for e in lanes)


def test_no_recorder_no_overhead_path(tpch_shaped):
    """Operators execute unchanged without an active recorder (the
    executor's compile path runs outside any recording context)."""
    session, li_dir, ord_dir = tpch_shaped
    sess = session()
    li = sess.read_parquet(li_dir)
    plan = li.filter(col("l_quantity") > lit(10)).select("l_orderkey")
    from hyperspace_tpu.engine.executor import execute_plan
    assert telemetry.current() is None
    batch = execute_plan(plan._optimized_plan(), conf=sess.conf)
    assert batch.num_rows > 0


def test_metrics_coverage_lint():
    """The tier-1 hook for scripts/check_metrics_coverage.py: no
    PhysicalNode subclass may execute without emitting a metrics
    record."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "check_metrics_coverage.py")],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
