"""Pallas kernel equivalence tests (interpret mode on the CPU mesh)."""

import numpy as np
import pyarrow as pa

from hyperspace_tpu.io import columnar
from hyperspace_tpu.ops import hash_partition
from hyperspace_tpu.ops.keys import key_lanes
from hyperspace_tpu.ops.pallas.hash_kernel import hash_lanes_to_buckets


def test_pallas_hash_matches_jnp_single_lane():
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 2**31, 10_000).astype(np.int32))
    batch = columnar.from_arrow(pa.table({"k": np.asarray(data)}))
    expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 32))
    lanes = key_lanes(batch.column("k").data)
    got = np.asarray(hash_lanes_to_buckets(lanes, 32, interpret=True))
    assert (got == expected).all()


def test_pallas_hash_matches_jnp_int64_two_lanes():
    rng = np.random.default_rng(1)
    vals = rng.integers(-2**62, 2**62, 5_000).astype(np.int64)
    batch = columnar.from_arrow(pa.table({"k": vals}))
    expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 64))
    lanes = key_lanes(batch.column("k").data)
    got = np.asarray(hash_lanes_to_buckets(lanes, 64, interpret=True))
    assert (got == expected).all()


def test_pallas_hash_ragged_tail():
    """Sizes that do not fill a block/tile exactly."""
    for n in (1, 127, 129, 4097):
        vals = np.arange(n, dtype=np.int64) * 7919
        batch = columnar.from_arrow(pa.table({"k": vals}))
        expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 8))
        lanes = key_lanes(batch.column("k").data)
        got = np.asarray(hash_lanes_to_buckets(lanes, 8, interpret=True))
        assert (got == expected).all(), n
