"""Pallas kernel equivalence tests (interpret mode on the CPU mesh)."""

import numpy as np
import pyarrow as pa

from hyperspace_tpu.io import columnar
from hyperspace_tpu.ops import hash_partition
from hyperspace_tpu.ops.keys import key_lanes
from hyperspace_tpu.ops.pallas.hash_kernel import hash_lanes_to_buckets


def test_pallas_hash_matches_jnp_single_lane():
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 2**31, 10_000).astype(np.int32))
    batch = columnar.from_arrow(pa.table({"k": np.asarray(data)}))
    expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 32))
    lanes = key_lanes(batch.column("k").data)
    got = np.asarray(hash_lanes_to_buckets(lanes, 32, interpret=True))
    assert (got == expected).all()


def test_pallas_hash_matches_jnp_int64_two_lanes():
    rng = np.random.default_rng(1)
    vals = rng.integers(-2**62, 2**62, 5_000).astype(np.int64)
    batch = columnar.from_arrow(pa.table({"k": vals}))
    expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 64))
    lanes = key_lanes(batch.column("k").data)
    got = np.asarray(hash_lanes_to_buckets(lanes, 64, interpret=True))
    assert (got == expected).all()


def test_pallas_hash_ragged_tail():
    """Sizes that do not fill a block/tile exactly."""
    for n in (1, 127, 129, 4097):
        vals = np.arange(n, dtype=np.int64) * 7919
        batch = columnar.from_arrow(pa.table({"k": vals}))
        expected = np.asarray(hash_partition.bucket_ids(batch, ["k"], 8))
        lanes = key_lanes(batch.column("k").data)
        got = np.asarray(hash_lanes_to_buckets(lanes, 8, interpret=True))
        assert (got == expected).all(), n


def test_partition_kernel_matches_reference_interpret():
    """Fused ids+histogram kernel == bucket_ids + bincount, bit-for-bit
    (interpret mode on CPU; the TPU path runs the same kernel)."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import hash_partition
    from hyperspace_tpu.ops.pallas.partition_kernel import batch_partition

    rng = np.random.default_rng(41)
    n = 70_000  # crosses multiple 256x128 tiles, last one ragged
    table = pa.table({
        "k": rng.integers(-2**60, 2**60, n).astype(np.int64),
        "s": pa.array(["w%d" % (i % 211) for i in range(n)]),
    })
    batch = columnar.from_arrow(table)
    for cols, B in ((["k"], 64), (["k", "s"], 200), (["s"], 16)):
        ids, lengths = batch_partition(batch, cols, B, interpret=True)
        ref_ids = np.asarray(hash_partition.bucket_ids(batch, cols, B))
        assert (np.asarray(ids) == ref_ids).all(), (cols, B)
        ref_len = np.bincount(ref_ids, minlength=B)
        assert (np.asarray(lengths) == ref_len).all(), (cols, B)
        assert int(np.asarray(lengths).sum()) == n
