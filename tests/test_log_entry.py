"""Pin the exact serialized log-entry shape.

Parity: the reference pins its JSON spec in `IndexLogEntryTest.scala:33-91`;
this test plays the same role for this framework's wire format — changing the
shape must fail here.
"""

import json

from hyperspace_tpu.constants import States
from hyperspace_tpu.index.log_entry import (Content, CoveringIndex, Directory,
                                            Hdfs, IndexLogEntry, LogEntry,
                                            LogicalPlanFingerprint,
                                            NoOpFingerprint, PlanSource,
                                            Signature, Source)

SPEC = {
    "name": "indexName",
    "derivedDataset": {
        "kind": "CoveringIndex",
        "properties": {
            "columns": {"indexed": ["col1"], "included": ["col2", "col3"]},
            "schemaString": "{\"type\": \"struct\", \"fields\": []}",
            "numBuckets": 200,
        },
    },
    "content": {"root": "rootContentPath", "directories": []},
    "source": {
        "plan": {
            "kind": "Plan",
            "properties": {
                "rawPlan": "planString",
                "fingerprint": {
                    "kind": "LogicalPlan",
                    "properties": {
                        "signatures": [
                            {"provider": "provider", "value": "signatureValue"}
                        ]
                    },
                },
            },
        },
        "data": [{
            "kind": "HDFS",
            "properties": {
                "content": {
                    "root": "",
                    "directories": [{
                        "path": "",
                        "files": ["f1", "f2"],
                        "fingerprint": {"kind": "NoOp", "properties": {}},
                    }],
                }
            },
        }],
    },
    "extra": {},
    "version": "0.1",
    "id": 0,
    "state": "ACTIVE",
    "timestamp": 1578818514080,
    "enabled": True,
}


def build_expected() -> IndexLogEntry:
    entry = IndexLogEntry(
        name="indexName",
        derived_dataset=CoveringIndex(
            ["col1"], ["col2", "col3"],
            "{\"type\": \"struct\", \"fields\": []}", 200),
        content=Content("rootContentPath", []),
        source=Source(
            plan=PlanSource("planString", LogicalPlanFingerprint(
                [Signature("provider", "signatureValue")])),
            data=[Hdfs(Content("", [Directory("", ["f1", "f2"],
                                              NoOpFingerprint())]))]),
        extra={})
    entry.state = States.ACTIVE
    entry.timestamp = 1578818514080
    return entry


def test_from_json_matches_expected():
    actual = LogEntry.from_json(json.dumps(SPEC))
    assert isinstance(actual, IndexLogEntry)
    assert actual == build_expected()


def test_to_json_roundtrip_is_exact():
    entry = build_expected()
    assert json.loads(entry.to_json()) == SPEC


def test_helpers():
    entry = build_expected()
    assert entry.indexed_columns == ["col1"]
    assert entry.included_columns == ["col2", "col3"]
    assert entry.num_buckets == 200
    assert entry.created
    assert entry.signature() == Signature("provider", "signatureValue")
    assert entry.source_file_list() == ["f1", "f2"]


def test_copy_with_state():
    entry = build_expected()
    clone = entry.copy_with_state(States.DELETED)
    assert clone.state == States.DELETED
    assert entry.state == States.ACTIVE
    assert clone.name == entry.name
