"""General query-shape coverage (round-3 breadth): expression projection,
computed aggregates, HAVING, full_outer / left_semi / left_anti execution,
SUBSTR, and string column-to-column comparison — each checked against a
pandas oracle on both the host lane and the forced-device lane, and (for
joins) through the index-accelerated bucketed path."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.expr import col, lit


def norm(d):
    out = d.sort_values(list(d.columns)).reset_index(drop=True)
    return out.astype({c: "float64" for c in out.columns
                       if out[c].dtype.kind in "fi"})


@pytest.fixture(params=["host", "device"])
def sess(request, tmp_path):
    conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
            "hyperspace.index.num.buckets": 4}
    if request.param == "device":
        conf["spark.hyperspace.execution.min.device.rows"] = "0"
    return HyperspaceSession(HyperspaceConf(conf))


@pytest.fixture
def tables(tmp_path):
    rng = np.random.default_rng(3)
    n = 300
    lpdf = pd.DataFrame({
        "k": rng.integers(0, 25, n).astype(np.int64),
        "x": rng.random(n),
        "q": rng.integers(1, 10, n).astype(np.int64),
        "s": pd.array([f"w{int(v):03d}xyz"[:6]
                       for v in rng.integers(0, 40, n)]),
    })
    rpdf = pd.DataFrame({
        "k": rng.integers(15, 40, 120).astype(np.int64),
        "y": rng.random(120),
        "t": pd.array([f"w{int(v):03d}abc"[:6]
                       for v in rng.integers(0, 40, 120)]),
    })
    lp, rp = str(tmp_path / "lt"), str(tmp_path / "rt")
    os.makedirs(lp), os.makedirs(rp)
    pq.write_table(pa.Table.from_pandas(lpdf), lp + "/p.parquet")
    pq.write_table(pa.Table.from_pandas(rpdf), rp + "/p.parquet")
    return lpdf, rpdf, lp, rp


def test_expression_projection_matches_pandas(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.select("k", (col("x") * col("q")).alias("xq"),
                    (col("k") + lit(100)).alias("k100")).collect().to_pandas()
    exp = pd.DataFrame({"k": lpdf.k, "xq": lpdf.x * lpdf.q,
                        "k100": lpdf.k + 100})
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_computed_aggregate_and_having(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = (df.group_by("k")
           .agg(("sum", col("x") * col("q"), "rev"),
                ("count", "*", "cnt"))
           .having(col("rev") > lit(3.0))
           .collect().to_pandas())
    g = lpdf.assign(rev=lpdf.x * lpdf.q).groupby("k").agg(
        rev=("rev", "sum"), cnt=("rev", "size")).reset_index()
    exp = g[g.rev > 3.0]
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_avg_over_expression(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.group_by("q").agg(
        ("avg", col("x") + col("k"), "m")).collect().to_pandas()
    exp = (lpdf.assign(m=lpdf.x + lpdf.k).groupby("q")
           .agg(m=("m", "mean")).reset_index())
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_full_outer_join_matches_pandas(sess, tables):
    lpdf, rpdf, lp, rp = tables
    l, r = sess.read_parquet(lp), sess.read_parquet(rp)
    got = (l.select("k", "x")
           .join(r.select("k", "y"), on=col("k") == col("k"),
                 how="full_outer").collect().to_pandas())
    exp = lpdf[["k", "x"]].merge(rpdf[["k", "y"]], on="k", how="outer")
    assert len(got) == len(exp)
    assert got["y"].notna().sum() == exp["y"].notna().sum()
    assert got["x"].isna().sum() == exp["x"].isna().sum()
    # inner portion matches exactly
    inner_got = got.dropna(subset=["x", "y"])[["x", "y"]]
    inner_exp = exp.dropna(subset=["x", "y"])[["x", "y"]]
    pd.testing.assert_frame_equal(norm(inner_got), norm(inner_exp),
                                  check_dtype=False)


def test_semi_anti_join_matches_pandas(sess, tables):
    lpdf, rpdf, lp, rp = tables
    l, r = sess.read_parquet(lp), sess.read_parquet(rp)
    semi = l.join(r, on=col("k") == col("k"), how="left_semi")
    anti = l.join(r, on=col("k") == col("k"), how="left_anti")
    assert semi.schema.names == ["k", "x", "q", "s"]
    got_semi = semi.collect().to_pandas()
    got_anti = anti.collect().to_pandas()
    exp_semi = lpdf[lpdf.k.isin(rpdf.k)]
    exp_anti = lpdf[~lpdf.k.isin(rpdf.k)]
    pd.testing.assert_frame_equal(norm(got_semi), norm(exp_semi),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(norm(got_anti), norm(exp_anti),
                                  check_dtype=False)


def test_indexed_full_outer_join(sess, tables):
    """full_outer through the bucketed index-pair machinery: both sides
    indexed, rule fires, results equal the rules-off run."""
    _, _, lp, rp = tables
    hs = Hyperspace(sess)
    l, r = sess.read_parquet(lp), sess.read_parquet(rp)
    hs.create_index(l, IndexConfig("idx_fo_l", ["k"], ["x"]))
    hs.create_index(r, IndexConfig("idx_fo_r", ["k"], ["y"]))
    q = (l.select("k", "x").join(r.select("k", "y"),
                                 on=col("k") == col("k"), how="full_outer"))
    sess.enable_hyperspace()
    opt = q._optimized_plan()
    roots = [p for s in opt.collect_leaves() for p in s.root_paths]
    assert any("v__=" in p for p in roots), roots
    on = q.collect().to_pandas()
    sess.disable_hyperspace()
    off = q.collect().to_pandas()
    assert len(on) == len(off)
    pd.testing.assert_frame_equal(
        norm(on.fillna(-1.0)), norm(off.fillna(-1.0)), check_dtype=False)


def test_string_column_comparison_and_substr(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.filter(col("s").substr(2, 3) == col("s").substr(2, 3)).count()
    assert got == len(lpdf)
    got2 = (df.filter(col("s").substr(1, 2) < lit("w1"))
            .collect().to_pandas())
    exp2 = lpdf[lpdf.s.str[:2] < "w1"]
    pd.testing.assert_frame_equal(norm(got2), norm(exp2),
                                  check_dtype=False)


def test_sort_by_aggregate_alias_descending(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = (df.group_by("k").agg(("sum", col("x") * col("q"), "rev"))
           .sort("-rev", "k").limit(5).collect().to_pandas())
    exp = (lpdf.assign(rev=lpdf.x * lpdf.q).groupby("k")
           .agg(rev=("rev", "sum")).reset_index()
           .sort_values(["rev", "k"], ascending=[False, True]).head(5)
           .reset_index(drop=True))
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True).astype("float64"),
        exp[got.columns.tolist()].astype("float64"), check_dtype=False,
        rtol=1e-9)


def test_string_literal_projection(sess, tables):
    """Constant string channel tags (the q5/q33/q56 pattern)."""
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.select("k", lit("store channel").alias("channel")) \
        .collect().to_pandas()
    assert (got["channel"] == "store channel").all()
    got2 = (df.select("k", lit("web").alias("channel"))
            .filter(col("channel") == lit("web")).count())
    assert got2 == len(got)


def test_with_column_replace_keeps_position(sess, tables):
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    assert df.columns == ["k", "x", "q", "s"]
    out = df.with_column("x", col("x") * lit(2.0))
    assert out.columns == ["k", "x", "q", "s"]
    out2 = df.with_column("z", col("q") + lit(1))
    assert out2.columns == ["k", "x", "q", "s", "z"]


def test_narrow_int_arithmetic_widens_to_int64(sess, tmp_path):
    pq.write_table(pa.table({"a": np.array([100000, 3], dtype=np.int32),
                             "b": np.array([100000, 4], dtype=np.int32)}),
                   str(tmp_path / "narrow.parquet"))
    df = sess.read_parquet(str(tmp_path / "narrow.parquet"))
    out = df.select((col("a") * col("b")).alias("p")).collect().to_pandas()
    assert out["p"].tolist() == [10_000_000_000, 12]


def test_suffixed_column_reference_above_join(sess, tables):
    """Filtering/selecting a `_r`-suffixed duplicate column above a join
    must resolve through projection pruning."""
    _, _, lp, rp = tables
    l, r = sess.read_parquet(lp), sess.read_parquet(rp)
    # both sides carry `k`; the right copy surfaces as k_r
    q = (l.select("k", "x").join(r.select("k", "y"),
                                 on=col("k") == col("k"))
         .filter(col("k_r") > lit(20)).select("x"))
    got = q.collect().to_pandas()
    lpdf = pd.read_parquet(lp)
    rpdf = pd.read_parquet(rp)
    j = lpdf[["k", "x"]].merge(rpdf[["k", "y"]], on="k")
    exp = j[j.k > 20][["x"]]
    assert len(got) == len(exp)


def test_bare_count_star(sess, tables):
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    out = df.group_by().agg(("count", "*", "cnt")).collect().to_pandas()
    assert out["cnt"].tolist() == [300]


def test_case_when_projection_matches_pandas(sess, tables):
    from hyperspace_tpu.plan.expr import when

    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    e = (when(col("k") < lit(5), col("x") * lit(10.0))
         .when(col("k") < lit(15), lit(1.5))
         .otherwise(col("x") - lit(1.0)))
    got = df.select("k", e.alias("v")).collect().to_pandas()
    exp = lpdf.assign(v=np.where(lpdf.k < 5, lpdf.x * 10.0,
                                 np.where(lpdf.k < 15, 1.5,
                                          lpdf.x - 1.0)))[["k", "v"]]
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_case_when_no_else_aggregation_skips_nulls(sess, tables):
    """sum/avg/count over `CASE WHEN ... THEN x END` skip unmatched rows
    (SQL NULL semantics) — the TPC-DS conditional-aggregation idiom."""
    from hyperspace_tpu.plan.expr import when

    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    e = when(col("q") == lit(3), col("x"))
    got = df.group_by("k").agg(("sum", e, "s3"),
                               ("count", e, "c3")).collect().to_pandas()
    m = lpdf.assign(v=np.where(lpdf.q == 3, lpdf.x, np.nan))
    exp = (m.groupby("k")
           .agg(s3=("v", lambda s: s.sum(min_count=1)), c3=("v", "count"))
           .reset_index())
    pd.testing.assert_frame_equal(norm(got), norm(exp), check_dtype=False)


def test_case_when_in_filter(sess, tables):
    from hyperspace_tpu.plan.expr import when

    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    band = when(col("k") < lit(10), lit(1)).otherwise(lit(2))
    got = df.filter(band == lit(1)).select("k").collect().to_pandas()
    exp = lpdf[lpdf.k < 10][["k"]]
    assert len(got) == len(exp)


def test_cross_join_suffixed_select(sess, tables):
    """Selecting only the right side's `_r` copy (or only right columns)
    through a cross join must keep the collision rename working."""
    _, _, lp, rp = tables
    l = sess.read_parquet(lp).select("k", "x").limit(5)
    r = sess.read_parquet(rp).select("k", "y").limit(3)
    got = l.join(r, how="cross").select("k_r").collect().to_pandas()
    assert len(got) == 15 and list(got.columns) == ["k_r"]
    only_right = l.join(r, how="cross").select("y").collect().to_pandas()
    assert len(only_right) == 15


def test_global_aggregate_over_zero_rows_is_one_row(sess, tables):
    """SQL: global aggregates over an empty input yield ONE row (count 0,
    sum/avg NULL) — and an empty bucket must not collapse a cross-join
    scalar assembly."""
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    empty = df.filter(col("k") == lit(-999))
    got = empty.agg(("count", "*", "c"), ("sum", "q", "s"),
                    ("count_distinct", "q", "d")).to_pandas()
    assert len(got) == 1
    assert got["c"][0] == 0 and got["d"][0] == 0
    assert pd.isna(got["s"][0])
    total = df.agg(("count", "*", "n"))
    crossed = empty.agg(("sum", "q", "s")).join(total, how="cross") \
        .to_pandas()
    assert len(crossed) == 1 and crossed["n"][0] == 300


def test_window_rank_and_partition_aggregates(sess, tables):
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.window(["k"], order_by=["-q"],
                    rk=("rank", "*"), drk=("dense_rank", "*"),
                    rn=("row_number", "*"), pavg=("avg", "x"),
                    pcnt=("count", "*")).to_pandas()
    gb = lpdf.groupby("k")
    # order_by present => aggregates use the SQL default RUNNING frame
    # (RANGE UNBOUNDED PRECEDING..CURRENT ROW, peers included): count(*)
    # at a row ordered by -q is the count of peers with q >= q_i — the
    # max-method rank — and avg is the expanding mean read at the last
    # row of each peer run.
    exp = lpdf.assign(
        rk=gb["q"].rank(method="min", ascending=False).astype("int64"),
        drk=gb["q"].rank(method="dense", ascending=False).astype("int64"),
        pcnt=gb["q"].rank(method="max", ascending=False).astype("int64"))
    s = lpdf.sort_values(["k", "q"], ascending=[True, False], kind="stable")
    ravg = (s.groupby("k", sort=False)["x"].expanding().mean()
            .reset_index(level=0, drop=True))
    ravg = ravg.groupby([s["k"], s["q"]], sort=False).transform("last")
    exp = exp.assign(pavg=ravg.reindex(lpdf.index))
    key = ["k", "q", "x", "s"]
    g = got.sort_values(key + ["rn"]).reset_index(drop=True)
    e = exp.sort_values(key).reset_index(drop=True)
    for c in ("rk", "drk", "pavg", "pcnt"):
        assert np.allclose(g[c], e[c]), c
    for _, grp in got.groupby("k"):
        assert sorted(grp.rn) == list(range(1, len(grp) + 1))


def test_window_whole_partition_aggregates(sess, tables):
    """No order_by => whole-partition values (SQL default frame without
    ORDER BY is the entire partition)."""
    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.window(["k"], pavg=("avg", "x"),
                    pcnt=("count", "*")).to_pandas()
    gb = lpdf.groupby("k")
    exp = lpdf.assign(pavg=gb["x"].transform("mean"),
                      pcnt=gb["x"].transform("size").astype("int64"))
    key = ["k", "q", "x", "s"]
    g = got.sort_values(key).reset_index(drop=True)
    e = exp.sort_values(key).reset_index(drop=True)
    assert np.allclose(g["pavg"], e["pavg"])
    assert np.allclose(g["pcnt"], e["pcnt"])


def test_window_running_frames(sess):
    """Cumulative sum/min/max/count with order_by (TPC-DS q51-style),
    including NULL inputs (skipped by aggregates) and order-key ties
    (peers share the frame value)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 1, 1, 1, 2, 2, 2],
        "o": [10, 20, 20, 30, 40, 5, 5, 7],
        "v": pd.array([3.0, None, 1.0, 7.0, 2.0, 4.0, 6.0, None],
                      dtype="float64"),
    })
    df = sess.create_dataframe(pdf)
    got = df.window(["k"], order_by=["o"], rsum=("sum", "v"),
                    rmin=("min", "v"), rmax=("max", "v"),
                    rcnt=("count", "v")).to_pandas()
    got = got.sort_values(["k", "o", "v"], na_position="first") \
        .reset_index(drop=True)
    # Hand-computed RANGE frames: k=1 rows ordered by o=10,20,20,30,40 —
    # the two o=20 peers (v NULL and 1.0) both see sum 3+1=4, count 2.
    exp = pd.DataFrame({
        "k": [1, 1, 1, 1, 1, 2, 2, 2],
        "o": [10, 20, 20, 30, 40, 5, 5, 7],
        "rsum": [3.0, 4.0, 4.0, 11.0, 13.0, 10.0, 10.0, 10.0],
        "rmin": [3.0, 1.0, 1.0, 1.0, 1.0, 4.0, 4.0, 4.0],
        "rmax": [3.0, 3.0, 3.0, 7.0, 7.0, 6.0, 6.0, 6.0],
        "rcnt": [1, 2, 2, 3, 4, 2, 2, 2],
    }).sort_values(["k", "o"]).reset_index(drop=True)
    # Align the two o=20 peer rows by v (NULL first) before comparing.
    for c in ("rsum", "rmin", "rmax", "rcnt"):
        assert np.allclose(got[c].astype("float64"),
                           exp[c].astype("float64")), c


def test_window_running_sum_no_cross_partition_cancellation(sess):
    """Float running sums use a segmented scan, not global-cumsum
    rebasing: a huge-magnitude partition sorted before a small one must
    not cancel the small partition's values away (review regression)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 2, 2, 2, 2],
        "o": [1, 2, 1, 2, 3, 4],
        "v": [1e16, 1e16, 0.1, 0.2, 0.3, 0.4],
    })
    got = sess.create_dataframe(pdf) \
        .window(["k"], order_by=["o"], rsum=("sum", "v")).to_pandas() \
        .sort_values(["k", "o"]).reset_index(drop=True)
    exp = [1e16, 2e16, 0.1, 0.3, 0.6, 1.0]
    assert np.allclose(got["rsum"], exp, rtol=1e-12), list(got["rsum"])


def test_window_serde_roundtrip(sess, tables):
    from hyperspace_tpu.plan.serde import plan_from_json, plan_to_json

    _, _, lp, _ = tables
    df = sess.read_parquet(lp).window(["k"], order_by=["q"],
                                      rk=("rank", "*"),
                                      tot=("sum", "q"))
    back = plan_from_json(plan_to_json(df.plan))
    assert back.to_dict() == df.plan.to_dict()


def test_window_validation(sess, tables):
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    with pytest.raises(HyperspaceException, match="ORDER BY"):
        df.window(["k"], rk=("rank", "*"))
    with pytest.raises(HyperspaceException, match="collides"):
        df.window(["k"], order_by=["q"], x=("rank", "*"))


def test_window_min_max_keep_float_dtype(sess, tables):
    """min/max window results keep the input dtype — float values must
    not truncate through the int64 default (review regression)."""
    _, _, lp, _ = tables
    df = sess.read_parquet(lp)
    got = df.window(["k"], pmin=("min", "x"),
                    pmax=("max", "x")).to_pandas()
    lpdf = pd.read_parquet(lp)
    gb = lpdf.groupby("k")
    exp = lpdf.assign(pmin=gb["x"].transform("min"),
                      pmax=gb["x"].transform("max"))
    key = ["k", "q", "x", "s"]
    g = got.sort_values(key).reset_index(drop=True)
    e = exp.sort_values(key).reset_index(drop=True)
    assert np.allclose(g["pmin"], e["pmin"]) and np.allclose(
        g["pmax"], e["pmax"])
    with pytest.raises(HyperspaceException, match="requires a column"):
        df.window(["k"], a=("avg", "*"))


def test_null_literal_projection_and_union(sess, tables):
    """Typed NULL projections (the ROLLUP idiom: coarser granularities
    union in with NULL-filled grouping columns)."""
    from hyperspace_tpu.engine.dataframe import DataFrame
    from hyperspace_tpu.plan.expr import null
    from hyperspace_tpu.plan.nodes import Union

    lpdf, _, lp, _ = tables
    df = sess.read_parquet(lp)
    out = df.select("k", null("string").alias("ns"),
                    null("int64").alias("ni"),
                    null("float64").alias("nf")).to_pandas()
    assert out["ns"].isna().all() and out["ni"].isna().all() \
        and out["nf"].isna().all()

    fine = df.group_by("k", "s").agg(("sum", "q", "sq")).select(
        "k", "s", "sq")
    coarse = df.group_by("k").agg(("sum", "q", "sq")).select(
        "k", null("string").alias("s"), "sq")
    u = DataFrame(Union([fine.plan, coarse.plan]), sess).to_pandas()
    exp_f = lpdf.groupby(["k", "s"]).q.sum().reset_index(name="sq")
    exp_c = lpdf.groupby("k").q.sum().reset_index(name="sq")
    exp_c["s"] = np.nan
    exp = pd.concat([exp_f, exp_c[["k", "s", "sq"]]], ignore_index=True)

    def nrm(d):
        d = d.copy()
        d["s"] = d["s"].astype(object).where(d["s"].notna(), np.nan)
        return d.sort_values(["k", "s", "sq"],
                             na_position="last").reset_index(drop=True)

    pd.testing.assert_frame_equal(nrm(u), nrm(exp), check_dtype=False)
