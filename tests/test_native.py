"""Native C++ host-lane kernels vs their numpy reference semantics.

The native radix sort (`native.bucket_key_sort_perm`) IS the index-build
host lane (`io/builder._host_build_permutation`); these tests pin it
bit-for-bit to the np.lexsort reference the lane falls back to, so the
on-disk layout can never depend on which engine computed the permutation.
"""

import numpy as np
import pytest

from hyperspace_tpu import native


pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native library unavailable")


def _ref_perm(bucket, lanes):
    return np.lexsort(tuple(reversed([bucket] + list(lanes))))


def _ref_bounds(bucket, perm, num_buckets):
    sb = bucket[perm]
    return (np.searchsorted(sb, np.arange(num_buckets), "left"),
            np.searchsorted(sb, np.arange(num_buckets), "right"))


def _check(bucket, num_buckets, lanes):
    out = native.bucket_key_sort_perm(bucket, num_buckets, lanes)
    assert out is not None
    perm, starts, ends = out
    ref = _ref_perm(bucket, lanes)
    np.testing.assert_array_equal(perm, ref)
    rs, re = _ref_bounds(bucket, ref, num_buckets)
    np.testing.assert_array_equal(starts, rs)
    np.testing.assert_array_equal(ends, re)


def test_single_int64_key_lanes():
    rng = np.random.default_rng(7)
    n = 100_000
    key = rng.integers(-(1 << 62), 1 << 62, n, dtype=np.int64)
    bucket = rng.integers(0, 32, n).astype(np.int32)
    lanes = [(key >> 32).astype(np.int32),
             (key & 0xFFFFFFFF).astype(np.uint32)]
    _check(bucket, 32, lanes)


def test_small_range_keys_skip_passes():
    rng = np.random.default_rng(8)
    n = 50_000
    key = rng.integers(0, 1000, n, dtype=np.int64)  # constant hi digits
    bucket = rng.integers(0, 8, n).astype(np.int32)
    lanes = [(key >> 32).astype(np.int32),
             (key & 0xFFFFFFFF).astype(np.uint32)]
    _check(bucket, 8, lanes)


def test_stability_ties_keep_input_order():
    n = 10_000
    bucket = np.zeros(n, dtype=np.int32)
    lane = np.full(n, 42, dtype=np.uint32)
    out = native.bucket_key_sort_perm(bucket, 4, [lane])
    perm, starts, ends = out
    np.testing.assert_array_equal(perm, np.arange(n, dtype=np.int32))
    assert starts[0] == 0 and ends[0] == n and ends[3] == n


def test_odd_lane_count_with_validity():
    rng = np.random.default_rng(9)
    n = 30_000
    bucket = rng.integers(0, 16, n).astype(np.int32)
    validity = rng.random(n) > 0.1  # bool lane leads (nulls first)
    lane = rng.integers(0, 1 << 31, n).astype(np.int32)
    _check(bucket, 16, [validity, lane])


def test_multi_key_four_lanes():
    rng = np.random.default_rng(10)
    n = 40_000
    bucket = rng.integers(0, 64, n).astype(np.int32)
    k1 = rng.integers(-(1 << 40), 1 << 40, n, dtype=np.int64)
    k2 = rng.integers(-(1 << 40), 1 << 40, n, dtype=np.int64)
    lanes = [(k1 >> 32).astype(np.int32), (k1 & 0xFFFFFFFF).astype(np.uint32),
             (k2 >> 32).astype(np.int32), (k2 & 0xFFFFFFFF).astype(np.uint32)]
    _check(bucket, 64, lanes)


def test_empty_and_tiny():
    _check(np.empty(0, dtype=np.int32), 4, [np.empty(0, dtype=np.uint32)])
    _check(np.zeros(1, dtype=np.int32), 1, [np.zeros(1, dtype=np.uint32)])


def test_signed_lane_ordering():
    # Signed int32 lanes must order negatives before positives after the
    # uint32 bias — exactly lexsort's int32 order.
    bucket = np.zeros(6, dtype=np.int32)
    lane = np.array([5, -3, 0, -(1 << 31), (1 << 31) - 1, -1],
                    dtype=np.int32)
    _check(bucket, 1, [lane])


def test_builder_host_permutation_uses_native_layout():
    """End-to-end: `_host_build_permutation` (native lane) must produce
    the identical layout the lexsort reference produces."""
    import pyarrow as pa

    from hyperspace_tpu.io.builder import _host_build_permutation

    rng = np.random.default_rng(11)
    n = 25_000
    table = pa.table({
        "key": rng.integers(0, n // 3, n).astype(np.int64),
        "val": rng.random(n),
    })
    chunks, starts, ends = _host_build_permutation(table, ["key"], 16)
    assert len(chunks) == 1
    perm = np.asarray(chunks[0])

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.host_hash import (host_column_hash_lanes,
                                              host_flat_hash32)
    from hyperspace_tpu.ops.keys import host_column_sort_lanes
    batch = columnar.from_arrow(table.select(["key"]), device=False)
    bucket = (host_flat_hash32(host_column_hash_lanes(batch.column("key")))
              % np.uint32(16)).astype(np.int32)
    ref = _ref_perm(bucket, host_column_sort_lanes(batch.column("key")))
    np.testing.assert_array_equal(perm, ref)
