"""TPC-DS subset: correctness of q17/q25/q64 (rules on == rules off ==
pandas oracle) and index acceleration observability (reference E2E
guarantee, `E2EHyperspaceRulesTests.scala:330-346`)."""

import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
from hyperspace_tpu.tpcds import QUERIES, generate
from hyperspace_tpu.tpcds.queries import create_indexes


@pytest.fixture(scope="module")
def tpcds_env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds")
    paths = generate(str(root / "data"), scale=0.05)
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(root / "wh"),
        "spark.hyperspace.index.num.buckets": "8"}))
    hs = Hyperspace(sess)
    dfs = {name: sess.read_parquet(path) for name, path in paths.items()}
    create_indexes(hs, dfs)
    pdfs = {name: pq.read_table(
        os.path.join(path, "part-0.parquet")).to_pandas()
        for name, path in paths.items()}
    return sess, dfs, pdfs


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.sort_values(list(df.columns)).reset_index(drop=True)
    return out.astype({c: "float64" for c in out.columns
                       if out[c].dtype.kind in "fi"})


@pytest.mark.parametrize("name", list(QUERIES))
def test_query_correctness_rules_on_off_vs_pandas(tpcds_env, name):
    sess, dfs, pdfs = tpcds_env
    build, oracle = QUERIES[name]
    expected = _norm(oracle(pdfs))
    assert len(expected) > 0, f"{name}: oracle produced no rows (bad data)"

    sess.enable_hyperspace()
    with_idx = _norm(build(dfs).collect().to_pandas())
    sess.disable_hyperspace()
    without = _norm(build(dfs).collect().to_pandas())

    pd.testing.assert_frame_equal(with_idx, expected, check_dtype=False,
                                  check_exact=False, rtol=1e-6)
    pd.testing.assert_frame_equal(without, expected, check_dtype=False,
                                  check_exact=False, rtol=1e-6)


def test_q17_uses_indexes(tpcds_env):
    """With rules on, q17's plan must read index data (v__= dirs) and its
    innermost ss-sr join must be the shuffle-free bucketed SMJ."""
    sess, dfs, _ = tpcds_env
    sess.enable_hyperspace()
    try:
        plan = QUERIES["q17"][0](dfs)._optimized_plan()
    finally:
        sess.disable_hyperspace()
    roots = [p for s in plan.collect_leaves() for p in s.root_paths]
    assert any("v__=" in p for p in roots), f"no index scans in {roots}"
    bucketed = [s for s in plan.collect_leaves()
                if s.bucket_spec is not None]
    assert len(bucketed) >= 2, "ss/sr sides not swapped to bucketed indexes"
