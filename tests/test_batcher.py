"""Inter-query batched execution (`engine/batcher.py`): signature
grouping, bit-identity vs the solo path, per-member deadline
settlement, snapshot-pin safety vs a concurrent refresher, the
per-query fallback contract on batch-lane failure, AOT warm-start, and
the PR-7 chaos harness rerun with batching ON.

Tests that need a cohort to form deterministically park a pad entry in
the scheduler (`_hold`, the test_serving.py idiom) so the lane's
"anything else in flight?" engagement check passes, and use a wide
gather window so staggered client threads land in one cohort.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig, telemetry)
from hyperspace_tpu.engine import batcher as batcher_mod
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.engine.batcher import (QueryBatcher, plan_signature,
                                           warmup)
from hyperspace_tpu.engine.scheduler import (Deadline, QueryScheduler,
                                             _QueryEntry)
from hyperspace_tpu.exceptions import QueryDeadlineExceededError
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.nodes import Filter, Project, Scan
from hyperspace_tpu.plan.schema import Field, Schema
from hyperspace_tpu.utils.faults import FaultRule

from chaos import canonical, run_chaos


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


@pytest.fixture
def fresh_lane():
    """Fresh scheduler AND batcher (cohorts, solo streaks, warm memo)."""
    sch = sched_mod.set_scheduler(QueryScheduler())
    bat = batcher_mod.set_batcher(QueryBatcher())
    yield sch, bat
    sched_mod.set_scheduler(QueryScheduler())
    batcher_mod.set_batcher(QueryBatcher())


@pytest.fixture
def batch_env(tmp_path):
    """A fact table (with a NULLABLE column) + session factory."""
    rng = np.random.default_rng(3)
    n = 20_000
    facts = tmp_path / "facts"
    facts.mkdir()
    w = rng.random(n)
    w_valid = rng.random(n) > 0.1  # ~10% nulls: validity lanes exercised
    pq.write_table(pa.table({
        "k": rng.integers(0, 500, n).astype(np.int64),
        "g": rng.integers(0, 32, n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
        "w": pa.array([float(x) if ok else None
                       for x, ok in zip(w, w_valid)], type=pa.float64()),
        # String column (with nulls): the batched lane's dictionary-code
        # constants resolve per member at gather time.
        "s": pa.array([f"cat{int(x):02d}" if x < 30 else None
                       for x in rng.integers(0, 33, n)]),
    }), str(facts / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update({k: str(v) for k, v in extra.items()})
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(facts)


def _hold(sch, qid="pad"):
    """Occupy one in-flight slot so the lane's engagement check (is
    anything else running?) passes for single-threaded arrivals."""
    ent = _QueryEntry(qid, Deadline(qid), 0, None)
    with sch._cv:
        sch._active[qid] = ent
        sch._grant(ent, telemetry.get_registry())
    return ent


def _run_concurrent(dfs, timeout_for=None):
    """Collect every df on its own thread; returns (results, errors)."""
    results = [None] * len(dfs)
    errors = [None] * len(dfs)

    def run(i):
        try:
            t = (timeout_for(i) if timeout_for is not None else None)
            results[i] = dfs[i].collect(timeout=t)
        except Exception as exc:
            errors[i] = exc

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(dfs))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(60)
    assert not any(th.is_alive() for th in threads), "batch lane hung"
    return results, errors


# ---------------------------------------------------------------------------
# Signature parsing
# ---------------------------------------------------------------------------


def _scan(root="/tmp/x", pinned=None, index=None):
    schema = Schema([Field("a", "int64"), Field("s", "string"),
                     Field("f", "float64")])
    return Scan([root], schema, pinned_version=pinned, index_name=index)


def test_signature_shapes_and_declines():
    s = _scan()
    sig = plan_signature(Project(["a"], Filter(
        (col("a") == lit(3)) & (col("f") > lit(0.5)), s)), 1)
    assert sig is not None
    assert sig.shape == (("cmp", "eq", 0, "i"), ("cmp", "gt", 1, "f"))
    assert sig.ints == [3] and sig.floats == [0.5]
    assert sig.projection == ("a",)
    # Same shape, different literals -> SAME key (they batch).
    sig2 = plan_signature(Project(["a"], Filter(
        (col("a") == lit(9)) & (col("f") > lit(0.25)), s)), 1)
    assert sig2.key == sig.key and sig2.ints == [9]
    # IN pads to a power of two and keys on the padded length.
    sig_in = plan_signature(Filter(col("a").isin(1, 2, 3), s), 1)
    assert sig_in.shape == (("in", 0, 4),)
    assert sig_in.ints == [1, 2, 3, 3]
    # String eq/IN qualify: code resolution is DEFERRED (int-lane
    # placeholder + a `strs` record the leader resolves against the
    # shared scan's dictionary at gather time).
    sig_s = plan_signature(Filter(col("s") == lit("x"), s), 1)
    assert sig_s.shape == (("cmp", "eq", 0, "i"),)
    assert sig_s.ints == [0]
    assert sig_s.strs == (("cmp", 0, 0, "eq", "x"),)
    sig_sin = plan_signature(Filter(col("s").isin("x", "y", "z"), s), 1)
    assert sig_sin.shape == (("in", 0, 4),)
    assert sig_sin.strs == (("in", 0, 0, 4, ("x", "y", "z")),)
    # Two members differing only in their string literals share a key.
    assert plan_signature(Filter(col("s") == lit("y"), s), 1).key \
        == sig_s.key
    # Declines: OR, computed projection, bare scan.
    assert plan_signature(Filter(
        (col("a") == lit(1)) | (col("a") == lit(2)), s), 1) is None
    assert plan_signature(Project(
        [(col("a") + lit(1)).alias("b")],
        Filter(col("a") == lit(1), s)), 1) is None
    assert plan_signature(s, 1) is None


def test_signature_never_mixes_index_versions():
    """Snapshot-pin safety: two plans over different committed versions
    (a refresher racing the serve path) can never share a cohort."""
    base = Filter(col("a") == lit(1), _scan("/w/idx/v__=0", 0, "idx"))
    newer = Filter(col("a") == lit(1), _scan("/w/idx/v__=1", 1, "idx"))
    k0 = plan_signature(base, 1).key
    k1 = plan_signature(newer, 1).key
    assert k0 != k1
    # ... and different sessions never share one either.
    assert plan_signature(base, 2).key != k0


# ---------------------------------------------------------------------------
# Bit-identity: batched vs solo, for every supported shape
# ---------------------------------------------------------------------------


def test_batched_results_bit_identical_to_solo(batch_env, fresh_lane):
    session, facts_dir = batch_env
    sch, _bat = fresh_lane
    sess = session(**{"spark.hyperspace.serve.batch.window.ms": 250})
    facts = sess.read_parquet(facts_dir)
    dfs = (
        # point: same signature, different constants
        [facts.filter(col("g") == lit(i)).select("k", "g", "v")
         for i in range(6)]
        # float range conjunctions
        + [facts.filter((col("v") > lit(lo)) & (col("v") <= lit(lo + .2)))
           .select("k", "v") for lo in (0.1, 0.6)]
        # IN over ints
        + [facts.filter(col("g").isin(2, 12, 22)).select("k", "g"),
           facts.filter(col("g").isin(5, 15, 25)).select("k", "g")]
        # nullable column: validity lanes + IS NOT NULL term
        + [facts.filter((col("w") > lit(0.5)) & col("w").is_not_null())
           .select("k", "w"),
           facts.filter((col("w") > lit(0.2)) & col("w").is_not_null())
           .select("k", "w")]
        # string eq (incl. an absent value) and string IN: constants
        # ride dictionary-code lanes resolved per member at gather time
        + [facts.filter(col("s") == lit(v)).select("k", "s")
           for v in ("cat03", "cat11", "no-such-value")]
        + [facts.filter(col("s").isin("cat01", "cat02", "cat29"))
           .select("k", "s"),
           facts.filter(col("s").isin("cat05", "zzz")).select("k", "s")]
    )
    expected = [canonical(df.collect()) for df in dfs]  # solo oracle
    inv0 = _counter("serve.batch.invocations")
    pad = _hold(sch)
    try:
        results, errors = _run_concurrent(dfs)
    finally:
        sch._release(pad)
    assert not any(errors), [repr(e) for e in errors if e]
    for r, e in zip(results, expected):
        assert canonical(r).equals(e)
    assert _counter("serve.batch.invocations") > inv0
    assert _counter("serve.batch.members") >= 2


def test_member_metrics_carry_cohort_and_operator(batch_env, fresh_lane):
    session, facts_dir = batch_env
    sch, _bat = fresh_lane
    sess = session(**{"spark.hyperspace.serve.batch.window.ms": 250})
    facts = sess.read_parquet(facts_dir)
    dfs = [facts.filter(col("g") == lit(i)).select("k", "v")
           for i in range(4)]
    for df in dfs:
        df.collect()  # warm solo
    collected = {}
    lock = threading.Lock()

    def run(i):
        table, m = dfs[i].collect(with_metrics=True)
        with lock:
            collected[i] = (table, m)

    pad = _hold(sch)
    try:
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60)
    finally:
        sch._release(pad)
    batched = [(i, m) for i, (_t, m) in collected.items()
               if m.events_of("serve", "batched")]
    assert batched, "no query recorded a batched event"
    for _i, m in batched:
        ev = m.events_of("serve", "batched")[-1]
        assert ev["cohort"] >= 2
        if not ev["leader"]:
            ops = [o for o in m.operators if o.name == "BatchedQuery"]
            assert ops and ops[-1].rows_out is not None
            assert ops[-1].detail["cohort"] == ev["cohort"]


# ---------------------------------------------------------------------------
# Per-member deadline: a cancelled member drops its slice, not the batch
# ---------------------------------------------------------------------------


def test_member_deadline_cancels_only_its_slice(batch_env, fresh_lane):
    session, facts_dir = batch_env
    sch, _bat = fresh_lane
    sess = session(**{"spark.hyperspace.serve.batch.window.ms": 700})
    facts = sess.read_parquet(facts_dir)
    leader_df = facts.filter(col("g") == lit(1)).select("k", "v")
    doomed_df = facts.filter(col("g") == lit(2)).select("k", "v")
    other_df = facts.filter(col("g") == lit(3)).select("k", "v")
    oracles = {id(d): canonical(d.collect())
               for d in (leader_df, doomed_df, other_df)}

    outcome = {}
    lock = threading.Lock()

    def run(tag, df, timeout=None, delay=0.0):
        time.sleep(delay)
        try:
            table = df.collect(timeout=timeout)
            with lock:
                outcome[tag] = table
        except Exception as exc:
            with lock:
                outcome[tag] = exc

    pad = _hold(sch)
    try:
        threads = [
            threading.Thread(target=run, args=("leader", leader_df)),
            threading.Thread(target=run,
                             args=("doomed", doomed_df, 0.15, 0.1)),
            threading.Thread(target=run,
                             args=("other", other_df, None, 0.2)),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60)
        assert not any(th.is_alive() for th in threads)
    finally:
        sch._release(pad)

    doomed = outcome["doomed"]
    assert isinstance(doomed, QueryDeadlineExceededError), repr(doomed)
    assert doomed.phase == "batch"
    # The survivors got their exact slices.
    assert canonical(outcome["leader"]).equals(oracles[id(leader_df)])
    assert canonical(outcome["other"]).equals(oracles[id(other_df)])


# ---------------------------------------------------------------------------
# Batch-lane failure: per-query fallback, never a cohort failure
# ---------------------------------------------------------------------------


def test_batch_lane_failure_falls_back_per_query(batch_env, fresh_lane,
                                                 fault_injector):
    session, facts_dir = batch_env
    sch, _bat = fresh_lane
    sess = session(**{"spark.hyperspace.serve.batch.window.ms": 250})
    facts = sess.read_parquet(facts_dir)
    dfs = [facts.filter(col("g") == lit(i)).select("k", "v")
           for i in range(4)]
    expected = [canonical(df.collect()) for df in dfs]
    fault_injector(FaultRule("batch.execute", kind="transient", nth=1,
                             times=-1))
    fb0 = _counter("serve.batch.fallbacks")
    pad = _hold(sch)
    try:
        results, errors = _run_concurrent(dfs)
    finally:
        sch._release(pad)
    # EVERY query succeeded via the per-query path, bit-identically.
    assert not any(errors), [repr(e) for e in errors if e]
    for r, e in zip(results, expected):
        assert canonical(r).equals(e)
    assert _counter("serve.batch.fallbacks") - fb0 >= 2


# ---------------------------------------------------------------------------
# Snapshot-pin safety, end to end, vs a concurrent refresher
# ---------------------------------------------------------------------------


def test_concurrent_refresher_never_breaks_batched_reads(
        tmp_path, fresh_lane):
    sch, _bat = fresh_lane
    rng = np.random.default_rng(11)
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 50, 6000).astype(np.int64),
        "x": rng.random(6000).astype(np.float64),
    }), str(src / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": "4",
        "spark.hyperspace.serve.batch.window.ms": 100}))
    hs = Hyperspace(sess)
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("bidx", ["k"], ["x"]))
    sess.enable_hyperspace()
    queries = [df.filter(col("k") == lit(i)).select("x")
               for i in range(8)]
    oracles = [canonical(q.collect()) for q in queries]
    # The rewritten plan is index-served and pinned: batchable.
    sig = plan_signature(sess.optimize(queries[0].plan), id(sess))
    assert sig is not None and sig.scan.index_name == "bidx"
    assert sig.scan.pinned_version is not None

    stop = threading.Event()
    failures = []

    def serve_loop(qi):
        while not stop.is_set():
            try:
                got = canonical(queries[qi].collect())
                if not got.equals(oracles[qi]):
                    failures.append(f"q{qi}: mismatch")
            except Exception as exc:
                failures.append(f"q{qi}: {exc!r}")

    threads = [threading.Thread(target=serve_loop, args=(i,))
               for i in range(4)]
    for th in threads:
        th.start()
    try:
        # Full refresh commits a NEW index version mid-traffic: plans
        # pinned to v0 and plans pinned to v1 must form separate
        # cohorts and both read exactly their pinned bytes.
        hs.refresh_index("bidx", mode="full")
        time.sleep(0.3)
    finally:
        stop.set()
        for th in threads:
            th.join(60)
    assert not failures, failures[:5]


# ---------------------------------------------------------------------------
# AOT warm-start
# ---------------------------------------------------------------------------


def test_aot_warmup_makes_first_cohorts_trace_free(tmp_path, fresh_lane):
    sch, _bat = fresh_lane
    # A UNIQUE shape + row count for this test: three-term conjunction
    # over a 7777-row table no other test reads, so process-wide jit
    # caches cannot mask a missing warmup.
    rng = np.random.default_rng(7)
    src = tmp_path / "aotsrc"
    src.mkdir()
    pq.write_table(pa.table({
        "a": rng.integers(0, 9, 7777).astype(np.int64),
        "b": rng.integers(0, 99, 7777).astype(np.int64),
        "c": rng.random(7777).astype(np.float64),
    }), str(src / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.serve.batch.window.ms": 250}))
    t = sess.read_parquet(str(src))
    dfs = [t.filter((col("a") == lit(i)) & (col("b") >= lit(10))
                    & (col("c") < lit(0.9))).select("a", "c")
           for i in range(5)]
    primed = warmup(dfs[0])
    assert primed >= 2  # one program per cohort bucket 2..max
    assert warmup(dfs[1]) == 0  # same signature: memo hit, nothing new
    expected = [canonical(df.collect()) for df in dfs]
    traces0 = _counter("compile.serve.batch.traces")
    inv0 = _counter("serve.batch.invocations")
    pad = _hold(sch)
    try:
        results, errors = _run_concurrent(dfs)
    finally:
        sch._release(pad)
    assert not any(errors), [repr(e) for e in errors if e]
    for r, e in zip(results, expected):
        assert canonical(r).equals(e)
    assert _counter("serve.batch.invocations") > inv0
    assert _counter("compile.serve.batch.traces") == traces0, \
        "warmed cohort shapes must dispatch without tracing"


# ---------------------------------------------------------------------------
# The PR-7 chaos harness, batching ON
# ---------------------------------------------------------------------------


def test_chaos_with_batching_on(batch_env, fresh_lane):
    session, facts_dir = batch_env
    _sch, _bat = fresh_lane
    sess = session(**{"spark.hyperspace.serve.queue.depth": 16})
    facts = sess.read_parquet(facts_dir)
    workload = (
        [(f"point{i}", facts.filter(col("g") == lit(i))
          .select("k", "g", "v")) for i in range(5)]
        + [("range", facts.filter((col("v") > lit(0.8))
                                  & (col("v") <= lit(0.9)))
            .select("k", "v")),
           ("inq", facts.filter(col("g").isin(7, 17, 27))
            .select("k", "g")),
           ("agg", facts.group_by("g").agg(("sum", "v", "total")))]
    )
    expected = {name: canonical(df.collect()) for name, df in workload}
    c0 = {k: _counter(k) for k in (
        "serve.rejected", "serve.deadline_exceeded", "serve.cancelled")}
    report = run_chaos(
        workload, expected, clients=8, total_queries=240,
        timeout_for=lambda i: 0.002 if i % 11 == 0 else None,
        join_timeout_s=300.0)
    # Zero deadlocks, zero untyped failures, bit-identical successes.
    assert not report.stuck_threads, report.summary()
    assert report.total == 240
    assert report.outcomes["error"] == 0, report.errors[:5]
    assert not report.mismatches, report.mismatches[:5]
    assert report.outcomes["ok"] >= 120, report.summary()
    # EXACT typed-outcome/counter agreement, batching engaged.
    assert _counter("serve.rejected") - c0["serve.rejected"] \
        == report.outcomes["rejected"]
    assert (_counter("serve.deadline_exceeded")
            - c0["serve.deadline_exceeded"]) \
        == report.outcomes["deadline"]
    assert _counter("serve.cancelled") - c0["serve.cancelled"] \
        == report.outcomes["cancelled"]
    assert all(p in ("queue", "plan", "scan", "operator", "stage",
                     "transfer", "write", "batch")
               for p in report.typed_phases)
    assert _counter("serve.batch.invocations") > 0
    # Occupancy: every invocation carries a real cohort (>= 2 members
    # by construction — an empty gather never invokes the program).
    assert _counter("serve.batch.members") \
        >= 2 * _counter("serve.batch.invocations")
    # The scheduler drained completely (no leaked admissions).
    sch = sched_mod.get_scheduler()
    assert sch.admitted_bytes() == 0
