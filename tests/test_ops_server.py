"""Ops endpoint: urllib round-trips of /metrics | /healthz |
/timeseries, Prometheus exposition conformance of the window series,
the SLO burn -> shed -> recovery cycle with exact counter agreement,
and cost-analysis counters after a warm TPC-DS-shaped query on the CPU
backend."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.engine.scheduler import (Deadline, QueryScheduler,
                                             _QueryEntry)
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import QueryRejectedError
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.telemetry import ops_server, timeseries


@pytest.fixture
def fresh_scheduler():
    sch = sched_mod.set_scheduler(QueryScheduler())
    yield sch
    sched_mod.set_scheduler(QueryScheduler())


@pytest.fixture
def server():
    """An ephemeral-port ops server (the process singleton, stopped on
    teardown so suites never leak a listener)."""
    srv = ops_server.start_server(port=0)
    yield srv
    ops_server.stop_server()


def _get(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode("utf-8")


def _tpcds_shaped_session(tmp_path):
    """A store_sales-shaped fact + date-dim pair, device lane forced so
    the warm query dispatches instrumented jits on the CPU backend."""
    rng = np.random.default_rng(5)
    n, n_dim = 4000, 365
    fact = tmp_path / "store_sales"
    dim = tmp_path / "date_dim"
    fact.mkdir()
    dim.mkdir()
    pq.write_table(pa.table({
        "ss_sold_date_sk": rng.integers(0, n_dim, n).astype(np.int64),
        "ss_quantity": rng.integers(1, 100, n).astype(np.int64),
        "ss_net_paid": rng.random(n) * 500,
    }), str(fact / "part-0.parquet"))
    pq.write_table(pa.table({
        "d_date_sk": np.arange(n_dim, dtype=np.int64),
        "d_moy": (np.arange(n_dim, dtype=np.int64) % 12) + 1,
    }), str(dim / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.execution.min.device.rows": "0",
    }))
    q = (sess.read_parquet(str(fact))
         .filter(col("ss_quantity") > lit(5))
         .join(sess.read_parquet(str(dim)),
               on=col("ss_sold_date_sk") == col("d_date_sk"))
         .group_by("d_moy")
         .agg(("sum", "ss_net_paid", "revenue"), cnt=("count", "*")))
    return sess, q


# ---------------------------------------------------------------------------
# Endpoint round-trips
# ---------------------------------------------------------------------------


def test_endpoints_round_trip_and_cost_counters(tmp_path, server):
    sess, q = _tpcds_shaped_session(tmp_path)
    q.collect()                      # trace (cost captured here)
    table, m = q.collect(with_metrics=True)   # warm dispatch
    assert table.num_rows > 0
    assert m.compile["traces"] == 0  # genuinely warm
    timeseries.get_sampler().tick()

    # /metrics: Prometheus text with the window gauges and the
    # cost-analysis counters of the warm TPC-DS-shaped query.
    status, ctype, body = _get(server, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    assert "hs_window_query_wall_s_p99" in body
    assert re.search(r"hs_compile_\w+_flops \d", body)
    assert "hs_device_dispatch_seconds" in body

    # Cost attribution landed registry- AND query-side.
    counters = telemetry.get_registry().counters_dict()
    flops = {k: v for k, v in counters.items()
             if k.startswith("compile.") and k.endswith(".flops")}
    assert flops and all(v > 0 for v in flops.values())
    assert counters.get("device.flops", 0) > 0
    roof = m.roofline
    assert roof["flops"] > 0
    assert roof["bytes_accessed"] > 0
    assert roof["dispatch_s"] > 0
    assert 0 < roof["device_share"] <= 1.0
    assert m.to_dict()["roofline"]["flops"] == roof["flops"]

    # /healthz: one JSON doc of serving state.
    status, ctype, body = _get(server, "/healthz")
    assert status == 200
    assert ctype == "application/json"
    doc = json.loads(body)
    assert doc["status"] == "ok"
    for key in ("scheduler", "breakers", "segments", "replicas",
                "flight"):
        assert key in doc, key
    assert "slo" in doc["scheduler"]
    assert "queue_depth" in doc["scheduler"]
    assert "by_replica" in doc["flight"]

    # /timeseries: the ring as JSON.
    status, ctype, body = _get(server, "/timeseries")
    assert status == 200
    doc = json.loads(body)
    assert doc["samples"], "sampler ring empty"
    assert "interval_s" in doc and "window_s" in doc

    # Unknown path: 404, not a stack trace.
    status, _ctype, _body = _get_allow_404(server, "/nope")
    assert status == 404


def _get_allow_404(srv, path):
    try:
        return _get(srv, path)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.headers.get("Content-Type"), ""


# ---------------------------------------------------------------------------
# Prometheus exposition conformance of the window series
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ")


def test_metrics_exposition_conformance_with_window_series(server):
    """The full /metrics payload — window gauges included — obeys the
    exposition format: HELP then TYPE per family, legal names, no
    repeated TYPE, cumulative histogram buckets."""
    reg = telemetry.get_registry()
    reg.histogram("query.wall_s").observe(0.004)
    reg.histogram("query.wall_s").observe(0.050)
    timeseries.get_sampler().tick()
    _status, _ctype, text = _get(server, "/metrics")
    assert text.endswith("\n")
    seen_type, seen_help = {}, set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert _NAME_RE.fullmatch(name), line
            assert name not in seen_help
            seen_help.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in seen_type, f"duplicate TYPE: {line}"
            assert name in seen_help, f"TYPE before HELP: {line}"
            seen_type[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        family = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        assert family in seen_type or m.group(1) in seen_type, line
    # The window series are exported as gauges under legal names.
    window_families = [n for n, k in seen_type.items()
                       if n.startswith("hs_window_")]
    assert window_families
    assert all(seen_type[n] == "gauge" for n in window_families)


# ---------------------------------------------------------------------------
# SLO burn -> shed -> recovery
# ---------------------------------------------------------------------------


def test_slo_burn_trip_shed_and_recovery(fresh_scheduler):
    """Trip the burn window, watch the tightened queue shed with EXACT
    counter agreement (every rejection while burning is a shed, and
    only those), then watch the window slide and the full depth
    return."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "100",
        "spark.hyperspace.serve.queue.depth": "2",
        "spark.hyperspace.serve.slo.p99.seconds": "0.01",
        "spark.hyperspace.serve.slo.window.seconds": "1.5",
        "spark.hyperspace.serve.slo.shed.enabled": "true",
    })
    reg = telemetry.get_registry()
    shed0 = reg.counters_dict().get("serve.slo.shed", 0)
    viol0 = reg.counters_dict().get("serve.slo.violations", 0)

    # Trip: every recorded wall violates the 10ms target.
    for _ in range(5):
        sch.slo.record(0.05, conf)
    assert sch.slo.burn_rate(conf) > sched_mod.SLO_SHED_BURN_THRESHOLD
    counters = reg.counters_dict()
    assert counters.get("serve.slo.violations", 0) - viol0 == 5
    assert reg.to_dict()["gauges"]["serve.slo.burn_rate"] > 1.0
    snap = sch.slo_snapshot(conf)
    assert snap["window_violations"] == 5
    assert snap["shed_enabled"] is True

    # Occupy the budget, queue ONE waiter (fills the tightened depth
    # 2 // 2 = 1 but not the configured 2).
    hold = _QueryEntry("hold", Deadline("hold"), 100, None)
    assert sch._admit(hold, conf) == 0.0
    admitted = threading.Event()

    def waiter():
        e = _QueryEntry("w1", Deadline("w1"), 60, None)
        sch._admit(e, conf)
        admitted.set()
        sch._release(e)

    t = threading.Thread(target=waiter)
    t.start()
    for _ in range(400):
        if sch.queue_depth() == 1:
            break
        time.sleep(0.005)
    assert sch.queue_depth() == 1

    # Re-trip just before the arrivals so a slow machine cannot let
    # the window slide mid-assert.
    for _ in range(5):
        sch.slo.record(0.05, conf)
    # Shed: each arrival is rejected by the TIGHTENED depth (1 waiter
    # >= shed depth 1, but < configured depth 2) and counts
    # serve.slo.shed exactly once.
    shed_rejects = 0
    for i in range(3):
        with pytest.raises(QueryRejectedError) as ei:
            sch._admit(_QueryEntry(f"s{i}", Deadline(f"s{i}"), 60,
                                   None), conf)
        assert "SLO shedding active" in str(ei.value)
        shed_rejects += 1
    assert reg.counters_dict().get("serve.slo.shed", 0) - shed0 \
        == shed_rejects == 3

    # Recovery: the window slides past the violations, burn decays to
    # zero, and the SAME arrival now queues instead of shedding.
    time.sleep(1.6)
    assert sch.slo.burn_rate(conf) == 0.0
    admitted2 = threading.Event()

    def waiter2():
        e = _QueryEntry("w2", Deadline("w2"), 60, None)
        sch._admit(e, conf)
        admitted2.set()
        sch._release(e)

    t2 = threading.Thread(target=waiter2)
    t2.start()
    for _ in range(400):
        if sch.queue_depth() == 2:
            break
        time.sleep(0.005)
    assert sch.queue_depth() == 2  # full depth back: w2 queued, no shed
    assert reg.counters_dict().get("serve.slo.shed", 0) - shed0 == 3

    sch._release(hold)
    assert admitted.wait(5.0) and admitted2.wait(5.0)
    t.join(5)
    t2.join(5)


def test_slo_off_by_default_records_nothing(fresh_scheduler):
    sch = fresh_scheduler
    conf = HyperspaceConf({})
    sch.slo.record(10.0, conf)  # way over any target — but SLO is off
    assert sch.slo.burn_rate(conf) == 0.0
    assert sch.slo_snapshot(conf)["window_queries"] == 0


# ---------------------------------------------------------------------------
# Replica / cohort dimensions on the flight ring
# ---------------------------------------------------------------------------


def test_flight_snapshot_replica_filter():
    rec = telemetry.FlightRecorder(capacity=8)
    for i, rep in enumerate((0, 1, None, 1)):
        qm = telemetry.QueryMetrics(description=f"q{i}")
        qm.finish()
        qm.replica = rep
        rec.record(qm)
    all_entries, last = rec.snapshot()
    assert len(all_entries) == 4
    rep1, last1 = rec.snapshot(replica=1)
    assert [m.description for m in rep1] == ["q1", "q3"]
    assert last1 == last  # the cursor stays global under the filter
    # Incremental + filtered compose.
    later, _ = rec.snapshot(since_seq=all_entries[1].flight_seq,
                            replica=1)
    assert [m.description for m in later] == ["q3"]


def test_metrics_dimensions_serialize():
    qm = telemetry.QueryMetrics(description="dims")
    qm.finish()
    assert "replica" not in qm.to_dict()  # unrouted stays undimensioned
    qm.replica = 2
    qm.cohort = {"id": "c-7", "size": 4, "leader": False}
    d = qm.to_dict()
    assert d["replica"] == 2
    assert d["cohort"]["id"] == "c-7"
    assert json.loads(qm.to_json())["cohort"]["size"] == 4


# ---------------------------------------------------------------------------
# Per-index rule-usage mining (the drop advisor's raw signal)
# ---------------------------------------------------------------------------


def test_rules_served_counters_and_index_usage_report(tmp_path):
    from hyperspace_tpu import Hyperspace, IndexConfig

    rng = np.random.default_rng(9)
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 50, 2000).astype(np.int64),
        "v": rng.random(2000),
        "w": rng.random(2000),
    }), str(src / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh")}))
    hs = Hyperspace(sess)
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("ops_hot", ["k"], ["v"]))
    hs.create_index(df, IndexConfig("ops_cold", ["w"], ["v"]))
    sess.enable_hyperspace()
    reg = telemetry.get_registry()
    served0 = reg.counters_dict().get("rules.served.ops_hot", 0)
    for _ in range(3):
        df.filter(col("k") == lit(7)).select("k", "v").collect()
    counters = reg.counters_dict()
    assert counters.get("rules.served.ops_hot", 0) - served0 == 3
    # The report names the index nothing selected as unused.
    usage = {row["index"]: row for row in hs.index_usage()}
    assert usage["ops_hot"]["served_in_ring"] >= 3
    assert usage["ops_hot"]["served_total"] >= 3
    assert usage["ops_hot"]["unused"] is False
    assert usage["ops_cold"]["served_in_ring"] == 0
    assert usage["ops_cold"]["unused"] is True
    # last_n narrows the ring window the report mines.
    narrowed = {row["index"]: row for row in hs.index_usage(last_n=1)}
    assert narrowed["ops_hot"]["ring_entries"] == 1


# ---------------------------------------------------------------------------
# Incident plane endpoints: /alerts, /healthz sections, /timeseries?since=
# ---------------------------------------------------------------------------


def test_alerts_endpoint_round_trip(server):
    """GET /alerts serves the conf-resolved rule table and the exact
    alert counters as JSON."""
    from hyperspace_tpu.telemetry import alerts

    status, ctype, body = _get(server, "/alerts")
    assert status == 200
    assert ctype.startswith("application/json")
    doc = json.loads(body)
    assert doc["enabled"] is True
    rule_names = {r["name"] for r in doc["rules"]}
    assert rule_names >= {r.name for r in alerts.DEFAULT_RULES}
    assert isinstance(doc["active"], list)
    for key in ("alerts.evaluations", "alerts.fired",
                "alerts.resolved", "alerts.suppressed"):
        assert key in doc["counters"]


def test_healthz_serves_incidents_and_index_usage(server):
    """/healthz carries the incident section (active list + exact
    fired/resolved counters) and the per-index usage report section."""
    status, _ctype, body = _get(server, "/healthz")
    assert status == 200
    doc = json.loads(body)
    inc = doc["incidents"]
    assert isinstance(inc["active"], list)
    assert inc["fired"] >= inc["resolved"] >= 0
    # No configured session in this bare server process: the section
    # degrades to a skip marker, never an error.
    usage = doc["index_usage"]
    assert "indexes" in usage or "skipped" in usage or "error" in usage


def test_timeseries_since_cursor_round_trip(server):
    """`?since=<seq>` returns only ticks newer than the cursor;
    `last_seq` is the next cursor; a malformed cursor degrades to the
    full ring."""
    s = timeseries.set_sampler(
        timeseries.TimeSeriesSampler(interval_s=1.0, capacity=64))
    try:
        s.tick(t=100.0)
        _status, _ctype, body = _get(server, "/timeseries")
        full = json.loads(body)
        assert full["samples"]
        cursor = full["last_seq"]
        assert cursor == full["samples"][-1]["seq"]

        s.tick(t=101.0)
        s.tick(t=102.0)
        _status, _ctype, body = _get(server, f"/timeseries?since={cursor}")
        doc = json.loads(body)
        assert len(doc["samples"]) == 2
        assert all(smp["seq"] > cursor for smp in doc["samples"])
        assert doc["last_seq"] == cursor + 2

        # Caught-up cursor: empty delta, cursor unchanged.
        _status, _ctype, body = _get(
            server, f"/timeseries?since={doc['last_seq']}")
        assert json.loads(body)["samples"] == []

        # Malformed cursor: full ring, not a 4xx.
        _status, _ctype, body = _get(server, "/timeseries?since=abc")
        assert len(json.loads(body)["samples"]) == 3
    finally:
        timeseries.reset_sampler()
