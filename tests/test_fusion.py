"""Whole-stage fusion (engine/fusion.py): masked-semantics equality
against eager per-operator execution, executable reuse across plan
rebuilds, and fallback behavior."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit


@pytest.fixture
def env(tmp_path):
    """Two tables: a fact (device lane forced) and a small dimension with
    nulls, strings, and a key the fact sometimes misses."""
    rng = np.random.default_rng(3)
    n = 5000
    fact_dir = tmp_path / "fact"
    dim_dir = tmp_path / "dim"
    fact_dir.mkdir()
    dim_dir.mkdir()
    fact_key = rng.integers(0, 60, n).astype(np.int64)  # dim has 0..49
    pq.write_table(pa.table({
        "k": fact_key,
        "v": rng.random(n),
        "grp": pa.array([f"g{int(x)}" for x in rng.integers(0, 7, n)]),
    }), str(fact_dir / "part-0.parquet"))
    dim_name = pa.array(
        [None if i % 13 == 0 else f"name_{i}" for i in range(50)])
    pq.write_table(pa.table({
        "k": np.arange(50, dtype=np.int64),
        "name": dim_name,
        "w": np.arange(50, dtype=np.int64) * 10,
    }), str(dim_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
                "spark.hyperspace.execution.min.device.rows": "0",
                "spark.hyperspace.distribution.enabled": "false"}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(fact_dir), str(dim_dir)


def run_query(sess, fact, dim, how):
    fdf = sess.read_parquet(fact)
    ddf = sess.read_parquet(dim)
    q = (fdf.filter(col("k") > lit(5))
         .join(ddf.filter(col("w") < lit(400)), on=col("k") == col("k"),
               how=how))
    if how in ("left_semi", "left_anti"):
        q = q.select("k", "v")
    else:
        q = q.select("k", "v", "name", "w")
    return q.to_pandas()


def norm(df):
    return (df.sort_values(list(df.columns)).reset_index(drop=True)
            .astype({c: "float64" for c in df.columns
                     if df[c].dtype.kind in "fi"}))


@pytest.mark.parametrize("how", ["inner", "left_outer", "left_semi",
                                 "left_anti"])
def test_fused_broadcast_join_matches_eager(env, how):
    session, fact, dim = env
    fused = run_query(session(), fact, dim, how)
    eager = run_query(
        session(**{"spark.hyperspace.execution.fusion.enabled": "false"}),
        fact, dim, how)
    pd.testing.assert_frame_equal(norm(fused), norm(eager),
                                  check_dtype=False)
    assert len(fused) > 0


def test_fused_plan_shows_stage_and_reuses_executable(env):
    session, fact, dim = env
    sess = session()
    from hyperspace_tpu.engine import fusion

    def q():
        fdf = sess.read_parquet(fact)
        ddf = sess.read_parquet(dim)
        return (fdf.filter(col("k") > lit(5))
                .join(ddf, on=col("k") == col("k"))
                .select("v", "name"))

    from hyperspace_tpu.engine.executor import compile_plan
    df = q()
    phys = compile_plan(df._optimized_plan(), conf=sess.conf)
    text = phys.tree_string()
    assert "FusedStage" in text and "BroadcastHashJoin" in text
    # explain stays at the operator level (display contract).
    assert "FusedStage" not in q().explain_plans()[2].tree_string()

    q().to_pandas()  # traces + compiles the stage
    assert fusion._run_stage_jit is not None
    size_before = fusion._run_stage_jit._cache_size()
    # A REBUILT plan (fresh physical nodes) must hit the same executable:
    # the program key, not object identity, is the cache key.
    q().to_pandas()
    assert fusion._run_stage_jit._cache_size() == size_before


def test_fused_expression_projection_and_case(env):
    """Computed projections + CASE + IN + LIKE through the fused lane."""
    session, fact, dim = env
    from hyperspace_tpu.plan.expr import CaseWhen

    def build(sess):
        fdf = sess.read_parquet(fact)
        q = (fdf.filter(col("grp").like("g%")
                        & col("k").isin(*range(4, 40)))
             .with_column("bonus", CaseWhen(
                 [(col("k") > lit(30), col("v") * lit(2.0))],
                 col("v")))
             .select("k", "bonus"))
        return q.to_pandas()

    fused = build(session())
    eager = build(session(
        **{"spark.hyperspace.execution.fusion.enabled": "false"}))
    pd.testing.assert_frame_equal(norm(fused), norm(eager),
                                  check_dtype=False)
    assert len(fused) > 0


def test_host_lane_matches_eager(env):
    """With the default device threshold the sources stay host-side;
    host-lane stages route to the eager operator graph (early
    compaction beats masked full-length evaluation on numpy) and must
    agree with fusion disabled."""
    session, fact, dim = env
    fused = run_query(
        session(**{"spark.hyperspace.execution.min.device.rows":
                   str(1 << 30)}), fact, dim, "inner")
    eager = run_query(
        session(**{"spark.hyperspace.execution.min.device.rows":
                   str(1 << 30),
                   "spark.hyperspace.execution.fusion.enabled": "false"}),
        fact, dim, "inner")
    pd.testing.assert_frame_equal(norm(fused), norm(eager),
                                  check_dtype=False)


def test_fusion_falls_back_on_string_join_keys(tmp_path):
    """String join keys are ineligible for the direct-address table; the
    fused stage must fall back to the eager graph and still be right."""
    rng = np.random.default_rng(5)
    n = 2000
    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    a_dir.mkdir(), b_dir.mkdir()
    pq.write_table(pa.table({
        "s": pa.array([f"k{int(x)}" for x in rng.integers(0, 30, n)]),
        "v": rng.random(n)}), str(a_dir / "p.parquet"))
    pq.write_table(pa.table({
        "s": pa.array([f"k{i}" for i in range(30)]),
        "w": np.arange(30, dtype=np.int64)}), str(b_dir / "p.parquet"))

    def run(fusion_on):
        sess = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": str(tmp_path / "wh"),
            "spark.hyperspace.execution.min.device.rows": "0",
            "spark.hyperspace.distribution.enabled": "false",
            "spark.hyperspace.execution.fusion.enabled":
                "true" if fusion_on else "false",
            # Force the broadcast planner path despite string keys.
            "spark.hyperspace.broadcast.threshold": str(1 << 20)}))
        adf = sess.read_parquet(str(a_dir))
        bdf = sess.read_parquet(str(b_dir))
        return (adf.join(bdf, on=col("s") == col("s"))
                .select("v", "w").to_pandas())

    pd.testing.assert_frame_equal(norm(run(True)), norm(run(False)),
                                  check_dtype=False)


def test_build_columns_defer_to_post_compaction(env):
    """Carried build-side columns must reach the runtime DEFERRED (only
    their join's hit/matched pair crosses the executable) and still
    decode to the exact eager values — including strings with nulls."""
    from hyperspace_tpu.engine import fusion

    session, fact, dim = env
    sess = session()
    fusion._OUT_META.clear()
    out = run_query(sess, fact, dim, "left_outer")
    # name/w are carried (never filtered on) -> recorded as lazy specs.
    lazy_names = {spec[0]
                  for meta in fusion._OUT_META.values()
                  for spec in meta[3]}
    assert {"name", "w"} <= lazy_names, lazy_names
    sess2 = session(**{"spark.hyperspace.execution.fusion.enabled":
                       "false"})
    want = run_query(sess2, fact, dim, "left_outer")
    import pandas as pd
    pd.testing.assert_frame_equal(norm(out), norm(want),
                                  check_dtype=False)
