"""Explain tests (reference `ExplainTest`, `BufferStreamTest`,
`DisplayModeTest`)."""

import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plananalysis.buffer_stream import BufferStream
from hyperspace_tpu.plananalysis.display_mode import (ConsoleMode, HTMLMode,
                                                      PlainTextMode,
                                                      get_display_mode)


def test_display_modes_and_custom_tags():
    assert PlainTextMode().highlight("x") == "<----x---->"
    assert "[32m" in ConsoleMode().highlight("x")
    assert HTMLMode().highlight("x").startswith("<b ")
    conf = HyperspaceConf({
        "spark.hyperspace.explain.displayMode": "html",
        "spark.hyperspace.explain.displayMode.highlight.beginTag": "<mark>",
        "spark.hyperspace.explain.displayMode.highlight.endTag": "</mark>",
    })
    mode = get_display_mode(conf)
    assert isinstance(mode, HTMLMode)
    assert mode.highlight("x") == "<mark>x</mark>"
    assert mode.newline == "<br>"


def test_buffer_stream():
    stream = BufferStream(PlainTextMode())
    stream.write("a").write_line("b").highlight("c").write_line()
    assert stream.to_string() == "ab\n<----c---->\n"


@pytest.fixture
def env(tmp_path, sample_parquet):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def test_explain_filter_query(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("exIdx", ["clicks"], ["id"]))
    query = df.filter(col("clicks") == 2).select("id")

    out = []
    hs.explain(query, verbose=True, redirect=out.append)
    text = out[0]
    assert "Plan with indexes:" in text
    assert "Plan without indexes:" in text
    assert "Indexes used:" in text
    assert "exIdx" in text
    # differing scans highlighted
    assert "<----" in text
    # verbose operator stats table present
    assert "Physical operator stats:" in text
    assert "Scan" in text


def test_explain_join_shows_exchange_elision(env):
    session, hs, src = env
    # Pin broadcast off so the rules-off plan shows the Exchange+Sort
    # the index elides (reference: `E2EHyperspaceRulesTests.scala:42`).
    session.conf.set("hyperspace.broadcast.threshold", -1)
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("el", ["imprs"], ["id"]))
    hs.create_index(df, IndexConfig("er", ["imprs"], ["score"]))
    query = (df.select("imprs", "id")
             .join(df.select("imprs", "score"), on="imprs"))
    out = []
    hs.explain(query, verbose=True, redirect=out.append)
    text = out[0]
    # The stats table must show Exchange going from 2 to 0.
    exchange_rows = [line for line in text.splitlines() if "Exchange" in line]
    assert any("-2" in line for line in exchange_rows)
    sort_rows = [line for line in text.splitlines()
                 if line.startswith("| Sort")]
    assert any("-2" in line for line in sort_rows)


def test_explain_leaves_session_state(env):
    session, hs, src = env
    df = session.read_parquet(src)
    query = df.filter(col("clicks") == 2)
    session.enable_hyperspace()
    hs.explain(query, redirect=lambda s: None)
    assert session.is_hyperspace_enabled
    session.disable_hyperspace()
    hs.explain(query, redirect=lambda s: None)
    assert not session.is_hyperspace_enabled


def test_lockstep_diff_classifies_repeated_lines_by_position():
    """Two textually identical operators of which only one differs in its
    subtree: the line-set diff mis-classified both; the lockstep walk
    highlights by position (reference `PlanAnalyzer.scala:56-101`)."""
    from hyperspace_tpu.engine.physical import PhysicalNode
    from hyperspace_tpu.plananalysis.analyzer import PlanAnalyzer

    class Fake(PhysicalNode):
        def __init__(self, label, children=()):
            self.label = label
            self._children = list(children)

        @property
        def children(self):
            return self._children

        def simple_string(self):
            return self.label

    # A: Join(Sort(X), Sort(B));  B: Join(Sort(A), Sort(B))
    a = Fake("Join", [Fake("Sort", [Fake("X")]), Fake("Sort", [Fake("B")])])
    b = Fake("Join", [Fake("Sort", [Fake("A")]), Fake("Sort", [Fake("B")])])
    out_a, out_b = [], []
    PlanAnalyzer._lockstep_diff(a, b, 0, out_a, out_b)
    # Equal nodes print plain at every level; ONLY the differing leaf
    # under the first Sort highlights — the second, textually identical,
    # Sort subtree stays plain (a line-set diff cannot distinguish them).
    assert [(l.strip("+- "), h) for l, h in out_a] == [
        ("Join", False), ("Sort", False), ("X", True),
        ("Sort", False), ("B", False)]
    assert [(l.strip("+- "), h) for l, h in out_b] == [
        ("Join", False), ("Sort", False), ("A", True),
        ("Sort", False), ("B", False)]


def test_explain_golden_strings(env, tmp_path):
    """Golden explain output in plain/console/HTML modes (reference
    `ExplainTest.scala`), with machine-specific paths normalized."""
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("goldIdx", ["clicks"], ["id"]))
    query = df.filter(col("clicks") == 2).select("id")

    import glob
    import os
    idx_root = glob.glob(str(tmp_path / "wh" / "indexes" / "goldIdx"
                             ) + "/v__=*")[0]

    def render():
        out = []
        hs.explain(query, redirect=out.append)
        text = out[0]
        text = text.replace(os.path.normpath(idx_root), "<INDEX>")
        return text.replace(os.path.normpath(src), "<SRC>")

    golden_plain = """\
=============================================================
Plan with indexes:
=============================================================
Project [id]
  +- Filter ((col(clicks) = lit(2)))
<----    +- Scan parquet [clicks, id] ['<INDEX>'], buckets=4, prunedBuckets=1/4---->

=============================================================
Plan without indexes:
=============================================================
Project [id]
  +- Filter ((col(clicks) = lit(2)))
<----    +- Scan parquet [id, clicks] ['<SRC>']---->

=============================================================
Indexes used:
=============================================================
goldIdx:<INDEX>

"""
    assert render() == golden_plain

    session.conf.set("spark.hyperspace.explain.displayMode", "html")
    html = render()
    assert "<b style" in html and "<br>" in html
    assert "Scan parquet [clicks, id] ['&lt;INDEX&gt;']" in html.replace(
        "<INDEX>", "&lt;INDEX&gt;") or "<INDEX>" in html

    session.conf.set("spark.hyperspace.explain.displayMode", "console")
    text = render()
    assert "\x1b[32m" in text  # ANSI green highlight
    session.conf.unset("spark.hyperspace.explain.displayMode")
