"""Explain tests (reference `ExplainTest`, `BufferStreamTest`,
`DisplayModeTest`)."""

import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plananalysis.buffer_stream import BufferStream
from hyperspace_tpu.plananalysis.display_mode import (ConsoleMode, HTMLMode,
                                                      PlainTextMode,
                                                      get_display_mode)


def test_display_modes_and_custom_tags():
    assert PlainTextMode().highlight("x") == "<----x---->"
    assert "[32m" in ConsoleMode().highlight("x")
    assert HTMLMode().highlight("x").startswith("<b ")
    conf = HyperspaceConf({
        "spark.hyperspace.explain.displayMode": "html",
        "spark.hyperspace.explain.displayMode.highlight.beginTag": "<mark>",
        "spark.hyperspace.explain.displayMode.highlight.endTag": "</mark>",
    })
    mode = get_display_mode(conf)
    assert isinstance(mode, HTMLMode)
    assert mode.highlight("x") == "<mark>x</mark>"
    assert mode.newline == "<br>"


def test_buffer_stream():
    stream = BufferStream(PlainTextMode())
    stream.write("a").write_line("b").highlight("c").write_line()
    assert stream.to_string() == "ab\n<----c---->\n"


@pytest.fixture
def env(tmp_path, sample_parquet):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 4,
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def test_explain_filter_query(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("exIdx", ["clicks"], ["id"]))
    query = df.filter(col("clicks") == 2).select("id")

    out = []
    hs.explain(query, verbose=True, redirect=out.append)
    text = out[0]
    assert "Plan with indexes:" in text
    assert "Plan without indexes:" in text
    assert "Indexes used:" in text
    assert "exIdx" in text
    # differing scans highlighted
    assert "<----" in text
    # verbose operator stats table present
    assert "Physical operator stats:" in text
    assert "Scan" in text


def test_explain_join_shows_exchange_elision(env):
    session, hs, src = env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("el", ["imprs"], ["id"]))
    hs.create_index(df, IndexConfig("er", ["imprs"], ["score"]))
    query = (df.select("imprs", "id")
             .join(df.select("imprs", "score"), on="imprs"))
    out = []
    hs.explain(query, verbose=True, redirect=out.append)
    text = out[0]
    # The stats table must show Exchange going from 2 to 0.
    exchange_rows = [line for line in text.splitlines() if "Exchange" in line]
    assert any("-2" in line for line in exchange_rows)
    sort_rows = [line for line in text.splitlines()
                 if line.startswith("| Sort")]
    assert any("-2" in line for line in sort_rows)


def test_explain_leaves_session_state(env):
    session, hs, src = env
    df = session.read_parquet(src)
    query = df.filter(col("clicks") == 2)
    session.enable_hyperspace()
    hs.explain(query, redirect=lambda s: None)
    assert session.is_hyperspace_enabled
    session.disable_hyperspace()
    hs.explain(query, redirect=lambda s: None)
    assert not session.is_hyperspace_enabled
