"""Fault-injected storage resilience: the retry seam, the fault
injector, crash consistency + recovery, and graceful query degradation.

These tests exercise the failure paths the happy-path suites never
reach: every Action phase boundary aborts and recovers (crash-point
matrix), concurrent writers race the op log on memory://, torn writes
land partial bytes, and queries over vanished index data degrade to the
source plan instead of failing.
"""

import json
import os
import shutil
import threading
import uuid

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig)
from hyperspace_tpu.constants import STABLE_STATES, States
from hyperspace_tpu.exceptions import (HyperspaceException,
                                       IndexDataUnavailableError)
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.utils import faults, file_utils, retry
from hyperspace_tpu.utils.faults import (FaultRule, InjectedCrash,
                                         InjectedPermanentError,
                                         InjectedTransientError,
                                         TornWriteError)

from fakes import FakeDataManager, FakeLogManager, make_entry


# -- retry policy ----------------------------------------------------------


def _sleep_recorder():
    delays = []
    return delays, delays.append


def test_retry_succeeds_after_transient():
    delays, sleep = _sleep_recorder()
    policy = retry.RetryPolicy(attempts=5, base_ms=10, max_ms=100,
                               sleep=sleep)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("transient")
        return "ok"

    assert retry.call(flaky, operation="t.flaky", policy=policy) == "ok"
    assert calls["n"] == 3
    assert len(delays) == 2
    assert delays[1] > delays[0]  # exponential growth


def test_retry_permanent_fails_immediately():
    delays, sleep = _sleep_recorder()
    policy = retry.RetryPolicy(attempts=5, sleep=sleep)
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        retry.call(broken, operation="t.broken", policy=policy)
    assert calls["n"] == 1 and not delays


def test_retry_gives_up_after_attempts():
    delays, sleep = _sleep_recorder()
    policy = retry.RetryPolicy(attempts=3, sleep=sleep)

    def always():
        raise TimeoutError("still down")

    from hyperspace_tpu import telemetry
    before = telemetry.get_registry().counters_dict()
    with pytest.raises(TimeoutError):
        retry.call(always, operation="t.always", policy=policy)
    assert len(delays) == 2  # attempts-1 backoffs
    after = telemetry.get_registry().counters_dict()
    assert after.get("io.retries", 0) - before.get("io.retries", 0) == 2
    assert after.get("io.giveups", 0) - before.get("io.giveups", 0) == 1


def test_retryable_extension_and_predicate():
    policy = retry.RetryPolicy(attempts=3, sleep=lambda s: None)
    calls = {"n": 0}

    def torn_then_ok():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("torn json")
        return 42

    # ValueError is permanent by default...
    with pytest.raises(ValueError):
        retry.call(lambda: (_ for _ in ()).throw(ValueError("x")),
                   operation="t.v", policy=policy)
    # ...but call sites can extend the classification.
    assert retry.call(torn_then_ok, operation="t.torn", policy=policy,
                      retryable=(ValueError,)) == 42


def test_classification_typed_and_status_based():
    assert retry.is_transient(ConnectionResetError("x"))
    assert retry.is_transient(TimeoutError("x"))
    assert retry.is_transient(TornWriteError("x"))
    assert not retry.is_transient(FileNotFoundError("x"))
    assert not retry.is_transient(PermissionError("x"))
    assert not retry.is_transient(ValueError("x"))

    class Http(Exception):
        def __init__(self, status):
            self.status = status

    assert retry.is_transient(Http(503))
    assert retry.is_transient(Http(429))
    assert not retry.is_transient(Http(404))


def test_backoff_deterministic_and_capped():
    policy = retry.RetryPolicy(attempts=10, base_ms=20, max_ms=100)
    first = [policy.delay_s("op.a", i) for i in range(1, 8)]
    again = [policy.delay_s("op.a", i) for i in range(1, 8)]
    assert first == again  # deterministic jitter
    assert first != [policy.delay_s("op.b", i) for i in range(1, 8)]
    assert all(d <= 0.100 for d in first)  # capped at max_ms
    assert all(d >= 0.5 * 0.020 for d in first[:1])


def test_policy_from_conf():
    conf = HyperspaceConf({"spark.hyperspace.io.retry.attempts": "7",
                           "spark.hyperspace.io.retry.base.ms": "5",
                           "spark.hyperspace.io.retry.max.ms": "50"})
    policy = retry.policy_for(conf)
    assert (policy.attempts, policy.base_ms, policy.max_ms) == (7, 5.0, 50.0)
    assert retry.policy_for(None) is retry.DEFAULT_POLICY


# -- fault injector --------------------------------------------------------


def test_injector_nth_and_times(fault_injector):
    inj = fault_injector(FaultRule("seam.*", kind="transient", nth=2,
                                   times=2))
    assert faults.fire("seam.x") is None  # call 1: before nth
    for _ in range(2):  # calls 2-3 fire
        with pytest.raises(InjectedTransientError):
            faults.fire("seam.x")
    assert faults.fire("seam.x") is None  # exhausted
    assert inj.fired("seam.*") == 2
    assert faults.fire("other.op") is None  # pattern mismatch


def test_injector_path_filter_and_kinds(fault_injector):
    fault_injector(
        FaultRule("file.create", kind="permanent", path="*report*",
                  times=-1))
    assert faults.fire("file.create", "/x/data.parquet") is None
    with pytest.raises(InjectedPermanentError):
        faults.fire("file.create", "/x/7.report.json")
    with pytest.raises(InjectedPermanentError):  # times=-1: forever
        faults.fire("file.create", "/x/8.report.json")


def test_injector_crash_is_baseexception(fault_injector):
    fault_injector(FaultRule("boom", kind="crash"))
    with pytest.raises(InjectedCrash):
        faults.fire("boom")
    assert not issubclass(InjectedCrash, Exception)


def test_injector_seeded_probability_replays(fault_injector):
    def pattern(seed):
        inj = faults.FaultInjector(
            [FaultRule("p.*", kind="transient", probability=0.5,
                       times=-1)], seed=seed)
        out = []
        for _ in range(32):
            try:
                inj.check("p.op")
                out.append(0)
            except InjectedTransientError:
                out.append(1)
        return out

    assert pattern(7) == pattern(7)  # same seed -> same chaos
    assert pattern(7) != pattern(8)
    assert 0 < sum(pattern(7)) < 32


def test_uninstalled_fire_is_noop():
    faults.uninstall()
    assert faults.fire("anything", "/p") is None


# -- log manager resilience ------------------------------------------------


def test_log_read_retries_transient_io(tmp_path, fault_injector):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.ACTIVE))
    fault_injector(FaultRule("file.read", kind="transient", times=2,
                             path="*_hyperspace_log*"))
    entry = mgr.get_log(0)  # survives two injected read failures
    assert entry.state == States.ACTIVE


def test_log_read_retries_torn_json(tmp_path, monkeypatch):
    """A parse failure during read is retried (the OCC fallback publishes
    the filename before its contents); the writer 'finishing' during the
    retry window makes the read succeed."""
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.ACTIVE))
    real_read = file_utils.read_contents
    calls = {"n": 0}

    def torn_then_full(path):
        calls["n"] += 1
        contents = real_read(path)
        return contents[: len(contents) // 2] if calls["n"] < 3 else contents

    monkeypatch.setattr(
        "hyperspace_tpu.index.log_manager.file_utils.read_contents",
        torn_then_full)
    assert mgr.get_log(0).state == States.ACTIVE
    assert calls["n"] == 3


def test_log_read_permanently_corrupt_raises(tmp_path):
    log_dir = tmp_path / "idx" / "_hyperspace_log"
    log_dir.mkdir(parents=True)
    (log_dir / "0").write_text("{torn forever")
    mgr = IndexLogManagerImpl(
        str(tmp_path / "idx"),
        conf=HyperspaceConf({"spark.hyperspace.io.retry.attempts": "2",
                             "spark.hyperspace.io.retry.base.ms": "1"}))
    with pytest.raises(HyperspaceException, match="Corrupt log entry"):
        mgr.get_log(0)


def test_atomic_publish_never_tears_target(tmp_path, fault_injector):
    target = str(tmp_path / "latestStable")
    file_utils.atomic_publish(target, '{"state": "OLD"}')
    fault_injector(FaultRule("file.publish", kind="torn", times=-1))
    with pytest.raises(TornWriteError):
        file_utils.atomic_publish(target, '{"state": "NEW-LONGER"}')
    # Reader sees the OLD contents in full — never a torn mix.
    assert json.loads(file_utils.read_contents(target)) == {"state": "OLD"}
    assert [f for f in os.listdir(tmp_path) if f.startswith("latestStable.")]\
        == []  # no temp litter


def test_latest_stable_copy_atomic_in_log_manager(tmp_path, fault_injector):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"),
                              conf=HyperspaceConf({
                                  "spark.hyperspace.io.retry.attempts": "2",
                                  "spark.hyperspace.io.retry.base.ms": "1"}))
    assert mgr.write_log(0, make_entry(state=States.ACTIVE))
    assert mgr.create_latest_stable_log(0)
    assert mgr.write_log(1, make_entry(state=States.DELETED))
    fault_injector(FaultRule("file.publish", kind="torn", times=-1))
    with pytest.raises(TornWriteError):
        mgr.create_latest_stable_log(1)
    # latestStable still parses, serving the previous stable entry.
    assert mgr.get_latest_stable_log().state == States.ACTIVE


def test_action_report_write_failure_never_fails_action(tmp_path,
                                                        fault_injector):
    """fsspec backends raise library-specific (non-OSError) exceptions;
    the sidecar guard must absorb ANY of them."""
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    fault_injector(FaultRule("file.create", kind="permanent",
                             path="*report.json*", times=-1))

    from test_actions import NoOpAction
    NoOpAction(mgr).run()  # must not raise
    assert mgr.get_latest_log().state == States.ACTIVE
    assert mgr.get_action_report(1) is None


# -- OCC under concurrency -------------------------------------------------


def test_occ_exactly_one_winner_per_log_id_on_memory():
    root = f"memory://occ-{uuid.uuid4().hex}"
    mgr = IndexLogManagerImpl(root + "/idx")
    workers = 8
    try:
        for log_id in range(3):
            barrier = threading.Barrier(workers)
            results = []

            def attempt():
                entry = make_entry(state=States.CREATING)
                barrier.wait()
                results.append(mgr.write_log(log_id, entry))

            threads = [threading.Thread(target=attempt)
                       for _ in range(workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sum(results) == 1, (log_id, results)
            assert mgr.get_latest_id() == log_id
    finally:
        file_utils.delete(root)


def test_occ_concurrent_actions_one_winner(tmp_path):
    """Two racing NoOpActions on one filesystem log: exactly one wins
    the begin slot; the loser raises the conflict error."""
    from test_actions import NoOpAction

    mgr_path = str(tmp_path / "idx")
    outcomes = []
    barrier = threading.Barrier(2)

    def run_action():
        action = NoOpAction(IndexLogManagerImpl(mgr_path))
        _ = action.base_id  # resolve base BEFORE the race
        barrier.wait()
        try:
            action.run()
            outcomes.append("won")
        except HyperspaceException:
            outcomes.append("lost")

    threads = [threading.Thread(target=run_action) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(outcomes) == ["lost", "won"]


# -- crash-point matrix ----------------------------------------------------


def _write_source(path, n=240, seed=3):
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    pq.write_table(
        pa.table({"k": rng.integers(0, 40, n).astype(np.int64),
                  "x": np.arange(n, dtype=np.int64)}),
        os.path.join(path, f"part-{seed}.parquet"))


def _fresh_env(tmp_path):
    src = str(tmp_path / "src")
    _write_source(src)
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": "4"}))
    hs = Hyperspace(sess)
    df = sess.read_parquet(src)
    return hs, sess, df, src


def _prepare(verb, hs, sess, df, src):
    """Drive the index into the state the verb's validate() requires."""
    cfg = IndexConfig("idx", ["k"], ["x"])
    if verb == "create":
        return
    hs.create_index(df, cfg)
    if verb in ("refresh", "optimize"):
        return
    if verb == "incremental":
        _write_source(src, n=60, seed=9)  # append a source file
        return
    if verb in ("restore", "vacuum"):
        hs.delete_index("idx")
        return
    if verb == "delete":
        return
    if verb == "cancel":
        # Strand the index mid-refresh so cancel's validate passes.
        faults.install(faults.FaultInjector(
            [FaultRule("action.RefreshAction.end", kind="crash")]))
        with pytest.raises(InjectedCrash):
            hs.refresh_index("idx")
        faults.uninstall()
        return
    raise AssertionError(verb)


def _run_verb(verb, hs, sess, df, src):
    cfg = IndexConfig("idx", ["k"], ["x"])
    if verb == "create":
        hs.create_index(df, cfg)
    elif verb == "refresh":
        hs.refresh_index("idx")
    elif verb == "incremental":
        hs.refresh_index("idx", mode="incremental")
    elif verb == "optimize":
        hs.optimize_index("idx")
    elif verb == "delete":
        hs.delete_index("idx")
    elif verb == "restore":
        hs.restore_index("idx")
    elif verb == "vacuum":
        hs.vacuum_index("idx")
    elif verb == "cancel":
        hs.cancel("idx")
    else:
        raise AssertionError(verb)


_VERB_CLASS = {
    "create": "CreateAction", "refresh": "RefreshAction",
    "incremental": "RefreshIncrementalAction", "optimize": "OptimizeAction",
    "delete": "DeleteAction", "restore": "RestoreAction",
    "vacuum": "VacuumAction", "cancel": "CancelAction",
}

_FINAL_STATE = {
    "create": States.ACTIVE, "refresh": States.ACTIVE,
    "incremental": States.ACTIVE, "optimize": States.ACTIVE,
    "delete": States.DELETED, "restore": States.ACTIVE,
    "vacuum": States.DOESNOTEXIST,
}


@pytest.mark.parametrize("phase", ["validate", "begin", "op", "end"])
@pytest.mark.parametrize("verb", sorted(_VERB_CLASS))
def test_crash_point_matrix(tmp_path, fault_injector, verb, phase):
    """Abort at every phase boundary of every Action subclass; the index
    must always recover to a stable state via recover_index, and the
    same maintenance op must then succeed with no manual surgery."""
    hs, sess, df, src = _fresh_env(tmp_path)
    _prepare(verb, hs, sess, df, src)

    fault_injector(FaultRule(f"action.{_VERB_CLASS[verb]}.{phase}",
                             kind="crash"))
    with pytest.raises(InjectedCrash):
        _run_verb(verb, hs, sess, df, src)
    faults.uninstall()

    log_mgr = IndexLogManagerImpl(str(tmp_path / "wh" / "indexes" / "idx"))
    try:
        hs.recover_index("idx")
    except HyperspaceException:
        # create crashed before its first log write: nothing to recover.
        assert verb == "create" and phase in ("validate", "begin")
    latest = log_mgr.get_latest_log()
    if latest is not None:
        assert latest.state in STABLE_STATES, (verb, phase, latest.state)

    if verb == "cancel":
        # Recovery IS the cancel; re-running cancel on a stable index is
        # (correctly) invalid. The stranded refresh resolved to stable.
        return
    _run_verb(verb, hs, sess, df, src)  # next maintenance op succeeds
    assert log_mgr.get_latest_log().state == _FINAL_STATE[verb], (verb,
                                                                  phase)


def test_crashed_create_then_query_and_rebuild(tmp_path, fault_injector):
    """End-to-end recovery: a create that crashes mid-op leaves a partial
    uncommitted `v__=0`; queries keep answering from source, recovery
    unblocks the name, the rebuild lands in `v__=1`, and the new index
    serves queries correctly."""
    hs, sess, df, src = _fresh_env(tmp_path)
    cfg = IndexConfig("idx", ["k"], ["x"])
    fault_injector(FaultRule("parquet.write", kind="crash", nth=3))
    with pytest.raises(InjectedCrash):
        hs.create_index(df, cfg)
    faults.uninstall()

    idx_root = str(tmp_path / "wh" / "indexes" / "idx")
    # Partial dir exists but carries no commit marker.
    from hyperspace_tpu.index.data_manager import IndexDataManagerImpl
    dm = IndexDataManagerImpl(idx_root)
    assert dm.all_version_ids() == [0]
    assert dm.get_latest_version_id() is None

    sess.enable_hyperspace()
    q = lambda: df.filter(col("k") == lit(5)).select("x")
    want = q().collect().to_pandas()  # no ACTIVE index: source plan

    assert hs.recover_index("idx") is True
    hs.create_index(df, cfg)
    assert dm.get_latest_version_id() == 1  # skipped the partial dir
    got = q().collect().to_pandas()
    assert sorted(got["x"]) == sorted(want["x"])
    # Vacuuming hard-deletes the partial dir along with the real one.
    hs.delete_index("idx")
    hs.vacuum_index("idx")
    assert dm.all_version_ids() == []


def test_lease_gated_auto_recovery(tmp_path, fault_injector):
    """Within the lease a stranded writer blocks (presumed alive); past
    it, the next maintenance action recovers automatically."""
    hs, sess, df, src = _fresh_env(tmp_path)
    cfg = IndexConfig("idx", ["k"], ["x"])
    fault_injector(FaultRule("action.CreateAction.op", kind="crash"))
    with pytest.raises(InjectedCrash):
        hs.create_index(df, cfg)
    faults.uninstall()

    sess.conf.set("spark.hyperspace.maintenance.lease.seconds", "3600")
    with pytest.raises(HyperspaceException, match="already exists"):
        hs.create_index(df, cfg)  # lease holds: writer presumed alive

    sess.conf.set("spark.hyperspace.maintenance.lease.seconds", "0")
    hs.create_index(df, cfg)  # auto-recovered, then built
    log_mgr = IndexLogManagerImpl(str(tmp_path / "wh" / "indexes" / "idx"))
    assert log_mgr.get_latest_log().state == States.ACTIVE
    reg = sess.metrics_registry().counters_dict()
    assert reg.get("resilience.recoveries", 0) >= 1


# -- graceful query degradation --------------------------------------------


def _indexed_env(tmp_path):
    hs, sess, df, src = _fresh_env(tmp_path)
    hs.create_index(df, IndexConfig("idx", ["k"], ["x"]))
    sess.enable_hyperspace()
    query = lambda: df.filter(col("k") == lit(5)).select("x")
    # Sanity: the rule serves the query from index data.
    roots = [p for leaf in query()._optimized_plan().collect_leaves()
             for p in leaf.root_paths]
    assert any("v__=" in p for p in roots)
    return hs, sess, df, query, str(tmp_path / "wh" / "indexes" / "idx")


def test_degrades_to_source_when_index_data_deleted(tmp_path):
    hs, sess, df, query, idx_root = _indexed_env(tmp_path)
    want = sorted(query().collect().to_pandas()["x"])
    shutil.rmtree(os.path.join(idx_root, "v__=0"))

    from hyperspace_tpu import telemetry
    before = telemetry.get_registry().counters_dict() \
        .get("resilience.fallbacks", 0)
    table, metrics = query().collect(with_metrics=True)
    assert sorted(table.to_pandas()["x"]) == want  # correct via source
    assert metrics.counters.get("resilience.fallbacks") == 1
    degraded = metrics.events_of("resilience", "degraded")
    assert degraded and degraded[0]["index"] == "idx"
    after = telemetry.get_registry().counters_dict() \
        .get("resilience.fallbacks", 0)
    assert after - before >= 1


def test_degrades_to_source_when_index_file_corrupt(tmp_path):
    hs, sess, df, query, idx_root = _indexed_env(tmp_path)
    want = sorted(query().collect().to_pandas()["x"])
    data_dir = os.path.join(idx_root, "v__=0")
    for name in os.listdir(data_dir):
        if name.endswith(".parquet"):
            with open(os.path.join(data_dir, name), "wb") as f:
                f.write(b"these are not the bytes you indexed")
    table, metrics = query().collect(with_metrics=True)
    assert sorted(table.to_pandas()["x"]) == want
    assert metrics.counters.get("resilience.fallbacks") == 1


def test_source_scan_errors_do_not_degrade(tmp_path):
    """Degradation is for RULE-SELECTED index scans only: a broken
    SOURCE relation has nothing to fall back to and must raise."""
    hs, sess, df, query, idx_root = _indexed_env(tmp_path)
    sess.disable_hyperspace()
    shutil.rmtree(str(tmp_path / "src"))
    with pytest.raises(Exception):
        df.filter(col("k") == lit(5)).select("x").collect()


def test_join_query_degrades_too(tmp_path):
    """The JoinIndexRule path: both sides' indexes vanish; the join
    answers from source."""
    src_a = str(tmp_path / "a")
    src_b = str(tmp_path / "b")
    _write_source(src_a, n=120, seed=1)
    _write_source(src_b, n=120, seed=2)
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": "4"}))
    hs = Hyperspace(sess)
    dfa = sess.read_parquet(src_a)
    dfb = sess.read_parquet(src_b)
    hs.create_index(dfa, IndexConfig("ia", ["k"], ["x"]))
    hs.create_index(dfb, IndexConfig("ib", ["k"], ["x"]))
    sess.enable_hyperspace()
    q = lambda: dfa.join(dfb, on="k").select("k")
    want = q().collect().num_rows
    for name in ("ia", "ib"):
        shutil.rmtree(str(tmp_path / "wh" / "indexes" / name / "v__=0"))
    table, metrics = q().collect(with_metrics=True)
    assert table.num_rows == want
    assert metrics.counters.get("resilience.fallbacks") == 1


# -- vacuum over sparse/partial layouts ------------------------------------


def test_vacuum_handles_sparse_versions():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.DELETED))
    data = FakeDataManager(versions=[0, 3, 7])  # sparse: 1,2,4-6 missing
    from hyperspace_tpu.actions.vacuum import VacuumAction
    VacuumAction(mgr, data).run()
    assert data.deleted == [7, 3, 0]
    assert mgr.get_latest_log().state == States.DOESNOTEXIST


def test_storage_transient_faults_ride_the_retry_seam(tmp_path,
                                                      fault_injector):
    """A transient storage failure mid-action is absorbed by the retry
    policy — the action completes as if nothing happened, and the
    io.retries counter shows the save."""
    from hyperspace_tpu import telemetry
    hs, sess, df, src = _fresh_env(tmp_path)
    before = telemetry.get_registry().counters_dict().get("io.retries", 0)
    fault_injector(FaultRule("parquet.write", kind="transient", nth=2,
                             times=1),
                   FaultRule("file.write_if_absent", kind="transient",
                             times=1))
    hs.create_index(df, IndexConfig("idx", ["k"], ["x"]))
    log_mgr = IndexLogManagerImpl(str(tmp_path / "wh" / "indexes" / "idx"))
    assert log_mgr.get_latest_log().state == States.ACTIVE
    after = telemetry.get_registry().counters_dict().get("io.retries", 0)
    assert after - before >= 2
